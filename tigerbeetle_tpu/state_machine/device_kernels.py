"""Device-resident semantic kernels: result codes computed ON the TPU.

Round 3 kept all create_transfers semantics on the host and used the
device as a write-behind balance replica.  Round 4 inverts that
authority for the three vectorizable batch classes (order-free,
linked-chain, two-phase): the kernels below read the authoritative
HBM balance/meta tables, run the full precedence ladder + the
order-dependent resolution, apply balance effects, and emit result
codes — the host's role shrinks to joins (id-directory probes, durable
row gathers), routing, and bookkeeping derived from the codes.

reference: src/state_machine.zig:1220-1306 (execute loop),
:1462-1741 (create_transfer + post/void), src/tigerbeetle.zig:31-39
(limit formulas).  The semantics ported here are the same ones the
vectorized host resolvers (resolve.py) implement; differential fuzz in
tests/test_device_engine.py pins all three kernels to the CPU oracle.

Link constraints (measured, experiments/README.md): the tunneled-TPU
downlink costs ~105 ms per fetch at ~15 MB/s, serialized.  Per-event
result readback is impossible at millions of events/s, so each kernel
writes a fixed-size FAILURE-SPARSE summary row (60 failure slots +
status flags) into a device ring; the host fetches the ring once per
burst.  Batches whose failures exceed the cap — or that hit an
overflow/precondition edge — raise a flag and are re-executed exactly
on the host engine (the fallback path), so the sparse encoding never
loses information.

Input marshaling split (who computes what): the host packs raw event
columns and *stateless byte predicates* (id == 0, id == maxInt,
debit id == credit id, ...) plus join booleans (duplicate-id found,
pending target found) into one u64 matrix per batch — pure wire
decoding and directory probes.  Every *decision* — precedence ladder
order, balance math, limit fixpoint, two-phase winner resolution,
overflow admission — happens on device against device state.
"""

from __future__ import annotations

import os

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
# Persistent XLA compilation cache: the scanned dispatch kernels cost
# minutes of one-time compile on the tunneled TPU; caching them on
# disk makes that a once-per-machine cost instead of once-per-process
# (bench runs six configs in separate engine instances).
if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/tigerbeetle_tpu_xla"),
        )
    # tbcheck: allow(broad-except): the XLA compile cache is an
    # optimization only — any backend rejection means compiles stay
    # per-process, never an error.
    except Exception:
        pass

import jax.numpy as jnp

from tigerbeetle_tpu.types import CreateTransferResult as CTR

# ---------------------------------------------------------------------------
# Batch geometry.

# Fixed event bucket (batches pad up to this; larger batches take the
# host path).  Tests shrink it via TB_DEV_B — CPU-backend matmuls at
# the production size would dominate the suite's runtime.
from tigerbeetle_tpu import envcheck as _envcheck

# Upper bound 8192: the linked kernel packs (event << 1 | side) into 14
# key bits and masks events with B-1, and f32 partial sums of 8-bit
# pieces over 4B rows (the two_phase add matmul) must stay below 2^24.
B = _envcheck.env_int("TB_DEV_B", 8192, minimum=1, maximum=8192)
if B & (B - 1) != 0:
    raise _envcheck.EnvVarError(
        f"TB_DEV_B={B} invalid: must be a power of 2 <= 8192"
    )
assert 4 * B * 255 < (1 << 24), "TB_DEV_B too large for exact f32 sums"
SUMMARY_WORDS = 64
FAIL_CAP = SUMMARY_WORDS - 4   # failure entries per batch summary

# Summary flag bits (word [1]).
FLAG_OVERFLOW = 1 << 0     # balance-overflow admission failed
FLAG_CAP = 1 << 1          # more than FAIL_CAP failures
FLAG_PRECOND = 1 << 2      # kernel precondition (u64-safety, fixpoint cap)
ITERS_SHIFT = 16           # linked fixpoint iterations (diagnostics)

# Packed input columns (u64 each, B rows).
COL_BITS = 0
COL_SLOTS = 1      # (dr_slot+1) u32 | (cr_slot+1) << 32 ; 0 = not found
COL_AMT_LO = 2
COL_AMT_HI = 3
COL_MISC = 4       # flags u16 | code u16 << 16 | ledger u32 << 32
COL_TIMEOUT = 5    # timeout u32 | (p_tgt+1) u32 << 32
N_COLS = 6
# two-phase extension columns:
COL_TP_JOIN = 6    # p_flags u16 | p_code u16 << 16 | p_ledger u32 << 32
COL_TP_SLOTS = 7   # (p_dr_slot+1) u32 | (p_cr_slot+1) u32 << 32  (durable)
COL_TP_AMT_LO = 8  # durable target amount
COL_TP_AMT_HI = 9
COL_TP_REF = 10    # (tgt_ev+1) u32 | dstat_init u32 << 32
N_COLS_TP = 11

# COL_BITS bits (host-marshaled stateless predicates + join booleans).
BIT_TS_NONZERO = 1 << 0
BIT_ID_ZERO = 1 << 1
BIT_ID_MAX = 1 << 2
BIT_DR_ZERO = 1 << 3
BIT_DR_MAX = 1 << 4
BIT_CR_ZERO = 1 << 5
BIT_CR_MAX = 1 << 6
BIT_SAME_ACCT = 1 << 7
BIT_PEND_NONZERO = 1 << 8
BIT_PEND_MAX = 1 << 9
BIT_PEND_SELF = 1 << 10
BIT_E_FOUND = 1 << 11
BIT_P_FOUND = 1 << 12
BIT_T_DR_SET = 1 << 13   # event names a debit account (pv ladder)
BIT_T_CR_SET = 1 << 14
BIT_DR_EQ_P = 1 << 15    # event dr id == target's dr id
BIT_CR_EQ_P = 1 << 16
BIT_LEDGER_EQ_P = 1 << 17  # unused (ledger compare runs on device)

# TransferFlags bits (reference: src/tigerbeetle.zig:127-140).
F_LINKED = 1 << 0
F_PENDING = 1 << 1
F_POST = 1 << 2
F_VOID = 1 << 3
F_BAL_DR = 1 << 4
F_BAL_CR = 1 << 5

# AccountFlags bits (reference: src/tigerbeetle.zig:42-63).
AF_DR_LIMIT = 1 << 1
AF_CR_LIMIT = 1 << 2

S_PENDING, S_POSTED, S_VOIDED, S_EXPIRED = 1, 2, 3, 4

NS_PER_S = jnp.uint64(1_000_000_000)
_MASK8 = jnp.uint64(0xFF)
_MASK16 = jnp.uint64(0xFFFF)
_MASK32 = jnp.uint64(0xFFFFFFFF)
# u64-exactness bound for the linked fixpoint (see resolve.py).
_U64_SAFE = np.uint64(1) << np.uint64(61)


def _first_nonzero(*pairs):
    r = jnp.uint32(0)
    for cond, code in pairs:
        r = jnp.where((r == 0) & cond, jnp.uint32(code), r)
    return r


def _unpack(pk):
    """Split the packed (B, C) u64 matrix into named columns."""
    bits = pk[:, COL_BITS]
    slots = pk[:, COL_SLOTS]
    misc = pk[:, COL_MISC]
    return {
        "bits": bits,
        "dr_slot": (slots & _MASK32).astype(jnp.int64) - 1,
        "cr_slot": (slots >> jnp.uint64(32)).astype(jnp.int64) - 1,
        "amt_lo": pk[:, COL_AMT_LO],
        "amt_hi": pk[:, COL_AMT_HI],
        "flags": (misc & _MASK16).astype(jnp.uint32),
        "code": ((misc >> jnp.uint64(16)) & _MASK16).astype(jnp.uint32),
        "ledger": (misc >> jnp.uint64(32)).astype(jnp.uint32),
        "timeout": (pk[:, COL_TIMEOUT] & _MASK32),
        "p_tgt": (pk[:, COL_TIMEOUT] >> jnp.uint64(32)).astype(jnp.int64) - 1,
    }


def _bit(bits, mask):
    return (bits & jnp.uint64(mask)) != 0


def _static_ladder_normal(ev, meta, active):
    """Static precedence ladder for non-post/void transfers, evaluated
    on device (reference ladder: src/state_machine.zig:1465-1504).
    `meta` is the (A, 2) u32 device table [flags, ledger]."""
    bits = ev["bits"]
    flags = ev["flags"]
    A = meta.shape[0]
    drc = jnp.clip(ev["dr_slot"], 0, A - 1)
    crc = jnp.clip(ev["cr_slot"], 0, A - 1)
    dr_found = ev["dr_slot"] >= 0
    cr_found = ev["cr_slot"] >= 0
    dr_ledger = jnp.where(dr_found, meta[drc, 1], 0)
    cr_ledger = jnp.where(cr_found, meta[crc, 1], 0)
    not_pending = (flags & F_PENDING) == 0
    not_balancing = (flags & (F_BAL_DR | F_BAL_CR)) == 0
    amount_zero = (ev["amt_lo"] == 0) & (ev["amt_hi"] == 0)
    r = _first_nonzero(
        (_bit(bits, BIT_TS_NONZERO), CTR.timestamp_must_be_zero),
        ((flags & ~jnp.uint32(0x3F)) != 0, CTR.reserved_flag),
        (_bit(bits, BIT_ID_ZERO), CTR.id_must_not_be_zero),
        (_bit(bits, BIT_ID_MAX), CTR.id_must_not_be_int_max),
        (_bit(bits, BIT_DR_ZERO), CTR.debit_account_id_must_not_be_zero),
        (_bit(bits, BIT_DR_MAX), CTR.debit_account_id_must_not_be_int_max),
        (_bit(bits, BIT_CR_ZERO), CTR.credit_account_id_must_not_be_zero),
        (_bit(bits, BIT_CR_MAX), CTR.credit_account_id_must_not_be_int_max),
        (_bit(bits, BIT_SAME_ACCT), CTR.accounts_must_be_different),
        (_bit(bits, BIT_PEND_NONZERO), CTR.pending_id_must_be_zero),
        (
            not_pending & (ev["timeout"] != 0),
            CTR.timeout_reserved_for_pending_transfer,
        ),
        (not_balancing & amount_zero, CTR.amount_must_not_be_zero),
        (ev["ledger"] == 0, CTR.ledger_must_not_be_zero),
        (ev["code"] == 0, CTR.code_must_not_be_zero),
        (~dr_found, CTR.debit_account_not_found),
        (~cr_found, CTR.credit_account_not_found),
        (dr_ledger != cr_ledger, CTR.accounts_must_have_the_same_ledger),
        (
            ev["ledger"] != dr_ledger,
            CTR.transfer_must_have_the_same_ledger_as_accounts,
        ),
    )
    # Inactive (padding) rows: poisoned so they never apply.
    return jnp.where(active, r, jnp.uint32(CTR.linked_event_failed))


def _accum_cols_multi(slot_rows, passes, A, lo_only=False):
    """Exact per-(slot, column) u128 sums via ONE one-hot MXU matmul
    shared across several accumulation passes.

    `passes` is a list of (col_rows, amt_lo_rows, amt_hi_rows, valid)
    over the SAME slot rows; their 8-bit-piece payloads concatenate
    along the feature axis, so the (rows, A) one-hot — the dominant
    HBM traffic of these kernels — is materialized once however many
    sums a kernel needs (linked: superset admission + final apply;
    two_phase: adds + releases).

    Amounts decompose into 8-bit pieces (each < 2^8); the one-hot
    bf16 matmul accumulates them in f32 — sums stay below
    rows * 255 < 2^24, so every partial is exact — and a base-256
    carry recombination rebuilds exact u128 column deltas.  Invalid
    rows contribute ZERO payload (their slot may be clip-garbage; a
    zero contribution to any slot is harmless).

    `lo_only` halves the payload (8 pieces) when every amount's high
    limb is zero — a trace-time specialization the host router
    selects (the high-limb sum is then just the carry chain's
    overflow).

    Returns one (d_lo, d_hi, limb_ov) of shape (A, 4) per pass.
    """
    rows = slot_rows.shape[0]
    zero = jnp.uint64(0)
    npieces = 8 if lo_only else 16
    payloads = []
    for col_rows, amt_lo_rows, amt_hi_rows, valid in passes:
        lo = jnp.where(valid, amt_lo_rows, zero)
        pieces = [((lo >> jnp.uint64(s)) & _MASK8).astype(jnp.float32)
                  for s in range(0, 64, 8)]
        if not lo_only:
            hi = jnp.where(valid, amt_hi_rows, zero)
            pieces += [((hi >> jnp.uint64(s)) & _MASK8).astype(jnp.float32)
                       for s in range(0, 64, 8)]
        P = jnp.stack(pieces, axis=-1)  # (rows, npieces)
        colmask = jax.nn.one_hot(col_rows, 4, dtype=jnp.float32)
        payloads.append(
            (colmask[:, :, None] * P[:, None, :]).reshape(rows, 4 * npieces)
        )
    payload = jnp.concatenate(payloads, axis=-1)
    onehot = jax.nn.one_hot(
        jnp.clip(slot_rows, 0, A - 1), A, dtype=jnp.bfloat16
    )
    acc_all = jax.lax.dot_general(
        onehot.T, payload.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(A, len(passes), 4, npieces).astype(jnp.uint64)

    out = []
    for p in range(len(passes)):
        acc = acc_all[:, p]
        c = acc[:, :, 0]
        d_lo = c & _MASK8
        carry = c >> jnp.uint64(8)
        for k in range(1, 8):
            c = acc[:, :, k] + carry
            d_lo = d_lo | ((c & _MASK8) << jnp.uint64(8 * k))
            carry = c >> jnp.uint64(8)
        if lo_only:
            out.append((d_lo, carry, jnp.zeros((A, 4), bool)))
            continue
        c = acc[:, :, 8] + carry
        d_hi = c & _MASK8
        carry = c >> jnp.uint64(8)
        for k in range(1, 8):
            c = acc[:, :, 8 + k] + carry
            d_hi = d_hi | ((c & _MASK8) << jnp.uint64(8 * k))
            carry = c >> jnp.uint64(8)
        out.append((d_lo, d_hi, carry != 0))
    return out


def _accum_cols(slot_rows, col_rows, amt_lo_rows, amt_hi_rows, valid, A,
                lo_only=False):
    """Single-pass convenience wrapper over _accum_cols_multi."""
    return _accum_cols_multi(
        slot_rows, [(col_rows, amt_lo_rows, amt_hi_rows, valid)], A,
        lo_only=lo_only,
    )[0]


def _admit_apply(table, d_lo, d_hi, limb_ov):
    """Admission + apply: add exact column deltas iff NO column u128
    add overflows and no account's combined dp+dpo / cp+cpo total
    overflows (mirrors BalanceMirror._admit_commit, which the host
    fast path proved bit-parity for).  Returns (new_table, ov)."""
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    cy = (new_lo < old_lo).astype(jnp.uint64)
    hi_p = old_hi + d_hi
    add_ov1 = hi_p < old_hi
    new_hi = hi_p + cy
    add_ov = add_ov1 | (new_hi < hi_p)

    def tot_ov(lo_a, hi_a, lo_b, hi_b):
        # u128 (a + b) overflow flag.
        lo = lo_a + lo_b
        c = (lo < lo_a).astype(jnp.uint64)
        hp = hi_a + hi_b
        h = hp + c
        return (hp < hi_a) | (h < hp)

    dr_tot_ov = tot_ov(new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1])
    cr_tot_ov = tot_ov(new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3])
    ov = limb_ov.any() | add_ov.any() | dr_tot_ov.any() | cr_tot_ov.any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]],
        axis=-1,
    )
    return jnp.where(ov, table, nt), ov


def _summary(results, active, flags_word, last_applied):
    """Failure-sparse fixed-size summary row: [n_fail, flags,
    last_applied+1, n_active, entries...] as (SUMMARY_WORDS,) u64."""
    fail = active & (results != 0)
    n_fail = fail.sum().astype(jnp.uint64)
    pos = jnp.cumsum(fail) - 1
    ent = (jnp.arange(B, dtype=jnp.uint64) << jnp.uint64(32)) | results.astype(
        jnp.uint64
    )
    entries = jnp.zeros(FAIL_CAP, jnp.uint64).at[
        jnp.where(fail, pos, FAIL_CAP)
    ].set(ent, mode="drop")
    cap = n_fail > FAIL_CAP
    flags_word = flags_word | jnp.where(
        cap, jnp.uint64(FLAG_CAP), jnp.uint64(0)
    )
    head = jnp.stack(
        [
            n_fail,
            flags_word,
            (last_applied + 1).astype(jnp.uint64),
            active.sum().astype(jnp.uint64),
        ]
    )
    return jnp.concatenate([head, entries])


# ---------------------------------------------------------------------------
# Order-free kernel.


def _orderfree(table, meta, ring, ring_at, pk, n, ts_base, lo_only=False):
    """Order-independent batch: full static ladder + overflow admission
    + scatter apply + result codes, all on device.

    Host routing guarantees (same class the r3 host fast path took):
    no linked/post/void/balancing flags, unique fresh ids, no
    limit/history accounts touched.  Within that class the only
    dynamic codes are the overflow family — excluded wholesale by the
    total-sum admission check (amounts are non-negative, so any prefix
    is bounded by the all-applied total; reference:
    src/state_machine.zig:1531-1545) — and overflows_timeout, which is
    order-independent and computed per event here.
    """
    return _orderfree_core(
        table, meta, ring, ring_at, _unpack(pk), n, ts_base, lo_only
    )


# Tight 20-byte/event format for the dominant order-free class: the
# tunnel's h2d bandwidth collapses to ~30 MB/s once any kernel has run
# in the process (measured, r5), so INPUT BYTES are the device
# engine's throughput ceiling — 5xu32 instead of 6xu64 is a 2.4x lift.
# Host gating (exact facts, not predictions — no device re-check
# needed): every amount_hi == 0, amount_lo < 2^32, timeout == 0.
# Word 0 packs the predicate bits (low 18), the 6 transfer-flag bits,
# a code!=0 bit, and a reserved-flags bit; words 1/2 are slot+1;
# word 3 the u32 amount; word 4 the full ledger.
TIGHT_FLAGS_SHIFT = 18
TIGHT_CODE_BIT = 1 << 24
TIGHT_RESERVED_BIT = 1 << 25
N_COLS_TIGHT = 5


def _orderfree_tight(table, meta, ring, ring_at, pk32, n, ts_base):
    w0 = pk32[:, 0]
    zero64 = jnp.zeros(B, jnp.uint64)
    # The reserved-flag predicate rides flag bit 6: the ladder's
    # (flags & ~0x3F) != 0 check then fires exactly for it.
    flags = (
        ((w0 >> jnp.uint32(TIGHT_FLAGS_SHIFT)) & jnp.uint32(0x3F))
        | (jnp.where(w0 & jnp.uint32(TIGHT_RESERVED_BIT), 1, 0) << 6)
    ).astype(jnp.uint32)
    ev = {
        "bits": w0.astype(jnp.uint64),
        "dr_slot": pk32[:, 1].astype(jnp.int64) - 1,
        "cr_slot": pk32[:, 2].astype(jnp.int64) - 1,
        "amt_lo": pk32[:, 3].astype(jnp.uint64),
        "amt_hi": zero64,
        "flags": flags,
        "code": jnp.where(
            w0 & jnp.uint32(TIGHT_CODE_BIT), jnp.uint32(1), jnp.uint32(0)
        ),
        "ledger": pk32[:, 4],
        "timeout": zero64,
        "p_tgt": jnp.full(B, -1, jnp.int64),
    }
    return _orderfree_core(
        table, meta, ring, ring_at, ev, n, ts_base, lo_only=True
    )


def _orderfree_core(table, meta, ring, ring_at, ev, n, ts_base, lo_only):
    A = table.shape[0]
    iota = jnp.arange(B, dtype=jnp.int64)
    active = iota < n
    r = _static_ladder_normal(ev, meta, active)

    ts_i = ts_base + iota.astype(jnp.uint64)
    expires = ts_i + ev["timeout"] * NS_PER_S
    ov_timeout = (ev["timeout"] != 0) & (expires < ts_i)
    r = jnp.where((r == 0) & ov_timeout, jnp.uint32(CTR.overflows_timeout), r)

    ok = active & (r == 0)
    is_pending = (ev["flags"] & F_PENDING) != 0
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    slot_rows = jnp.concatenate([ev["dr_slot"], ev["cr_slot"]])
    col_rows = jnp.concatenate([dcol, ccol])
    amt_lo2 = jnp.concatenate([ev["amt_lo"]] * 2)
    amt_hi2 = jnp.concatenate([ev["amt_hi"]] * 2)
    valid = jnp.concatenate([ok, ok])
    d_lo, d_hi, limb_ov = _accum_cols(
        slot_rows, col_rows, amt_lo2, amt_hi2, valid, A, lo_only=lo_only
    )
    new_table, ov = _admit_apply(table, d_lo, d_hi, limb_ov)

    applied_idx = jnp.where(ok, iota, -1)
    last_applied = applied_idx.max()
    flags_word = jnp.where(ov, jnp.uint64(FLAG_OVERFLOW), jnp.uint64(0))
    s = _summary(r, active, flags_word, last_applied)
    ring = jax.lax.dynamic_update_slice(ring, s[None, :], (ring_at, 0))
    return new_table, ring


# ---------------------------------------------------------------------------
# Linked-chain kernel (port of resolve.linked_resolve to device).


def _linked(table, meta, ring, ring_at, pk, n, ts_base, small=False):
    """Linked-chain batch of plain posted transfers; limit-flag
    accounts allowed.  Jacobi fixpoint over per-account segmented
    prefix sums converges to the exact sequential verdicts (see
    resolve.py for the correctness argument; reference:
    src/state_machine.zig:1220-1306, src/tigerbeetle.zig:31-39).

    `small` is a trace-time specialization the host router selects
    when the batch's total amount contribution fits i32: each
    fixpoint prefix is then ONE i32 cumsum instead of four 16-bit
    pieces (the fixpoint's dominant per-iteration cost).  The device
    still verifies the bound and raises the precondition flag (exact
    host fallback) if the router's pick was wrong."""
    ev = _unpack(pk)
    A = table.shape[0]
    iota = jnp.arange(B, dtype=jnp.int64)
    active = iota < n
    static = _static_ladder_normal(ev, meta, active)

    linked = active & ((ev["flags"] & F_LINKED) != 0)
    # Chain structure: maximal runs of linked + following event.
    start = jnp.concatenate(
        [jnp.ones(1, bool), ~linked[:-1]]
    )
    chain_id = jnp.cumsum(start.astype(jnp.int64)) - 1
    # chain_start event per chain (segment min of index).
    chain_start_ev = jax.ops.segment_min(iota, chain_id, num_segments=B)
    chain_last_ev = jax.ops.segment_max(iota, chain_id, num_segments=B)
    start_of_ev = chain_start_ev[chain_id]

    # Unconditional per-event codes; chain_open overrides on the last
    # active event when it still carries the linked flag.
    code0 = static
    is_last = iota == (n - 1)
    code0 = jnp.where(
        is_last & linked, jnp.uint32(CTR.linked_event_chain_open), code0
    )
    static_ok = active & (code0 == 0)

    drc = jnp.clip(ev["dr_slot"], 0, A - 1)
    crc = jnp.clip(ev["cr_slot"], 0, A - 1)
    dr_flags = jnp.where(ev["dr_slot"] >= 0, meta[drc, 0], 0)
    cr_flags = jnp.where(ev["cr_slot"] >= 0, meta[crc, 0], 0)
    LIM = jnp.uint32(AF_DR_LIMIT | AF_CR_LIMIT)
    dlim = (dr_flags & AF_DR_LIMIT) != 0
    clim = (cr_flags & AF_CR_LIMIT) != 0

    # ---- preconditions (device-evaluated; violations -> host fallback)
    precond_bad = (static_ok & (ev["amt_hi"] != 0)).any()
    ent_d = static_ok & ((dr_flags & LIM) != 0)
    ent_c = static_ok & ((cr_flags & LIM) != 0)
    lim_touch = jnp.zeros(A + 1, bool)
    lim_touch = lim_touch.at[jnp.where(ent_d, drc, A)].set(True, mode="drop")
    lim_touch = lim_touch.at[jnp.where(ent_c, crc, A)].set(True, mode="drop")
    lim_touch = lim_touch[:A]
    hi_cols = table[:, 1::2]
    lo_cols = table[:, 0::2]
    precond_bad = precond_bad | (
        lim_touch[:, None] & (hi_cols != 0)
    ).any() | (
        lim_touch[:, None] & (lo_cols >= jnp.uint64(_U64_SAFE))
    ).any()
    contrib = jnp.where(static_ok, ev["amt_lo"], jnp.uint64(0))
    sum_bound = jnp.float64((1 << 31) - 1) if small else jnp.float64(_U64_SAFE)
    precond_bad = precond_bad | (
        contrib.astype(jnp.float64).sum() >= sum_bound
    )

    # ---- superset overflow admission rows (static_ok events, posted
    # cols); the sums themselves ride the SAME one-hot matmul as the
    # final apply below (one materialization of the (2B, A) one-hot).
    slot_rows = jnp.concatenate([ev["dr_slot"], ev["cr_slot"]])
    col_rows = jnp.concatenate(
        [jnp.ones(B, jnp.int32), jnp.full(B, 3, jnp.int32)]
    )
    amt_lo2 = jnp.concatenate([ev["amt_lo"]] * 2)
    amt_hi2 = jnp.concatenate([ev["amt_hi"]] * 2)
    sup_valid = jnp.concatenate([static_ok, static_ok])

    # ---- fixpoint over (slot, event)-sorted limit entries.
    # Entries: 2B rows (dr side then cr side); invalid rows get
    # sentinel keys that sort to the end.  The TPU sort's cost scales
    # with operand count, so everything is PACKED into one u64 key —
    # slot << 14 | event << 1 | side — and the per-entry columns are
    # recovered arithmetically from the sorted keys (events are
    # distinct within a slot because dr != cr, so the side bit never
    # affects the required event order).
    eslot2 = jnp.concatenate([ev["dr_slot"], ev["cr_slot"]])
    entv = jnp.concatenate([ent_d, ent_c])
    side2 = jnp.concatenate([jnp.zeros(B, jnp.uint64), jnp.ones(B, jnp.uint64)])
    evs2 = jnp.concatenate([iota, iota]).astype(jnp.uint64)
    key64 = (
        (eslot2.astype(jnp.uint64) << jnp.uint64(14))
        | (evs2 << jnp.uint64(1)) | side2
    )
    # u64 sorts as a variadic (u32, u32) pair on TPU — twice the
    # compare/swap traffic.  The packed key needs log2(A) + 14 bits,
    # so any table up to 2^17 rows sorts in native u32.
    if A <= (1 << 17):
        key = jnp.where(
            entv, key64.astype(jnp.uint32), jnp.uint32(0xFFFFFFFF)
        )
        sentinel = jnp.uint32(0xFFFFFFFF)
    else:
        key = jnp.where(entv, key64, jnp.uint64(0xFFFFFFFFFFFFFFFF))
        sentinel = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    (key_s,) = jax.lax.sort([key], num_keys=1)
    valid_s = key_s != sentinel
    key_su = key_s.astype(jnp.uint64)
    evs_s = jnp.where(
        valid_s, (key_su >> jnp.uint64(1)) & jnp.uint64(B - 1), jnp.uint64(0)
    ).astype(jnp.int32)
    eslot_s = jnp.where(
        valid_s, key_su >> jnp.uint64(14), jnp.uint64(0x7FFFFFFF)
    ).astype(jnp.int32)
    edeb_s = valid_s & ((key_su & jnp.uint64(1)) == 0)
    eamt_s = ev["amt_lo"][evs_s]
    M = 2 * B
    jpos = jnp.arange(M)
    seg_new = jnp.concatenate(
        [jnp.ones(1, bool), eslot_s[1:] != eslot_s[:-1]]
    ) & valid_s
    seg_first = jax.lax.associative_scan(
        jnp.maximum, jnp.where(seg_new, jpos, 0)
    )
    # Chain-start boundary per entry, in the SAME packed-key encoding
    # and dtype (side bit 0 sorts before either side of the start
    # event).
    bkey64 = (
        (eslot_s.astype(jnp.uint64) << jnp.uint64(14))
        | (
            start_of_ev[jnp.clip(evs_s, 0, B - 1)].astype(jnp.uint64)
            << jnp.uint64(1)
        )
    )
    bkey = jnp.where(valid_s, bkey64.astype(key_s.dtype), sentinel)
    bpos = jnp.searchsorted(key_s, bkey, side="left")

    esl = jnp.clip(eslot_s, 0, A - 1)
    init_dp = table[esl, 0]
    init_dpo = table[esl, 2]
    init_cp = table[esl, 4]
    init_cpo = table[esl, 6]
    evc = jnp.clip(evs_s, 0, B - 1)
    view_d = valid_s & edeb_s & dlim[evc]
    view_c = valid_s & ~edeb_s & clim[evc]
    amt_d = jnp.where(edeb_s & valid_s, eamt_s, jnp.uint64(0))
    amt_c = jnp.where(~edeb_s & valid_s, eamt_s, jnp.uint64(0))

    def chain_state(pass_):
        fails = (~pass_ & active).astype(jnp.int32)
        F = jnp.cumsum(fails)
        base = (F - fails)[chain_start_ev]
        applied_prefix = (F - base[chain_id]) == 0
        chain_ok = applied_prefix[chain_last_ev]
        return applied_prefix, chain_ok

    def excl_prefix(v):
        # Exact u64 inclusive cumsum.  A direct u64 cumsum lowers to a
        # variadic (u32, u32) reduce-window that blows XLA:TPU's
        # scoped vmem inside while_loop bodies — see
        # experiments/tpu_compile_check.py.  small: the verified
        # < 2^31 total makes one i32 cumsum exact.  General: four
        # 16-bit-piece i32 cumsums (totals < 2^61 by the
        # precondition; piece sums < M * 2^16 < 2^31).
        if small:
            return (
                jnp.cumsum(v.astype(jnp.int32)).astype(jnp.uint64) - v
            )
        cs = jnp.uint64(0)
        for k in range(4):
            p = ((v >> jnp.uint64(16 * k)) & _MASK16).astype(jnp.int32)
            cs = cs + (jnp.cumsum(p).astype(jnp.uint64) << jnp.uint64(16 * k))
        return cs - v  # exclusive prefix at each position

    def body(state):
        pass_prev, _dr_fail, _cr_fail, it, _conv = state
        applied_prefix, chain_ok = chain_state(pass_prev)
        wce = chain_ok[chain_id][evc]
        wie = applied_prefix[evc]
        Pdc = excl_prefix(jnp.where(wce, amt_d, jnp.uint64(0)))
        Pcc = excl_prefix(jnp.where(wce, amt_c, jnp.uint64(0)))
        Pdi = excl_prefix(jnp.where(wie, amt_d, jnp.uint64(0)))
        Pci = excl_prefix(jnp.where(wie, amt_c, jnp.uint64(0)))

        def seg_diff(P, at):
            # inclusive-exclusive segmented windows: P is the exclusive
            # prefix, so P[b] - P[a] sums entries [a, b).
            return P[at]

        deb_before = (
            seg_diff(Pdc, bpos) - seg_diff(Pdc, seg_first)
        ) + (Pdi[jpos] - seg_diff(Pdi, bpos))
        cred_before = (
            seg_diff(Pcc, bpos) - seg_diff(Pcc, seg_first)
        ) + (Pci[jpos] - seg_diff(Pci, bpos))
        bad_d = view_d & (
            init_dp + init_dpo + deb_before + eamt_s
            > init_cpo + cred_before
        )
        bad_c = view_c & (
            init_cp + init_cpo + cred_before + eamt_s
            > init_dpo + deb_before
        )
        dr_fail = jnp.zeros(B, bool).at[jnp.where(bad_d, evc, B)].set(
            True, mode="drop"
        )
        cr_fail = jnp.zeros(B, bool).at[jnp.where(bad_c, evc, B)].set(
            True, mode="drop"
        )
        pass_ = static_ok & ~dr_fail & ~cr_fail
        conv = (pass_ == pass_prev).all()
        return pass_, dr_fail, cr_fail, it + 1, conv

    def cond(state):
        _p, _d, _c, it, conv = state
        return (~conv) & (it < 64)

    init = (
        static_ok,
        jnp.zeros(B, bool),
        jnp.zeros(B, bool),
        jnp.int32(0),
        jnp.bool_(False),
    )
    # One unconditional iteration then loop to convergence: matches the
    # host resolver's "verdict of event 0 is unconditional" induction.
    state = body(init)
    pass_, dr_fail, cr_fail, iters, conv = jax.lax.while_loop(
        cond, body, state
    )
    fix_failed = ~conv

    applied_prefix, chain_ok = chain_state(pass_)

    # ---- result codes.
    results = jnp.zeros(B, jnp.uint32)
    bad_chain = ~chain_ok
    member_bad = bad_chain[chain_id] & active
    fail_pos = jnp.where(active & ~pass_, iota, B)
    first_fail = jax.ops.segment_min(fail_pos, chain_id, num_segments=B)
    ff_of_ev = first_fail[chain_id]
    own_code = jnp.where(
        code0 != 0,
        code0,
        jnp.where(
            dr_fail,
            jnp.uint32(CTR.exceeds_credits),
            jnp.uint32(CTR.exceeds_debits),
        ),
    )
    results = jnp.where(
        member_bad, jnp.uint32(CTR.linked_event_failed), results
    )
    is_ff = member_bad & (iota == ff_of_ev)
    results = jnp.where(is_ff, own_code, results)
    results = jnp.where(
        is_last & linked & member_bad,
        jnp.uint32(CTR.linked_event_chain_open),
        results,
    )

    # ---- superset admission + apply in ONE shared-one-hot matmul
    # (events with results == 0 are exactly the members of
    # fully-passing chains).
    okev = active & (results == 0)
    ap_valid = jnp.concatenate([okev, okev])
    (d_lo_s, d_hi_s, limb_ov_s), (d_lo, d_hi, limb_ov) = _accum_cols_multi(
        slot_rows,
        [
            (col_rows, amt_lo2, amt_hi2, sup_valid),
            (col_rows, amt_lo2, amt_hi2, ap_valid),
        ],
        A, lo_only=True,
    )
    _, sup_ov = _admit_apply(table, d_lo_s, d_hi_s, limb_ov_s)
    fallback = sup_ov | precond_bad | fix_failed
    new_table, _ov2 = _admit_apply(table, d_lo, d_hi, limb_ov)
    new_table = jnp.where(fallback, table, new_table)

    last_applied = jnp.where(applied_prefix & active, iota, -1).max()
    flags_word = (
        jnp.where(sup_ov, jnp.uint64(FLAG_OVERFLOW), jnp.uint64(0))
        | jnp.where(
            precond_bad | fix_failed, jnp.uint64(FLAG_PRECOND), jnp.uint64(0)
        )
        | (iters.astype(jnp.uint64) << jnp.uint64(ITERS_SHIFT))
    )
    s = _summary(results, active, flags_word, last_applied)
    ring = jax.lax.dynamic_update_slice(ring, s[None, :], (ring_at, 0))
    return new_table, ring


# ---------------------------------------------------------------------------
# Two-phase kernel (port of resolve.two_phase_resolve to device).


def _two_phase(table, meta, ring, ring_at, pk, n, ts_base, lo_only=False):
    """Pending-create + post/void batch with balance-independent
    verdicts (router preconditions: no linked/balancing, all timeouts
    zero, no limit/history accounts, unique fresh ids).  Closed-form:
    vectorized ladder + first-wins winner reduction, then scatter
    apply of adds and releases (reference:
    src/state_machine.zig:1608-1741)."""
    ev = _unpack(pk)
    A = table.shape[0]
    iota = jnp.arange(B, dtype=jnp.int64)
    active = iota < n
    bits = ev["bits"]
    flags = ev["flags"]
    is_pv = (flags & (F_POST | F_VOID)) != 0
    pend_flag = (flags & F_PENDING) != 0

    # --- static ladders (normal for creates, pv prefix for post/void).
    static_n = _static_ladder_normal(ev, meta, active)
    post = (flags & F_POST) != 0
    void = (flags & F_VOID) != 0
    pv_excl = (
        (post & void)
        | (is_pv & ((flags & F_PENDING) != 0))
        | (is_pv & ((flags & F_BAL_DR) != 0))
        | (is_pv & ((flags & F_BAL_CR) != 0))
    )
    static_pv = _first_nonzero(
        (_bit(bits, BIT_TS_NONZERO), CTR.timestamp_must_be_zero),
        ((flags & ~jnp.uint32(0x3F)) != 0, CTR.reserved_flag),
        (_bit(bits, BIT_ID_ZERO), CTR.id_must_not_be_zero),
        (_bit(bits, BIT_ID_MAX), CTR.id_must_not_be_int_max),
        (pv_excl, CTR.flags_are_mutually_exclusive),
        (~_bit(bits, BIT_PEND_NONZERO), CTR.pending_id_must_not_be_zero),
        (_bit(bits, BIT_PEND_MAX), CTR.pending_id_must_not_be_int_max),
        (_bit(bits, BIT_PEND_SELF), CTR.pending_id_must_be_different),
        (ev["timeout"] != 0, CTR.timeout_reserved_for_pending_transfer),
    )
    static_pv = jnp.where(
        active, static_pv, jnp.uint32(CTR.linked_event_failed)
    )
    code = jnp.where(is_pv, static_pv, static_n)

    # --- pv dynamic ladder.
    tp_join = pk[:, COL_TP_JOIN]
    p_flags_d = (tp_join & _MASK16).astype(jnp.uint32)
    p_code_d = ((tp_join >> jnp.uint64(16)) & _MASK16).astype(jnp.uint32)
    p_ledger_d = (tp_join >> jnp.uint64(32)).astype(jnp.uint32)
    tp_slots = pk[:, COL_TP_SLOTS]
    p_dr_slot_d = (tp_slots & _MASK32).astype(jnp.int64) - 1
    p_cr_slot_d = (tp_slots >> jnp.uint64(32)).astype(jnp.int64) - 1
    p_amt_lo_d = pk[:, COL_TP_AMT_LO]
    p_amt_hi_d = pk[:, COL_TP_AMT_HI]
    tp_ref = pk[:, COL_TP_REF]
    tgt_ev = (tp_ref & _MASK32).astype(jnp.int64) - 1
    dstat_init = (tp_ref >> jnp.uint64(32)).astype(jnp.uint32)
    p_found = _bit(bits, BIT_P_FOUND)

    pv = is_pv & (code == 0)
    tgt_c = jnp.clip(tgt_ev, 0, B - 1)
    in_batch = pv & (tgt_ev >= 0) & (tgt_ev < iota)
    tgt_created = in_batch & (code[tgt_c] == 0)
    durable = pv & p_found & ~in_batch
    found = tgt_created | durable

    def app(c, cond, v):
        return jnp.where((c == 0) & cond & is_pv, jnp.uint32(v), c)

    code = app(code, pv & ~found, CTR.pending_transfer_not_found)
    p_flags = jnp.where(in_batch, flags[tgt_c], p_flags_d)
    code = app(
        code,
        found & ((p_flags & F_PENDING) == 0),
        CTR.pending_transfer_not_pending,
    )
    # Account-id mismatches: host ships equality predicates (u128 id
    # compares are stateless byte predicates); validity gating here.
    code = app(
        code,
        found & _bit(bits, BIT_T_DR_SET) & ~_bit(bits, BIT_DR_EQ_P),
        CTR.pending_transfer_has_different_debit_account_id,
    )
    code = app(
        code,
        found & _bit(bits, BIT_T_CR_SET) & ~_bit(bits, BIT_CR_EQ_P),
        CTR.pending_transfer_has_different_credit_account_id,
    )
    p_ledger = jnp.where(in_batch, ev["ledger"][tgt_c], p_ledger_d)
    p_code_t = jnp.where(in_batch, ev["code"][tgt_c], p_code_d)
    code = app(
        code,
        found & (ev["ledger"] > 0) & (ev["ledger"] != p_ledger),
        CTR.pending_transfer_has_different_ledger,
    )
    code = app(
        code,
        found & (ev["code"] > 0) & (ev["code"] != p_code_t),
        CTR.pending_transfer_has_different_code,
    )
    p_amt_lo = jnp.where(in_batch, ev["amt_lo"][tgt_c], p_amt_lo_d)
    p_amt_hi = jnp.where(in_batch, ev["amt_hi"][tgt_c], p_amt_hi_d)
    t_amt_set = (ev["amt_lo"] != 0) | (ev["amt_hi"] != 0)
    res_amt_lo = jnp.where(t_amt_set, ev["amt_lo"], p_amt_lo)
    res_amt_hi = jnp.where(t_amt_set, ev["amt_hi"], p_amt_hi)

    def gt128(a_lo, a_hi, b_lo, b_hi):
        return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo > b_lo))

    code = app(
        code,
        found & gt128(res_amt_lo, res_amt_hi, p_amt_lo, p_amt_hi),
        CTR.exceeds_pending_transfer_amount,
    )
    code = app(
        code,
        found & void & gt128(p_amt_lo, p_amt_hi, res_amt_lo, res_amt_hi),
        CTR.pending_transfer_has_different_amount,
    )
    dstat_ev = jnp.where(durable, dstat_init, jnp.uint32(S_PENDING))
    code = app(code, durable & (dstat_ev == S_POSTED),
               CTR.pending_transfer_already_posted)
    code = app(code, durable & (dstat_ev == S_VOIDED),
               CTR.pending_transfer_already_voided)
    code = app(code, durable & (dstat_ev == S_EXPIRED),
               CTR.pending_transfer_expired)

    # --- first-wins winner per target.
    cand = pv & (code == 0)
    p_tgt = ev["p_tgt"]
    tkey = jnp.where(
        cand,
        jnp.where(in_batch, tgt_c, B + jnp.clip(p_tgt, 0, B - 1)),
        2 * B,
    )
    first_idx = jax.ops.segment_min(
        jnp.where(cand, iota, B), tkey, num_segments=2 * B + 1
    )
    winner = cand & (iota == first_idx[tkey])
    loser = cand & ~winner
    win_ev = jnp.clip(first_idx[tkey], 0, B - 1)
    code = jnp.where(
        loser,
        jnp.where(
            post[win_ev],
            jnp.uint32(CTR.pending_transfer_already_posted),
            jnp.uint32(CTR.pending_transfer_already_voided),
        ),
        code,
    )

    ok = active & (code == 0)

    # --- apply.  Unified target slots for pv rows.
    p_drs = jnp.where(in_batch, ev["dr_slot"][tgt_c], p_dr_slot_d)
    p_crs = jnp.where(in_batch, ev["cr_slot"][tgt_c], p_cr_slot_d)
    pend_ok = ok & pend_flag
    plain_ok = ok & ~pend_flag & ~is_pv
    post_win = ok & winner & post

    # Adds: pending -> dp/cp, plain -> dpo/cpo, post -> dpo/cpo at
    # target slots.  4B rows.
    add_slots = jnp.concatenate([
        ev["dr_slot"], ev["cr_slot"], p_drs, p_crs,
    ])
    add_cols = jnp.concatenate([
        jnp.where(pend_flag, 0, 1), jnp.where(pend_flag, 2, 3),
        jnp.ones(B, jnp.int32), jnp.full(B, 3, jnp.int32),
    ])
    add_amt_lo = jnp.concatenate(
        [ev["amt_lo"], ev["amt_lo"], res_amt_lo, res_amt_lo]
    )
    add_amt_hi = jnp.concatenate(
        [ev["amt_hi"], ev["amt_hi"], res_amt_hi, res_amt_hi]
    )
    add_valid = jnp.concatenate(
        [pend_ok | plain_ok, pend_ok | plain_ok, post_win, post_win]
    )
    # Releases: winners subtract the pending amount from dp/cp (cannot
    # underflow: each live pending's amount is contained by invariant).
    # They ride the SAME 4B-row one-hot as the adds — the release rows
    # are the [p_drs, p_crs] halves with their own columns and
    # validity; the [dr, cr] halves contribute zero.
    falseB = jnp.zeros(B, bool)
    win = ok & winner
    sub_cols = jnp.concatenate(
        [
            jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
            jnp.zeros(B, jnp.int32), jnp.full(B, 2, jnp.int32),
        ]
    )
    sub_amt_lo = jnp.concatenate([p_amt_lo] * 4)
    sub_amt_hi = jnp.concatenate([p_amt_hi] * 4)
    sub_valid = jnp.concatenate([falseB, falseB, win, win])
    (d_lo, d_hi, limb_ov), (s_lo, s_hi, s_limb) = _accum_cols_multi(
        add_slots,
        [
            (add_cols, add_amt_lo, add_amt_hi, add_valid),
            (sub_cols, sub_amt_lo, sub_amt_hi, sub_valid),
        ],
        A, lo_only=lo_only,
    )
    mid_table, ov = _admit_apply(table, d_lo, d_hi, limb_ov)
    old_lo = mid_table[:, 0::2]
    old_hi = mid_table[:, 1::2]
    n_lo = old_lo - s_lo
    borrow = (old_lo < s_lo).astype(jnp.uint64)
    n_hi = old_hi - s_hi - borrow
    under = (old_hi < s_hi) | ((old_hi == s_hi) & (old_lo < s_lo))
    final = jnp.stack(
        [n_lo[:, 0], n_hi[:, 0], n_lo[:, 1], n_hi[:, 1],
         n_lo[:, 2], n_hi[:, 2], n_lo[:, 3], n_hi[:, 3]],
        axis=-1,
    )
    fallback = ov | s_limb.any() | under.any()
    new_table = jnp.where(fallback, table, final)

    last_applied = jnp.where(ok, iota, -1).max()
    flags_word = jnp.where(fallback, jnp.uint64(FLAG_OVERFLOW), jnp.uint64(0))
    s = _summary(code, active, flags_word, last_applied)
    ring = jax.lax.dynamic_update_slice(ring, s[None, :], (ring_at, 0))
    return new_table, ring


# ---------------------------------------------------------------------------
# Auxiliary device ops.


def _lookup(table, slots):
    """Gather balance rows for lookup_accounts: slot < 0 -> zeros."""
    A = table.shape[0]
    rows = table[jnp.clip(slots, 0, A - 1)]
    return jnp.where(slots[:, None] >= 0, rows, jnp.uint64(0))


def _apply_deltas(table, packed):
    """Compact unique (slot, col, delta) modular adds — the exact-path
    write-behind lane (mirrors kernel_fast._flush_impl)."""
    A = table.shape[0]
    slots = packed[0].astype(jnp.int32)
    cols = packed[1].astype(jnp.int32)
    dense_lo = (
        jnp.zeros((A, 4), jnp.uint64)
        .at[slots, cols]
        .set(packed[2], mode="drop", unique_indices=True)
    )
    dense_hi = (
        jnp.zeros((A, 4), jnp.uint64)
        .at[slots, cols]
        .set(packed[3], mode="drop", unique_indices=True)
    )
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + dense_lo
    carry = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + dense_hi + carry
    return jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]],
        axis=-1,
    )


def _meta_update(meta, slots, acct_flags, acct_ledger):
    m = meta.at[slots, 0].set(acct_flags, mode="drop")
    return m.at[slots, 1].set(acct_ledger, mode="drop")


def _checksum(table):
    """Order-independent table digest: per-column modular sums plus a
    position-mixed sum (catches transposed rows)."""
    col_sums = table.sum(axis=0)
    rows = jnp.arange(table.shape[0], dtype=jnp.uint64)[:, None]
    mixed = (table * (rows * jnp.uint64(0x9E3779B97F4A7C15) + jnp.uint64(1))).sum(
        axis=0
    )
    return jnp.concatenate([col_sums, mixed])


import functools as _ft

orderfree = jax.jit(_orderfree)
orderfree_lo = jax.jit(_ft.partial(_orderfree, lo_only=True))
orderfree_tight = jax.jit(_orderfree_tight)
linked = jax.jit(_linked)
linked_small = jax.jit(_ft.partial(_linked, small=True))
two_phase = jax.jit(_two_phase)
two_phase_lo = jax.jit(_ft.partial(_two_phase, lo_only=True))


# Scanned dispatch: G same-kind batches per device LAUNCH.  The
# tunneled link charges ~10 ms of launch overhead per dispatch even
# with resident inputs (experiments/scan_resident_probe.py: solo
# 11 ms/batch vs scan-16 2.0 ms/batch; the op-level trace puts actual
# device compute at ~0.8 ms) — lax.scan amortizes that overhead over
# the chunk.  Ring rows are addressed (ring_at0 + g) % ring_rows per
# step, so chunks may wrap the ring freely.

def _scan_of(fn, G):
    def run(table, meta, ring, ring_at0, stack, ns, tsb):
        R = ring.shape[0]

        def step(carry, xs):
            table, ring = carry
            g, nn, t = xs
            table, ring = fn(
                table, meta, ring, (ring_at0 + g) % R, stack[g], nn, t
            )
            return (table, ring), None

        (table, ring), _ = jax.lax.scan(
            step, (table, ring),
            (jnp.arange(G), ns, tsb),
        )
        return table, ring

    return jax.jit(run)


_BASE_FNS = {
    "orderfree": _orderfree,
    "orderfree_lo": _ft.partial(_orderfree, lo_only=True),
    "orderfree_tight": _orderfree_tight,
    "linked": _linked,
    "linked_small": _ft.partial(_linked, small=True),
    "two_phase": _two_phase,
    "two_phase_lo": _ft.partial(_two_phase, lo_only=True),
}

# Packed-input geometry per kernel kind (host pack + prewarm shapes).
PK_SPEC = {
    "orderfree": (N_COLS, np.uint64),
    "orderfree_lo": (N_COLS, np.uint64),
    "orderfree_tight": (N_COLS_TIGHT, np.uint32),
    "linked": (N_COLS, np.uint64),
    "linked_small": (N_COLS, np.uint64),
    "two_phase": (N_COLS_TP, np.uint64),
    "two_phase_lo": (N_COLS_TP, np.uint64),
}
# Batches per scan launch, largest first (exact decomposition in the
# engine's chunk planner).  Larger tiers amortize the per-launch
# tunnel overhead (~10 ms quiet, 100x worse under contention) over
# more batches; lax.scan compile time is length-independent, so the
# only cost of a big tier is its staged input buffer.
def _scan_sizes() -> tuple[int, ...]:
    from tigerbeetle_tpu import envcheck

    raw = envcheck.env_str("TB_DEV_SCAN_SIZES", "16,4")
    try:
        sizes = {int(x) for x in raw.split(",") if x.strip()}
    except ValueError:
        sizes = set()
    sizes = {g for g in sizes if g > 0}
    # Greedy exact decomposition needs descending tiers; an empty or
    # invalid override falls back to the default rather than hanging
    # the chunk planner (G=0) or crashing import (trailing comma).
    return tuple(sorted(sizes, reverse=True)) if sizes else (16, 4)


SCAN_SIZES = _scan_sizes()
# kind -> {G: jitted scan}; compiled lazily per (kind, G) actually used.
scan_kernels = {
    kind: {G: _scan_of(fn, G) for G in SCAN_SIZES}
    for kind, fn in _BASE_FNS.items()
}


# Window-buffer scans: the G-batch chunk reads its inputs from a
# window-sized device buffer at a traced row offset, so the engine
# uploads ONE (W, B, C) buffer (+ one ns and one tsb array) per input
# spec per window instead of one stack per chunk — after the first
# kernel runs, every h2d on this tunnel pays a large FIXED cost, so
# transfer COUNT is what matters (measured, r5).

def _scan_win_of(fn, G):
    def run(table, meta, ring, ring_at0, big, off, ns_all, tsb_all):
        R = ring.shape[0]

        def step(carry, g):
            table, ring = carry
            pk = jax.lax.dynamic_slice(
                big, (off + g, 0, 0), (1,) + big.shape[1:]
            )[0]
            nn = jax.lax.dynamic_slice(ns_all, (off + g,), (1,))[0]
            tb = jax.lax.dynamic_slice(tsb_all, (off + g,), (1,))[0]
            table, ring = fn(
                table, meta, ring, (ring_at0 + g) % R, pk, nn, tb
            )
            return (table, ring), None

        (table, ring), _ = jax.lax.scan(
            step, (table, ring), jnp.arange(G)
        )
        return table, ring

    return jax.jit(run)


scan_win_kernels = {
    kind: {G: _scan_win_of(fn, G) for G in SCAN_SIZES}
    for kind, fn in _BASE_FNS.items()
}


def _staged(fn, ncols):
    """Staged variant: the batch is a slice of a device-resident
    superbatch (one h2d covers many batches — transfers issued while
    the stream is busy cost ~25 ms each on this link, so they are
    amortized across a stage; see experiments/staged_probe.py)."""

    def run(table, meta, ring, ring_at, super_pk, g, n, ts_base):
        pk = jax.lax.dynamic_slice(super_pk, (g * B, 0), (B, ncols))
        return fn(table, meta, ring, ring_at, pk, n, ts_base)

    return jax.jit(run)


orderfree_staged = _staged(_orderfree, N_COLS)
orderfree_lo_staged = _staged(_ft.partial(_orderfree, lo_only=True), N_COLS)
linked_staged = _staged(_linked, N_COLS)
two_phase_staged = _staged(_two_phase, N_COLS_TP)
two_phase_lo_staged = _staged(_ft.partial(_two_phase, lo_only=True), N_COLS_TP)
lookup = jax.jit(_lookup)
apply_deltas = jax.jit(_apply_deltas)
meta_update = jax.jit(_meta_update)
checksum = jax.jit(_checksum)


# ---------------------------------------------------------------------------
# Host-side packing (wire decoding + stateless predicates + joins).


def _predicate_bits(dtype, n, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
                    pend_lo, pend_hi, ts_nonzero):
    """The stateless wire predicates every packed format ships (one
    implementation — pack_base and pack_tight must never diverge)."""
    U64M = np.uint64(0xFFFFFFFFFFFFFFFF)
    bits = np.zeros(n, dtype)

    def setbit(mask, cond):
        np.bitwise_or(bits, np.where(cond, dtype(mask), dtype(0)), out=bits)

    setbit(BIT_TS_NONZERO, ts_nonzero)
    setbit(BIT_ID_ZERO, (id_lo == 0) & (id_hi == 0))
    setbit(BIT_ID_MAX, (id_lo == U64M) & (id_hi == U64M))
    setbit(BIT_DR_ZERO, (dr_lo == 0) & (dr_hi == 0))
    setbit(BIT_DR_MAX, (dr_lo == U64M) & (dr_hi == U64M))
    setbit(BIT_CR_ZERO, (cr_lo == 0) & (cr_hi == 0))
    setbit(BIT_CR_MAX, (cr_lo == U64M) & (cr_hi == U64M))
    setbit(BIT_SAME_ACCT, (dr_lo == cr_lo) & (dr_hi == cr_hi))
    setbit(BIT_PEND_NONZERO, (pend_lo != 0) | (pend_hi != 0))
    setbit(BIT_PEND_MAX, (pend_lo == U64M) & (pend_hi == U64M))
    setbit(BIT_PEND_SELF, (pend_lo == id_lo) & (pend_hi == id_hi))
    return bits


def pack_base(
    n, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi, pend_lo, pend_hi,
    amount_lo, amount_hi, flags, ledger, code, timeout, ts_nonzero,
    dr_slot, cr_slot, e_found, p_found=None, p_tgt=None,
    n_cols: int = N_COLS,
):
    """Build the packed (B, n_cols) u64 input matrix on the host.

    Everything here is wire decoding, stateless byte predicates, and
    join results — no result-code decisions (those live on device)."""
    pk = np.zeros((B, n_cols), np.uint64)
    bits = _predicate_bits(
        np.uint64, n, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
        pend_lo, pend_hi, ts_nonzero,
    )
    if e_found is not None:
        np.bitwise_or(
            bits,
            np.where(e_found, np.uint64(BIT_E_FOUND), np.uint64(0)),
            out=bits,
        )
    if p_found is not None:
        np.bitwise_or(
            bits,
            np.where(p_found, np.uint64(BIT_P_FOUND), np.uint64(0)),
            out=bits,
        )
    pk[:n, COL_BITS] = bits
    pk[:n, COL_SLOTS] = (
        (dr_slot.astype(np.int64) + 1).astype(np.uint64)
        | ((cr_slot.astype(np.int64) + 1).astype(np.uint64) << np.uint64(32))
    )
    pk[:n, COL_AMT_LO] = amount_lo
    pk[:n, COL_AMT_HI] = amount_hi
    pk[:n, COL_MISC] = (
        flags.astype(np.uint64)
        | (code.astype(np.uint64) << np.uint64(16))
        | (ledger.astype(np.uint64) << np.uint64(32))
    )
    tcol = timeout.astype(np.uint64)
    if p_tgt is not None:
        tcol = tcol | (
            (p_tgt.astype(np.int64) + 1).astype(np.uint64) << np.uint64(32)
        )
    pk[:n, COL_TIMEOUT] = tcol
    return pk


def pack_tight(
    n, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi, pend_lo, pend_hi,
    amount_lo, flags, ledger, code, ts_nonzero, dr_slot, cr_slot,
):
    """Tight (B, 5) u32 order-free input (see _orderfree_tight).

    Caller-guaranteed facts: amount_hi == 0, amount_lo < 2^32,
    timeout == 0 for every event."""
    pk = np.zeros((B, N_COLS_TIGHT), np.uint32)
    bits = _predicate_bits(
        np.uint32, n, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
        pend_lo, pend_hi, ts_nonzero,
    )
    np.bitwise_or(
        bits, np.where(code != 0, np.uint32(TIGHT_CODE_BIT), np.uint32(0)),
        out=bits,
    )
    np.bitwise_or(
        bits,
        np.where(
            (flags & ~np.uint32(0x3F)) != 0,
            np.uint32(TIGHT_RESERVED_BIT), np.uint32(0),
        ),
        out=bits,
    )
    np.bitwise_or(
        bits,
        (flags.astype(np.uint32) & np.uint32(0x3F))
        << np.uint32(TIGHT_FLAGS_SHIFT),
        out=bits,
    )
    pk[:n, 0] = bits
    pk[:n, 1] = (dr_slot.astype(np.int64) + 1).astype(np.uint32)
    pk[:n, 2] = (cr_slot.astype(np.int64) + 1).astype(np.uint32)
    pk[:n, 3] = amount_lo.astype(np.uint32)
    pk[:n, 4] = ledger
    return pk


def pack_two_phase_ext(
    pk, n, bits_extra_mask,
    p_flags, p_code, p_ledger, p_dr_slot, p_cr_slot,
    p_amt_lo, p_amt_hi, tgt_ev, dstat_init_ev,
):
    """Fill the two-phase join columns (durable target fields) and OR
    extra predicate bits into COL_BITS."""
    pk[:n, COL_BITS] |= bits_extra_mask
    pk[:n, COL_TP_JOIN] = (
        p_flags.astype(np.uint64)
        | (p_code.astype(np.uint64) << np.uint64(16))
        | (p_ledger.astype(np.uint64) << np.uint64(32))
    )
    pk[:n, COL_TP_SLOTS] = (
        (p_dr_slot.astype(np.int64) + 1).astype(np.uint64)
        | ((p_cr_slot.astype(np.int64) + 1).astype(np.uint64) << np.uint64(32))
    )
    pk[:n, COL_TP_AMT_LO] = p_amt_lo
    pk[:n, COL_TP_AMT_HI] = p_amt_hi
    pk[:n, COL_TP_REF] = (
        (tgt_ev.astype(np.int64) + 1).astype(np.uint64)
        | (dstat_init_ev.astype(np.uint64) << np.uint64(32))
    )
    return pk


def unpack_summary(row: np.ndarray) -> dict:
    """Decode one (SUMMARY_WORDS,) u64 summary row."""
    n_fail = int(row[0])
    flags = int(row[1])
    entries = row[4 : 4 + min(n_fail, FAIL_CAP)]
    idx = (entries >> np.uint64(32)).astype(np.int64)
    codes = (entries & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return {
        "n_fail": n_fail,
        "overflow": bool(flags & FLAG_OVERFLOW),
        "cap_exceeded": bool(flags & FLAG_CAP) or n_fail > FAIL_CAP,
        "precond": bool(flags & FLAG_PRECOND),
        "iters": flags >> ITERS_SHIFT,
        "last_applied": int(row[2]) - 1,
        "n_active": int(row[3]),
        "fail_idx": idx,
        "fail_codes": codes,
    }
