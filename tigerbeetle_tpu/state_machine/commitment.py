"""Incremental state commitments over the account table.

Every integrity compare in the system used to re-digest the whole
table: the healthy-mode scrub, the demote/re-promote checksum
handshake, and `verify_device_mirror` each paid a full-table pass (and
on the tunneled link, a fixed ~105 ms d2h crossing) per check, which is
why scrub cadence was throttled and why checkpoints carried no state
root.  AlDBaran's lesson (arXiv:2508.10493) is that a state commitment
can be maintained *incrementally*, decoupled from execution: hash each
row once, fold the row hashes with an order-independent operator, and
update the fold from just the rows a step touched.

Construction
------------
- **Per-row hash** `h(i, row)`: the row index and the 10 row columns
  (8 u64 balance limbs in device layout ``dp_lo, dp_hi, dpo_lo,
  dpo_hi, cp_lo, cp_hi, cpo_lo, cpo_hi`` + 2 u32 account-meta columns
  ``flags, ledger``) are mixed lane-wise through a splitmix64-style
  finalizer, xor-combined across columns, and finalized into two u64
  lanes.  An ALL-ZERO row hashes to exactly (0, 0), so the digest is
  **capacity-independent**: zero padding, table growth, and
  mirror-vs-device capacity mismatches cannot move the root, and a
  row-sharded table's shard-local partial digests fold to the same
  value as the dense table's.
- **Fold**: per-lane sum mod 2^64 over all row hashes — order
  independent, and invertible per row: ``fold' = fold - h(old) +
  h(new)``.  The 16-byte fold is THE state root.
- **Incremental update**: keep the per-row hashes; to absorb a step,
  re-hash just the touched rows, subtract the stored hashes, add the
  new ones.  O(touched), not O(table).

The same formula runs bit-identically on numpy (the host twin on
BalanceMirror, the CPU oracle, recovery recompute) and on device under
jit (the engine's on-device accumulator, GSPMD-sharded on row-sharded
engines — XLA inserts the ICI all-reduce for the fold).  A pinned
golden digest in tests/test_commitment.py fails tier-1 on any silent
drift of the formula.

What the root proves (and does not)
-----------------------------------
The root commits to the *balances + account-meta table contents by
slot*.  Two states with equal roots are equal tables with overwhelming
probability (128-bit fold of 64-bit mixed lanes; adversarial collision
resistance is NOT claimed — this is an integrity/divergence check, not
a cryptographic accumulator).  It does not cover transfer history,
pending-transfer state, or session tables — those are covered by the
checkpoint blob checksum; the root is the cheap always-on commitment
the table-shaped state lacked.  Full Merkle paths (per-row inclusion
proofs) are a deliberate scope cut: nothing in the system needs
point proofs yet, and a flat fold updates ~30x cheaper.
"""

from __future__ import annotations

import numpy as np

# Hash-stream constants.  GAMMA binds the row index, K_COL the column
# index; M1/M2 are the splitmix64 finalizer multipliers; C_LO/C_HI
# split the combined column accumulator into two independent lanes.
# PINNED by the golden-digest test: changing any of these is a state
# -root format change and must break tier-1 loudly.
GAMMA = 0x9E3779B97F4A7C15
K_COL = 0xC2B2AE3D27D4EB4F
M1 = 0xFF51AFD7ED558CCD
M2 = 0xC4CEB9FE1A85EC53
C_LO = 0x8BADF00D5CA1AB1E
C_HI = 0xFACEFEED0DDBA11D

ROOT_BYTES = 16  # (2,) u64 little-endian

_MASK64 = (1 << 64) - 1


def _xp_of(array):
    if isinstance(array, np.ndarray):
        return np
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)  # u64 lanes throughout
    return jnp


def _mix64(x, xp):
    """splitmix64-style finalizer, lane-wise on u64 arrays.  Works on
    numpy and jnp alike (both wrap u64 multiplies mod 2^64)."""
    x = x ^ (x >> xp.uint64(33))
    x = x * xp.uint64(M1)
    x = x ^ (x >> xp.uint64(29))
    x = x * xp.uint64(M2)
    return x ^ (x >> xp.uint64(32))


def rows_hash(rows, bal8, meta2, xp=None):
    """Per-row hash lanes: (k,) lo and (k,) hi u64 for global row
    indices `rows` with (k, 8) u64 balances and (k, 2) meta columns.
    All-zero rows hash to (0, 0) — see the module docstring."""
    if xp is None:
        xp = _xp_of(bal8)
    r = rows.astype(xp.uint64)
    rstream = r * xp.uint64(GAMMA)
    acc = xp.zeros(r.shape, xp.uint64)
    nonzero = xp.zeros(r.shape, bool)
    cols = [bal8[:, j] for j in range(8)] + [meta2[:, j] for j in range(2)]
    for j, col in enumerate(cols):
        v = col.astype(xp.uint64)
        nonzero = nonzero | (v != 0)
        acc = acc ^ _mix64(
            v ^ rstream ^ xp.uint64(((j + 1) * K_COL) & _MASK64), xp
        )
    lo = _mix64(acc ^ rstream ^ xp.uint64(C_LO), xp)
    hi = _mix64(acc ^ rstream ^ xp.uint64(C_HI), xp)
    zero = xp.uint64(0)
    return xp.where(nonzero, lo, zero), xp.where(nonzero, hi, zero)


def fold(lo, hi, xp=None):
    """(2,) u64 order-independent fold (per-lane sum mod 2^64)."""
    if xp is None:
        xp = _xp_of(lo)
    if xp is np:
        return np.array(
            [np.add.reduce(lo, dtype=np.uint64),
             np.add.reduce(hi, dtype=np.uint64)],
            np.uint64,
        )
    return xp.stack([lo.sum(dtype=xp.uint64), hi.sum(dtype=xp.uint64)])


def table_digest(bal8, meta2, start_row: int = 0):
    """From-scratch digest of a (n, 8) balance table + (n, 2) meta
    table whose first row has global index `start_row`.  The oracle
    every incremental path must match."""
    xp = _xp_of(bal8)
    n = bal8.shape[0]
    rows = xp.arange(n, dtype=xp.uint64) + xp.uint64(start_row)
    lo, hi = rows_hash(rows, bal8, meta2, xp)
    return fold(lo, hi, xp)


def root_bytes(digest) -> bytes:
    """16-byte little-endian state root of a (2,) u64 digest."""
    return np.asarray(digest, dtype="<u8").tobytes()


def root_int(digest) -> int:
    return int.from_bytes(root_bytes(digest), "little")


def digest_of_root(root: bytes) -> np.ndarray:
    assert len(root) == ROOT_BYTES, len(root)
    return np.frombuffer(root, "<u8").copy()


# ----------------------------------------------------------------------
# Cluster commitment: fold per-shard roots into one deterministic
# 16-byte value.  The shard INDEX is mixed into each shard's
# contribution (shards own disjoint account ranges, so two shards
# swapping state must move the cluster root), then lanes sum — the
# same fold algebra as rows, one level up.


def fold_cluster(roots: list[bytes]) -> bytes:
    acc = np.zeros(2, np.uint64)
    lane_c = np.array([C_LO, C_HI], np.uint64)
    for index, root in enumerate(roots):
        d = digest_of_root(root)
        stream = np.uint64((((index + 1) * GAMMA) & _MASK64))
        acc = acc + _mix64(d ^ stream ^ lane_c, np)
    return root_bytes(acc)


# ----------------------------------------------------------------------
# Wire codec for the read-only `state_root` query (rides the
# sessionless stats-op shape; obs/scrape.py is the client).  Fixed
# little-endian layout, safe to decode from untrusted bytes:
#   shard reply:   root[16] + commit_min u64          (24 bytes)
#   cluster reply: root[16] + n_shards u64            (24 bytes)

_ROOT_BODY = np.dtype([("root", "V16"), ("aux", "<u8")])


def root_body(root: bytes, aux: int) -> bytes:
    row = np.zeros(1, _ROOT_BODY)[0]
    row["root"] = root
    row["aux"] = aux
    return row.tobytes()


def parse_root_body(body: bytes) -> tuple[bytes, int]:
    if len(body) != _ROOT_BODY.itemsize:
        raise ValueError(f"state_root body: {len(body)} bytes")
    row = np.frombuffer(body, _ROOT_BODY)[0]
    return bytes(row["root"]), int(row["aux"])


# At-op query (round 19): a `state_root` REQUEST may carry an 8-byte
# little-endian op — "give me your root as of op N" — answered from
# the replica's root ring (vsr/replica.py enable_root_ring).  An empty
# request body keeps the legacy meaning (current root + commit_min);
# a server without the requested op in its ring answers current too,
# and the caller detects the op mismatch (unverifiable-at-N, not an
# error).  The follower attestation loop is the primary client.


def root_query_body(op: int) -> bytes:
    return int(op).to_bytes(8, "little")


def parse_root_query(body: bytes) -> int | None:
    """Requested op of a state_root query body, or None for the
    legacy empty (current-root) shape / any unknown shape."""
    if len(body) != 8:
        return None
    return int.from_bytes(body, "little")


# ----------------------------------------------------------------------
# Host twin: the incrementally-maintained digest of the BalanceMirror
# + account-meta columns.  Bit-identical to the device accumulator
# (same formula, numpy lanes), so healthy/degraded/recovery modes all
# agree on the root.


class HostCommitment:
    """Per-row hashes + running fold over the host mirror.

    `meta_fn(slots) -> (k, 2) uint32` supplies the account-meta
    columns (flags, ledger) — owned by the state machine's attribute
    store, which outlives mirror re-pointing (native rebuilds) and
    restores.  Balance bytes are always read live from the mirror the
    caller passes, so array swaps (native fast path re-pointing
    mirror.lo/hi) need no re-registration.
    """

    def __init__(self, capacity: int, meta_fn) -> None:
        self.row_lo = np.zeros(capacity, np.uint64)
        self.row_hi = np.zeros(capacity, np.uint64)
        self.digest = np.zeros(2, np.uint64)
        self.meta_fn = meta_fn

    def _ensure(self, capacity: int) -> None:
        if capacity <= len(self.row_lo):
            return
        from tigerbeetle_tpu.state_machine.hot_tier import grow_zero_host

        self.row_lo = grow_zero_host(self.row_lo, capacity)
        self.row_hi = grow_zero_host(self.row_hi, capacity)

    def refresh(self, slots, mirror) -> None:
        """Re-hash `slots` (any order, duplicates fine) from current
        mirror + meta content and roll the fold forward.  Idempotent:
        refreshing an untouched row is a no-op."""
        slots = np.unique(np.asarray(slots, np.int64))
        slots = slots[slots >= 0]
        if len(slots) == 0:
            return
        self._ensure(len(mirror.lo))
        slots = slots[slots < len(self.row_lo)]
        if len(slots) == 0:
            return
        bal8 = np.empty((len(slots), 8), np.uint64)
        bal8[:, 0::2] = mirror.lo[slots]
        bal8[:, 1::2] = mirror.hi[slots]
        lo, hi = rows_hash(slots, bal8, self.meta_fn(slots), np)
        delta = np.array(
            [
                np.add.reduce(lo - self.row_lo[slots], dtype=np.uint64),
                np.add.reduce(hi - self.row_hi[slots], dtype=np.uint64),
            ],
            np.uint64,
        )
        self.digest = self.digest + delta
        self.row_lo[slots] = lo
        self.row_hi[slots] = hi

    def rebuild(self, mirror) -> None:
        """From-scratch recompute (restore, divergence repair)."""
        cap = len(mirror.lo)
        self.row_lo = np.zeros(cap, np.uint64)
        self.row_hi = np.zeros(cap, np.uint64)
        self.digest = np.zeros(2, np.uint64)
        self.refresh(np.arange(cap, dtype=np.int64), mirror)

    def partial(self, rows) -> np.ndarray:
        """(2,) u64 fold of the STORED hashes of `rows` (any order,
        duplicates collapsed) — the host-side view of a tiered device
        engine's hot partial.  Because the fold is an order-independent
        per-lane sum, ``digest == partial(hot) + partial(cold)`` for
        any split of the table, and the cold partial is just
        ``digest - partial(hot)`` — no cold-row hashing needed."""
        rows = np.unique(np.asarray(rows, np.int64))
        rows = rows[(rows >= 0) & (rows < len(self.row_lo))]
        return np.array(
            [
                np.add.reduce(self.row_lo[rows], dtype=np.uint64),
                np.add.reduce(self.row_hi[rows], dtype=np.uint64),
            ],
            np.uint64,
        )

    def root_bytes(self) -> bytes:
        return root_bytes(self.digest)


# ----------------------------------------------------------------------
# Device-side kernels (lazy: importing this module must not initialize
# a JAX backend — mirror.py's host paths import it too).  The jitted
# callables are built once per process and dispatched by the engine
# through its DeviceLink seam; on a row-sharded engine the (capacity,)
# inputs carry NamedSharding and GSPMD partitions the hash lane-wise,
# all-reducing the fold over ICI.


_DEVICE_FNS: dict | None = None


def device_fns() -> dict:
    global _DEVICE_FNS
    if _DEVICE_FNS is not None:
        return _DEVICE_FNS
    import jax

    jax.config.update("jax_enable_x64", True)  # u64 lanes throughout
    import jax.numpy as jnp

    # Every kernel takes an explicit `rows` binding — the LOGICAL row
    # id hashed into each table row.  A dense (untiered) engine passes
    # arange / the slot array itself; a TIERED engine's hot-shaped
    # tables pass logical_of / the logical rows behind its hot slots,
    # so the device digest is the HOT PARTIAL of the logical table's
    # fold and fold(hot_partial, cold_partial) == root.  Free hot slots
    # are all-zero rows, which hash to (0, 0) regardless of binding.

    def _rebuild(balances, meta, rows):
        lo, hi = rows_hash(rows, balances, meta, jnp)
        return jnp.stack([lo, hi], axis=-1), fold(lo, hi, jnp)

    def _update(balances, meta, row_hash, digest, slots, rows):
        """Incremental absorb of (deduplicated) touched `slots`
        (indices into the device tables) hashed under logical ids
        `rows`; -1 slot entries are padding and contribute nothing."""
        A = balances.shape[0]
        valid = slots >= 0
        idx = jnp.where(valid, slots, 0)
        r = jnp.where(valid, rows, 0)
        lo, hi = rows_hash(r, balances[idx], meta[idx], jnp)
        zero = jnp.uint64(0)
        lo = jnp.where(valid, lo, zero)
        hi = jnp.where(valid, hi, zero)
        old = jnp.where(valid[:, None], row_hash[idx], zero)
        new = jnp.stack([lo, hi], axis=-1)
        digest = digest + (new - old).sum(axis=0, dtype=jnp.uint64)
        scatter = jnp.where(valid, idx, A)
        row_hash = row_hash.at[scatter].set(new, mode="drop")
        return row_hash, digest

    def _admit(row_hash, digest, slots, new_lo, new_hi):
        """Tiered admission/eviction in one step: replace the hashes
        at hot `slots` (the victims' — or zero for free slots) with
        the admitted rows' host-twin hashes `new_lo`/`new_hi`, rolling
        the hot-partial digest by (new - old).  Exact because admitted
        device content is uploaded from the very mirror rows the twin
        hashed; -1 slots are padding."""
        A = row_hash.shape[0]
        valid = slots >= 0
        idx = jnp.where(valid, slots, 0)
        zero = jnp.uint64(0)
        new = jnp.stack(
            [jnp.where(valid, new_lo, zero), jnp.where(valid, new_hi, zero)],
            axis=-1,
        )
        old = jnp.where(valid[:, None], row_hash[idx], zero)
        digest = digest + (new - old).sum(axis=0, dtype=jnp.uint64)
        scatter = jnp.where(valid, idx, A)
        row_hash = row_hash.at[scatter].set(new, mode="drop")
        return row_hash, digest

    def _probe(balances, meta, digest, rows):
        """(2, 2): [maintained digest, from-scratch digest] — ONE
        dispatch + one 32-byte fetch covers both the drift check and
        the memory-corruption check."""
        lo, hi = rows_hash(rows, balances, meta, jnp)
        return jnp.stack([digest, fold(lo, hi, jnp)])

    _DEVICE_FNS = {
        "rebuild": jax.jit(_rebuild),
        "update": jax.jit(_update),
        "admit": jax.jit(_admit),
        "probe": jax.jit(_probe),
    }
    return _DEVICE_FNS


def pad_slots(slots: np.ndarray, minimum: int = 256) -> np.ndarray:
    """Pad a deduplicated slot array to a power-of-two bucket (-1
    fill) so the update kernel compiles O(log max-batch) shapes, not
    one per touched-set size."""
    n = max(int(len(slots)), 1)
    bucket = minimum
    while bucket < n:
        bucket <<= 1
    out = np.full(bucket, -1, np.int64)
    out[: len(slots)] = slots
    return out
