"""JAX create_transfers kernel: sequential-semantics batch apply.

Re-expresses the reference's per-event commit loop (reference:
src/state_machine.zig:1220-1306 execute, :1462-1741 create_transfer +
post/void) as a `lax.scan` over the event batch against an HBM-resident
account-balance table.

Division of labor (see tpu.py for the host side):

- The HOST resolves everything *static within a batch*: account-id ->
  slot lookups (accounts are only created by separate create_accounts
  operations, so existence/ledger/flags are immutable here), the
  static validation ladder (codes 3-24), durable-transfer side tables
  for `id`/`pending_id`, and compact *id groups*: every distinct
  transfer-id value in the batch gets an index in [0, B) so the kernel
  can track in-batch creations without u128 hashing.
- The KERNEL owns everything *order-dependent*: balance math (u128 as
  2xuint64 limbs), balancing clamps, overflow/limit ladders, in-batch
  exists checks, two-phase status transitions, and linked-chain
  rollback via an undo log — the reference's scoped-rollback semantics
  (reference: src/state_machine.zig:1190-1218,1269-1300).

The scan carry keeps the balance table in place (donated buffer);
per-event state (results, created-transfer records, statuses, undo,
group->creator directory) are (B,)-shaped arrays so chain rollback is
a bounded reverse replay.
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
from jax import lax

from tigerbeetle_tpu.ops import u128 as w  # "wide" math

# TransferFlags bits (reference: src/tigerbeetle.zig:127-140).
F_LINKED = 1 << 0
F_PENDING = 1 << 1
F_POST = 1 << 2
F_VOID = 1 << 3
F_BAL_DR = 1 << 4
F_BAL_CR = 1 << 5

# AccountFlags bits (reference: src/tigerbeetle.zig:42-63).
AF_DR_LIMIT = 1 << 1
AF_CR_LIMIT = 1 << 2

# Pending statuses (reference: src/tigerbeetle.zig:113-125).
S_NONE, S_PENDING, S_POSTED, S_VOIDED, S_EXPIRED = 0, 1, 2, 3, 4

NS_PER_S = jnp.uint64(1_000_000_000)
U64_MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)

# Result codes used kernel-side (reference: src/tigerbeetle.zig:185-265).
R_OK = 0
R_LINKED_EVENT_FAILED = 1
R_LINKED_EVENT_CHAIN_OPEN = 2
R_TIMESTAMP_MUST_BE_ZERO = 3
R_PENDING_NOT_FOUND = 25
R_PENDING_NOT_PENDING = 26
R_PENDING_DIFF_DR = 27
R_PENDING_DIFF_CR = 28
R_PENDING_DIFF_LEDGER = 29
R_PENDING_DIFF_CODE = 30
R_EXCEEDS_PENDING_AMOUNT = 31
R_PENDING_DIFF_AMOUNT = 32
R_ALREADY_POSTED = 33
R_ALREADY_VOIDED = 34
R_PENDING_EXPIRED = 35
R_EXISTS_DIFF_FLAGS = 36
R_EXISTS_DIFF_DR = 37
R_EXISTS_DIFF_CR = 38
R_EXISTS_DIFF_AMOUNT = 39
R_EXISTS_DIFF_PENDING_ID = 40
R_EXISTS_DIFF_UD128 = 41
R_EXISTS_DIFF_UD64 = 42
R_EXISTS_DIFF_UD32 = 43
R_EXISTS_DIFF_TIMEOUT = 44
R_EXISTS_DIFF_CODE = 45
R_EXISTS = 46
R_OVERFLOWS_DP = 47
R_OVERFLOWS_CP = 48
R_OVERFLOWS_DPO = 49
R_OVERFLOWS_CPO = 50
R_OVERFLOWS_DEBITS = 51
R_OVERFLOWS_CREDITS = 52
R_OVERFLOWS_TIMEOUT = 53
R_EXCEEDS_CREDITS = 54
R_EXCEEDS_DEBITS = 55

# Sentinel for "run the exists ladder here" in precedence cascades;
# code 1 (linked_event_failed) can never be produced by those ladders.
_EXISTS_SENTINEL = 1

# Balance-row column layout: 4 u128s as (lo, hi) limb pairs.
DP_LO, DP_HI, DPO_LO, DPO_HI, CP_LO, CP_HI, CPO_LO, CPO_HI = range(8)

# Fields of the in-batch "created transfers" buffer (all (B,) arrays).
CREATED_FIELDS = (
    "flags",       # uint32
    "dr_slot",     # int32
    "cr_slot",     # int32
    "amount_lo", "amount_hi",
    "pending_lo", "pending_hi",   # pending_id
    "ud128_lo", "ud128_hi",
    "ud64",
    "ud32",        # uint32
    "timeout",     # uint64 (widened)
    "ledger",      # uint32
    "code",        # uint32
)

_CREATED_DTYPES = {
    "dr_slot": jnp.int32,
    "cr_slot": jnp.int32,
    "flags": jnp.uint32,
    "ud32": jnp.uint32,
    "ledger": jnp.uint32,
    "code": jnp.uint32,
}

# Batch shape buckets (jit compile cache keys): the host pads every
# exact-path batch up to the next bucket.  Shared by the scan path
# (tpu.py routing) and the wave executor's prewarm (waves.py).
BATCH_BUCKETS = (32, 256, 2048, 8192)

# Per-event input arrays the host must provide (all shape (B,)).
EVENT_FIELDS = (
    ("i", jnp.int32),
    ("flags", jnp.uint32),
    ("ts_nonzero", jnp.bool_),
    ("static_result", jnp.uint32),
    ("amount_lo", jnp.uint64), ("amount_hi", jnp.uint64),
    ("pending_lo", jnp.uint64), ("pending_hi", jnp.uint64),
    ("ud128_lo", jnp.uint64), ("ud128_hi", jnp.uint64),
    ("ud64", jnp.uint64),
    ("ud32", jnp.uint32),
    ("timeout", jnp.uint64),
    ("ledger", jnp.uint32),
    ("code", jnp.uint32),
    ("dr_slot", jnp.int32), ("cr_slot", jnp.int32),
    ("dr_flags", jnp.uint32), ("cr_flags", jnp.uint32),
    ("dr_id_zero", jnp.bool_), ("cr_id_zero", jnp.bool_),
    # Compact id-value groups: id_group in [0, B); p_group = group of
    # this event's pending_id value if that value is also some event's
    # id, else -1.
    ("id_group", jnp.int32),
    ("p_group", jnp.int32),
    # Durable transfer with the same id (exists-check), zeros if none:
    ("e_found", jnp.bool_),
    ("e_flags", jnp.uint32),
    ("e_dr_slot", jnp.int32), ("e_cr_slot", jnp.int32),
    ("e_amount_lo", jnp.uint64), ("e_amount_hi", jnp.uint64),
    ("e_pending_lo", jnp.uint64), ("e_pending_hi", jnp.uint64),
    ("e_ud128_lo", jnp.uint64), ("e_ud128_hi", jnp.uint64),
    ("e_ud64", jnp.uint64),
    ("e_ud32", jnp.uint32),
    ("e_timeout", jnp.uint64),
    ("e_code", jnp.uint32),
    # Durable transfer matching pending_id (post/void), zeros if none:
    ("p_found", jnp.bool_),
    ("p_flags", jnp.uint32),
    ("p_dr_slot", jnp.int32), ("p_cr_slot", jnp.int32),
    ("p_amount_lo", jnp.uint64), ("p_amount_hi", jnp.uint64),
    ("p_ud128_lo", jnp.uint64), ("p_ud128_hi", jnp.uint64),
    ("p_ud64", jnp.uint64),
    ("p_ud32", jnp.uint32),
    ("p_timeout", jnp.uint64),
    ("p_ledger", jnp.uint32),
    ("p_code", jnp.uint32),
    ("p_timestamp", jnp.uint64),
    ("p_tgt", jnp.int32),  # index into the durable-status array
)

_E_FIELD_MAP = {
    "flags": "e_flags", "dr_slot": "e_dr_slot", "cr_slot": "e_cr_slot",
    "amount_lo": "e_amount_lo", "amount_hi": "e_amount_hi",
    "pending_lo": "e_pending_lo", "pending_hi": "e_pending_hi",
    "ud128_lo": "e_ud128_lo", "ud128_hi": "e_ud128_hi",
    "ud64": "e_ud64", "ud32": "e_ud32", "timeout": "e_timeout",
    "code": "e_code",
}

_P_FIELD_MAP = {
    "flags": "p_flags", "dr_slot": "p_dr_slot", "cr_slot": "p_cr_slot",
    "amount_lo": "p_amount_lo", "amount_hi": "p_amount_hi",
    "ud128_lo": "p_ud128_lo", "ud128_hi": "p_ud128_hi",
    "ud64": "p_ud64", "ud32": "p_ud32", "timeout": "p_timeout",
    "ledger": "p_ledger", "code": "p_code",
}


def _first_nonzero(*pairs):
    """Precedence cascade: the first true (cond, code) pair wins."""
    result = jnp.uint32(0)
    for cond, code in pairs:
        result = jnp.where((result == 0) & cond, jnp.uint32(code), result)
    return result


def _gather_created(created, idx, B):
    j = jnp.clip(idx, 0, B - 1)
    return {f: created[f][j] for f in CREATED_FIELDS}


def _merge(cond, inbatch_rec, ev, field_map):
    out = {}
    for field, ev_name in field_map.items():
        out[field] = jnp.where(
            cond, ev[ev_name].astype(inbatch_rec[field].dtype), inbatch_rec[field]
        )
    return out


def _exists_ladder_normal(ev, e):
    """reference: src/state_machine.zig:1587-1606 (raw t.amount)."""
    return _first_nonzero(
        (ev["flags"] != e["flags"], R_EXISTS_DIFF_FLAGS),
        (ev["dr_slot"] != e["dr_slot"], R_EXISTS_DIFF_DR),
        (ev["cr_slot"] != e["cr_slot"], R_EXISTS_DIFF_CR),
        (
            (ev["amount_lo"] != e["amount_lo"]) | (ev["amount_hi"] != e["amount_hi"]),
            R_EXISTS_DIFF_AMOUNT,
        ),
        (
            (ev["ud128_lo"] != e["ud128_lo"]) | (ev["ud128_hi"] != e["ud128_hi"]),
            R_EXISTS_DIFF_UD128,
        ),
        (ev["ud64"] != e["ud64"], R_EXISTS_DIFF_UD64),
        (ev["ud32"] != e["ud32"], R_EXISTS_DIFF_UD32),
        (ev["timeout"] != e["timeout"], R_EXISTS_DIFF_TIMEOUT),
        (ev["code"] != e["code"], R_EXISTS_DIFF_CODE),
        (jnp.bool_(True), R_EXISTS),
    )


def _exists_ladder_post_void(ev, e, p):
    """reference: src/state_machine.zig:1743-1804 (zero-means-inherit)."""
    t_amount_zero = (ev["amount_lo"] == 0) & (ev["amount_hi"] == 0)
    amount_diff = jnp.where(
        t_amount_zero,
        (e["amount_lo"] != p["amount_lo"]) | (e["amount_hi"] != p["amount_hi"]),
        (ev["amount_lo"] != e["amount_lo"]) | (ev["amount_hi"] != e["amount_hi"]),
    )
    ud128_diff = jnp.where(
        (ev["ud128_lo"] == 0) & (ev["ud128_hi"] == 0),
        (e["ud128_lo"] != p["ud128_lo"]) | (e["ud128_hi"] != p["ud128_hi"]),
        (ev["ud128_lo"] != e["ud128_lo"]) | (ev["ud128_hi"] != e["ud128_hi"]),
    )
    ud64_diff = jnp.where(
        ev["ud64"] == 0, e["ud64"] != p["ud64"], ev["ud64"] != e["ud64"]
    )
    ud32_diff = jnp.where(
        ev["ud32"] == 0, e["ud32"] != p["ud32"], ev["ud32"] != e["ud32"]
    )
    return _first_nonzero(
        (ev["flags"] != e["flags"], R_EXISTS_DIFF_FLAGS),
        (amount_diff, R_EXISTS_DIFF_AMOUNT),
        (
            (ev["pending_lo"] != e["pending_lo"])
            | (ev["pending_hi"] != e["pending_hi"]),
            R_EXISTS_DIFF_PENDING_ID,
        ),
        (ud128_diff, R_EXISTS_DIFF_UD128),
        (ud64_diff, R_EXISTS_DIFF_UD64),
        (ud32_diff, R_EXISTS_DIFF_UD32),
        (jnp.bool_(True), R_EXISTS),
    )


@jax.jit
def _noop(x):
    return x


def make_carry(balances, dstat_init, B):
    """Initial scan carry for a B-event batch (also the segment-resume
    state the wave executor threads between wave steps and scan
    segments — see waves.py)."""
    return {
        "balances": balances,
        "results": jnp.zeros(B, jnp.uint32),
        "created_mask": jnp.zeros(B, jnp.bool_),
        "created": {
            f: jnp.zeros(B, _CREATED_DTYPES.get(f, jnp.uint64))
            for f in CREATED_FIELDS
        },
        # group index -> event that currently holds a created transfer
        # with that id value (-1 none). At most one at any time.
        "group_creator": jnp.full(B, -1, jnp.int32),
        "inb_status": jnp.zeros(B, jnp.uint32),
        "dstat": dstat_init.astype(jnp.uint32),
        # Undo log for chain rollback:
        "u_dr_slot": jnp.full(B, -1, jnp.int32),
        "u_cr_slot": jnp.full(B, -1, jnp.int32),
        "u_dr_bal": jnp.zeros((B, 8), jnp.uint64),
        "u_cr_bal": jnp.zeros((B, 8), jnp.uint64),
        "u_status_kind": jnp.zeros(B, jnp.int32),  # 0 none, 1 durable, 2 in-batch
        "u_status_idx": jnp.zeros(B, jnp.int32),
        # Post-apply balance snapshots for historical balances:
        "hist_dr": jnp.zeros((B, 8), jnp.uint64),
        "hist_cr": jnp.zeros((B, 8), jnp.uint64),
        "chain_start": jnp.int32(-1),
        "chain_broken": jnp.bool_(False),
        # Last event index that reached the apply point — including
        # chain events later rolled back: the reference sets
        # commit_timestamp before any rollback and never reverts it
        # (reference: src/state_machine.zig:1583 + scope semantics).
        "last_applied": jnp.int32(-1),
        # pulse_next_timestamp bookkeeping signals, recorded at apply
        # time and NEVER rolled back (the reference mutates
        # expire_pending_transfers.pulse_next_timestamp outside any
        # groove scope — src/state_machine.zig:1576-1580,1704-1708):
        # pulse_create[i] = expires_at of a pending-with-timeout created
        # at i; pulse_remove[i] = expires_at of the pending that event i
        # posted/voided. Zero means no signal.
        "pulse_create": jnp.zeros(B, jnp.uint64),
        "pulse_remove": jnp.zeros(B, jnp.uint64),
    }


def make_body(n, ts_base, B, A, id_group_full, arange_b):
    """The per-event scan body, parameterized by batch globals.

    Shared between the full-batch scan (`_run_impl`) and the wave
    executor's conflict-group segments (`scan_segment`): events carry
    their GLOBAL index `i`, so the body works identically over any
    contiguous sub-range of the batch.
    """

    def body(carry, ev):
        i = ev["i"]
        active = i < n
        table = carry["balances"]
        created = carry["created"]
        group_creator = carry["group_creator"]
        flags = ev["flags"]
        linked = (flags & F_LINKED) != 0
        is_pv = (flags & (F_POST | F_VOID)) != 0
        ts_i = ts_base + i.astype(jnp.uint64)

        # -- Chain bookkeeping (reference: src/state_machine.zig:1240-1248).
        open_chain = active & linked & (carry["chain_start"] < 0)
        chain_start = jnp.where(open_chain, i, carry["chain_start"])
        chain_broken = carry["chain_broken"]

        pre = _first_nonzero(
            (linked & (i == n - 1), R_LINKED_EVENT_CHAIN_OPEN),
            (chain_broken, R_LINKED_EVENT_FAILED),
            (ev["ts_nonzero"], R_TIMESTAMP_MUST_BE_ZERO),
        )
        pre = jnp.where(pre == 0, ev["static_result"], pre)

        # -- Exists resolution via the in-batch id directory.
        e_creator = group_creator[jnp.clip(ev["id_group"], 0, B - 1)]
        e_inb = e_creator >= 0
        e_dur = ev["e_found"]
        e_any = e_inb | e_dur
        e = _merge(~e_inb, _gather_created(created, e_creator, B), ev, _E_FIELD_MAP)

        # ==================== normal create_transfer ====================
        # (reference: src/state_machine.zig:1506-1547)
        dr_row = table[jnp.clip(ev["dr_slot"], 0, A - 1)]
        cr_row = table[jnp.clip(ev["cr_slot"], 0, A - 1)]
        dr_dp = (dr_row[DP_LO], dr_row[DP_HI])
        dr_dpo = (dr_row[DPO_LO], dr_row[DPO_HI])
        dr_cpo = (dr_row[CPO_LO], dr_row[CPO_HI])
        cr_dp = (cr_row[DP_LO], cr_row[DP_HI])
        cr_dpo = (cr_row[DPO_LO], cr_row[DPO_HI])
        cr_cp = (cr_row[CP_LO], cr_row[CP_HI])
        cr_cpo = (cr_row[CPO_LO], cr_row[CPO_HI])

        exists_rn = _exists_ladder_normal(ev, e)

        is_balancing = (flags & (F_BAL_DR | F_BAL_CR)) != 0
        amount = (ev["amount_lo"], ev["amount_hi"])
        # amount == 0 with balancing means maxInt(u64)
        # (reference: src/state_machine.zig:1512).
        amount = w.select(
            is_balancing & w.is_zero(amount),
            (jnp.full_like(amount[0], U64_MAX), jnp.zeros_like(amount[1])),
            amount,
        )
        dr_balance, _ = w.add(dr_dpo, dr_dp)
        bd_avail = w.sub_sat(dr_cpo, dr_balance)
        amount = w.select((flags & F_BAL_DR) != 0, w.minimum(amount, bd_avail), amount)
        bd_fail = ((flags & F_BAL_DR) != 0) & w.is_zero(amount)

        cr_balance, _ = w.add(cr_cpo, cr_cp)
        bc_avail = w.sub_sat(cr_dpo, cr_balance)
        amount_bc = w.minimum(amount, bc_avail)
        amount = w.select(
            ((flags & F_BAL_CR) != 0) & ~bd_fail, amount_bc, amount
        )
        bc_fail = ((flags & F_BAL_CR) != 0) & w.is_zero(amount) & ~bd_fail

        is_pending = (flags & F_PENDING) != 0
        _, ov_dp = w.add(amount, dr_dp)
        _, ov_cp = w.add(amount, cr_cp)
        _, ov_dpo = w.add(amount, dr_dpo)
        _, ov_cpo = w.add(amount, cr_cpo)
        dr_total, _ = w.add(dr_dp, dr_dpo)
        _, ov_debits = w.add(amount, dr_total)
        cr_total, _ = w.add(cr_cp, cr_cpo)
        _, ov_credits = w.add(amount, cr_total)

        timeout_ns = ev["timeout"] * NS_PER_S
        ts_plus = ts_i + timeout_ns
        ov_timeout = ts_plus < ts_i

        # Limit flags (reference: src/tigerbeetle.zig:31-39).
        dr_lhs, _ = w.add(dr_total, amount)
        exceeds_cr = ((ev["dr_flags"] & AF_DR_LIMIT) != 0) & w.gt(dr_lhs, dr_cpo)
        cr_lhs, _ = w.add(cr_total, amount)
        exceeds_dr = ((ev["cr_flags"] & AF_CR_LIMIT) != 0) & w.gt(cr_lhs, cr_dpo)

        rn = _first_nonzero(
            (e_any, _EXISTS_SENTINEL),
            (bd_fail, R_EXCEEDS_CREDITS),
            (bc_fail, R_EXCEEDS_DEBITS),
            (is_pending & ov_dp, R_OVERFLOWS_DP),
            (is_pending & ov_cp, R_OVERFLOWS_CP),
            (ov_dpo, R_OVERFLOWS_DPO),
            (ov_cpo, R_OVERFLOWS_CPO),
            (ov_debits, R_OVERFLOWS_DEBITS),
            (ov_credits, R_OVERFLOWS_CREDITS),
            (ov_timeout, R_OVERFLOWS_TIMEOUT),
            (exceeds_cr, R_EXCEEDS_CREDITS),
            (exceeds_dr, R_EXCEEDS_DEBITS),
        )
        rn = jnp.where(rn == _EXISTS_SENTINEL, exists_rn, rn)

        # ==================== post/void pending transfer ====================
        # (reference: src/state_machine.zig:1608-1741)
        p_creator = group_creator[jnp.clip(ev["p_group"], 0, B - 1)]
        p_inb = (ev["p_group"] >= 0) & (p_creator >= 0)
        p_dur = ev["p_found"]
        p_any = p_dur | p_inb
        p = _merge(p_dur, _gather_created(created, p_creator, B), ev, _P_FIELD_MAP)
        p_timestamp = jnp.where(
            p_dur,
            ev["p_timestamp"],
            ts_base + jnp.clip(p_creator, 0, B - 1).astype(jnp.uint64),
        )
        p_amount = (p["amount_lo"], p["amount_hi"])

        pv_amount_raw = (ev["amount_lo"], ev["amount_hi"])
        pv_amount = w.select(w.is_zero(pv_amount_raw), p_amount, pv_amount_raw)
        is_void = (flags & F_VOID) != 0

        exists_rp = _exists_ladder_post_void(ev, e, p)

        # Pending status as visible to this event.
        st = jnp.where(
            p_dur,
            carry["dstat"][jnp.clip(ev["p_tgt"], 0, B - 1)],
            carry["inb_status"][jnp.clip(p_creator, 0, B - 1)],
        )

        rp_pre_insert = _first_nonzero(
            (~p_any, R_PENDING_NOT_FOUND),
            ((p["flags"] & F_PENDING) == 0, R_PENDING_NOT_PENDING),
            (~ev["dr_id_zero"] & (ev["dr_slot"] != p["dr_slot"]), R_PENDING_DIFF_DR),
            (~ev["cr_id_zero"] & (ev["cr_slot"] != p["cr_slot"]), R_PENDING_DIFF_CR),
            ((ev["ledger"] > 0) & (ev["ledger"] != p["ledger"]), R_PENDING_DIFF_LEDGER),
            ((ev["code"] > 0) & (ev["code"] != p["code"]), R_PENDING_DIFF_CODE),
            (w.gt(pv_amount, p_amount), R_EXCEEDS_PENDING_AMOUNT),
            (is_void & w.lt(pv_amount, p_amount), R_PENDING_DIFF_AMOUNT),
            (e_any, _EXISTS_SENTINEL),
            (st == S_POSTED, R_ALREADY_POSTED),
            (st == S_VOIDED, R_ALREADY_VOIDED),
            (st == S_EXPIRED, R_PENDING_EXPIRED),
        )
        rp_pre_insert = jnp.where(
            rp_pre_insert == _EXISTS_SENTINEL, exists_rp, rp_pre_insert
        )

        # QUIRK (reference: src/state_machine.zig:1687-1696): the t2
        # insert lands BEFORE the overdue-expiry check, so an overdue
        # post/void leaks its transfer while returning an error.
        p_expires = p_timestamp + p["timeout"] * NS_PER_S
        overdue = (p["timeout"] > 0) & (p_expires <= ts_i)
        rp = jnp.where(
            (rp_pre_insert == 0) & overdue, R_PENDING_EXPIRED, rp_pre_insert
        )

        # ==================== merge & apply ====================
        dyn_r = jnp.where(is_pv, rp, rn)
        gate = active & (pre == 0)
        r = jnp.where(gate, dyn_r, jnp.where(active, pre, 0))

        pv_inserted = gate & is_pv & (rp_pre_insert == 0)
        normal_applied = gate & ~is_pv & (rn == 0)
        pv_applied = gate & is_pv & (rp == 0)
        inserted = pv_inserted | normal_applied
        applied = pv_applied | normal_applied

        # Created-transfer record (reference t2 construction:
        # src/state_machine.zig:1549-1551,1672-1687).
        ud128_inherit = is_pv & (ev["ud128_lo"] == 0) & (ev["ud128_hi"] == 0)
        rec = {
            "flags": flags,
            "dr_slot": jnp.where(is_pv, p["dr_slot"], ev["dr_slot"]),
            "cr_slot": jnp.where(is_pv, p["cr_slot"], ev["cr_slot"]),
            "amount_lo": jnp.where(is_pv, pv_amount[0], amount[0]),
            "amount_hi": jnp.where(is_pv, pv_amount[1], amount[1]),
            "pending_lo": ev["pending_lo"],
            "pending_hi": ev["pending_hi"],
            "ud128_lo": jnp.where(ud128_inherit, p["ud128_lo"], ev["ud128_lo"]),
            "ud128_hi": jnp.where(ud128_inherit, p["ud128_hi"], ev["ud128_hi"]),
            "ud64": jnp.where(is_pv & (ev["ud64"] == 0), p["ud64"], ev["ud64"]),
            "ud32": jnp.where(is_pv & (ev["ud32"] == 0), p["ud32"], ev["ud32"]),
            "timeout": jnp.where(is_pv, jnp.uint64(0), ev["timeout"]),
            "ledger": jnp.where(is_pv, p["ledger"], ev["ledger"]),
            "code": jnp.where(is_pv, p["code"], ev["code"]),
        }

        # Balance updates.
        up_dr_slot = jnp.where(is_pv, p["dr_slot"], ev["dr_slot"])
        up_cr_slot = jnp.where(is_pv, p["cr_slot"], ev["cr_slot"])
        safe_dr = jnp.clip(up_dr_slot, 0, A - 1)
        safe_cr = jnp.clip(up_cr_slot, 0, A - 1)
        old_dr_row = table[safe_dr]
        old_cr_row = table[safe_cr]

        o_dr_dp = (old_dr_row[DP_LO], old_dr_row[DP_HI])
        o_dr_dpo = (old_dr_row[DPO_LO], old_dr_row[DPO_HI])
        o_cr_cp = (old_cr_row[CP_LO], old_cr_row[CP_HI])
        o_cr_cpo = (old_cr_row[CPO_LO], old_cr_row[CPO_HI])

        is_post = (flags & F_POST) != 0
        # Normal: pending adds to *_pending, else *_posted.
        # Post/void: release p.amount pending; post adds pv_amount posted.
        n_dr_dp = w.select(
            is_pv,
            w.sub(o_dr_dp, p_amount)[0],
            w.select(is_pending, w.add(o_dr_dp, amount)[0], o_dr_dp),
        )
        n_dr_dpo = w.select(
            is_pv,
            w.select(is_post, w.add(o_dr_dpo, pv_amount)[0], o_dr_dpo),
            w.select(is_pending, o_dr_dpo, w.add(o_dr_dpo, amount)[0]),
        )
        n_cr_cp = w.select(
            is_pv,
            w.sub(o_cr_cp, p_amount)[0],
            w.select(is_pending, w.add(o_cr_cp, amount)[0], o_cr_cp),
        )
        n_cr_cpo = w.select(
            is_pv,
            w.select(is_post, w.add(o_cr_cpo, pv_amount)[0], o_cr_cpo),
            w.select(is_pending, o_cr_cpo, w.add(o_cr_cpo, amount)[0]),
        )

        new_dr_row = jnp.stack(
            [
                n_dr_dp[0], n_dr_dp[1],
                n_dr_dpo[0], n_dr_dpo[1],
                old_dr_row[CP_LO], old_dr_row[CP_HI],
                old_dr_row[CPO_LO], old_dr_row[CPO_HI],
            ]
        )
        new_cr_row = jnp.stack(
            [
                old_cr_row[DP_LO], old_cr_row[DP_HI],
                old_cr_row[DPO_LO], old_cr_row[DPO_HI],
                n_cr_cp[0], n_cr_cp[1],
                n_cr_cpo[0], n_cr_cpo[1],
            ]
        )

        table = table.at[safe_dr].set(jnp.where(applied, new_dr_row, table[safe_dr]))
        table = table.at[safe_cr].set(jnp.where(applied, new_cr_row, table[safe_cr]))

        # Record created transfer + id directory + statuses.
        created = {
            f: created[f]
            .at[i]
            .set(jnp.where(inserted, rec[f].astype(created[f].dtype), created[f][i]))
            for f in CREATED_FIELDS
        }
        created_mask = carry["created_mask"].at[i].set(inserted)
        gidx = jnp.clip(ev["id_group"], 0, B - 1)
        group_creator = group_creator.at[gidx].set(
            jnp.where(inserted, i, group_creator[gidx])
        )

        inb_status = carry["inb_status"].at[i].set(
            jnp.where(normal_applied & is_pending, jnp.uint32(S_PENDING), 0)
        )
        new_status = jnp.where(is_post, jnp.uint32(S_POSTED), jnp.uint32(S_VOIDED))
        dstat = carry["dstat"]
        tgt = jnp.clip(ev["p_tgt"], 0, B - 1)
        dstat = dstat.at[tgt].set(jnp.where(pv_applied & p_dur, new_status, dstat[tgt]))
        pcr = jnp.clip(p_creator, 0, B - 1)
        inb_status = inb_status.at[pcr].set(
            jnp.where(pv_applied & ~p_dur, new_status, inb_status[pcr])
        )

        # Undo log entries (balance restore, creation, status change).
        u_dr_slot = carry["u_dr_slot"].at[i].set(jnp.where(applied, up_dr_slot, -1))
        u_cr_slot = carry["u_cr_slot"].at[i].set(jnp.where(applied, up_cr_slot, -1))
        u_dr_bal = carry["u_dr_bal"].at[i].set(old_dr_row)
        u_cr_bal = carry["u_cr_bal"].at[i].set(old_cr_row)
        u_status_kind = carry["u_status_kind"].at[i].set(
            jnp.where(pv_applied, jnp.where(p_dur, 1, 2), 0)
        )
        u_status_idx = carry["u_status_idx"].at[i].set(
            jnp.where(p_dur, ev["p_tgt"], p_creator)
        )

        hist_dr = carry["hist_dr"].at[i].set(new_dr_row)
        hist_cr = carry["hist_cr"].at[i].set(new_cr_row)

        results = carry["results"].at[i].set(r)

        # -- Chain failure: roll back [chain_start, i] in reverse
        # (reference: src/state_machine.zig:1269-1290).
        fail = active & (r != 0)
        chain_fail = fail & (chain_start >= 0) & ~chain_broken

        def do_rollback(state):
            table, created_mask, group_creator, inb_status, dstat = state
            count = i - chain_start + 1

            def rb(k, st):
                table, created_mask, group_creator, inb_status, dstat = st
                idx = i - k
                ds = u_dr_slot[idx]
                has = ds >= 0
                sds = jnp.clip(ds, 0, A - 1)
                scs = jnp.clip(u_cr_slot[idx], 0, A - 1)
                table = table.at[scs].set(jnp.where(has, u_cr_bal[idx], table[scs]))
                table = table.at[sds].set(jnp.where(has, u_dr_bal[idx], table[sds]))
                # Un-create (clears the id directory entry if we own it).
                g = jnp.clip(id_group_full[idx], 0, B - 1)
                group_creator = group_creator.at[g].set(
                    jnp.where(group_creator[g] == idx, -1, group_creator[g])
                )
                created_mask = created_mask.at[idx].set(False)
                inb_status = inb_status.at[idx].set(0)
                kind = u_status_kind[idx]
                sidx = jnp.clip(u_status_idx[idx], 0, B - 1)
                dstat = dstat.at[sidx].set(
                    jnp.where(kind == 1, jnp.uint32(S_PENDING), dstat[sidx])
                )
                inb_status = inb_status.at[sidx].set(
                    jnp.where(kind == 2, jnp.uint32(S_PENDING), inb_status[sidx])
                )
                return (table, created_mask, group_creator, inb_status, dstat)

            return lax.fori_loop(
                0, count, rb, (table, created_mask, group_creator, inb_status, dstat)
            )

        table, created_mask, group_creator, inb_status, dstat = lax.cond(
            chain_fail,
            do_rollback,
            lambda s: s,
            (table, created_mask, group_creator, inb_status, dstat),
        )

        # Rewrite earlier chain results to linked_event_failed (FIFO
        # order is preserved because results stay indexed by event).
        rewrite = chain_fail & (arange_b >= chain_start) & (arange_b < i)
        results = jnp.where(rewrite, jnp.uint32(R_LINKED_EVENT_FAILED), results)

        chain_broken = chain_broken | chain_fail

        # Chain close (reference: src/state_machine.zig:1292-1300).
        tail = (chain_start >= 0) & (~linked | (r == R_LINKED_EVENT_CHAIN_OPEN))
        chain_start = jnp.where(tail, jnp.int32(-1), chain_start)
        chain_broken = jnp.where(tail, jnp.bool_(False), chain_broken)

        new_carry = {
            "balances": table,
            "results": results,
            "created_mask": created_mask,
            "created": created,
            "group_creator": group_creator,
            "inb_status": inb_status,
            "dstat": dstat,
            "u_dr_slot": u_dr_slot,
            "u_cr_slot": u_cr_slot,
            "u_dr_bal": u_dr_bal,
            "u_cr_bal": u_cr_bal,
            "u_status_kind": u_status_kind,
            "u_status_idx": u_status_idx,
            "hist_dr": hist_dr,
            "hist_cr": hist_cr,
            "chain_start": chain_start,
            "chain_broken": chain_broken,
            "last_applied": jnp.where(applied, i, carry["last_applied"]),
            "pulse_create": carry["pulse_create"]
            .at[i]
            .set(
                jnp.where(
                    normal_applied & is_pending & (ev["timeout"] > 0),
                    ts_i + timeout_ns,
                    jnp.uint64(0),
                )
            ),
            "pulse_remove": carry["pulse_remove"]
            .at[i]
            .set(
                jnp.where(
                    pv_applied & (p["timeout"] > 0), p_expires, jnp.uint64(0)
                )
            ),
        }
        return new_carry, ()

    return body


def finalize_outputs(final):
    """(final carry) -> (balances, packed output matrix)."""
    out = {
        "balances": final["balances"],
        "results": final["results"],
        "created_mask": final["created_mask"],
        "created": final["created"],
        "inb_status": final["inb_status"],
        "dstat": final["dstat"],
        "hist_dr": final["hist_dr"],
        "hist_cr": final["hist_cr"],
        "last_applied": final["last_applied"],
        "pulse_create": final["pulse_create"],
        "pulse_remove": final["pulse_remove"],
    }
    return out["balances"], _pack_outputs(out)


def _run_impl(balances, events, dstat_init, n, ts_base):
    B = events["flags"].shape[0]
    A = balances.shape[0]
    arange_b = jnp.arange(B, dtype=jnp.int32)
    carry = make_carry(balances, dstat_init, B)
    body = make_body(n, ts_base, B, A, events["id_group"], arange_b)
    final, _ = lax.scan(body, carry, events)
    return finalize_outputs(final)


def _scan_segment_impl(carry, events_seg, id_group_full, n, ts_base):
    """Run the exact scan over a contiguous batch sub-range, resuming
    from (and returning) a segment carry.  Events keep their global
    `i`; padded lanes use i == B, which is inactive (i >= n) and whose
    per-event writes fall out of bounds and drop."""
    B = id_group_full.shape[0]
    A = carry["balances"].shape[0]
    arange_b = jnp.arange(B, dtype=jnp.int32)
    body = make_body(n, ts_base, B, A, id_group_full, arange_b)
    final, _ = lax.scan(body, carry, events_seg)
    return final


scan_segment = jax.jit(_scan_segment_impl, donate_argnums=(0,))
# Non-donating twin for the device engine's wave dispatch (waves.py):
# the engine's authoritative table handle must survive a mid-batch
# retry, so no buffer it still references may be donated.
scan_segment_keep = jax.jit(_scan_segment_impl)


# Packed-output column layout: the device link is high-latency, so all
# per-event outputs ride ONE (B, N_COLS) uint64 matrix fetched in a
# single device->host transfer (unpacked by unpack_outputs below).
_SCALAR_COLS = (
    ["results", "created_mask"]
    + list(CREATED_FIELDS)
    + ["inb_status", "dstat", "pulse_create", "pulse_remove", "last_applied"]
)
N_COLS = len(_SCALAR_COLS) + 16  # + hist_dr (8) + hist_cr (8)


def _pack_outputs(out):
    cols = []
    for name in _SCALAR_COLS:
        if name == "last_applied":
            # Scalar; may be -1 -> stored (+1) in element 0.
            v = jnp.zeros_like(out["results"], shape=out["results"].shape)
            v = v.astype(jnp.uint64).at[0].set(
                (out["last_applied"] + 1).astype(jnp.uint64)
            )
        elif name in CREATED_FIELDS:
            v = out["created"][name].astype(jnp.uint64)
        else:
            v = out[name].astype(jnp.uint64)
        cols.append(v)
    mat = jnp.stack(cols, axis=1)
    return jnp.concatenate([mat, out["hist_dr"], out["hist_cr"]], axis=1)


def unpack_outputs(packed: "np.ndarray") -> dict:
    """Host-side inverse of _pack_outputs (packed: (B, N_COLS) u64)."""
    import numpy as np

    assert packed.shape[1] == N_COLS, packed.shape
    out = {"created": {}}
    for k, name in enumerate(_SCALAR_COLS):
        col = packed[:, k]
        if name == "last_applied":
            out[name] = int(col[0]) - 1
        elif name in ("dr_slot", "cr_slot"):
            out["created"][name] = col.view(np.int64).astype(np.int32)
        elif name in CREATED_FIELDS:
            dtype = _CREATED_DTYPES.get(name, np.uint64)
            out["created"][name] = col.astype(dtype)
        elif name == "created_mask":
            out[name] = col.astype(bool)
        elif name in ("results", "inb_status", "dstat"):
            out[name] = col.astype(np.uint32)
        else:
            out[name] = col.copy()
    base = len(_SCALAR_COLS)
    out["hist_dr"] = packed[:, base : base + 8]
    out["hist_cr"] = packed[:, base + 8 : base + 16]
    return out


_run = jax.jit(_run_impl, donate_argnums=(0,))


def run_create_transfers(balances, events, dstat_init, n, ts_base):
    """Run the scan kernel.

    `events` is a dict of (B,) arrays per EVENT_FIELDS; `balances` is
    the donated (A, 8) uint64 account-balance table. Returns the new
    balances plus per-event outputs (results, created records,
    statuses, post-apply balance snapshots).
    """
    return _run(
        balances,
        events,
        jnp.asarray(dstat_init, jnp.uint32),
        jnp.int32(n),
        jnp.uint64(ts_base),
    )
