"""LSM spill tier for the TPU state machine's transfer + history state.

The commit hot path appends to RAM columnar stores (tpu.py `Columns`) —
the memtable of this design.  At checkpoint, rows that can no longer
change (everything except live pendings, which post/void/expiry still
mutate) spill into LSM grooves on the grid, so durable state scales
past host RAM while the hot path never touches the LSM
(reference: src/lsm/groove.zig:136-176 — grooves feed the state
machine; src/state_machine.zig:178-324).

Key scheme (vs the reference's IdTree/ObjectTree pair,
src/lsm/groove.zig):
- object tree: key = GLOBAL ROW NUMBER (commit order).  Rows are
  assigned monotonically and timestamps rise with rows, so row order ==
  timestamp order.  The id -> row map stays in the RAM run-compressed
  id directories (utils/hashindex.py RunIndex + the native IdDir) —
  sequential-id workloads compress to O(1) ranges; the object tree
  rebuilds them after restore.
- dr/cr index trees: key = (account slot, timestamp), value = row —
  timestamp-ordered range scans per account for get_account_transfers
  (reference: src/state_machine.zig:931-996).
- history tree: key = transfer timestamp (unique), value = packed
  dr/cr balance snapshots for get_account_balances.

Spilled objects are immutable; `gather` serves reads for exists-ladder
joins, lookup_transfers, and query materialization.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.lsm.runs import pack_u128

# Spilled transfer object layout (little-endian), 144 bytes:
#   0..128  wire Transfer image (types.py TRANSFER_DTYPE, incl.
#           timestamp at 120)
# 128..132  dr_slot  i32
# 132..136  cr_slot  i32
# 136..137  status   u8 (TransferPendingStatus; final by spill time)
# 137..144  pad
TRANSFER_OBJECT_SIZE = 144

# Spilled history object layout, 160 bytes total:
#   0..16   dr account id (lo, hi)
#  16..32   cr account id (lo, hi)
#  32..96   dr balances (dp, dpo, cp, cpo as u128 lo/hi pairs, 64B)
#  96..160  cr balances (same packing, 64B)
HISTORY_OBJECT_SIZE = 160

# Store-column -> byte offset within the 128B wire image.
_WIRE_FIELDS = (
    ("id_lo", 0, np.uint64), ("id_hi", 8, np.uint64),
    # debit/credit account ids are not store columns (slots are); they
    # are written from the attrs table at spill time.
    ("amount_lo", 48, np.uint64), ("amount_hi", 56, np.uint64),
    ("pending_lo", 64, np.uint64), ("pending_hi", 72, np.uint64),
    ("ud128_lo", 80, np.uint64), ("ud128_hi", 88, np.uint64),
    ("ud64", 96, np.uint64), ("ud32", 104, np.uint32),
    ("timeout", 108, np.uint32),
    ("ledger", 112, np.uint32), ("code", 116, np.uint16),
    ("flags", 118, np.uint16), ("timestamp", 120, np.uint64),
)


def _row_keys(rows: np.ndarray) -> np.ndarray:
    return pack_u128(
        np.asarray(rows, np.uint64), np.zeros(len(rows), np.uint64)
    )


class TransferSpill:
    """Spilled (immutable) transfer rows in a groove; `base` rows
    [0, base) live here, the store's RAM tail holds [base, count)."""

    def __init__(self, groove, attrs_fn=None) -> None:
        self.groove = groove
        self.base = 0
        # Account attrs accessor for id reconstruction at gather:
        # dr/cr ACCOUNT IDS are derivable from the stored slots (slots
        # are append-only and an account's id is immutable), so the
        # spilled image zeroes those 32 bytes — the sparse block codec
        # then writes nothing for them (write-amp lever, VERDICT r4
        # #5).  Falls back to storing the ids when no accessor is
        # wired (standalone groove tests).
        self._attrs_fn = attrs_fn

    # -- write (checkpoint path) ---------------------------------------

    def spill(self, rows: np.ndarray, cols: dict, attrs) -> None:
        """Append objects for global rows (ascending, == arange from
        self.base) built from store columns + account attrs."""
        n = len(rows)
        if n == 0:
            return
        assert int(rows[0]) == self.base and int(rows[-1]) == self.base + n - 1
        obj = np.zeros((n, TRANSFER_OBJECT_SIZE), np.uint8)
        for name, off, dt in _WIRE_FIELDS:
            width = np.dtype(dt).itemsize
            obj[:, off : off + width] = (
                np.ascontiguousarray(cols[name].astype(dt, copy=False))
                .view(np.uint8)
                .reshape(n, width)
            )
        dr = cols["dr_slot"].astype(np.int64)
        cr = cols["cr_slot"].astype(np.int64)
        if self._attrs_fn is None:
            obj[:, 16:24] = attrs["id_lo"][dr].view(np.uint8).reshape(n, 8)
            obj[:, 24:32] = attrs["id_hi"][dr].view(np.uint8).reshape(n, 8)
            obj[:, 32:40] = attrs["id_lo"][cr].view(np.uint8).reshape(n, 8)
            obj[:, 40:48] = attrs["id_hi"][cr].view(np.uint8).reshape(n, 8)
        # else: bytes 16..48 stay zero on disk; gather() reconstructs
        # them from the slots + account attrs.
        obj[:, 128:132] = (
            cols["dr_slot"].astype(np.int32).view(np.uint8).reshape(n, 4)
        )
        obj[:, 132:136] = (
            cols["cr_slot"].astype(np.int32).view(np.uint8).reshape(n, 4)
        )
        obj[:, 136] = cols["status"].astype(np.uint8)

        ts = cols["timestamp"].astype(np.uint64)
        self.groove.object_tree.put_batch(_row_keys(rows), obj)
        rows_v = np.asarray(rows, np.uint64).astype("<u8").view("V8")
        # Pre-sort index entries by (slot, ts): a stable u64 argsort on
        # the slot (ts ascends within the batch already) hands
        # put_batch strictly-increasing V16 keys, skipping its far
        # slower void-dtype argsort on the ingest hot path.
        do = np.argsort(dr, kind="stable")
        self.groove.indexes["dr_slot"].put_batch(
            pack_u128(ts[do], dr[do].astype(np.uint64)), rows_v[do]
        )
        co = np.argsort(cr, kind="stable")
        self.groove.indexes["cr_slot"].put_batch(
            pack_u128(ts[co], cr[co].astype(np.uint64)), rows_v[co]
        )
        # Seal overflowing memtables NOW: paced spill beats must turn
        # into bounded level-0 runs per beat, not one giant run at the
        # checkpoint (which would re-create the latency cliff the
        # beats exist to remove).
        self.groove.maybe_seal()
        self.base += n

    # -- read ----------------------------------------------------------

    def _lookup_raw(self, rows: np.ndarray) -> np.ndarray:
        """Raw on-disk objects (ids NOT reconstructed) for rows < base."""
        found, vals = self.groove.object_tree.lookup_batch(_row_keys(rows))
        assert found.all(), "spilled row missing from object tree"
        return np.ascontiguousarray(vals)

    def gather(self, rows: np.ndarray) -> np.ndarray:
        """Global rows (< base) -> (n, TRANSFER_OBJECT_SIZE) u8."""
        vals = self._lookup_raw(rows)
        if self._attrs_fn is not None:
            vals = self._reconstruct_ids(vals)
        return vals

    def _reconstruct_ids(self, obj: np.ndarray) -> np.ndarray:
        n = len(obj)
        attrs = self._attrs_fn()
        dr = np.ascontiguousarray(obj[:, 128:132]).view(np.int32).reshape(n)
        cr = np.ascontiguousarray(obj[:, 132:136]).view(np.int32).reshape(n)
        dr = dr.astype(np.int64)
        cr = cr.astype(np.int64)
        obj[:, 16:24] = attrs["id_lo"][dr].view(np.uint8).reshape(n, 8)
        obj[:, 24:32] = attrs["id_hi"][dr].view(np.uint8).reshape(n, 8)
        obj[:, 32:40] = attrs["id_lo"][cr].view(np.uint8).reshape(n, 8)
        obj[:, 40:48] = attrs["id_hi"][cr].view(np.uint8).reshape(n, 8)
        return obj

    def update_status(self, rows: np.ndarray, statuses: np.ndarray) -> None:
        """Finalize spilled pendings: rewrite their objects with the
        new status (LSM overwrite; newest version wins on read).  The
        only mutable byte of a spilled object — everything else is
        immutable after spill."""
        obj = self._lookup_raw(rows)
        obj[:, 136] = np.asarray(statuses, np.uint8)
        self.groove.object_tree.put_batch(_row_keys(rows), obj)


    def iter_objects(self, batch: int = 8192):
        """Yield (rows, objects) over all spilled rows ascending —
        restore uses this to rebuild the RAM id directories, which
        read only the transfer id (bytes 0..16), so the account-id
        reconstruction is skipped (it would be pure per-row waste on
        every crash recovery / state sync)."""
        at = 0
        while at < self.base:
            n = min(batch, self.base - at)
            rows = np.arange(at, at + n, dtype=np.int64)
            yield rows, self._lookup_raw(rows)
            at += n


def unpack_objects(obj: np.ndarray) -> dict:
    """(n, 144) u8 -> store-column dict (the inverse of spill)."""
    n = len(obj)
    out = {}
    for name, off, dt in _WIRE_FIELDS:
        width = np.dtype(dt).itemsize
        out[name] = (
            np.ascontiguousarray(obj[:, off : off + width])
            .view(dt)
            .reshape(n)
        )
    out["dr_slot"] = (
        np.ascontiguousarray(obj[:, 128:132]).view(np.int32).reshape(n)
    )
    out["cr_slot"] = (
        np.ascontiguousarray(obj[:, 132:136]).view(np.int32).reshape(n)
    )
    out["status"] = obj[:, 136].copy()
    out["dr_id_lo"] = np.ascontiguousarray(obj[:, 16:24]).view(np.uint64).reshape(n)
    out["dr_id_hi"] = np.ascontiguousarray(obj[:, 24:32]).view(np.uint64).reshape(n)
    out["cr_id_lo"] = np.ascontiguousarray(obj[:, 32:40]).view(np.uint64).reshape(n)
    out["cr_id_hi"] = np.ascontiguousarray(obj[:, 40:48]).view(np.uint64).reshape(n)
    return out


class HistorySpill:
    """Spilled historical-balance rows keyed by transfer timestamp."""

    def __init__(self, groove) -> None:
        self.groove = groove
        self.base = 0  # history rows [0, base) spilled

    def spill(self, cols: dict) -> None:
        n = len(cols["timestamp"])
        if n == 0:
            return
        obj = np.zeros((n, HISTORY_OBJECT_SIZE), np.uint8)
        obj[:, 0:8] = cols["dr_id_lo"].view(np.uint8).reshape(n, 8)
        obj[:, 8:16] = cols["dr_id_hi"].view(np.uint8).reshape(n, 8)
        obj[:, 16:24] = cols["cr_id_lo"].view(np.uint8).reshape(n, 8)
        obj[:, 24:32] = cols["cr_id_hi"].view(np.uint8).reshape(n, 8)
        obj[:, 32:96] = (
            np.ascontiguousarray(cols["dr_bal"]).view(np.uint8).reshape(n, 64)
        )
        obj[:, 96:160] = (
            np.ascontiguousarray(cols["cr_bal"]).view(np.uint8).reshape(n, 64)
        )
        ts = cols["timestamp"].astype(np.uint64)
        self.groove.object_tree.put_batch(
            pack_u128(ts, np.zeros(n, np.uint64)), obj
        )
        self.base += n

    def gather_by_ts(self, ts: np.ndarray) -> tuple[np.ndarray, dict]:
        found, obj = self.groove.object_tree.lookup_batch(
            pack_u128(np.asarray(ts, np.uint64), np.zeros(len(ts), np.uint64))
        )
        n = len(obj)
        return found, {
            "dr_id_lo": np.ascontiguousarray(obj[:, 0:8]).view(np.uint64).reshape(n),
            "dr_id_hi": np.ascontiguousarray(obj[:, 8:16]).view(np.uint64).reshape(n),
            "dr_bal": np.ascontiguousarray(obj[:, 32:96]).view(np.uint64).reshape(n, 8),
            "cr_bal": np.ascontiguousarray(obj[:, 96:160]).view(np.uint64).reshape(n, 8),
        }
