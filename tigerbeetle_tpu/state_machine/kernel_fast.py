"""Write-behind device apply: fused modular scatter-add flushes.

The TPU link has high per-dispatch latency (the state machine must
never block the commit path on it), so balance updates from fast-path
batches (see tpu.py `_commit_fast` for the admission conditions) are
queued host-side as compact (slot, column, u128 delta) entries and
flushed to the HBM table in large fused scatter-adds, asynchronously —
no host<->device sync anywhere on the hot path.

Overflow admission runs on the host BalanceMirror (mirror.py) before
enqueueing, so the device apply is a pure mod-2^128 addition;
subtractions (pending expiry) ride the same path as two's-complement
deltas. Queued deltas are compacted host-side to one entry per
(slot, column) before each flush, so the device kernel scatters with
unique indices (no accumulation on device) and finishes with a single
elementwise u128 carry add over the table.

The exact scan kernel (kernel.py) reads the table through a flush
barrier, so order-dependent batches always see current state.
"""

from __future__ import annotations

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)  # u64 limb math throughout

import jax.numpy as jnp

from tigerbeetle_tpu.ops import u128 as w

# Fixed flush chunk: ONE compiled shape ever (larger delta sets loop —
# chunks chain serially through the donated table, so the chunk is
# sized to cover accounts*4 entries for large account tables in a few
# dispatches; small flushes pad, which costs <1ms on the link).
# Entries within a flush are unique per (slot, col) after compaction, so
# the kernel scatters with unique_indices instead of accumulating — no
# limb decomposition needed, just one u128 carry add over the table.
_FLUSH_CHUNK = 32_768
# Queue high-water mark: flush (async) once this many entries queue up.
# Bounds queue memory and overlaps device work with the host commit
# loop; compaction collapses each flush to at most accounts*4 entries.
FLUSH_THRESHOLD = 65_536


def _flush_impl(balances, packed):
    """balances[slot, col] += delta (mod 2^128) for unique (slot, col).

    packed is (4, _FLUSH_CHUNK) u64 rows: slot, col, delta_lo, delta_hi.
    Padding entries use slot >= A and are dropped by the scatter.
    """
    A = balances.shape[0]
    slots = packed[0].astype(jnp.int32)
    cols = packed[1].astype(jnp.int32)
    dense_lo = (
        jnp.zeros((A, 4), jnp.uint64)
        .at[slots, cols]
        .set(packed[2], mode="drop", unique_indices=True)
    )
    dense_hi = (
        jnp.zeros((A, 4), jnp.uint64)
        .at[slots, cols]
        .set(packed[3], mode="drop", unique_indices=True)
    )
    old_lo = balances[:, 0::2]
    old_hi = balances[:, 1::2]
    new_lo = old_lo + dense_lo
    carry = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + dense_hi + carry
    return jnp.stack(
        [
            new_lo[:, 0], new_hi[:, 0],
            new_lo[:, 1], new_hi[:, 1],
            new_lo[:, 2], new_hi[:, 2],
            new_lo[:, 3], new_hi[:, 3],
        ],
        axis=-1,
    )


_flush = jax.jit(_flush_impl, donate_argnums=(0,))


class DeviceTable:
    """The authoritative HBM balance table + its write-behind queue.

    On a multi-device mesh the table is sharded ROW-WISE across every
    device (jax.sharding.NamedSharding over a 1-D "shard" mesh), so
    the fused flush scatter runs SPMD with XLA-inserted collectives —
    the production-path integration of the parallel/sharded.py design.
    Single-device (the common one-chip TPU case) stays a plain array.
    """

    def __init__(self, capacity: int) -> None:
        from tigerbeetle_tpu.state_machine import hot_tier

        # Hot/cold tiering (TB_HOT_CAPACITY): when set below the
        # logical capacity, the device table holds only the hot rows;
        # the host mirror is the cold tier and full-table reads are
        # served from it.  None (default) = all-resident, the untiered
        # behavior bit-for-bit.
        self.hot = hot_tier.from_env(capacity)
        self.capacity = capacity
        device_rows = capacity if self.hot is None else self.hot.hot_rows
        self.sharding = None
        devices = jax.devices()
        if len(devices) > 1 and device_rows % len(devices) == 0:
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            mesh = Mesh(np.array(devices), ("shard",))
            self.sharding = NamedSharding(mesh, P("shard", None))
        self.balances = self._place(jnp.zeros((device_rows, 8), jnp.uint64))
        self._q: list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]] = []
        self._queued = 0
        # Host BalanceMirror this table shadows (set by the owning
        # state machine): the native fast path mutates the mirror
        # arrays in place and feeds the deltas ONLY through enqueue,
        # so the incremental-commitment twin refreshes here.
        self.mirror = None
        # Tiered full-table read cache, keyed by the mirror's mutation
        # stamp (read() serves the LOGICAL table from the cold tier).
        self._full_cache = None

    def _place(self, table):
        if self.sharding is None:
            return table
        return jax.device_put(table, self.sharding)

    def grow(self, capacity: int) -> None:
        if capacity <= self.capacity:
            return
        if self.hot is not None:
            # The hot-row budget is a fixed HBM allowance: logical
            # growth widens only the maps (new rows are cold; the
            # mirror — the cold tier — grows through its own path).
            self.hot.grow_logical(capacity)
            self.capacity = capacity
            return
        from tigerbeetle_tpu.state_machine.hot_tier import grow_zero_device

        # Dense growth stays on-device and async; sharded growth
        # reshards through the host (shared growth-policy helper).
        self.balances = grow_zero_device(
            self.balances, capacity, self.sharding, self._place
        )
        self.capacity = capacity

    def write_back(self, value) -> None:
        """Replace the device table from a full LOGICAL table image.

        Untiered this is a plain handle swap; tiered the hot rows are
        gathered out of the logical image (the mirror stays the
        authority for cold rows).
        """
        if self.hot is None:
            self.balances = value
            return
        self._full_cache = None
        occ = jnp.asarray(self.hot.logical_of)
        rows = jnp.asarray(value)[jnp.where(occ >= 0, occ, 0)]
        rows = jnp.where((occ >= 0)[:, None], rows, jnp.zeros_like(rows))
        self.balances = self._place(rows)

    def _tier_enqueue(self, slots, cols, add_lo, add_hi) -> None:
        """Tiered write-behind: admit misses, queue only hot deltas.

        The mirror LEADS in host mode (it was mutated before this
        call), so cold rows need no device delta at all — the mirror
        IS the cold tier — and rows admitted now are uploaded with
        this batch's effects already folded in, so their queue entries
        are dropped too.
        """
        import time as _time

        hot = self.hot
        self._full_cache = None
        sl = np.asarray(slots, np.int64)
        uniq, missing = hot.plan(sl)
        admitted = np.zeros(0, np.int64)
        if len(missing):
            # Quiesce the lane BEFORE the map moves: queued deltas
            # must flush under the map they were enqueued with, and a
            # victim slot's pending deltas must land before reuse.
            t0 = _time.perf_counter()
            self.flush()
            got = hot.admit(missing, protect=uniq, partial=True)
            if got is not None:
                admitted, hot_slots, _evicted = got
                if len(admitted):
                    # Bucket-padded upload: ONE compiled scatter shape
                    # per power-of-two bucket, not one per admitted
                    # count.  Padding uses DISTINCT out-of-range slots
                    # (mode="drop") so unique_indices stays honest.
                    from tigerbeetle_tpu.state_machine.commitment import (
                        pad_slots,
                    )

                    padded = pad_slots(np.asarray(hot_slots, np.int64))
                    k = len(hot_slots)
                    idx = np.where(
                        padded >= 0, padded,
                        hot.hot_rows + np.arange(len(padded), dtype=np.int64),
                    )
                    rows = np.zeros((len(padded), 8), np.uint64)
                    rows[:k] = self.mirror.rows8(admitted)
                    self.balances = self.balances.at[jnp.asarray(idx)].set(
                        jnp.asarray(rows), mode="drop", unique_indices=True
                    )
            hot.note_stall(_time.perf_counter() - t0)
        hot.record_use(uniq, len(uniq) - len(missing), len(missing))
        keep = hot.hot_of[sl] >= 0
        if len(admitted):
            keep &= ~np.isin(sl, admitted)
        if not keep.any():
            return
        self._q.append(
            (
                sl[keep].astype(np.int32),
                np.asarray(cols, np.int32)[keep],
                np.asarray(add_lo, np.uint64)[keep],
                np.asarray(add_hi, np.uint64)[keep],
            )
        )
        self._queued += int(keep.sum())
        if self._queued >= FLUSH_THRESHOLD:
            self.flush()

    def enqueue(self, slots, cols, add_lo, add_hi,
                refresh_twin: bool = True) -> None:
        """Queue compact (slot, col, delta) modular adds.

        `refresh_twin=False`: the caller's deltas came through the
        mirror's own methods, whose _touch already refreshed the
        commitment twin — only native in-place mutations (which
        bypass those methods) need the refresh here."""
        if len(slots) == 0:
            return
        if refresh_twin and (
            self.mirror is not None and self.mirror.commitment is not None
        ):
            self.mirror.commitment.refresh(
                np.asarray(slots, np.int64), self.mirror
            )
        if self.hot is not None:
            self._tier_enqueue(slots, cols, add_lo, add_hi)
            return
        self._q.append(
            (
                np.asarray(slots, np.int32),
                np.asarray(cols, np.int32),
                np.asarray(add_lo, np.uint64),
                np.asarray(add_hi, np.uint64),
            )
        )
        self._queued += len(slots)
        if self._queued >= FLUSH_THRESHOLD:
            self.flush()

    def flush(self) -> None:
        """Dispatch all queued deltas to the device (async, no sync).

        The queue is first re-compacted globally — modular adds merge
        across batches — so one flush covers many commits with at most
        accounts*4 entries, and each compacted (slot, col) appears
        exactly once (the kernel's unique_indices contract).
        """
        if not self._queued:
            return
        from tigerbeetle_tpu.state_machine.mirror import compact_deltas

        slots = np.concatenate([e[0] for e in self._q]).astype(np.int64)
        cols = np.concatenate([e[1] for e in self._q]).astype(np.int64)
        a_lo = np.concatenate([e[2] for e in self._q])
        a_hi = np.concatenate([e[3] for e in self._q])
        self._q.clear()
        self._queued = 0
        # Compact in bounded chunks (exactness limit of compact_deltas),
        # then once more over the per-chunk sums.
        chunk = (1 << 21) - 1
        if len(slots) > chunk:
            parts = [
                compact_deltas(
                    slots[i : i + chunk], cols[i : i + chunk],
                    a_lo[i : i + chunk], a_hi[i : i + chunk],
                )
                for i in range(0, len(slots), chunk)
            ]
            slots = np.concatenate([p[0] for p in parts])
            cols = np.concatenate([p[1] for p in parts])
            a_lo = np.concatenate([p[2] for p in parts])
            a_hi = np.concatenate([p[3] for p in parts])
        u_slot, u_col, d_lo, d_hi, _ = compact_deltas(slots, cols, a_lo, a_hi)
        if self.hot is not None:
            # Queue entries carry LOGICAL slots; the device table is
            # hot-shaped.  All queued rows are hot at flush time (the
            # map only moves against an empty queue), but translate
            # defensively and drop any that fell cold.
            h = self.hot.hot_of[u_slot]
            keep = h >= 0
            u_slot, u_col = h[keep], u_col[keep]
            d_lo, d_hi = d_lo[keep], d_hi[keep]

        A = self.balances.shape[0]
        at = 0
        while at < len(u_slot):
            take = min(len(u_slot) - at, _FLUSH_CHUNK)
            # One packed host array -> ONE device transfer per chunk.
            packed = np.empty((4, _FLUSH_CHUNK), np.uint64)
            packed[0, :take] = u_slot[at : at + take].astype(np.uint64)
            # Padding: DISTINCT out-of-range slots (dropped by the
            # scatter) — duplicate indices would void the
            # unique_indices promise even for dropped entries.
            packed[0, take:] = A + np.arange(_FLUSH_CHUNK - take, dtype=np.uint64)
            packed[1, :take] = u_col[at : at + take].astype(np.uint64)
            packed[1, take:] = 0
            packed[2, :take] = d_lo[at : at + take]
            packed[2, take:] = 0
            packed[3, :take] = d_hi[at : at + take]
            packed[3, take:] = 0
            self.balances = _flush(self.balances, jnp.asarray(packed))
            at += take

    def read(self):
        """Flush barrier + current LOGICAL-table handle (still async).

        Tiered, the device holds only the hot rows, so the logical
        table is materialized from the cold tier (the mirror leads in
        host mode) and cached against its mutation stamp — enqueue
        invalidates the cache too, covering native in-place mutation
        that bypasses the stamp.
        """
        self.flush()
        if self.hot is None:
            return self.balances
        key = self.mirror.version
        if self._full_cache is None or self._full_cache[0] != key:
            self._full_cache = (
                key, jnp.asarray(self.mirror.table8(self.capacity))
            )
        return self._full_cache[1]
