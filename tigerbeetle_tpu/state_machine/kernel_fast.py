"""Write-behind device apply: fused modular scatter-add flushes.

The TPU link has high per-dispatch latency (the state machine must
never block the commit path on it), so balance updates from fast-path
batches (see tpu.py `_commit_fast` for the admission conditions) are
queued host-side as compact (slot, column, u128 delta) entries and
flushed to the HBM table in large fused scatter-adds, asynchronously —
no host<->device sync anywhere on the hot path.

Overflow admission runs on the host BalanceMirror (mirror.py) before
enqueueing, so the device apply is a pure mod-2^128 addition;
subtractions (pending expiry) ride the same path as two's-complement
deltas. Deltas are accumulated as 4x32-bit limbs in uint64 lanes so
scatter-adds cannot wrap (limb sums < 2^32 * entries), then one carry
pass recombines exact sums.

The exact scan kernel (kernel.py) reads the table through a flush
barrier, so order-dependent batches always see current state.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu.ops import u128 as w

# Flush shape buckets: only a few shapes ever compile.
_FLUSH_BUCKETS = (4096, 32768, 131072, 524288)
# Queue high-water mark: flush (async) once this many entries queue up.
# Low enough that device work overlaps the host commit loop (dispatch is
# async); global compaction at flush time collapses each flush to at
# most accounts*4 entries, so extra flushes cost one small dispatch, not
# duplicated work — and the final drain barrier then waits on almost
# nothing (the device link is high-latency, so a tail-end burst of
# flushes is the worst case).
FLUSH_THRESHOLD = 65_536


def _flush_impl(balances, slots, cols, add_lo, add_hi):
    """balances[slot, col] += delta (mod 2^128), fused over K entries.

    Padding entries use slot 0 / col 0 / amount 0 (a no-op add).
    """
    A = balances.shape[0]
    limbs = w.limbs32(add_lo, add_hi)
    acc = jnp.zeros((A, 4, 4), jnp.uint64)
    acc = acc.at[jnp.clip(slots, 0, A - 1), cols].add(limbs)
    d_lo, d_hi, _ = w.from_limbs32(acc)  # (A, 4); mod 2^128 by design

    old_lo = balances[:, 0::2]
    old_hi = balances[:, 1::2]
    (new_lo, new_hi), _ = w.add((old_lo, old_hi), (d_lo, d_hi))
    return jnp.stack(
        [
            new_lo[:, 0], new_hi[:, 0],
            new_lo[:, 1], new_hi[:, 1],
            new_lo[:, 2], new_hi[:, 2],
            new_lo[:, 3], new_hi[:, 3],
        ],
        axis=-1,
    )


_flush = jax.jit(_flush_impl, donate_argnums=(0,))


class DeviceTable:
    """The authoritative HBM balance table + its write-behind queue."""

    def __init__(self, capacity: int) -> None:
        self.balances = jnp.zeros((capacity, 8), jnp.uint64)
        self._q: list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]] = []
        self._queued = 0

    def grow(self, capacity: int) -> None:
        have = self.balances.shape[0]
        if capacity <= have:
            return
        extra = jnp.zeros((capacity - have, 8), jnp.uint64)
        self.balances = jnp.concatenate([self.balances, extra])

    def enqueue(self, slots, cols, add_lo, add_hi) -> None:
        """Queue compact (slot, col, delta) modular adds."""
        if len(slots) == 0:
            return
        self._q.append(
            (
                np.asarray(slots, np.int32),
                np.asarray(cols, np.int32),
                np.asarray(add_lo, np.uint64),
                np.asarray(add_hi, np.uint64),
            )
        )
        self._queued += len(slots)
        if self._queued >= FLUSH_THRESHOLD:
            self.flush()

    def flush(self) -> None:
        """Dispatch all queued deltas to the device (async, no sync).

        The queue is first re-compacted globally — modular adds merge
        across batches — so one flush covers many commits with at most
        accounts*4 entries, usually landing in the smallest bucket.
        """
        if not self._queued:
            return
        from tigerbeetle_tpu.state_machine.mirror import compact_deltas

        slots = np.concatenate([e[0] for e in self._q]).astype(np.int64)
        cols = np.concatenate([e[1] for e in self._q]).astype(np.int64)
        a_lo = np.concatenate([e[2] for e in self._q])
        a_hi = np.concatenate([e[3] for e in self._q])
        self._q.clear()
        self._queued = 0
        # Compact in bounded chunks (exactness limit of compact_deltas),
        # then once more over the per-chunk sums.
        chunk = (1 << 21) - 1
        if len(slots) > chunk:
            parts = [
                compact_deltas(
                    slots[i : i + chunk], cols[i : i + chunk],
                    a_lo[i : i + chunk], a_hi[i : i + chunk],
                )
                for i in range(0, len(slots), chunk)
            ]
            slots = np.concatenate([p[0] for p in parts])
            cols = np.concatenate([p[1] for p in parts])
            a_lo = np.concatenate([p[2] for p in parts])
            a_hi = np.concatenate([p[3] for p in parts])
        u_slot, u_col, d_lo, d_hi, _ = compact_deltas(slots, cols, a_lo, a_hi)

        at = 0
        while at < len(u_slot):
            take = min(len(u_slot) - at, _FLUSH_BUCKETS[-1])
            bucket = next(b for b in _FLUSH_BUCKETS if b >= take)
            pad = np.zeros(bucket, np.int64)
            pslots, pcols = pad.copy(), pad.copy()
            plo = np.zeros(bucket, np.uint64)
            phi = np.zeros(bucket, np.uint64)
            pslots[:take] = u_slot[at : at + take]
            pcols[:take] = u_col[at : at + take]
            plo[:take] = d_lo[at : at + take]
            phi[:take] = d_hi[at : at + take]
            self.balances = _flush(
                self.balances,
                jnp.asarray(pslots.astype(np.int32)),
                jnp.asarray(pcols.astype(np.int32)),
                jnp.asarray(plo), jnp.asarray(phi),
            )
            at += take

    def read(self):
        """Flush barrier + current device handle (still async)."""
        self.flush()
        return self.balances
