"""Benchmark driver + load generator (reference:
src/tigerbeetle/benchmark_driver.zig, benchmark_load.zig).

With no --addresses, formats a temp single-replica data file and runs
the server in-process on a background thread (the reference spawns a
temp cluster the same way), then streams `create_transfers` batches
through the real client/wire/VSR/state-machine stack and reports
throughput and batch-latency percentiles.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time

import numpy as np

from tigerbeetle_tpu import constants as cfg


def run_benchmark(*, addresses: str | None, cluster: int, n_transfers: int,
                  n_accounts: int, batch: int, use_cpu: bool,
                  seed: int = 42, statsd_port: int | None = None) -> dict:
    from tigerbeetle_tpu.client import Client

    server = None
    thread = None
    tmp = None
    if addresses is None:
        from tigerbeetle_tpu.cli import _sm_factory
        from tigerbeetle_tpu.runtime.server import (
            ReplicaServer,
            format_data_file,
        )

        tmp = tempfile.TemporaryDirectory(prefix="tb_bench_")
        path = os.path.join(tmp.name, "bench.tigerbeetle")
        format_data_file(path, cluster=cluster)
        server = ReplicaServer(
            path, cluster=cluster, addresses=["127.0.0.1:0"], replica_index=0,
            state_machine_factory=_sm_factory(use_cpu),
        )
        address = f"127.0.0.1:{server.port}"
        server._stop = False

        def loop():
            while not server._stop:
                server.poll_once(timeout_ms=1)

        thread = threading.Thread(target=loop, daemon=True)
        thread.start()
    else:
        address = addresses.split(",")[0]

    try:
        client = Client(address, cluster, timeout_ms=120_000)
        rng = np.random.default_rng(seed)

        # Accounts.
        for at in range(0, n_accounts, batch):
            n = min(batch, n_accounts - at)
            client.create_accounts(
                [{"id": at + i + 1, "ledger": 1, "code": 1} for i in range(n)]
            )

        # Transfer batches, pre-generated (generation isn't timed).
        from tigerbeetle_tpu.types import TRANSFER_DTYPE

        batches = []
        next_id = 1_000_000
        remaining = n_transfers
        while remaining > 0:
            n = min(batch, remaining)
            arr = np.zeros(n, TRANSFER_DTYPE)
            arr["id_lo"] = np.arange(next_id, next_id + n, dtype=np.uint64)
            dr = rng.integers(1, n_accounts + 1, n, np.uint64)
            arr["debit_account_id_lo"] = dr
            arr["credit_account_id_lo"] = dr % np.uint64(n_accounts) + np.uint64(1)
            arr["amount_lo"] = rng.integers(1, 100, n, np.uint64)
            arr["ledger"] = 1
            arr["code"] = 1
            batches.append(arr)
            next_id += n
            remaining -= n

        latencies = []
        t0 = time.perf_counter()
        for arr in batches:
            b0 = time.perf_counter()
            results = client.create_transfers(arr)
            assert not results, results[:3]
            latencies.append(time.perf_counter() - b0)
        elapsed = time.perf_counter() - t0

        # Query latency (devhub tracks query p100 alongside load tx/s —
        # reference: src/scripts/devhub.zig:36-41).
        query_lat = []
        q_ids = [int(i) for i in rng.integers(1, n_accounts + 1, 100)]
        for _ in range(20):
            q0 = time.perf_counter()
            rows = client.lookup_accounts(q_ids)
            assert len(rows) == len(q_ids)  # one row per requested id
            query_lat.append(time.perf_counter() - q0)
        client.close()

        lat = np.sort(np.array(latencies))
        pct = lambda p: float(lat[min(len(lat) - 1, int(p / 100 * len(lat)))])
        result = {
            "transfers": n_transfers,
            "transfers_per_second": round(n_transfers / elapsed, 1),
            "batch": batch,
            "batch_latency_p50_ms": round(pct(50) * 1e3, 3),
            "batch_latency_p99_ms": round(pct(99) * 1e3, 3),
            "batch_latency_p100_ms": round(float(lat[-1]) * 1e3, 3),
            "query_latency_p100_ms": round(max(query_lat) * 1e3, 3),
        }
        if statsd_port is not None:
            # reference: src/tigerbeetle/benchmark_load.zig:360-380
            # optional StatsD emit of the same metrics.
            from tigerbeetle_tpu.utils.statsd import StatsD

            s = StatsD(port=statsd_port, prefix="benchmark")
            s.gauge("load_accepted_tx_per_s", result["transfers_per_second"])
            s.timing("batch_p100_ms", result["batch_latency_p100_ms"])
            s.timing("batch_p99_ms", result["batch_latency_p99_ms"])
            s.timing("query_p100_ms", result["query_latency_p100_ms"])
            s.close()
        return result
    finally:
        if server is not None:
            server._stop = True
            thread.join(timeout=5)
            server.close()
        if tmp is not None:
            tmp.cleanup()
