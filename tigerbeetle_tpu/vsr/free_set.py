"""FreeSet: deterministic grid-block allocator.

reference: src/vsr/free_set.zig:16-45 — the reserve -> acquire ->
forfeit protocol makes allocation deterministic even when multiple
logical workers (compactions) allocate concurrently: each worker
reserves a contiguous window up front, acquires from its own window,
and forfeits the remainder in a fixed order.  EWAH-compressed at
checkpoint (reference: :27-41).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu.lsm import ewah


@dataclasses.dataclass
class Reservation:
    blocks: np.ndarray  # window of block indices, fixed at reserve time
    acquired: int = 0

    @property
    def size(self) -> int:
        return len(self.blocks)


class FreeSet:
    def __init__(self, block_count: int) -> None:
        self.block_count = block_count
        self.free = np.ones(block_count, bool)
        # Blocks released this checkpoint stay unavailable until the
        # checkpoint durably commits (reference: staging set).
        self.staging = np.zeros(block_count, bool)
        # Released-this-checkpoint blocks that became free at the
        # FREEZE but whose flip is not yet the durable recovery root
        # (async checkpoints): the PREVIOUS superblock's manifest may
        # still reference them, so reuse is quarantined until
        # release_quarantine() after the flip lands.  Empty whenever
        # checkpoints are synchronous (freeze and flip are adjacent).
        self.quarantine = np.zeros(block_count, bool)
        # Blocks inside outstanding reservations (not yet acquired).
        self._reserved_mask = np.zeros(block_count, bool)
        self._reservations = 0

    def count_free(self) -> int:
        return int(self.free.sum())

    # -- reserve/acquire/forfeit (reference: src/vsr/free_set.zig) --

    def reserve(self, blocks_needed: int) -> Reservation:
        """Reserve a window of exactly `blocks_needed` free blocks —
        the window is fixed now, so concurrent reservations allocate
        deterministically regardless of acquire interleaving.
        Quarantined blocks (freed by a checkpoint whose flip is still
        in flight) are excluded: the previous superblock — the durable
        recovery root until the flip lands — may reference them."""
        candidates = np.flatnonzero(
            self.free & ~self._reserved_mask & ~self.quarantine
        )
        assert blocks_needed <= len(candidates), "grid full"
        window = candidates[:blocks_needed].copy()
        self._reserved_mask[window] = True
        self._reservations += 1
        return Reservation(blocks=window)

    def acquire(self, reservation: Reservation) -> int:
        """-> block address (1-based, 0 is the null address)."""
        assert reservation.acquired < reservation.size, "reservation exhausted"
        block = int(reservation.blocks[reservation.acquired])
        reservation.acquired += 1
        self.free[block] = False
        self._reserved_mask[block] = False
        return block + 1

    def forfeit(self, reservation: Reservation) -> None:
        remainder = reservation.blocks[reservation.acquired :]
        self._reserved_mask[remainder] = False
        self._reservations -= 1

    def is_free(self, address: int) -> bool:
        return bool(self.free[address - 1])

    def release(self, address: int) -> None:
        """Stage a block for release at the next checkpoint."""
        assert not self.free[address - 1]
        self.staging[address - 1] = True

    def leaving_live_set(self, addresses):
        """Free OR staged-for-release, vectorized over addresses: such
        blocks' frames may legitimately go stale and peers that
        already checkpointed no longer serve them — the shared
        predicate behind the scrubber's skip and the repair filter."""
        import numpy as np

        idx = np.asarray(addresses, np.int64) - 1
        return self.free[idx] | self.staging[idx]

    def checkpoint(self) -> None:
        """Freeze point: staged releases become free (the checkpoint
        blob encodes them free — it is only ever read once its flip is
        durable) but quarantined from REUSE until the NEXT freeze.
        The next-freeze boundary (rather than "when the flip lands")
        keeps allocation a pure function of the commit stream: flip
        wall time varies per replica, and the replica's checkpoint
        join guarantees freeze N+1 runs after flip N is durable, so
        the quarantine always covers the vulnerable window."""
        assert self._reservations == 0, "checkpoint with open reservations"
        # Replacing the mask IS the release of the previous freeze's
        # quarantine.
        self.quarantine = self.staging.copy()
        self.free |= self.staging
        self.staging[:] = False

    def release_quarantine(self) -> None:
        """Explicit early release — for harnesses that know no older
        superblock can reference the blocks (standalone forests,
        fuzzers modeling a landed flip).  The replica itself never
        calls this: reuse timing must not depend on flip wall time."""
        self.quarantine[:] = False

    def count_reservable(self) -> int:
        return int((self.free & ~self.quarantine).sum())

    # -- persistence --

    def encode(self) -> bytes:
        bits = np.packbits(self.free.view(np.uint8), bitorder="little")
        words = np.zeros((self.block_count + 63) // 64, np.uint64)
        words.view(np.uint8)[: len(bits)] = bits
        return ewah.encode(words)

    @classmethod
    def decode(cls, data: bytes, block_count: int) -> "FreeSet":
        fs = cls(block_count)
        words = ewah.decode(data, (block_count + 63) // 64)
        bits = np.unpackbits(
            words.view(np.uint8), count=block_count, bitorder="little"
        )
        fs.free = bits.astype(bool)
        return fs
