"""Multi-replica VSR consensus (Viewstamped Replication Revisited).

Message-driven port of the reference's replica protocol (reference:
src/vsr/replica.zig — on_request :1494, on_prepare :1557, on_prepare_ok
:1670, commit piggybacking :1792, DVC quorum :9779) on top of the
single-replica commit pipeline in replica.py.  Protocol facts kept:

- Ring replication: the primary sends each prepare to its successor
  only; every backup forwards to the next while journaling in parallel
  (reference: src/vsr/replica.zig:1532-1556).
- Replication quorum: majority of replicas, capped by
  `quorum_replication_max` (reference: src/config.zig:151,
  docs/about/performance.md:48-53).
- Pipeline: up to `pipeline_prepare_queue_max` prepares in flight
  (reference: src/config.zig:149).
- Backups learn commits from the `commit` number piggybacked on later
  prepares plus a periodic commit heartbeat.
- View change: start_view_change broadcast -> do_view_change quorum at
  the new primary (which adopts the longest log of the highest
  log_view) -> start_view installs the canonical tail everywhere.
  View/log_view are persisted to the superblock before participating
  in the new view.
- Repair: `request_prepare(op, checksum)` fetches missing/corrupt
  prepares from peers (reference: src/vsr/replica.zig:2259-2497).

Everything is deterministic: no threads, no wall clock — `tick()`
advances timeouts and the bus delivers messages, so the in-process
cluster (testing/cluster.py) reproduces any seed exactly, the same way
the reference's VOPR does (reference: src/testing/cluster.zig:56-70).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from tigerbeetle_tpu import constants, envcheck, types
from tigerbeetle_tpu.obs import stat_property as obs_stat_property
from tigerbeetle_tpu.state_machine import demuxer
from tigerbeetle_tpu.vsr import superblock as superblock_mod
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.clock import Clock
from tigerbeetle_tpu.vsr.replica import Replica, Session
from tigerbeetle_tpu.vsr.wire import Command, VsrOperation

# Timeout cadences, in ticks (reference tunes these in src/constants.zig;
# ratios preserved: heartbeat << view-change timeout).
PING_TICKS = 2
# Election timeout: ~5s of primary silence (TICK_NS = 10ms).  This must
# comfortably exceed the primary's worst-case scheduling + commit stall
# — an 8190-event durable commit beat runs ~60-100ms, a checkpoint
# several hundred, and on a single-core host (this container: nproc=1)
# co-located replicas legitimately starve each other for over a second
# — or loaded clusters thrash through spurious view changes (observed:
# the replicated benchmark stalling seconds per false election at the
# original 100ms setting).  Deterministic simulation tests drive ticks
# directly, so this only prices real-time failover.
VIEW_CHANGE_TICKS = 500
VIEW_CHANGE_RESEND_TICKS = 4
REPAIR_RETRY_TICKS = 3
# Scrub one block probe per interval: a full cycle over a 4k-block
# grid takes ~minutes at 10ms ticks, matching the reference's
# hours-per-cycle pacing philosophy scaled to test horizons.
SCRUB_INTERVAL_TICKS = 8



# Sentinel: the in-flight request set cannot be determined yet.
UNDECIDABLE = object()

# Virtual tick length for the per-replica monotonic clock; shared with
# the simulator's wall-clock step and the server's tick cadence so
# clock-sync RTT math stays consistent.
TICK_NS = constants.TICK_NS


@dataclasses.dataclass
class PipelineEntry:
    header: np.ndarray
    body: bytes
    ok_replicas: set[int]
    # Logical batch sub-requests [(client, request, event_count)] when
    # this prepare multiplexes several client requests (see
    # state_machine/demuxer.py); None for plain prepares.
    subs: list[tuple[int, int, int]] | None = None
    # False while the PRIMARY's own WAL write for this op is not yet
    # covered by a sync (group commit): the self-vote in ok_replicas
    # must not count toward a commit until then — committing earlier
    # would let commit_min (which rides UNGATED heartbeats and prepare
    # headers) advertise an op with one durable copy fewer than the
    # quorum promises.  flush_group_commit marks entries synced.
    synced: bool = True


class VsrReplica(Replica):
    """A replica wired to a message bus.

    `bus.send(dst_replica, header, body)` / `bus.send_client(client_id,
    header, body)` deliver messages; the harness calls `on_message` and
    `tick`.
    """

    def __init__(self, storage, cluster, state_machine, bus, *,
                 replica: int, replica_count: int,
                 standby_count: int = 0,
                 release: int = 1,
                 releases_available: tuple[int, ...] = (1,),
                 aof=None) -> None:
        super().__init__(storage, cluster, state_machine,
                         replica=replica, replica_count=replica_count,
                         aof=aof)
        self.bus = bus
        # Standbys (reference: replicas beyond replica_count in the
        # cluster topology): journal prepares, commit, repair, and
        # state-sync like backups, but never ack (prepare_ok), never
        # vote in view changes, and never become primary — hot spares
        # that don't count toward (or endanger) any quorum.
        self.standby_count = standby_count
        self.standby = replica >= replica_count
        self.status = "recovering"
        self.log_view = 0
        # Identity membership until a reconfigure op (or a restored
        # superblock) says otherwise: slot k is process k.
        self.members = list(range(replica_count + standby_count))

        # Multiversion upgrades (reference: src/vsr/replica.zig:4298
        # replica_release_execute, Operation.upgrade, `release` in every
        # header).  `release` is what we RUN; `releases_available` is
        # what the installed binary bundle COULD run.  Peers advertise
        # their max available release on pings; when every replica can
        # run something newer, the primary replicates an upgrade op,
        # and committing it sets upgrade_target — the process then
        # re-executes into the new release (the harness/operator
        # restarts it with release=target).
        assert release in releases_available
        self.release = release
        self.releases_available = tuple(sorted(releases_available))
        self.peer_release: dict[int, int] = {
            replica: max(self.releases_available)
        }
        self.upgrade_target: int | None = None
        self._upgrade_proposed = False

        majority = replica_count // 2 + 1
        self.quorum_replication = min(
            majority, self.config.quorum_replication_max
        )
        self.quorum_view_change = majority

        self.pipeline: dict[int, PipelineEntry] = {}
        self.request_queue: list[tuple[np.ndarray, bytes]] = []
        self._queued_keys: set[tuple[int, int]] = set()
        # Admission control (runtime/server.py sets both): bound on
        # the request queue — None = unbounded (sim clusters) — and
        # an owner callback fired per shed (counters, flight ring).
        self.admit_queue: int | None = None
        self.on_shed = None
        # Multi-tenant QoS (qos.TenantQos; round 16): tenant-keyed
        # admission + weighted-fair drain.  None (the TB_TENANT_QOS=0
        # path) keeps every queue operation on the legacy single-FIFO
        # code exactly.  When set, `_queue_tenants` mirrors
        # request_queue entry-for-entry with each request's tenant
        # (ledger), so per-tenant depths and the WFQ pick index are
        # one list scan over small ints, bounded by admit_queue.
        self.qos = None
        self._queue_tenants: list[int] = []
        # tenant -> queued-request count, maintained incrementally on
        # enqueue/pop/clear: admission and the busy payload read a
        # tenant's depth per fresh request, and a list .count() there
        # would put an O(admit_queue) scan on the ingest hot path.
        self._tenant_depth: dict[int, int] = {}
        self._last_pop_tenant: int | None = None
        # Weighted-fair drain engages only inside an OVERLOAD EPISODE:
        # the first shed opens it, the queue running empty closes it.
        # Outside an episode the queue is strict FIFO and batch
        # lookahead reads the global head — bit-identical to the
        # TB_TENANT_QOS=0 path (the differential contract: QoS on
        # under non-overload load must not reorder anything).
        self._qos_episode = False

        # Cluster clock synchronization (reference: src/vsr/clock.zig).
        self.clock = Clock(replica, replica_count)
        # Local monotonic ns: tick-advanced in the simulator; a real
        # runtime sets monotonic_external and feeds time.monotonic_ns()
        # so RTT error bounds reflect real elapsed time.
        self.monotonic = 0
        self.monotonic_external = False

        # Timers.
        self._ticks = 0
        self._last_primary_seen = 0
        self._last_ping_sent = 0
        self._last_clock_ping = 0
        self._vc_last_sent = 0
        self._repair_last_sent = 0
        self._sync_last_requested = -10**9

        # Grid scrubber + automated peer block repair (reference:
        # src/vsr/grid_scrubber.zig, src/vsr/grid_blocks_missing.zig).
        # The forest writes grid blocks only at checkpoint and
        # checkpoints are byte-identical cluster-wide, so any peer at
        # the same checkpoint_op holds an intact copy of every live
        # block.
        self.scrubber = None
        if self.forest is not None:
            from tigerbeetle_tpu.vsr.scrubber import GridScrubber

            # Pace a full tour across ~4096 scrub ticks, one small
            # read burst each (reference cycle pacing).
            self.scrubber = GridScrubber(
                self.forest.grid, cycle_ticks=4096, blocks_per_tick_max=8
            )
            # Scrub progress rides the registry as pull gauges: the
            # scrubber owns its tour counters; snapshots read them.
            scrubber = self.scrubber
            self.metrics.gauge_fn(
                "scrub.blocks_verified", lambda: scrubber.blocks_verified
            )
            self.metrics.gauge_fn("scrub.cycles", lambda: scrubber.cycles)
            self.metrics.gauge_fn(
                "scrub.faults_found", lambda: scrubber.faults_found
            )
        self._blocks_missing: set[int] = set()
        self._block_repair_last = -10**9
        self._block_repair_attempt = 0
        self._stats["stat_blocks_repaired"] = self.metrics.counter(
            "blocks_repaired"
        )
        # WAL scrubber: probes committed journal slots for latent
        # sector errors, self-healing the redundant header ring from
        # memory and fetching corrupt prepares from peers pinned by
        # their canonical checksum.
        self._wal_scrub_cursor = 0
        self._wal_scrub_attempt = 0
        self._wal_scrub_wanted: dict[int, int] = {}
        self._stats["stat_wal_scrub_repaired"] = self.metrics.counter(
            "wal_scrub_repaired"
        )
        # Canonical vouches: op -> checksum of the prepare the current
        # view's history assigns to that op.  The commit path executes
        # an op ONLY with a matching vouch — the parent-linkage check
        # alone cannot reject a stale SIBLING (same parent, different
        # content, e.g. an old primary's pulse superseded by a view
        # change: VOPR seed 8005).  Vouch sources: own prepares
        # (primary), accepted current-view prepares (self + their
        # parent), heartbeat commit checksums, start_view / DVC
        # canonical headers, checksum-pinned repairs.  View transitions
        # clear vouches above commit_min.
        self._vouched: dict[int, int] = {}
        self._installed_canonical: list[np.ndarray] = []
        # The superblock's persisted canonical suffix covers the
        # pipeline-deep HEAD of the uncommitted range, not all of it:
        # under stalled commits (commit_min, op] can grow to
        # journal_slot_count >> the suffix, and overflow truncation
        # then drops coverage of the deeper ops — those are protected
        # by the DVC merge sanitize + canonical-vouch chain walk, as
        # in the reference.  Mirror the reference's invariant family
        # (constants.zig: view_change_headers_max >= pipeline + 3 and
        # <= journal_slot_count) so a config change can't silently
        # shrink the suffix below what the head-anchoring needs.
        assert (
            superblock_mod.VIEW_HEADERS_MAX
            >= self.config.pipeline_prepare_queue_max + 3
        ), "view_headers suffix must cover the pipeline-deep head (+3)"
        assert (
            superblock_mod.VIEW_HEADERS_MAX
            <= self.config.journal_slot_count
        ), "view_headers suffix cannot exceed the journal"
        self._last_retransmit = 0
        self._repair_round = 0

        # Pending canonical-log install after passively entering a view
        # (commits gated until start_view arrives).
        self._canon_pending = False
        # True when we are primary but the canonical head's checksum is
        # unknown (the DVC merge proved ops through op_head committed
        # yet no header for op_head survived into it): preparing new
        # ops against a stale parent_checksum would bake a chain break
        # into the committed log (VOPR seed 170611267), so every
        # prepare path holds until the head is resolved + repaired.
        self._anchor_pending = False
        # View of the header that currently resolves the anchor pin:
        # replies are collected from ALL peers and a higher-view
        # header re-pins, so a single stale peer cannot fix the anchor
        # to a superseded sibling.
        self._anchor_pin_view = -1
        # True while the journal chain between commit_min and the head
        # is not fully verified (stale siblings possible): commits wait.
        self._chain_suspect = False
        # View-change state.
        self._svc_votes: dict[int, set[int]] = {}   # view -> replicas
        self._dvc: dict[int, dict] = {}             # replica -> dvc payload
        # Repair state: op -> checksum we want.
        self._repair_wanted: dict[int, int] = {}
        # Stashed out-of-order prepares: op -> (header, body).
        self._stash: dict[int, tuple[np.ndarray, bytes]] = {}
        # State-sync chunk assembly: blob checksum -> {index: bytes}.
        self._sync_chunks: dict[int, dict[int, bytes]] = {}
        # Throttle: dst replica -> tick of last sync blob sent.
        self._sync_sent: dict[int, int] = {}

        # WAL group commit (deferred-sync): prepares append to the WAL
        # unsynced; ONE covering fdatasync per poll-drain (or per
        # TB_GROUP_COMMIT_MAX_US deadline) is issued by
        # flush_group_commit() BEFORE any prepare_ok / client reply it
        # covers leaves the process — up to a pipeline's worth of
        # prepares share a single durability syscall instead of paying
        # one each.  Only on backends whose deferred sync is crash
        # -equivalent (FileStorage); the deterministic MemoryStorage
        # clusters keep the synchronous path (tests opt in per
        # -instance via storage.supports_deferred_sync).
        self.group_commit_max_us = envcheck.group_commit_max_us()
        self._gc_enabled = (
            bool(getattr(storage, "supports_deferred_sync", False))
            and self.group_commit_max_us > 0
        )
        # Deferred outbound acks: (kind, dst, header, body) released in
        # order by flush_group_commit() after the covering sync.
        self._gc_pending: list[tuple[str, object, np.ndarray, bytes]] = []
        # Leading-edge covering sync riding the WAL worker (disk wait
        # overlaps the drain's commit CPU work) + how many deferred
        # writes it covers.
        self._gc_sync_job = None
        self._gc_sync_cover = 0
        # Sampled trace ids whose WAL writes await the covering sync
        # (drained and stage-stamped by _gc_covering_sync).
        self._gc_trace_ids: list[int] = []
        self._stats["stat_prepares_written"] = self.metrics.counter(
            "prepares_written"
        )
        self._stats["stat_gc_flushes"] = self.metrics.counter("gc_flushes")
        self._h_gc_sync = self.metrics.histogram("gc.sync_us")
        self._c_gc_deferred_acks = self.metrics.counter("gc.deferred_acks")

        # Native commit pipeline (round 20): per-prepare header
        # construction, journal append framing, the in-flight slot
        # table, and the group-commit gate run in
        # native/tb_pipeline.cpp when available; this replica keeps
        # orchestration (view changes, checkpoints, recovery) and the
        # Python pipeline dict stays authoritative for everything the
        # slow paths scan (retransmit, eviction, view-change DVC).
        # The C table mirrors the dict by pairing every mutation site;
        # TB_NATIVE_PIPELINE=0 pins the pure-Python arm bit-identically.
        from tigerbeetle_tpu.runtime import fastpath as _fastpath

        self._np = (
            _fastpath.create_pipeline()
            if envcheck.native_pipeline() == 1
            else None
        )
        # Per-prepare Python wall time (µs) on the primary's hot path —
        # the `decode_us_per_event`-style instrument the native arm is
        # graded against.  The replica registry grafts into the server
        # snapshot under "vsr.", so these scrape as vsr.prepare_us /
        # vsr.prepare_ok_us.  prepare_us times the primary's header
        # build + pipeline bookkeeping; prepare_ok_us times the
        # backup's ack build (body-independent, so the native-vs-
        # Python delta stays visible under group-commit coalescing).
        # unit_scale=16 widens the sub-µs floor (1/16-µs buckets below
        # 1 µs) so the native drain's amortized per-prepare cost stays
        # resolvable instead of collapsing into bucket 0.
        self._h_prepare_us = self.metrics.histogram("prepare_us",
                                                    unit_scale=16)
        self._h_prepare_ok_us = self.metrics.histogram("prepare_ok_us",
                                                       unit_scale=16)

        # C-resident drain loop (round 22): whole prepare/ack runs
        # cross into native/tb_pipeline.cpp as ONE call per batch seam
        # (tb_pl_build_prepares / tb_pl_accept_prepares / tb_pl_on_acks
        # / tb_pl_commit_ready_run) — Python keeps the per-BATCH
        # orchestration plus every slow path (dedupe misses, QoS
        # shedding, view change, checkpoint, recovery, commit
        # execution).  TB_NATIVE_DRAIN=0 pins the per-item loop over
        # the SAME batch seams, so the 0/1 frames are structurally
        # bit-identical.  native_calls counts batch C crossings;
        # py_fallbacks counts items that took a per-item arm while the
        # drain loop was on (ineligible run, non-gc mode, arena
        # overflow) — the "one call per drain" scrape assertion.
        self._c_drain_native = self.metrics.counter("drain.native_calls")
        self._c_drain_fallback = self.metrics.counter("drain.py_fallbacks")
        # Hash-once counters (_c_hash_bytes / _c_hash_reuse /
        # _c_hash_commit, _hash_reuse) are inherited from the base
        # Replica __init__ — see vsr/replica.py for the counting
        # contract.
        self._drain_native = False
        if envcheck.native_drain() == 1:
            err = _fastpath.drain_error()
            if err is not None and envcheck.env_is_set("TB_NATIVE_DRAIN"):
                # Explicit TB_NATIVE_DRAIN=1 against a loaded-but-
                # stale library: fail fast with the rebuild hint (the
                # r20 forensics extended to the batch symbols).
                raise RuntimeError(err)
            self._drain_native = (
                err is None
                and self._np is not None
                and _fastpath.drain_available()
            )

    # Compatibility properties over the registry handles (obs).
    stat_blocks_repaired = obs_stat_property("stat_blocks_repaired")
    stat_wal_scrub_repaired = obs_stat_property("stat_wal_scrub_repaired")
    stat_prepares_written = obs_stat_property("stat_prepares_written")
    stat_gc_flushes = obs_stat_property("stat_gc_flushes")

    # ------------------------------------------------------------------

    def primary_index(self, view: int | None = None) -> int:
        return (self.view if view is None else view) % self.replica_count

    # ------------------------------------------------------------------
    # Reconfiguration (reference: src/vsr.zig:273-311): protocol slots
    # are stable; a committed epoch bump re-assigns which PROCESS fills
    # each slot (standby promotion: swap a dead active's slot with a
    # standby's — the standby has been replicating all along, so it
    # carries the state its new active role needs).

    def _member_total(self) -> int:
        return self.total_count

    def _apply_membership(self, members: list[int]) -> None:
        members = list(members)
        slot = members.index(self.process_index)
        self.replica = slot
        self.standby = slot >= self.replica_count
        if hasattr(self.bus, "set_slot_map"):
            self.bus.set_slot_map(members)
        # Clock samples are slot-keyed; restart sampling under the new
        # identity (commits gate on resynchronization, briefly).
        self.clock = Clock(slot, self.replica_count)
        self.peer_release = {slot: max(self.releases_available)}

    @property
    def is_primary(self) -> bool:
        return self.status == "normal" and self.primary_index() == self.replica

    def open(self, *, replay_tail: bool | None = None) -> None:
        super().open(replay_tail=replay_tail)
        self.log_view = int(self.superblock.working["log_view"])
        self.status = "normal"
        self.commit_max = self.commit_min
        # Restore the durable canonical-log claim: journal recovery
        # can understate it (prepares never fetched before the crash),
        # and an understating DVC let a view-change quorum truncate
        # committed ops (VOPR seed 1064614514).  Missing bodies repair
        # through the rejoin below.
        recovered_head = self.op
        self.op = max(self.op, int(self.superblock.working["op_claimed"]))
        if self.op > recovered_head:
            # The claimed head's prepare is not in our journal: the
            # anchor is unknown, and a chain walk from the recovered
            # head's checksum would derive garbage pins.  Hold until
            # the head resolves (pin 0 -> request_headers -> repair).
            self._anchor_pending = True
            self._repair_wanted[self.op] = 0
            self._anchor_pin_view = -1
        # An unexecuted journal tail above the checkpoint must be
        # confirmed by the cluster before this replica may commit or
        # serve: rejoin through a view change, whose DVC quorum
        # establishes the canonical log (VSR recovery — the reference's
        # .recovering_head rejoins the same way).
        self._recovering_tail = (
            self.replica_count > 1 and self.op > self.commit_min
        )

    # ------------------------------------------------------------------
    # Tick: timeouts.

    def tick(self) -> None:
        self._ticks += 1
        if self._recovering_tail:
            self._recovering_tail = False
            if self.primary_index() == self.replica:
                # We'd be the primary: only a DVC round can establish
                # the canonical log.
                self._start_view_change(self.view + 1)
            else:
                # Non-disruptive rejoin: gate commits and ask the live
                # primary for the canonical view state; the normal
                # heartbeat timeout escalates to a view change if the
                # primary is gone.
                self._canon_pending = True
                self._request_start_view()
        if not self.monotonic_external:
            self.monotonic += TICK_NS
        if self.total_count > 1 and not self.standby:
            # Pings double as release advertisement, so a solo active
            # with standbys still pings (upgrades gate on EVERY
            # process's release, standbys included).
            if self._ticks - self._last_clock_ping >= PING_TICKS:
                self._send_clock_pings()
            self.clock.expire(self.monotonic)
        if self.status == "normal":
            if self.is_primary:
                if self._ticks - self._last_ping_sent >= PING_TICKS:
                    self._send_heartbeat()
                self._drain_request_queue()
                self._maybe_pulse()
                self._maybe_propose_upgrade()
                if self.pipeline and (
                    self._ticks - self._last_retransmit >= REPAIR_RETRY_TICKS
                ):
                    self._retransmit_pipeline()
            else:
                if self._ticks - self._last_primary_seen >= VIEW_CHANGE_TICKS:
                    if self.standby:
                        # Cannot vote a new view in: poll the actives
                        # for the canonical state instead.
                        self._last_primary_seen = self._ticks
                        self._request_start_view()
                    else:
                        self._start_view_change(self.view + 1)
        elif self.status == "view_change":
            if self._ticks - self._vc_last_sent >= VIEW_CHANGE_RESEND_TICKS:
                self._broadcast_svc()
        if self._repair_wanted and (
            self._ticks - self._repair_last_sent >= REPAIR_RETRY_TICKS
        ):
            self._send_repair_requests(force=True)
        if self.status == "normal" and self._ticks % SCRUB_INTERVAL_TICKS == 0:
            self._wal_scrub_tick()
        if self.scrubber is not None and self.status == "normal":
            if self._ticks % SCRUB_INTERVAL_TICKS == 0:
                self._blocks_missing.update(self.scrubber.tick())
            if self._blocks_missing and self.replica_count > 1 and (
                self._ticks - self._block_repair_last >= REPAIR_RETRY_TICKS
            ):
                self._send_request_blocks()
        if (
            self._canon_pending
            and self.status == "normal"
            and self._ticks % VIEW_CHANGE_RESEND_TICKS == 0
        ):
            self._request_start_view()

    def _retransmit_pipeline(self) -> None:
        """Re-send the lowest non-quorate prepare directly to every
        backup: recovers lost prepares and routes around a broken ring
        (reference repairs these via request_prepare timeouts)."""
        self._last_retransmit = self._ticks
        op = min(self.pipeline)
        entry = self.pipeline[op]
        for r in range(self.replica_count):
            if r != self.replica and r not in entry.ok_replicas:
                self.bus.send(r, entry.header, entry.body)

    def _prepare_headroom(self, pending: int = 0) -> bool:
        """True while the NEXT prepare's ring slot would not overwrite
        an op above the checkpoint.  Replay and repair need every op in
        (checkpoint_op, op]; without this bound a commit stall plus
        repeated view changes (each clears the pipeline, letting a new
        primary accept another pipeline's worth of requests) pushed op
        67 past the stuck commit point and the ring wrap destroyed the
        only copies of two uncommitted ops cluster-wide (VOPR seed
        202019721).  `pending` counts plan-deferred prepares that have
        not advanced self.op yet (the r22 drain plan)."""
        return (
            self.op + pending + 1
            <= self.checkpoint_op + self.config.journal_slot_count
        )

    def _maybe_propose_upgrade(self) -> None:
        """Replicate Operation.upgrade once EVERY replica advertises a
        release newer than the one we run (reference: the primary
        coordinates the upgrade so the cluster switches atomically at
        one op)."""
        if self._upgrade_proposed or self.upgrade_target is not None:
            return
        if self.replica_count > 1 and not self.clock.synchronized:
            return  # same clock gate as every other prepare path
        if len(self.peer_release) < self.total_count:
            return
        target = min(self.peer_release.values())
        if target <= self.release:
            return
        if len(self.pipeline) >= self.config.pipeline_prepare_queue_max:
            return
        if self._anchor_pending:
            return  # canonical head checksum still being repaired
        if not self._prepare_headroom():
            return
        self._upgrade_proposed = True
        req = wire.make_header(
            command=Command.request, operation=VsrOperation.upgrade,
            cluster=self.cluster, view=self.view,
        )
        body = int(target).to_bytes(8, "little")
        wire.finalize_header(req, body)
        self._primary_prepare(req, body)

    def _maybe_pulse(self) -> None:
        """Self-clocked expiry (reference: src/vsr/replica.zig:3126-3143):
        the primary turns due timeouts into a replicated pulse op."""
        if len(self.pipeline) >= self.config.pipeline_prepare_queue_max:
            return
        if self.replica_count > 1 and not self.clock.synchronized:
            return  # same clock gate as client requests
        if self._anchor_pending:
            return  # canonical head checksum still being repaired
        if not self._prepare_headroom():
            return
        self._advance_prepare_timestamp()
        if not self.sm.pulse_needed():
            return
        req = wire.make_header(
            command=Command.request, operation=types.Operation.pulse,
            cluster=self.cluster, view=self.view,
        )
        wire.finalize_header(req, b"")
        self._primary_prepare(req, b"")

    @property
    def total_count(self) -> int:
        """Actives + standbys."""
        return self.replica_count + self.standby_count

    def _send_heartbeat(self) -> None:
        self._last_ping_sent = self._ticks
        # Body: freshest ADOPTED membership advertisement (see
        # _on_commit — committed epoch moves only via the op stream).
        body = self._membership_advert()
        h = wire.make_header(
            command=Command.commit, cluster=self.cluster, view=self.view,
            replica=self.replica, commit=self.commit_min,
            # Canonical checksum of the prepare at commit_min, so
            # backups vouch their local copy before executing
            # (reference: Command.commit carries commit_checksum).
            context=self.commit_parent or 0,
        )
        wire.finalize_header(h, body)
        for r in range(self.total_count):
            if r != self.replica:
                self.bus.send(r, h, body)

    # ------------------------------------------------------------------
    # Message dispatch.

    def on_message(self, header: np.ndarray, body: bytes,
                   verified: bool = False) -> None:
        # `verified=True`: the server's drain already ran the checksum
        # verification (columnar batch pass) — re-hashing every body
        # here doubled the per-message decode cost for years.
        if not verified and not wire.verify_header(header, body):
            return
        if wire.u128(header, "cluster") != self.cluster:
            return
        try:
            cmd = Command(int(header["command"]))
        except ValueError:
            # Unknown command byte (e.g. a client_busy shed bounced
            # off a forwarded request, or a newer peer): drop, never
            # crash the protocol loop.
            return
        handler = {
            Command.request: self._on_request_msg,
            Command.prepare: self._on_prepare,
            Command.prepare_ok: self._on_prepare_ok,
            Command.commit: self._on_commit,
            Command.start_view_change: self._on_start_view_change,
            Command.do_view_change: self._on_do_view_change,
            Command.start_view: self._on_start_view,
            Command.request_prepare: self._on_request_prepare,
            Command.request_headers: self._on_request_headers,
            Command.headers: self._on_headers,
            Command.request_start_view: self._on_request_start_view,
            Command.request_sync_checkpoint: self._on_request_sync,
            Command.sync_checkpoint: self._on_sync_checkpoint,
            Command.request_blocks: self._on_request_blocks,
            Command.block: self._on_block,
            Command.ping: self._on_ping,
            Command.pong: self._on_pong,
        }.get(cmd)
        if handler is not None:
            handler(header, body)

    # ------------------------------------------------------------------
    # Normal operation: primary.

    def _on_request_msg(self, header: np.ndarray, body: bytes) -> None:
        if self.status != "normal":
            return
        if not self.is_primary:
            # Forward to the primary (clients may have a stale view).
            self.bus.send(self.primary_index(), header, body)
            return
        operation = int(header["operation"])
        if operation in (
            int(VsrOperation.stats), int(VsrOperation.state_root)
        ):
            # Admin scrape / proof-of-state query: answered by the
            # server loop (obs/scrape.py), never prepared — such a
            # request reaching the pipeline would hit the asserting
            # state-machine dispatch at commit.
            return
        if operation >= constants.VSR_OPERATIONS_RESERVED:
            # Malformed client input (unknown op byte, wrong event
            # size, over batch_max) must not reach the state machine's
            # asserting prepare path: drop it here.  Well-behaved
            # clients validate before sending; only a buggy or
            # malicious client hits this.
            try:
                op_enum = types.Operation(operation)
            except ValueError:
                return
            if not self.sm.input_valid(op_enum, body):
                return
        verdict = self._request_dedupe(header)
        if verdict is not None:
            if verdict == "queue":
                self._enqueue_request(header, body)
            else:
                # Duplicate delivery (retransmit / stale number): the
                # ingress verify already hashed this body, and that
                # pass can never be elided — charge it to the dup
                # counter so the reuse ratio stays exact.
                self._c_hash_dup.inc(len(body))
            return
        if (
            len(self.pipeline) >= self.config.pipeline_prepare_queue_max
            or (self.replica_count > 1 and not self.clock.synchronized)
            or self._anchor_pending
            or not self._prepare_headroom()
        ):
            # Pipeline full, no timestamps yet because the cluster
            # clock window doesn't exist (reference: src/vsr/replica.zig
            # on_request gates on realtime_synchronized), or the
            # canonical head checksum is still being repaired: queue
            # and drain from tick()/commit.
            self._enqueue_request(header, body)
            return
        self._primary_prepare(header, body)

    def on_requests_batch(self, headers, bodies) -> None:
        """Columnar request intake (runtime/server.py fast drain): one
        drain's worth of client requests, headers pre-verified and
        decoded in a single batch pass.  Request-level semantics are
        identical to per-message _on_request_msg — at-most-once
        dedupe, admission shed, eviction deferral — but the in-flight
        scan runs ONCE per drain (it walks the pipeline + journal
        tail, and running it per request was O(drain x pipeline)), and
        fresh requests funnel through the queue so one drain drains
        into few multiplexed prepares instead of re-entering the
        prepare path per message."""
        if self.status != "normal":
            return
        # The drain verified checksums, not addressing: a frame for a
        # DIFFERENT cluster must be dropped exactly as on_message
        # drops it (cross-cluster isolation; the legacy arm's behavior).
        keep = [
            i for i, h in enumerate(headers)
            if wire.u128(h, "cluster") == self.cluster
        ]
        if len(keep) != len(headers):
            headers = [headers[i] for i in keep]
            bodies = [bodies[i] for i in keep]
        if not self.is_primary:
            for i, h in enumerate(headers):
                self.bus.send(self.primary_index(), h, bytes(bodies[i]))
            return
        inflight = self._inflight_requests()
        undecidable = inflight is UNDECIDABLE
        # Per-drain dedupe pre-pass (r22): classify the common case —
        # fresh, registered, in-order, not in flight — in one
        # vectorized pass so only exceptions (retransmits, registers,
        # catch-up) walk _request_dedupe's branch ladder.  A True
        # entry is PROVEN to be exactly what _request_dedupe returns
        # None for, with zero side effects skipped; everything else
        # (including a retransmit-of-committed, which must get its
        # stored reply mid-drain, never a busy) drops to the per-
        # request slow path unchanged.
        fast = (
            None if undecidable or not headers
            else self._admit_prepass(headers, inflight)
        )
        for i, h in enumerate(headers):
            operation = int(h["operation"])
            if operation in (
                int(VsrOperation.stats), int(VsrOperation.state_root)
            ):
                continue  # answered by the server loop, never prepared
            body = bytes(bodies[i])
            if operation >= constants.VSR_OPERATIONS_RESERVED:
                try:
                    op_enum = types.Operation(operation)
                except ValueError:
                    continue
                if not self.sm.input_valid(op_enum, body):
                    continue
            if fast is not None and fast[i]:
                verdict = None
            else:
                verdict = self._request_dedupe(h, inflight=inflight)
            if verdict == "drop":
                # Duplicate delivery: its ingress verify pass was
                # unavoidable — charge hash.dup_body_bytes (the reuse
                # ratio's retransmission term), not a reuse miss.
                self._c_hash_dup.inc(len(body))
                continue
            if (
                self.admit_queue is not None
                and len(self.request_queue) >= self.admit_queue
                and len(self.pipeline)
                < self.config.pipeline_prepare_queue_max
                and self._prepare_headroom()
            ):
                # Queue at the admission bound with pipeline room:
                # move what the pipeline can take BEFORE deciding to
                # shed — the per-message path used free pipeline slots
                # directly (they never counted against the queue), so
                # shedding here without draining first would refuse
                # requests the pipeline could hold and diverge the
                # TB_FASTPATH_DECODE arms under overload.  The
                # queue-depth bound itself stays intact (the overload
                # smoke asserts the gauge), and a full pipeline skips
                # the call entirely — draining would no-op after an
                # O(pipeline + tail) in-flight rescan per shed.
                self._drain_request_queue()
            self._enqueue_request(h, body)
            if not undecidable and verdict is None:
                key = (wire.u128(h, "client"), int(h["request"]))
                # Only if actually queued (not shed): a shed duplicate
                # later in the batch must shed again, not "drop".
                if key[0] and key in self._queued_keys:
                    inflight.add(key)
        self._drain_request_queue()

    def _admit_prepass(self, headers, inflight) -> list[bool]:
        """Vectorized fast/slow classification for one drain's request
        batch (r22 satellite).  out[i] is True only when request i is
        PROVABLY what _request_dedupe returns None for with no side
        effects: a non-reserved client op from a registered session,
        request number strictly advancing, no catch-up in progress,
        not in flight, and not a duplicate of any earlier request in
        this same batch.  Everything else — registers, retransmits
        (committed → stored reply), stale numbers, eviction candidates
        — stays on the per-request slow path."""
        arr = np.array(headers)
        ok = (
            ((arr["client_lo"] != 0) | (arr["client_hi"] != 0))
            & (arr["operation"] >= constants.VSR_OPERATIONS_RESERVED)
        )
        if self.commit_min != self.commit_max:
            # Catching up: session entries may predate the
            # re-committing suffix — everything goes slow.
            ok[:] = False
        out: list[bool] = []
        seen: set[tuple[int, int]] = set()
        sessions = self.sessions
        lo, hi, req = arr["client_lo"], arr["client_hi"], arr["request"]
        for i in range(len(headers)):
            client = int(lo[i]) | (int(hi[i]) << 64)
            key = (client, int(req[i]))
            fast = bool(ok[i])
            if fast:
                entry = sessions.get(client)
                fast = (
                    entry is not None
                    and key[1] > entry.request
                    and key not in inflight
                    and key not in seen
                )
            # EVERY key joins `seen`: a later copy of any earlier
            # batch item must take the slow path, where the
            # incrementally-updated inflight set (or the shed state)
            # decides — exactly as the per-item arm does.
            if key[0]:
                seen.add(key)
            out.append(fast)
        return out

    def on_prepare_oks_batch(self, headers: list[np.ndarray]) -> None:
        """A contiguous drain run of prepare_ok frames (runtime/
        server.py): vote the whole run through the slot table in ONE C
        call, then run the commit gate once.  Decision-equivalent to
        per-message _on_prepare_ok: acks emit no frames, and
        _maybe_commit_pipeline commits the ready run in op order with
        the same commit->drain interleaving whether entered after each
        vote or after all of them.  TB_NATIVE_DRAIN=0 pins the
        per-message loop over the same seam (bit-identical frames)."""
        if not self.is_primary:
            return  # per-message arm drops each ack identically
        if not (self._drain_native and self._np is not None):
            for h in headers:
                # on_message's cluster gate, then the per-ack handler.
                if wire.u128(h, "cluster") == self.cluster:
                    self._on_prepare_ok(h, b"")
            return
        arr = np.array(headers)
        _accepted, verdicts = self._np.on_acks(arr, self.cluster, self.view)
        self._c_drain_native.inc()
        voted = False
        for i, h in enumerate(headers):
            if int(verdicts[i]) < 0:
                # -4 cluster / -3 view / -1 unknown op / -2 stale
                # sibling: exactly the per-ack drops (on_message's
                # cluster gate + _on_prepare_ok's early returns).
                continue
            entry = self.pipeline.get(int(h["op"]))
            if entry is None:
                continue  # C table ahead of a just-dropped entry
            entry.ok_replicas.add(int(h["replica"]))
            self.anatomy.stage_h(h, "prepare_ok")
            voted = True
        if voted:
            self._maybe_commit_pipeline()

    def on_prepares_batch(self, headers: list[np.ndarray],
                          bodies: list) -> None:
        """A contiguous drain run of prepare frames (runtime/
        server.py): when the WHOLE run is the steady-state shape — our
        view, normal status, sequential ops extending our head with an
        intact parent chain, no stash/anchor interference — frame
        every WAL write and build every prepare_ok in ONE C call, then
        replay the per-item side effects (journal descriptors,
        replicate, ack routing, commit advance) in legacy order.  The
        run splits at the FIRST deviating frame: the eligible prefix
        still takes the one C call, only the suffix (typically a
        stale duplicate from primary retransmission under load) walks
        per-message _on_prepare — a retransmitted copy must not
        demote the fresh frames ahead of it.  TB_NATIVE_DRAIN=0 pins
        the per-message loop over the same seam (bit-identical
        frames)."""
        split = 0
        if (
            self._drain_native
            and self._np is not None
            and self._gc_enabled  # framed writes are unsynced-only
            and self.journal._native_frame
            and self.status == "normal"
            and not self.is_primary
            and not self._anchor_pending
            # A stashed successor could double-accept the run's next
            # op via _drain_stash; per-message handles that ordering.
            and not self._stash
        ):
            op0 = self.op + 1
            parent = self.parent_checksum
            split = len(headers)
            for i, h in enumerate(headers):
                if (
                    wire.u128(h, "cluster") != self.cluster
                    or int(h["view"]) != self.view
                    or int(h["op"]) != op0 + i
                    or wire.u128(h, "parent") != parent
                ):
                    split = i
                    break
                parent = wire.u128(h, "checksum")
        rest_h, rest_b = headers[split:], bodies[split:]
        if rest_h and self._drain_native:
            self._c_drain_fallback.inc(len(rest_h))
        if split == 0:
            for i, h in enumerate(rest_h):
                # on_message's cluster gate, then the per-msg handler.
                if wire.u128(h, "cluster") == self.cluster:
                    self._on_prepare(h, bytes(rest_b[i]))
            return
        headers, bodies = headers[:split], bodies[:split]

        from tigerbeetle_tpu.constants import SECTOR_SIZE
        from tigerbeetle_tpu.runtime import fastpath as _fastpath
        from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR

        self._last_primary_seen = self._ticks
        k = len(headers)
        bodies = [bytes(b) for b in bodies]
        arr = np.array(headers)
        build_oks = not self.standby
        t0 = time.perf_counter_ns()
        accepted = _fastpath.accept_prepares(
            arr, bodies, view=self.view, replica=self.replica,
            build_oks=build_oks,
            headers_ring=self.journal.headers,
            slot_count=self.journal.slot_count,
            headers_per_sector=HEADERS_PER_SECTOR,
            sector_size=SECTOR_SIZE,
        )
        batch_ns = time.perf_counter_ns() - t0
        if accepted is None:
            raise RuntimeError(
                "native drain: accept arena refused exact-sized run"
            )
        self._c_drain_native.inc()
        oks, frames = accepted
        wal_arena, wal_off, wal_len, slots, sector_arena, sector_index = (
            frames
        )
        per_item_us = batch_ns / k / 1000.0
        wal_mv = memoryview(wal_arena)
        sector_mv = memoryview(sector_arena)
        for i, h in enumerate(headers):
            op = int(h["op"])
            off = int(wal_off[i])
            length = int(wal_len[i])
            self._journal_write_framed(
                h, len(bodies[i]), wal_mv[off:off + length],
                int(slots[i]),
                sector_mv[i * SECTOR_SIZE:(i + 1) * SECTOR_SIZE],
                int(sector_index[i]),
            )
            self.op = op
            self.parent_checksum = wire.u128(h, "checksum")
            self._vouched[op] = self.parent_checksum
            if op - 1 > self.commit_min:
                self._vouched.setdefault(op - 1, wire.u128(h, "parent"))
            self._repair_wanted.pop(op, None)
            self._replicate(h, bodies[i])
            if build_oks:
                self.tracer.instant("prepare_ok", op=op)
                self._gc_send(self.primary_index(), oks[i], b"")
            self._h_prepare_ok_us.observe(per_item_us)
            # Legacy order: each message's commit field advances the
            # backup commit point before the next message is handled.
            self._advance_commit(int(h["commit"]))
        # The deviating suffix (if any) runs per-message AFTER the
        # prefix — exactly the order the per-item arm would process
        # the run in.
        for i, h in enumerate(rest_h):
            if wire.u128(h, "cluster") == self.cluster:
                self._on_prepare(h, bytes(rest_b[i]))

    def _enqueue_request(self, header: np.ndarray, body: bytes,
                         readmit: bool = False) -> None:
        """Queue a request exactly once: broadcast retransmissions of
        the same (client, request) must not pile up (a batched drain
        would execute every copy).

        Admission control lives HERE, after the at-most-once gate —
        a retransmission of an already-committed request must get its
        stored reply even under overload, never a busy (shedding at
        the server's raw-message layer had exactly that bug).  A
        fresh request past the `admit_queue` bound — or, with QoS on,
        past its TENANT's token bucket / queue bound — is shed with a
        typed Command.client_busy: session intact, client may retry.
        `readmit` (a "queue"-verdict request cycling back from the
        drain) skips the token bucket: its arrival was already
        charged once."""
        key = (wire.u128(header, "client"), int(header["request"]))
        if key in self._queued_keys:
            return
        tenant = None
        if self.qos is not None:
            tenant = wire.tenant_of(header, body)
            if not readmit:
                self.qos.observe(tenant, self.monotonic)
        # Global bound FIRST: a request the full queue sheds anyway
        # must not consume one of its tenant's tokens (an unrefunded
        # charge here would let a flood that fills the global queue
        # drain a victim tenant's bucket, throttling the victim below
        # its configured rate after the queue clears).
        if self.admit_queue is not None and (
            len(self.request_queue) >= self.admit_queue
        ):
            self._shed_request(header, tenant)
            return
        if self.qos is not None and not readmit:
            if not self.qos.admit(
                tenant, self.monotonic,
                self._tenant_depth.get(tenant, 0),
                body_bytes=len(body),
            ):
                self._shed_request(header, tenant)
                return
        self._queued_keys.add(key)
        self.anatomy.stage_h(header, "queued")
        self.request_queue.append((header, body))
        if self.qos is not None:
            self._queue_tenants.append(tenant)
            self._tenant_depth[tenant] = (
                self._tenant_depth.get(tenant, 0) + 1
            )
            if not readmit:
                self.qos.on_admit(tenant)

    def _shed_request(self, header: np.ndarray,
                      tenant: int | None = None) -> None:
        """Typed load shed: the queue (global or the tenant's) is
        full.  The busy reply rides the client's registered connection
        (a request forwarded from a backup has none here — its client
        recovers by retransmit timeout, which is the legacy-client
        path anyway).  With QoS on the body carries WHO was shed and
        the rate the server observed for that tenant (wire.busy_body)
        so the client can size its backoff; QoS off keeps the legacy
        empty body bit-identically."""
        client = wire.u128(header, "client")
        payload = b""
        if self.qos is not None and tenant is not None:
            payload = wire.busy_body(
                tenant, self._tenant_depth.get(tenant, 0),
                self.qos.rate_of(tenant),
            )
            self.qos.on_shed(tenant)
            # First shed opens an overload episode: weighted-fair
            # drain engages until the queue next runs empty.
            self._qos_episode = True
        busy = wire.make_header(
            command=Command.client_busy, cluster=self.cluster,
            client=client, request=int(header["request"]),
            replica=self.replica, view=self.view,
        )
        wire.copy_trace(busy, header)
        wire.finalize_header(busy, payload)
        if client:
            self.bus.send_client(client, busy, payload)
        if self.on_shed is not None:
            self.on_shed(header, tenant)

    def _pop_request(self, tenant: int | None = None,
                     ) -> tuple[np.ndarray, bytes]:
        """FIFO head when QoS is off or no overload episode is open;
        weighted-fair across tenant FIFOs inside an episode (`tenant`
        pins the pick — logical-batch continuation stays within one
        tenant, so inside an episode a prepare's multiplexed requests
        share one tenant and reply attribution is exact; outside one,
        FIFO batches may mix tenants and attribution is head-of-batch
        approximate — mixed batches only form under non-overload,
        where the per-tenant histograms are not the diagnostic).

        The episode gate is the differential contract: outside an
        episode (no shed since the queue last ran empty) the drain is
        strict FIFO — bit-identical to TB_TENANT_QOS=0 — because a
        weighted-fair pick depends on queue CONTENT at pop time, and
        content varies with ingest drain cadence (per-message vs
        columnar batch) even when arrivals are identical."""
        idx = 0
        if self.qos is not None:
            if self._qos_episode:
                if tenant is None:
                    tenant = self.qos.pick(self._queue_tenants)
                idx = self._queue_tenants.index(tenant)
            self._last_pop_tenant = self._queue_tenants.pop(idx)
            depth = self._tenant_depth.get(self._last_pop_tenant, 0) - 1
            if depth > 0:
                self._tenant_depth[self._last_pop_tenant] = depth
            else:
                self._tenant_depth.pop(self._last_pop_tenant, None)
        h, b = self.request_queue.pop(idx)
        self._queued_keys.discard(
            (wire.u128(h, "client"), int(h["request"]))
        )
        if self.qos is not None and not self.request_queue:
            # Queue drained: the overload episode (if any) is over;
            # the next pops are FIFO again until the next shed.
            self._qos_episode = False
        return h, b

    def _queue_peek(self, tenant: int | None,
                    ) -> tuple[np.ndarray, bytes] | None:
        """The next request a `_pop_request(tenant)` would return —
        the queue head (legacy / outside an episode), or the tenant's
        FIFO head (weighted-fair episode)."""
        if self.qos is None or not self._qos_episode:
            return self.request_queue[0] if self.request_queue else None
        try:
            return self.request_queue[self._queue_tenants.index(tenant)]
        except ValueError:
            return None

    def _request_dedupe(
        self, header: np.ndarray, in_queue: bool = False,
        peek: bool = False, inflight=None,
    ) -> str | None:
        """At-most-once gate, shared by request arrival and queue drain.

        -> None ("fresh: prepare it"), "drop" (duplicate/stale/handled),
        or "queue" (cannot decide yet: catching up or tail not yet
        materialized — retry once current).  `peek` suppresses the
        reply/eviction side effects (batch lookahead must not send
        twice)."""
        client = wire.u128(header, "client")
        request = int(header["request"])
        operation = int(header["operation"])

        if not client:
            return None
        is_register = operation == int(VsrOperation.register)
        entry = self.sessions.get(client)

        if is_register:
            if entry is not None:
                # Re-sent register whose reply was lost: replay it
                # instead of re-committing (a fresh commit would leak a
                # reply slot and evict an innocent session — reference:
                # src/vsr/replica.zig:5035-5100).
                if not peek:
                    # The resume hint must also cover the session's
                    # IN-FLIGHT requests (pipeline/queue/tail): a
                    # failed-over session owner resuming from the
                    # committed number alone collided with its dead
                    # predecessor's uncommitted ops and adopted their
                    # replies (sharded-VOPR seed 2046).  While the
                    # tail is not materialized the bound is unknowable
                    # — defer the replay instead of guessing.
                    inflight_now = self._inflight_requests()
                    if inflight_now is UNDECIDABLE:
                        return "queue"
                    self._send_register_reply(
                        client, entry, inflight_now
                    )
                return "drop"
            # No session yet: fall through to the in-flight scans — a
            # retransmitted register whose original is still in flight
            # must not be prepared twice.
        elif entry is None:
            if (
                self.commit_min < self.commit_max
                or self._canon_pending
                or self._anchor_pending
                or self._chain_suspect
                or self._repair_wanted
                or self._recovering_tail
                # A requeued-uncommitted register can sit in the
                # pipeline awaiting quorum (new primary re-replicating
                # an adopted tail, acks lost — VOPR seed 653186412);
                # bounded scan of <= pipeline_max entries, so no
                # eviction starvation under steady load.
                or any(
                    int(e.header["operation"]) == int(VsrOperation.register)
                    and wire.u128(e.header, "client") == client
                    for e in self.pipeline.values()
                )
                # An adopted-but-unapplied tail not yet covered by the
                # pipeline: a fresh primary with commit_max still 0
                # and repairs pending requeued only the prepares it
                # HELD — the register can sit in the holes (VOPR
                # reconfigure seed 460103075).  Exact membership, not
                # a count: committed entries linger in the pipeline
                # until lazily purged and would mask a hole.  Under
                # steady load the range is <= pipeline depth and fully
                # covered, so this defers nothing then.
                or any(
                    o not in self.pipeline
                    for o in range(self.commit_min + 1, self.op + 1)
                )
            ):
                # Still re-committing, or holding a recovered/claimed
                # journal suffix not yet re-applied: the session may
                # live in that suffix — evicting here killed a
                # registered client whose register op sat in the
                # unapplied tail (VOPR seed 666677761).  Gated on the
                # recovery/repair states (all bounded), NOT on
                # commit_min < self.op, which is true under steady
                # load and would defer legitimate evictions forever.
                return "queue"
            if not peek:
                self._send_eviction(client)
            return "drop"
        else:
            if request == entry.request and request > 0:
                if not peek:
                    self._send_stored_reply(client, entry)
                return "drop"
            if request < entry.request:
                return "drop"  # stale duplicate
            if self.commit_min < self.commit_max:
                # Catching up: the re-committing suffix may already
                # contain this request (our session entry is from an
                # older checkpoint) — preparing it now would execute it
                # twice.
                return "queue"

        # In-flight dedupe: pipeline, queued requests, and the
        # uncommitted journal tail (a prepare adopted via repair never
        # enters OUR pipeline) — a retransmission must not be prepared
        # a second time anywhere (reference: primary pipeline
        # message_by_client lookup).
        if inflight is None:
            inflight = self._inflight_requests(include_queue=not in_queue)
        if inflight is UNDECIDABLE:
            return "queue"
        return "drop" if (client, request) in inflight else None

    def _inflight_requests(self, include_queue: bool = True):
        """(client, request) pairs currently in the pipeline, queue,
        and uncommitted journal tail — or UNDECIDABLE while the tail is
        not fully materialized (repair in flight)."""
        pairs: set[tuple[int, int]] = set()
        for pe in self.pipeline.values():
            c = wire.u128(pe.header, "client")
            if c:
                pairs.add((c, int(pe.header["request"])))
            if pe.subs:
                pairs.update((sc, sr) for sc, sr, _ in pe.subs if sc)
        if include_queue:
            for qh, _ in self.request_queue:
                c = wire.u128(qh, "client")
                if c:
                    pairs.add((c, int(qh["request"])))
        for tail_op in range(self.commit_min + 1, self.op + 1):
            if tail_op in self.pipeline:
                continue  # scanned above
            read = self.journal.read_prepare(tail_op)
            if read is None:
                return UNDECIDABLE
            th, tb = read
            c = wire.u128(th, "client")
            if c:
                pairs.add((c, int(th["request"])))
            t_subs = wire.u128(th, "context")
            if t_subs and (
                int(th["operation"]) >= constants.VSR_OPERATIONS_RESERVED
            ):
                _ev, subs2 = demuxer.decode_trailer(tb, t_subs)
                pairs.update((sc, sr) for sc, sr, _ in subs2 if sc)
        return pairs

    def _advance_prepare_timestamp(self) -> None:
        """Primary timestamping through the synchronized cluster clock:
        the local wall clock is clamped into the Marzullo window before
        it feeds the strictly-monotonic prepare timestamp (reference:
        src/vsr/replica.zig:5762-5772).  Falls back to the raw wall
        clock while unsynchronized (e.g. before the first ping round)."""
        rt = self.clock.realtime_synchronized(self.realtime)
        if rt is None:
            rt = self.realtime
        self.sm.prepare_timestamp = max(
            max(self.sm.prepare_timestamp, self.sm.commit_timestamp) + 1, rt
        )

    def _primary_prepare(
        self, request: np.ndarray, body: bytes,
        subs: list[tuple[int, int, int]] | None = None,
    ) -> None:
        operation = int(request["operation"])
        self._advance_prepare_timestamp()
        if operation >= constants.VSR_OPERATIONS_RESERVED:
            events = demuxer.strip_trailer(body, subs) if subs else body
            self.sm.prepare(types.Operation(operation), events)
        timestamp = self.sm.prepare_timestamp

        op = self.op + 1
        # The instrument times exactly the spans the native pipeline
        # replaces — header build + checksum stamping here, pipeline
        # bookkeeping below — NOT sm.prepare / WAL write / replicate
        # (body-proportional or I/O work both arms share; including it
        # buried the arm delta under disk + scheduler noise).
        t0 = time.perf_counter_ns()
        if self._np is not None:
            # Native arm: one C call builds + checksums the prepare
            # header (client/request/operation/trace copied from the
            # request in C) — bit-identical to the make_header +
            # copy_trace + finalize_header sequence below.
            prepare = self._np.build_prepare(
                request, body, cluster=self.cluster, view=self.view,
                op=op, commit=self.commit_min, timestamp=timestamp,
                parent=self.parent_checksum, replica=self.replica,
                context=len(subs) if subs else 0, release=self.release,
                reuse=self._hash_reuse,
            )
            if self._hash_reuse:
                self._c_hash_reuse.inc()
            else:
                self._c_hash_bytes.inc(len(body))
        else:
            prepare = wire.make_header(
                command=Command.prepare, operation=operation,
                cluster=self.cluster, client=wire.u128(request, "client"),
                request=int(request["request"]), view=self.view,
                op=op, commit=self.commit_min, timestamp=timestamp,
                parent=self.parent_checksum, replica=self.replica,
                context=len(subs) if subs else 0,
                release=self.release,
            )
            # Trace context rides the prepare so every replica's hops
            # key off the same request id (backups record
            # journal_write / prepare_ok against it without any side
            # channel).
            wire.copy_trace(prepare, request)
            if self._hash_reuse:
                # Header-carry reuse (round 23): the request header's
                # checksum_body field IS this body's digest — proven by
                # the ingress verify pass (unit requests) or stamped by
                # _build_batch_request's finalize (coalesced bodies).
                wire.finalize_header(prepare, body, checksum_body=(
                    int(request["checksum_body_lo"]),
                    int(request["checksum_body_hi"]),
                ))
                self._c_hash_reuse.inc()
            else:
                wire.finalize_header(prepare, body)
                self._c_hash_bytes.inc(len(body))
        build_ns = time.perf_counter_ns() - t0
        self.anatomy.stage_h(prepare, "prepare")

        self._journal_write(prepare, body)
        self.op = op
        self.parent_checksum = wire.u128(prepare, "checksum")
        self._vouched[op] = self.parent_checksum  # we ARE the canon
        # A leftover pin for this op named dead-view content; the new
        # prepare supersedes it (a matching stale fill would otherwise
        # overwrite this slot — seed 460991023).
        self._repair_wanted.pop(op, None)
        t1 = time.perf_counter_ns()
        synced = not self._gc_enabled
        self.pipeline[op] = PipelineEntry(
            prepare, body, {self.replica}, subs, synced=synced,
        )
        if self._np is not None:
            self._np.note_prepare(prepare, synced, self.replica)
        build_ns += time.perf_counter_ns() - t1
        self._h_prepare_us.observe(build_ns / 1000.0)
        self._replicate(prepare, body)
        self._maybe_commit_pipeline()

    def _primary_prepare_plan(
        self,
        plan: list[tuple[np.ndarray, bytes, list | None]],
    ) -> None:
        """Materialize a drain plan: the whole run of collected
        (request, body, subs) triples becomes prepares in ONE native
        call (build + checksum + self-vote + WAL framing below Python),
        or — on the TB_NATIVE_DRAIN=0 arm / non-sector-aligned
        journal — the per-item _primary_prepare loop over the same
        plan.  Only reachable with group commit on (see
        _drain_request_queue), so no plan entry can commit before the
        run is fully materialized: both arms emit bit-identical frames
        in identical order."""
        if not plan:
            return
        use_native = (
            self._drain_native
            and self._np is not None
            and self.journal._native_frame
        )
        if not use_native:
            if self._drain_native:
                self._c_drain_fallback.inc(len(plan))
            for head, pbody, subs in plan:
                if subs is not None:
                    self._primary_prepare(head, pbody, subs=subs)
                else:
                    self._primary_prepare(head, pbody)
            return

        from tigerbeetle_tpu.constants import SECTOR_SIZE
        from tigerbeetle_tpu.runtime import fastpath as _fastpath
        from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR

        k = len(plan)
        req_hdrs = np.empty(k, dtype=wire.HEADER_DTYPE)
        timestamps = np.empty(k, dtype=np.uint64)
        contexts = np.empty(k, dtype=np.uint64)
        bodies: list[bytes] = []
        # Pre-work (state-machine prepare + timestamp advance) runs in
        # plan order, exactly as the per-item arm interleaves it with
        # header builds — sm.prepare side effects are order-sensitive.
        for i, (head, pbody, subs) in enumerate(plan):
            operation = int(head["operation"])
            self._advance_prepare_timestamp()
            if operation >= constants.VSR_OPERATIONS_RESERVED:
                events = (
                    demuxer.strip_trailer(pbody, subs) if subs else pbody
                )
                self.sm.prepare(types.Operation(operation), events)
            req_hdrs[i] = head
            timestamps[i] = self.sm.prepare_timestamp
            contexts[i] = len(subs) if subs else 0
            bodies.append(pbody)

        op0 = self.op + 1
        t0 = time.perf_counter_ns()
        built = _fastpath.build_prepares(
            self._np, req_hdrs, bodies, timestamps, contexts,
            cluster=self.cluster, view=self.view, op0=op0,
            commit=self.commit_min, parent=self.parent_checksum,
            replica=self.replica, release=self.release, synced=False,
            headers_ring=self.journal.headers,
            slot_count=self.journal.slot_count,
            headers_per_sector=HEADERS_PER_SECTOR,
            sector_size=SECTOR_SIZE,
            reuse=self._hash_reuse,
        )
        build_ns = time.perf_counter_ns() - t0
        if self._hash_reuse:
            self._c_hash_reuse.inc(k)
        else:
            self._c_hash_bytes.inc(sum(len(b) for b in bodies))
        if built is None:
            # Arena capacity refused (cannot happen with the exact
            # allocation above — belt and braces): nothing was mutated,
            # the per-item arm redoes the run.  sm.prepare already ran,
            # and _primary_prepare re-runs it — sm.prepare is
            # idempotent per (op, events) only at execute time, so
            # instead re-enter via the loop WITHOUT re-prepare by
            # failing hard: this is a programming error.
            raise RuntimeError(
                "native drain: prepare arena refused exact-sized run"
            )
        self._c_drain_native.inc()
        prepares, frames = built
        wal_arena, wal_off, wal_len, slots, sector_arena, sector_index = (
            frames
        )
        per_prepare_us = build_ns / k / 1000.0
        wal_mv = memoryview(wal_arena)
        sector_mv = memoryview(sector_arena)
        for i in range(k):
            prepare = prepares[i]
            op = op0 + i
            self.anatomy.stage_h(prepare, "prepare")
            off = int(wal_off[i])
            length = int(wal_len[i])
            self._journal_write_framed(
                prepare, len(bodies[i]),
                wal_mv[off:off + length], int(slots[i]),
                sector_mv[i * SECTOR_SIZE:(i + 1) * SECTOR_SIZE],
                int(sector_index[i]),
            )
            self.op = op
            self.parent_checksum = wire.u128(prepare, "checksum")
            self._vouched[op] = self.parent_checksum
            self._repair_wanted.pop(op, None)
            # C already registered the slot entry + our self-vote
            # (tb_pl_build_prepares calls note_prepare per item): only
            # the Python-side mirror is created here.
            self.pipeline[op] = PipelineEntry(
                prepare, bodies[i], {self.replica}, plan[i][2],
                synced=False,
            )
            self._h_prepare_us.observe(per_prepare_us)
            self._replicate(prepare, bodies[i])
        self._maybe_commit_pipeline()

    def _replicate(self, prepare: np.ndarray, body: bytes) -> None:
        """Ring forwarding: send to successor only (reference:
        src/vsr/replica.zig:1532-1556).  The primary additionally
        feeds each standby directly; standbys never forward."""
        if self.is_primary:
            for s in range(self.replica_count, self.total_count):
                self.bus.send(s, prepare, body)
        if self.standby or self.replica_count == 1:
            return
        succ = (self.replica + 1) % self.replica_count
        if succ != self.primary_index(int(prepare["view"])):
            self.bus.send(succ, prepare, body)

    def _on_prepare_ok(self, header: np.ndarray, body: bytes) -> None:
        if not self.is_primary or int(header["view"]) != self.view:
            return
        op = int(header["op"])
        entry = self.pipeline.get(op)
        if entry is None:
            return
        if self._np is not None:
            # Native vote record: the C table checks op + exact
            # checksum and updates the ack bitset; a None mirrors the
            # Python early returns (unknown op / stale sibling).
            if self._np.on_ack(header) is None:
                return
        elif wire.u128(header, "context") != wire.u128(entry.header, "checksum"):
            return
        # The Python set stays maintained either way — retransmit,
        # eviction, and view-change scans read it.
        entry.ok_replicas.add(int(header["replica"]))
        self.anatomy.stage_h(header, "prepare_ok")
        self._maybe_commit_pipeline()

    def _primary_requeue_uncommitted(self) -> None:
        """After a view change, the adopted-but-uncommitted tail must be
        re-committed under the new view: enqueue every tail op we hold
        and re-replicate it so backups ack into this view."""
        for op in range(self.commit_min + 1, self.op + 1):
            if op in self.pipeline:
                continue
            read = self.journal.read_prepare(op)
            if read is None:
                continue  # still repairing; retried on fill
            header, body = read
            # Reconstruct logical-batch sub-requests from the body
            # trailer: the retransmission dedupe scans them, and a
            # requeued batch without its subs would let a client's
            # retransmit be prepared (and executed) a second time.
            subs = None
            n_subs = wire.u128(header, "context")
            if n_subs and (
                int(header["operation"]) >= constants.VSR_OPERATIONS_RESERVED
            ):
                _events, subs = demuxer.decode_trailer(body, n_subs)
            synced = not self._gc_defer()
            self.pipeline[op] = PipelineEntry(
                header, body, {self.replica}, subs,
                # Journaled earlier, but possibly within the current
                # unsynced window — conservative.
                synced=synced,
            )
            if self._np is not None:
                self._np.note_prepare(header, synced, self.replica)
            self._replicate(header, body)
        self._maybe_commit_pipeline()

    def _maybe_commit_pipeline(self) -> None:
        # Native-drain ready-run cache: ONE C walk answers how many
        # contiguous ops past commit_min are commit-ready, then each
        # loop iteration decrements instead of re-walking.  The cache
        # is keyed to the commit_min it was computed at — any foreign
        # commit_min movement (recursive drains on non-gc clusters,
        # _advance_commit) forces a re-walk, so staleness cannot
        # commit an unready op.
        ready_run = 0
        ready_from = -1
        while self.pipeline:
            op = min(self.pipeline)
            if op <= self.commit_min:  # committed via _advance_commit
                del self.pipeline[op]
                if self._np is not None:
                    self._np.drop(op)
                continue
            entry = self.pipeline[op]
            if self._np is not None:
                # Native group-commit gate: quorum of exact-checksum
                # votes AND sync-covered AND contiguous (commit_min+1)
                # answered by one C call over the slot table — the
                # same three gates the Python arm below walks.
                if self._drain_native:
                    if ready_from != self.commit_min:
                        ready_run = self._np.commit_ready_run(
                            self.commit_min, self.quorum_replication
                        )
                        ready_from = self.commit_min
                    if ready_run <= 0:
                        return
                elif not self._np.commit_ready(
                    self.commit_min, self.quorum_replication
                ):
                    return
                if op != self.commit_min + 1:
                    return  # waiting on repair of earlier ops
            else:
                if len(entry.ok_replicas) < self.quorum_replication:
                    return
                if not entry.synced:
                    # Our own WAL copy is not yet covered: backup acks
                    # alone must not commit (the quorum's durable-copy
                    # count includes our self-vote), and the committed
                    # commit_min would leak pre-sync through heartbeats
                    # and the next prepare's header.  flush_group_commit
                    # re-enters after the covering sync.
                    return
                if op != self.commit_min + 1:
                    return  # waiting on repair of earlier ops
            if int(entry.header["release"]) > self.release:
                return  # prepared by a newer release; upgrade first
            reply_body = self._commit_prepare(entry.header, entry.body)
            self.commit_parent = wire.u128(entry.header, "checksum")
            self.commit_max = max(self.commit_max, op)
            client = wire.u128(entry.header, "client")
            if entry.subs:
                # Batched prepare: forward each sub-request's OWN
                # reply, captured at commit — re-reading the session's
                # stored reply here would send the batch's LAST reply
                # to every sub when one client multiplexed several
                # requests into the batch (open-loop sessions).
                batch_replies, self._batch_replies = (
                    self._batch_replies, []
                )
                for sub_client, rh_bytes, piece in batch_replies:
                    self._gc_send_client(
                        sub_client,
                        wire.header_from_bytes(rh_bytes), piece,
                    )
            elif client:
                self._send_reply(entry.header, reply_body)
            # The request's timeline closes at reply: e2e into the
            # anatomy histogram, tail exemplars retained.
            self.anatomy.finish_h(entry.header, "reply")
            if self.qos is not None and client:
                # Per-tenant reply latency, attributed to the batch
                # head's tenant: exact inside an overload episode
                # (WFQ keeps logical batches within one tenant),
                # head-of-batch approximate for FIFO batches outside
                # one (see _pop_request).
                self.qos.on_reply(
                    wire.tenant_of(entry.header, entry.body), entry.header
                )
            del self.pipeline[op]
            if self._np is not None:
                self._np.drop(op)
            if ready_from >= 0:
                # Our own commit advanced commit_min to `op`: keep the
                # cached run valid without a re-walk.
                ready_run -= 1
                ready_from = op
            if self._checkpoint_due():
                # Deterministic checkpoint point: commit_min crosses the
                # interval boundary at the same op on every replica, so
                # spill bases and manifests are byte-identical cluster-wide
                # (the convergence checkers compare snapshot bytes).
                self.checkpoint()
            self._drain_request_queue()

    def _drain_request_queue(self) -> None:
        """Prepare queued requests while pipeline slots are free — only
        under a synchronized clock (every prepare path shares this
        gate; see _on_request_msg).  Consecutive queued requests for
        the same batchable operation are multiplexed into one prepare
        (logical batching — reference: src/state_machine.zig:122-131),
        cutting per-request consensus overhead under load."""
        if self.replica_count > 1 and not self.clock.synchronized:
            return
        if self._anchor_pending:
            return  # canonical head checksum still being repaired
        requeue: list[tuple[np.ndarray, bytes]] = []
        # ONE in-flight scan per drain, updated incrementally as
        # prepares land (the scan walks the pipeline + uncommitted
        # journal tail; per-pop recomputation made queue drains
        # O(queue x pipeline) — the per-request Python the columnar
        # ingest path is built to avoid).  Committed-then-stale keys
        # are harmless: the session-table check runs first in
        # _request_dedupe and already answers for them.
        inflight = (
            self._inflight_requests(include_queue=False)
            if self.request_queue
            else None
        )
        # Drain plan (r22): with group commit on, a new prepare CANNOT
        # commit mid-drain (entries start unsynced until the covering
        # flush), so the drain first COLLECTS the whole run and then
        # materializes it in _primary_prepare_plan — one native call
        # for the run, or the per-prepare loop on the TB_NATIVE_DRAIN=0
        # arm (same seam, bit-identical frames).  Without group commit
        # (sim clusters), prepares may commit inline per item, so the
        # legacy immediate path stays untouched.
        plan: list | None = [] if self._gc_enabled else None
        pending = 0
        while self.request_queue and (
            len(self.pipeline) + pending
            < self.config.pipeline_prepare_queue_max
            and self._prepare_headroom(pending)
        ):
            h, b = self._pop_request()
            cur_tenant = self._last_pop_tenant
            if plan:
                client = wire.u128(h, "client")
                if client and client not in self.sessions:
                    # The dedupe ladder scans the PIPELINE for this
                    # client's pending register — flush so planned
                    # prepares are visible to it exactly where the
                    # per-item arm would already have them (rare:
                    # only unregistered clients flush).
                    self._primary_prepare_plan(plan)
                    plan = []
                    pending = 0
            # Queued requests re-run the at-most-once gate: their
            # duplicate may have committed (or become decidable) while
            # they waited.
            verdict = self._request_dedupe(
                h, in_queue=True, inflight=inflight
            )
            if verdict == "drop":
                # Its twin committed while this copy waited: the
                # ingress pass that verified this body joins the dup
                # term of the reuse ratio (see vsr/replica.py).
                self._c_hash_dup.inc(len(b))
                continue
            if verdict == "queue":
                requeue.append((h, b))
                continue
            operation = int(h["operation"])
            batch = []
            if (
                operation >= constants.VSR_OPERATIONS_RESERVED
                and demuxer.batch_logical_allowed(types.Operation(operation))
            ):
                # Budget in BODY BYTES: events plus the per-sub demux
                # trailer must fit the message body (and therefore the
                # fixed-size WAL slot).
                sub_size = demuxer.TRAILER_DTYPE.itemsize
                total = len(b) + sub_size
                limit = self.config.message_body_size_max
                while self.request_queue:
                    nxt = self._queue_peek(cur_tenant)
                    if nxt is None:
                        break
                    h2, b2 = nxt
                    if int(h2["operation"]) != operation:
                        break
                    if total + len(b2) + sub_size > limit:
                        break
                    if (
                        self._request_dedupe(
                            h2, in_queue=True, peek=True, inflight=inflight
                        )
                        is not None
                    ):
                        break  # handled/undecidable: not batchable now
                    batch.append(self._pop_request(cur_tenant))
                    total += len(b2) + sub_size
            prepared = [(h, b)] + batch
            if batch:
                head, pbody, subs = self._build_batch_request(prepared)
            else:
                head, pbody, subs = h, b, None
            if plan is not None:
                plan.append((head, pbody, subs))
                pending += 1
            elif subs is not None:
                self._primary_prepare(head, pbody, subs=subs)
            else:
                self._primary_prepare(head, pbody)
            if inflight is not UNDECIDABLE and inflight is not None:
                for ph, _pb in prepared:
                    c = wire.u128(ph, "client")
                    if c:
                        inflight.add((c, int(ph["request"])))
        if plan:
            self._primary_prepare_plan(plan)
        for rh, rb in requeue:
            self._enqueue_request(rh, rb, readmit=True)

    def _build_batch_request(
        self, requests: list[tuple[np.ndarray, bytes]]
    ) -> tuple[np.ndarray, bytes, list]:
        """Multiplex several client requests into one request frame:
        the body is events || trailer, the header's `context` carries
        the sub-request count so every replica demuxes identically."""
        subs = [
            (wire.u128(h, "client"), int(h["request"]),
             len(b) // demuxer.EVENT_SIZE)
            for h, b in requests
        ]
        body = b"".join(b for _, b in requests) + demuxer.encode_trailer(subs)
        head = wire.make_header(
            command=Command.request,
            operation=int(requests[0][0]["operation"]),
            cluster=self.cluster, view=self.view,
            client=0, request=0, context=len(subs),
        )
        # A multiplexed prepare carries ONE trace context: the first
        # sampled sub-request's (the batch executes as one unit, so
        # one timeline describes them all).
        for rh, _ in requests:
            if wire.trace_sampled(rh):
                wire.copy_trace(head, rh)
                break
        # Coalescing concatenates bodies into NEW bytes, so this is a
        # legitimate extra hash pass in BOTH reuse arms (the table keys
        # on (ptr,len) of ingress frames; concatenation has no cached
        # digest).  It stamps head.checksum_body = digest(body), which
        # the prepare-build seam then reuses — the pass happens once,
        # here, not again at build.
        wire.finalize_header(head, body)
        self._c_hash_bytes.inc(len(body))
        return head, body, subs

    def _primary_prepare_batch(
        self, requests: list[tuple[np.ndarray, bytes]]
    ) -> None:
        """One prepare multiplexing several client requests (the
        immediate form; the drain plan uses _build_batch_request +
        _primary_prepare_plan instead)."""
        head, body, subs = self._build_batch_request(requests)
        self._primary_prepare(head, body, subs=subs)

    def _send_register_reply(self, client: int, entry: Session,
                             inflight=None) -> None:
        # Session-resume hint: the highest request number this session
        # has committed OR still has in flight (pipeline, queue,
        # journal tail — anything that could yet commit is visible to
        # a normal-status primary).  A failed-over session owner (the
        # sharded router's coordinator identity) resumes its numbering
        # safely above it — re-registering under a fresh id instead
        # would grow the session table until an eviction hit an
        # innocent live session (found by the sharded VOPR at 18
        # coordinator kills).  Plain clients ignore the field.
        bound = entry.request
        if inflight:
            bound = max(
                [bound] + [r for (c, r) in inflight if c == client]
            )
        reply = wire.make_header(
            command=Command.reply, operation=VsrOperation.register,
            cluster=self.cluster, client=client,
            request=0, view=self.view,
            op=entry.session, commit=entry.session,
            context=bound,
        )
        wire.finalize_header(reply, b"")
        self._gc_send_client(client, reply, b"")

    def _send_reply(self, prepare: np.ndarray, reply_body: bytes) -> None:
        self.tracer.instant("reply", op=int(prepare["op"]))
        client = wire.u128(prepare, "client")
        operation = int(prepare["operation"])
        if operation == int(VsrOperation.register):
            self._send_register_reply(client, self.sessions[client])
            return
        entry = self.sessions.get(client)
        if entry is not None and entry.reply_header:
            header = wire.header_from_bytes(entry.reply_header)
            self._gc_send_client(client, header, reply_body)

    def _send_stored_reply(self, client: int, entry: Session) -> None:
        body = self._read_reply(entry)
        self._gc_send_client(
            client, wire.header_from_bytes(entry.reply_header), body
        )

    def _notify_eviction(self, client: int) -> None:
        if self.is_primary:
            self._send_eviction(client)

    def _send_eviction(self, client: int) -> None:
        h = wire.make_header(
            command=Command.eviction, cluster=self.cluster, view=self.view,
            client=client, replica=self.replica,
        )
        wire.finalize_header(h, b"")
        self._gc_send_client(client, h, b"")

    # ------------------------------------------------------------------
    # WAL group commit (deferred-sync mode).

    def _journal_write(self, header: np.ndarray, body: bytes) -> None:
        """WAL append on the group-commit plan when enabled: written
        unsynced, covered by flush_group_commit()'s one fdatasync per
        drain; a leading-edge sync is kicked onto the WAL worker so
        the disk wait overlaps the rest of the drain's commit CPU."""
        self._stats["stat_prepares_written"].inc()
        self.tracer.instant("prepare", op=int(header["op"]))
        if not self._gc_enabled:
            self.journal.write_prepare(header, body)
            return
        # Sampled requests deferred behind this drain's covering sync
        # get a gc_covering_sync stage stamped when it lands — the
        # group-commit gate's contribution to THIS request's latency.
        tid = wire.trace_sampled(header)
        if tid:
            self._gc_trace_ids.append(tid)
        self.journal.write_prepare(header, body, sync=False)
        if self._wal_sync_worker is not None and self._gc_sync_job is None:
            self._gc_sync_cover = self.journal.unsynced_writes
            self._gc_sync_job = self._wal_sync_worker.submit(
                self.storage.sync_wal
            )

    def _journal_write_framed(
        self, header: np.ndarray, body_len: int, wal_view, slot: int,
        sector_view, sector_index: int,
    ) -> None:
        """_journal_write for a drain-plan prepare whose WAL frame the
        native batch call already laid out (padded slot image + header
        sector image): write the pre-framed views, skip Python-side
        framing entirely.  Only reachable with group commit on, so
        writes are always unsynced + covered like _journal_write's gc
        branch — including the leading-edge sync kick on the first
        write of the drain."""
        self._stats["stat_prepares_written"].inc()
        self.tracer.instant("prepare", op=int(header["op"]))
        tid = wire.trace_sampled(header)
        if tid:
            self._gc_trace_ids.append(tid)
        self.journal.write_prepare_framed(
            header, body_len, wal_view, slot, sector_view, sector_index
        )
        if self._wal_sync_worker is not None and self._gc_sync_job is None:
            self._gc_sync_cover = self.journal.unsynced_writes
            self._gc_sync_job = self._wal_sync_worker.submit(
                self.storage.sync_wal
            )

    def _gc_defer(self) -> bool:
        """True while an ack sent NOW could precede its covering sync."""
        return self._gc_enabled and (
            self.journal.unsynced_writes > 0 or self._gc_sync_job is not None
        )

    def _gc_send(self, dst: int, header: np.ndarray, body: bytes) -> None:
        if self._gc_defer():
            self._c_gc_deferred_acks.inc()
            self._gc_pending.append(("replica", dst, header, body))
        else:
            self.bus.send(dst, header, body)

    def _gc_send_client(self, client: int, header: np.ndarray,
                        body: bytes) -> None:
        if self._gc_defer():
            self._c_gc_deferred_acks.inc()
            self._gc_pending.append(("client", client, header, body))
        else:
            self.bus.send_client(client, header, body)

    def _gc_covering_sync(self) -> None:
        """Make every deferred WAL write durable NOW (acks stay
        buffered — flush_group_commit releases them)."""
        with self.tracer.span(
            "gc_covering_sync", deferred=self.journal.unsynced_writes
        ), self._h_gc_sync.time():
            job, self._gc_sync_job = self._gc_sync_job, None
            if job is not None:
                job.result()
                # Writes that landed after the leading-edge sync was
                # submitted may have raced past its fdatasync: only the
                # covered prefix is settled, the rest re-syncs below.
                self.journal.unsynced_writes = max(
                    0, self.journal.unsynced_writes - self._gc_sync_cover
                )
                self._gc_sync_cover = 0
            self.journal.sync_batch()
        if self._gc_trace_ids:
            # One covering sync settled every deferred write in this
            # batch: stamp the shared stage timestamp on each sampled
            # request that waited for it.
            ids, self._gc_trace_ids = self._gc_trace_ids, []
            self.anatomy.stage_many(ids, "gc_covering_sync")

    def flush_group_commit(self) -> None:
        """Group-commit flush point (end of a server poll drain, or
        the TB_GROUP_COMMIT_MAX_US deadline): one covering sync for
        the drain's deferred WAL writes, THEN the acks it gates
        (prepare_ok, client replies, evictions) go out in order.  No
        ack ever precedes its covering sync."""
        if not self._gc_enabled:
            return
        if self.journal.unsynced_writes or self._gc_sync_job is not None:
            self._gc_covering_sync()
            self.stat_gc_flushes += 1
        if self._gc_pending:
            pending, self._gc_pending = self._gc_pending, []
            # Scatter-gather release (r22): a backup drain typically
            # defers a whole run of prepare_oks to ONE destination (the
            # primary) — batch those into a single vectored bus call
            # when the transport supports it.  Mixed destinations or
            # client replies keep the in-order per-frame loop.
            send_frames = getattr(self.bus, "send_frames", None)
            if (
                self._drain_native
                and send_frames is not None
                and len(pending) > 1
                and all(
                    kind == "replica" and dst == pending[0][1]
                    for kind, dst, _h, _b in pending
                )
            ):
                send_frames(
                    pending[0][1],
                    [(header, body) for _k, _d, header, body in pending],
                )
            else:
                for kind, dst, header, body in pending:
                    if kind == "client":
                        self.bus.send_client(dst, header, body)
                    else:
                        self.bus.send(dst, header, body)
        # The covering sync makes our self-votes count: commit any
        # pipeline entries that were waiting on it (their replies go
        # out directly — nothing is deferred any more).
        if self.is_primary and any(
            not e.synced for e in self.pipeline.values()
        ):
            for e in self.pipeline.values():
                e.synced = True
            if self._np is not None:
                self._np.mark_all_synced()
            self._maybe_commit_pipeline()

    def _aof_barrier(self) -> None:
        # The AOF must never record an op a crash could erase from the
        # WAL: in group-commit mode the covering sync is forced before
        # the AOF append (per-op syncs return — AOF trades the group
        # -commit batching for its stream guarantee).
        super()._aof_barrier()
        if self._gc_enabled:
            self._gc_covering_sync()

    # ------------------------------------------------------------------
    # Normal operation: backup.

    def _on_prepare(self, header: np.ndarray, body: bytes) -> None:
        view = int(header["view"])
        op = int(header["op"])
        if view < self.view:
            # Stale-view prepares arrive as repair responses and as the
            # new primary's re-replication of an adopted tail; the fill
            # path accepts them only when requested/matching.
            self._repair_fill(header, body)
            return
        if view > self.view:
            # We missed a view change: catch up passively (the new
            # primary's start_view was lost; prepares prove the view).
            self._enter_view(view)
        self._last_primary_seen = self._ticks
        if self.status != "normal":
            return
        if self.is_primary:
            # Ring wrapped all the way around — EXCEPT a repair reply
            # for a slot we pinned: the PRIMARY's scrubber must be able
            # to heal its own WAL from a backup, and those replies
            # carry the current view (found by VOPR seed 99911308: the
            # primary dropped every scrub-repair reply for a
            # current-view op, leaving the corrupt slot unhealable).
            self._try_wal_scrub_repair(header, body)
            return

        if op <= self.op:
            # Retransmitted (or repair-overlap) prepare: the journal
            # already holds this op, so the ingress verify that proved
            # this copy was a duplicate-delivery pass — charged to
            # hash.dup_body_bytes, the retransmission term of the
            # reuse ratio (see vsr/replica.py).
            self._c_hash_dup.inc(len(body))
            self._repair_fill(header, body)
            return
        if op > self.op + 1:
            # Gap: stash and repair the missing range; for a big gap
            # additionally request a state-sync jump (see
            # _repair_gap_forward).
            window = 4 * self.config.pipeline_prepare_queue_max
            if len(self._stash) < 2 * window:
                self._stash[op] = (header, body)
            self._repair_gap_forward(op - 1)
            return

        if wire.u128(header, "parent") != self.parent_checksum:
            # Chain mismatch: OUR head is a stale sibling (uncommitted
            # garbage from an old view).  Accept anyway ONLY if this
            # prepare is the exact one we pinned (checksum vouched
            # canonical) — _flag_stale_predecessor then pins the stale
            # head for repair and the commit gate keeps it from
            # executing.  Otherwise pin it and wait.
            checksum = wire.u128(header, "checksum")
            if self._repair_wanted.get(op) != checksum:
                self._repair_wanted[op] = checksum
                self._send_repair_requests()
                return
            self._accept_prepare(header, body)
            self._flag_stale_predecessor(header)
            self._drain_stash()
            self._advance_commit(int(header["commit"]))
            return

        self._accept_prepare(header, body)
        self._drain_stash()
        self._advance_commit(int(header["commit"]))

    def _drain_stash(self) -> None:
        """Extend the head with stashed successors that POSITIVELY
        link to the verified head anchor.  The check must be against
        parent_checksum, not a journal read-back — the read is
        transiently None while the head's WAL write is in flight, and
        failing open let a delayed prior-view prepare extend a
        just-prepared head with stale content (seed 460991023).  No
        draining while the anchor itself is unresolved:
        parent_checksum is stale then."""
        while not self._anchor_pending and self.op + 1 in self._stash:
            h, b = self._stash.pop(self.op + 1)
            if int(h["view"]) != self.view:
                # Stashed before a view change: a later view may have
                # replaced this op with a sibling CHAINING FROM THE
                # SAME PARENT, which the linkage check cannot tell
                # apart — draining one committed a dead view-2 copy
                # where peers committed its view-3 replacement (soak
                # seed 323928758).  Superseded candidates re-enter
                # only via checksum-pinned repair.
                continue
            if wire.u128(h, "parent") != self.parent_checksum:
                break
            self._accept_prepare(h, b)

    def _accept_prepare(self, header: np.ndarray, body: bytes) -> None:
        op = int(header["op"])
        self._journal_write(header, body)
        self.op = op
        self.parent_checksum = wire.u128(header, "checksum")
        # A current-view prepare is canonical for its op, and its
        # parent field vouches its predecessor.
        self._vouched[op] = self.parent_checksum
        if op - 1 > self.commit_min:
            self._vouched.setdefault(op - 1, wire.u128(header, "parent"))
        self._repair_wanted.pop(op, None)
        self._replicate(header, body)
        # Backup-side instrument: just the prepare_ok build span (the
        # work the native pipeline replaces here) — body-independent,
        # so the arm delta survives heavy group-commit coalescing.
        t0 = time.perf_counter_ns()
        self._send_prepare_ok(header)
        self._h_prepare_ok_us.observe((time.perf_counter_ns() - t0) / 1000.0)

    def _flag_stale_predecessor(self, header: np.ndarray) -> None:
        """Chain continuity at journal-write time: the accepted prepare
        vouches (via `parent`) for exactly one predecessor checksum.  A
        mismatched local predecessor is a superseded SIBLING from an
        older view (same parent, different content — the parent check
        alone cannot catch it); pin it for exact-checksum repair so the
        commit path never executes it.  (_verify_chain_down subsumes
        this during suspect phases.)"""
        op = int(header["op"])
        if op - 1 <= self.commit_min:
            return
        prev = self.journal.read_prepare(op - 1)
        want = wire.u128(header, "parent")
        if prev is None or wire.u128(prev[0], "checksum") != want:
            self._repair_wanted[op - 1] = want
            self._chain_suspect = True
            self._send_repair_requests()

    def _send_prepare_ok(self, prepare: np.ndarray) -> None:
        if self.status != "normal" or self.is_primary or self.standby:
            return  # standbys replicate without acking: no quorum role
        if self._np is not None:
            # Native arm: header build + checksum stamping in one C
            # call (cluster/context/client/op/trace copied from the
            # prepare in C) — bit-identical to the sequence below.
            ok = self._np.build_prepare_ok(prepare, self.view, self.replica)
        else:
            ok = wire.make_header(
                command=Command.prepare_ok, cluster=self.cluster,
                view=self.view,
                op=int(prepare["op"]), replica=self.replica,
                context=wire.u128(prepare, "checksum"),
                client=wire.u128(prepare, "client"),
            )
            # The ack echoes the prepare's trace context so the
            # PRIMARY can stamp a prepare_ok stage (per acking backup)
            # onto the request's timeline.
            wire.copy_trace(ok, prepare)
            wire.finalize_header(ok, b"")
        self.tracer.instant("prepare_ok", op=int(prepare["op"]))
        # Routed through the group-commit gate: a prepare_ok for an op
        # whose WAL write is not yet covered by a sync must wait for
        # the flush (the durability-before-ack contract).
        self._gc_send(self.primary_index(), ok, b"")

    def _on_commit(self, header: np.ndarray, body: bytes) -> None:
        # Heartbeats advertise the freshest adopted membership: a
        # process that crashed before a reconfigure committed
        # re-learns the ROLE it fills here (without this it is
        # unreachable — its repair requests carry the old slot, so
        # responses route to whoever fills that slot now).  Adoption
        # runs BEFORE the status/view gate: a restarted process stuck
        # in view_change under a superseded identity would otherwise
        # drop the very advertisement it needs — its DVCs then came
        # from a slot someone else fills, replies routed to the new
        # holder, and it never rejoined (soak seed 420704875).  Only
        # the adopted identity moves; the committed epoch/members
        # advance exclusively through the replicated op so
        # reconfigure replies stay deterministic across replicas.
        self._maybe_adopt_advert(body)
        if int(header["view"]) < self.view or self.status != "normal":
            return
        if int(header["view"]) > self.view:
            self._enter_view(int(header["view"]))
        self._last_primary_seen = self._ticks
        commit = int(header["commit"])
        vouch = wire.u128(header, "context")
        if vouch and commit > self.commit_min:
            self._vouched[commit] = vouch
        self._advance_commit(commit)

    def _extend_vouches_down(self) -> None:
        """Derive vouches downward: if op K's canonical content is
        vouched and our journal's K matches it, K's parent field
        vouches K-1 — repeat to the commit frontier."""
        for k in sorted(self._vouched, reverse=True):
            while k - 1 > self.commit_min and k - 1 not in self._vouched:
                # The in-memory redundant header ring supplies the
                # checksum/parent fields without re-reading (and
                # re-hashing) the full 1 MiB prepare slot.
                mem = self.journal.headers[self.journal.slot_for_op(k)]
                if (
                    int(mem["op"]) != k
                    or int(mem["command"]) != int(Command.prepare)
                    or wire.u128(mem, "checksum") != self._vouched[k]
                ):
                    # Cannot derive through a missing/divergent slot —
                    # and nothing else repairs it when commits are
                    # already gated BELOW the hole (_advance_commit
                    # never reaches it): a standby with a mid-suffix
                    # hole wedged at the vouch gate forever (soak seed
                    # 157503236).  Pin the exact canonical checksum.
                    self._repair_wanted.setdefault(k, self._vouched[k])
                    self._send_repair_requests()
                    break
                self._vouched[k - 1] = wire.u128(mem, "parent")
                k -= 1

    def _maybe_resolve_anchor(self) -> None:
        """Re-anchor parent_checksum once the pinned canonical head
        prepare has been repaired into our journal."""
        if not self._anchor_pending:
            return
        read = self.journal.read_prepare(self.op)
        if read is None:
            return
        pin = self._repair_wanted.get(self.op)
        if pin == 0:
            return  # canonical checksum not yet resolved: a local
            # prepare could be the stale sibling — keep waiting
        h = read[0]
        want = pin or self._vouched.get(self.op)
        if want and wire.u128(h, "checksum") != want:
            return
        self.parent_checksum = wire.u128(h, "checksum")
        self._anchor_pending = False
        self._verify_chain_down()

    def _advance_commit(self, commit_max: int) -> None:
        self.commit_max = max(self.commit_max, commit_max)
        self._maybe_resolve_anchor()
        if self._canon_pending:
            return  # tail not yet confirmed canonical (start_view pending)
        if self._chain_suspect:
            self._verify_chain_down()
            if self._chain_suspect:
                return  # stale siblings may lurk; repairs in flight
        while self.commit_min < min(self.commit_max, self.op):
            op = self.commit_min + 1
            read = self.journal.read_prepare(op)
            if op in self._repair_wanted:
                want = self._repair_wanted[op]
                if (
                    want
                    and read is not None
                    and wire.u128(read[0], "checksum") == want
                ):
                    # The pin is already satisfied locally.
                    del self._repair_wanted[op]
                else:
                    # Flagged as superseded/missing: wait for the
                    # canonical prepare instead of executing the local
                    # candidate.
                    self._send_repair_requests()
                    return
            if read is None:
                self._repair_wanted.setdefault(op, 0)
                self._send_repair_requests()
                return
            header, body = read
            if int(header["release"]) > self.release:
                return  # prepared by a newer release; upgrade first
            if (
                self.commit_parent is not None
                and wire.u128(header, "parent") != self.commit_parent
            ):
                # Local candidate diverges from the committed chain
                # (e.g. a speculative pre-crash prepare superseded by a
                # view change): fetch the canonical prepare instead of
                # executing the stale one.
                self._repair_wanted.setdefault(op, 0)
                self._send_repair_requests()
                return
            # Canonical vouch gate: parent linkage alone cannot reject
            # a stale SIBLING (same parent, different content).  Only
            # execute content the current history vouches for; without
            # a vouch, wait (the next heartbeat / prepare / start_view
            # supplies one within ticks).
            self._extend_vouches_down()
            want = self._vouched.get(op)
            if want is None:
                return
            if wire.u128(header, "checksum") != want:
                self._repair_wanted[op] = want
                self._send_repair_requests()
                return
            self._commit_prepare(header, body)
            # Backups (and a catching-up primary) close the record at
            # commit — there is no reply hop on this replica; the
            # partial timeline still feeds exemplars/e2e.
            self.anatomy.finish_h(header)
            self.commit_parent = wire.u128(header, "checksum")
            self._vouched.pop(op, None)
            if self._checkpoint_due():
                # Deterministic checkpoint point: commit_min crosses the
                # interval boundary at the same op on every replica, so
                # spill bases and manifests are byte-identical cluster-wide
                # (the convergence checkers compare snapshot bytes).
                self.checkpoint()
        if self.op < self.commit_max and not self.is_primary:
            # Our log ends below the commit frontier (e.g. we rejoined
            # after the pipeline drained).
            self._repair_gap_forward(self.commit_max)

    def _repair_gap_forward(self, target_op: int) -> None:
        """Catch the log up toward `target_op`: windowed WAL repair
        always; for a big gap also request a state-sync jump.  Both
        stay in flight on separate throttles — the remote checkpoint
        may be OLDER than our commit frontier (sync would install
        nothing), so whichever lands first advances us."""
        window = 4 * self.config.pipeline_prepare_queue_max
        if target_op - self.op > window:
            self._request_sync()
        for op in range(self.op + 1, min(self.op + window, target_op) + 1):
            self._repair_wanted.setdefault(op, 0)
        self._send_repair_requests()

    def _membership_advert(self) -> bytes:
        return (
            self.encode_reconfigure(self.epoch_adopted, self.members_adopted)
            if self.epoch_adopted
            else b""
        )

    def _maybe_adopt_advert(self, body: bytes) -> None:
        if not body:
            return
        decoded = self.decode_reconfigure(body)
        if decoded is None:
            return
        epoch, members = decoded
        if epoch > self.epoch_adopted and sorted(members) == list(
            range(self.total_count)
        ):
            self._adopt_roles(epoch, members)

    def _send_clock_pings(self) -> None:
        """Sample every peer's wall clock: ping carries our monotonic
        send time m0; the pong echoes it alongside the peer's wall
        clock t1 (reference: src/vsr/replica.zig on_ping/on_pong)."""
        self._last_clock_ping = self._ticks
        ping = wire.make_header(
            command=Command.ping, cluster=self.cluster, view=self.view,
            replica=self.replica, timestamp=self.monotonic,
            release=max(self.releases_available),
        )
        # Pings gossip the freshest adopted membership: heartbeats
        # only flow primary->normal-status peers, so a process whose
        # adopted epoch ran ahead and then got isolated in
        # view_change-as-standby could be the ONLY holder of a
        # committed membership the rest of the cluster needs to even
        # agree who the next primary is (soak seed 421977104 wedged
        # exactly so).  Pings flow between ALL processes in ANY
        # status.
        adv = self._membership_advert()
        wire.finalize_header(ping, adv)
        # Standbys are pinged too: their pong advertises their release,
        # so an upgrade never commits while the hot spare would be left
        # behind unable to execute the new release's prepares.
        for r in range(self.total_count):
            if r != self.replica:
                self.bus.send(r, ping, adv)

    def _on_ping(self, header: np.ndarray, body: bytes) -> None:
        # Echo m0 in `timestamp`; our wall clock rides in `op` (clamped
        # at 0 — the wire field is u64 and a skewed simulated clock can
        # sit before the epoch at startup).
        # Adopt BEFORE learning the release: adoption resets the
        # slot-keyed peer_release map, which would wipe the sample
        # this same message carries.
        self._maybe_adopt_advert(body)
        self._learn_peer_release(header)
        pong = wire.make_header(
            command=Command.pong, cluster=self.cluster, view=self.view,
            replica=self.replica, timestamp=int(header["timestamp"]),
            op=max(0, self.realtime),
            release=max(self.releases_available),
        )
        adv = self._membership_advert()
        wire.finalize_header(pong, adv)
        self.bus.send(int(header["replica"]), pong, adv)

    def _learn_peer_release(self, header: np.ndarray) -> None:
        rel = int(header["release"])
        if rel:
            peer = int(header["replica"])
            self.peer_release[peer] = max(self.peer_release.get(peer, 0), rel)

    def _on_pong(self, header: np.ndarray, body: bytes) -> None:
        self._maybe_adopt_advert(body)
        self._learn_peer_release(header)
        if int(header["replica"]) >= self.replica_count:
            return  # standby pongs advertise releases, not clock samples
        self.clock.learn(
            int(header["replica"]),
            m0=int(header["timestamp"]),
            t1=int(header["op"]),
            m2=self.monotonic,
            realtime_now=self.realtime,
        )

    # ------------------------------------------------------------------
    # Repair.

    def _repair_fill(self, header: np.ndarray, body: bytes) -> None:
        """A prepare at or below our op: overwrite if we wanted it or
        our copy is missing/diverged; ack matching content into the
        current view so a new primary can re-commit an adopted tail."""
        op = int(header["op"])
        checksum_pinned = self._repair_wanted.get(op)
        if op > self.op:
            # Log extension via pinned repair (op prepared in an older
            # view, checksum vouched for by the current primary's
            # headers response).
            if (
                op == self.op + 1
                and checksum_pinned
                and checksum_pinned == wire.u128(header, "checksum")
                and self.status == "normal"
            ):
                self._accept_prepare(header, body)
                self._flag_stale_predecessor(header)
                self._drain_stash()
                self._advance_commit(self.commit_max)
            return
        if self._try_wal_scrub_repair(header, body):
            return
        want = self._repair_wanted.get(op)
        have = self.journal.read_prepare(op)
        checksum = wire.u128(header, "checksum")
        if have is not None and wire.u128(have[0], "checksum") == checksum:
            if want == checksum:
                # The local copy already IS the pinned canonical one:
                # unpin, keep cascading the chain check, unblock commit.
                del self._repair_wanted[op]
                self._flag_stale_predecessor(have[0])
                self._advance_commit(self.commit_max)
            self._send_prepare_ok(header)  # already hold it: just ack
            return
        # Accept ONLY checksum-pinned repairs: a stale prepare from a
        # dead view could otherwise overwrite the committed one (want=0
        # entries first resolve to a checksum via request_headers).
        if want != checksum or want == 0:
            return
        self._journal_write(header, body)
        self._repair_wanted.pop(op, None)
        self._vouched[op] = checksum  # pinned fill == canonical content
        if op == self.op:
            self.parent_checksum = checksum
        # Re-verify: the canonical fill vouches for its predecessor,
        # exposing the next stale sibling (if any).
        if self._chain_suspect:
            self._verify_chain_down()
        else:
            self._flag_stale_predecessor(header)
        self._send_prepare_ok(header)
        if self.is_primary:
            self._primary_requeue_uncommitted()
        # Try draining stash / committing past the filled hole.
        self._drain_stash()
        self._advance_commit(self.commit_max)

    def _send_repair_requests(self, force: bool = False) -> None:
        """Rate-limited: message handlers may call this on every packet,
        and un-throttled request bursts amplify exponentially (each
        response can trigger another burst)."""
        if not force and (
            self._ticks - self._repair_last_sent < REPAIR_RETRY_TICKS
        ):
            return
        self._repair_last_sent = self._ticks
        # Drop pins the commit frontier has passed (already executed
        # canonically; their journal slots may even be recycled).
        for op in [o for o in self._repair_wanted if o <= self.commit_min]:
            del self._repair_wanted[op]
        if not self._repair_wanted:
            return
        # Ask the primary (authoritative for the committed prefix);
        # ourselves-as-primary asks the successor.
        target = self.primary_index()
        if target == self.replica:
            target = (self.replica + 1) % self.replica_count

        # Two-step repair (reference: src/vsr/replica.zig:2259-2497):
        # unpinned ops first learn their canonical checksum via
        # request_headers, pinned ops fetch the prepare by checksum.
        unpinned = [op for op, cs in self._repair_wanted.items() if cs == 0]
        if unpinned or (self._anchor_pending and self.op in self._repair_wanted):
            lo = min(unpinned) if unpinned else self.op
            hi = max(unpinned) if unpinned else self.op
            h = wire.make_header(
                command=Command.request_headers, cluster=self.cluster,
                view=self.view, replica=self.replica,
                op=lo, commit=hi,
            )
            wire.finalize_header(h, b"")
            if self._anchor_pending:
                # Anchor resolution must see every peer's sibling for
                # the head op, not one possibly-stale target's.
                for r in range(self.replica_count):
                    if r != self.replica:
                        self.bus.send(r, h, b"")
            else:
                self.bus.send(target, h, b"")
        pinned = [
            (op, cs) for op, cs in self._repair_wanted.items() if cs != 0
        ]
        if pinned:
            # The primary is the preferred source but not guaranteed
            # to HOLD every pinned body: with the primary and one
            # backup both missing an op, primary-asks-successor and
            # successor-asks-primary never reaches the lone holder
            # (VOPR seed 803272239 wedged exactly so).  Checksum-
            # addressed fetches are safe from ANY peer — including
            # standbys, which replicate the log and can be the lone
            # surviving holder after actives corrupt — so retries
            # rotate across the full membership.
            peers = [
                r for r in range(self.total_count) if r != self.replica
            ]
            if peers:
                base = peers.index(target) if target in peers else 0
                target = peers[(base + self._repair_round) % len(peers)]
                self._repair_round += 1
        for op, checksum in pinned[:8]:
            h = wire.make_header(
                command=Command.request_prepare, cluster=self.cluster,
                view=self.view, op=op, replica=self.replica, context=checksum,
            )
            wire.finalize_header(h, b"")
            self.bus.send(target, h, b"")

    def _on_request_headers(self, header: np.ndarray, body: bytes) -> None:
        lo, hi = int(header["op"]), int(header["commit"])
        out = []
        for op in range(lo, min(hi, lo + 64) + 1):
            read = self.journal.read_prepare(op)
            if read is not None:
                out.append(read[0].tobytes())
        if not out:
            if hi <= self.checkpoint_op:
                self._send_sync_checkpoint(int(header["replica"]))
            return
        reply = wire.make_header(
            command=Command.headers, cluster=self.cluster, view=self.view,
            replica=self.replica, commit=self.commit_min,
        )
        payload = b"".join(out)
        wire.finalize_header(reply, payload)
        self.bus.send(int(header["replica"]), reply, payload)

    def _on_headers(self, header: np.ndarray, body: bytes) -> None:
        from tigerbeetle_tpu.constants import HEADER_SIZE

        pinned_any = False
        for at in range(0, len(body), HEADER_SIZE):
            h = wire.header_from_bytes(body[at : at + HEADER_SIZE])
            if not wire.verify_header(h):
                continue
            op = int(h["op"])
            if (
                self._anchor_pending
                and op == self.op
                and op in self._repair_wanted
                and int(h["view"]) > self._anchor_pin_view
            ):
                # Anchor resolution collects from every peer and keeps
                # the highest-view sibling: the committed content for
                # an op is the one prepared in the latest view, and a
                # single partitioned peer's stale header must not win.
                self._repair_wanted[op] = wire.u128(h, "checksum")
                self._anchor_pin_view = int(h["view"])
                pinned_any = True
            elif self._repair_wanted.get(op) == 0:
                self._repair_wanted[op] = wire.u128(h, "checksum")
                pinned_any = True
            if self._wal_scrub_wanted.get(op) == 0 and op <= self.commit_min:
                # Scrub pin resolved: fetch the prepare by checksum.
                checksum = wire.u128(h, "checksum")
                self._wal_scrub_wanted[op] = checksum
                req = wire.make_header(
                    command=Command.request_prepare, cluster=self.cluster,
                    view=self.view, op=op, replica=self.replica,
                    context=checksum,
                )
                wire.finalize_header(req, b"")
                self.bus.send(int(header["replica"]), req, b"")
        if pinned_any:
            self._send_repair_requests(force=True)

    def _request_sync(self) -> None:
        # Own throttle: repair requests share the network but must not
        # starve sync retries (and vice versa).
        if self._ticks - self._sync_last_requested < REPAIR_RETRY_TICKS:
            return
        self._sync_last_requested = self._ticks
        h = wire.make_header(
            command=Command.request_sync_checkpoint, cluster=self.cluster,
            view=self.view, replica=self.replica,
        )
        wire.finalize_header(h, b"")
        target = self.primary_index()
        if target == self.replica:
            target = (self.replica + 1) % self.replica_count
        self.bus.send(target, h, b"")

    def _try_wal_scrub_repair(self, header: np.ndarray, body: bytes) -> bool:
        """WAL-scrub repair of a committed slot: the pin came from OUR
        in-memory redundant header, so a checksum-matching prepare is
        the committed canonical content — rewrite both rings."""
        op = int(header["op"])
        checksum = wire.u128(header, "checksum")
        slot = self.journal.slot_for_op(op)
        if (
            checksum != 0
            and self._wal_scrub_wanted.get(op) == checksum
            # Slot-recycle guard: a checkpoint may have advanced past
            # the pinned op and the ring wrapped — a late repair reply
            # must not clobber the NEWER prepare now in the slot.  The
            # in-memory ring is authoritative for what the slot holds;
            # <= (not ==) so a pin resolved via request_headers after
            # DOUBLE corruption (in-memory header lost, slot shows op
            # 0) still repairs.
            and int(self.journal.headers[slot]["op"]) <= op
            and self.journal.read_prepare(op) is None
        ):
            # Deferred-sync mode folds the prepare-ring write and any
            # header-sector heal into ONE covering sync at the next
            # flush — a repaired prepare no longer fsyncs twice.
            self._journal_write(header, body)
            del self._wal_scrub_wanted[op]
            self.stat_wal_scrub_repaired += 1
            self.tracer.instant("wal_scrub", op=op)
            return True
        return False

    def _on_request_prepare(self, header: np.ndarray, body: bytes) -> None:
        op = int(header["op"])
        want = wire.u128(header, "context")
        read = self.journal.read_prepare(op)
        if read is None:
            # The WAL ring wrapped past this op: repair is impossible,
            # the peer must state-sync to our checkpoint instead
            # (reference: src/vsr/sync.zig — sync supersedes WAL repair).
            if op <= self.checkpoint_op:
                self._send_sync_checkpoint(int(header["replica"]))
            return
        prepare, pbody = read
        if want and wire.u128(prepare, "checksum") != want:
            return
        self.bus.send(int(header["replica"]), prepare, pbody)

    # ------------------------------------------------------------------
    # State sync: ship the checkpoint snapshot in body-sized chunks
    # (reference: src/vsr/sync.zig stage machine; Command
    # .request_sync_checkpoint/.sync_checkpoint).

    def _sync_wrap(self, blob: bytes) -> bytes:
        """With a forest attached, the snapshot's manifest references
        grid blocks that exist only in OUR grid zone — ship them with
        the blob so the syncing replica can install a working LSM tier
        (reference: the sync target fetches missing grid blocks,
        src/vsr/grid_blocks_missing.zig)."""
        if self.forest is None:
            return blob
        from tigerbeetle_tpu.utils import snapshot as snapcodec

        grid = self.forest.grid
        grid.flush_writes()  # queued async block writes must be on disk
        live = (np.flatnonzero(~grid.free_set.free) + 1).astype(np.uint64)
        raw = bytearray()
        for addr in live:
            raw += self.storage.read(grid._offset(int(addr)), grid.block_size)
        return snapcodec.encode(
            {
                "snapshot": blob,
                "addrs": live,
                "blocks": bytes(raw),
                "block_size": grid.block_size,
            }
        )

    def _sync_unwrap(self, payload: bytes) -> bytes:
        """Install shipped grid blocks (verified by address + length)
        and return the inner snapshot blob."""
        if self.forest is None:
            return payload
        from tigerbeetle_tpu.utils import snapshot as snapcodec

        state = snapcodec.decode(payload)
        grid = self.forest.grid
        # Drain OUR stale queued writes first — a pre-sync write
        # landing after the install would silently overwrite a shipped
        # block with old-lineage (checksum-valid) content.
        grid.flush_writes()
        addrs = state["addrs"]
        blocks = state["blocks"]
        bs = int(state["block_size"])
        if bs != grid.block_size or len(blocks) != len(addrs) * bs:
            raise ValueError("sync payload block geometry mismatch")
        for i, addr in enumerate(addrs):
            addr = int(addr)
            if not 1 <= addr <= grid.block_count:
                raise ValueError("sync payload block address out of range")
            self.storage.write(
                grid._offset(addr), blocks[i * bs : (i + 1) * bs]
            )
        # Invalidate the block cache: shipped blocks replace anything
        # read before the sync.
        from tigerbeetle_tpu.utils.cache import SetAssociativeCache

        grid._cache = SetAssociativeCache(capacity=256, ways=4)
        return state["snapshot"]

    # ------------------------------------------------------------------
    # Single-block peer repair: scrubber findings heal from any peer at
    # the same checkpoint without re-shipping the whole snapshot
    # (reference: src/vsr/grid_blocks_missing.zig:1-30,
    # Command.request_blocks / Command.block, src/vsr/grid.zig:34-60).
    # Scrub pacing: one probe every SCRUB_INTERVAL_TICKS, cycling the
    # whole grid over many seconds (reference: grid_scrubber paces on a
    # slow timer) — steady-state cost stays negligible.

    def _wal_scrub_tick(self) -> None:
        """Probe one committed journal slot above the checkpoint for
        latent sector errors (reference's scrubbing philosophy applied
        to the WAL; the uncommitted window is covered by the normal
        repair protocol)."""
        lo, hi = self.checkpoint_op + 1, self.commit_min
        if hi < lo:
            return
        op = lo + self._wal_scrub_cursor % (hi - lo + 1)
        self._wal_scrub_cursor += 1
        self._wal_scrub_probe(op)

    def wal_scrub_window(self) -> None:
        """Probe the ENTIRE committed window at once — used by test
        harnesses before journal-reading checkers, and usable by an
        operator hook; production pacing uses the per-tick probe."""
        for op in range(self.checkpoint_op + 1, self.commit_min + 1):
            self._wal_scrub_probe(op)

    def _wal_scrub_probe(self, op: int) -> None:
        """Header-ring damage self-heals from the in-memory ring;
        prepare-sector damage repairs from a peer, pinned by the
        canonical checksum from memory — or, when that was lost too
        (restart after double corruption), resolved via a targeted
        request_headers round first."""
        slot = self.journal.slot_for_op(op)
        have = self.journal.read_prepare(op)
        if have is not None:
            self._wal_scrub_wanted.pop(op, None)
            if not self.journal.header_sector_intact(slot):
                # Deferred-sync mode: the heal rides the next covering
                # flush instead of paying its own fdatasync.
                self.journal.rewrite_header_sector(
                    slot, sync=not self._gc_enabled
                )
                self.stat_wal_scrub_repaired += 1
            return
        if self.replica_count <= 1:
            return
        # Rotate targets across probes: the preferred peer may hold
        # the same latent damage (block repair round-robins the same
        # way).
        peers = [r for r in range(self.replica_count) if r != self.replica]
        target = peers[self._wal_scrub_attempt % len(peers)]
        self._wal_scrub_attempt += 1
        mem = self.journal.headers[slot]
        if int(mem["op"]) == op and int(mem["command"]) == Command.prepare:
            checksum = wire.u128(mem, "checksum")
        else:
            checksum = 0
        self._wal_scrub_wanted[op] = checksum
        if checksum:
            h = wire.make_header(
                command=Command.request_prepare, cluster=self.cluster,
                view=self.view, op=op, replica=self.replica,
                context=checksum,
            )
        else:
            # Canonical checksum unknown locally: learn it from a peer
            # (ops <= commit_min are committed, hence unique per op).
            h = wire.make_header(
                command=Command.request_headers, cluster=self.cluster,
                view=self.view, replica=self.replica, op=op, commit=op,
            )
        wire.finalize_header(h, b"")
        self.bus.send(target, h, b"")

    def _send_request_blocks(self) -> None:
        """Ask a peer for our corrupt blocks (round-robin over peers,
        bounded batch per request)."""
        self._block_repair_last = self._ticks
        # Blocks freed — or staged for release — since they were
        # flagged no longer need repair (a peer that already
        # checkpointed holds them free and would silently drop the
        # request; same invariant as the scrubber's skip).
        fs = self.forest.grid.free_set
        self._blocks_missing = {
            a for a in self._blocks_missing
            if not fs.leaving_live_set([a])[0]
        }
        if not self._blocks_missing:
            return
        peers = [r for r in range(self.replica_count) if r != self.replica]
        dst = peers[self._block_repair_attempt % len(peers)]
        self._block_repair_attempt += 1
        addrs = np.asarray(sorted(self._blocks_missing)[:64], np.uint64)
        h = wire.make_header(
            command=Command.request_blocks, cluster=self.cluster,
            replica=self.replica, op=self.checkpoint_op,
        )
        body = addrs.tobytes()
        wire.finalize_header(h, body)
        self.bus.send(dst, h, body)

    def _on_request_blocks(self, header: np.ndarray, body: bytes) -> None:
        """Serve raw block frames — only when our grid is guaranteed
        identical to the requester's (same checkpoint; the forest
        writes blocks only at checkpoint)."""
        if self.forest is None or self.status != "normal":
            return
        if int(header["op"]) != self.checkpoint_op:
            return
        if len(body) % 8 != 0:
            return  # malformed (this handler takes untrusted input)
        dst = int(header["replica"])
        if not 0 <= dst < self.replica_count or dst == self.replica:
            return
        from tigerbeetle_tpu.vsr.grid import block_frame_valid

        grid = self.forest.grid
        # Serve at most the sender's cap regardless of what the body
        # claims — one message must not trigger unbounded disk reads.
        for addr in np.frombuffer(body, np.uint64)[:64]:
            addr = int(addr)
            if not 1 <= addr <= grid.block_count:
                continue
            if grid.free_set.free[addr - 1]:
                continue  # not live here (diverged free set: stale req)
            # One raw read serves both the intactness check and the
            # reply payload.
            frame = self.storage.read(grid._offset(addr), grid.block_size)
            if not block_frame_valid(frame, addr, grid.payload_size):
                continue  # our copy is corrupt too; another peer's turn
            bh = wire.make_header(
                command=Command.block, cluster=self.cluster,
                replica=self.replica, op=self.checkpoint_op,
            )
            wire.finalize_header(bh, frame)
            self.bus.send(dst, bh, frame)

    def _on_block(self, header: np.ndarray, body: bytes) -> None:
        """Install a repaired block after verifying its self-described
        address + payload checksum against what we asked for."""
        from tigerbeetle_tpu.vsr.grid import BLOCK_DTYPE, BLOCK_HEADER_SIZE

        if self.forest is None or int(header["op"]) != self.checkpoint_op:
            return
        grid = self.forest.grid
        if len(body) != grid.block_size:
            return
        bh = np.frombuffer(body[:BLOCK_HEADER_SIZE], BLOCK_DTYPE)[0]
        addr = int(bh["address"])
        if addr not in self._blocks_missing:
            return
        length = int(bh["length"])
        if length > grid.payload_size:
            return
        payload = body[BLOCK_HEADER_SIZE : BLOCK_HEADER_SIZE + length]
        want = int(bh["checksum_lo"]) | (int(bh["checksum_hi"]) << 64)
        if wire.checksum(payload) != want:
            return
        grid.flush_writes()  # stale queued write must not overwrite us
        self.storage.write(grid._offset(addr), body)
        grid._cache.remove(addr)
        self._blocks_missing.discard(addr)
        if self.scrubber is not None:
            self.scrubber.repaired(addr)  # a relapse is a new fault
        self._block_repair_attempt = 0
        self.stat_blocks_repaired += 1
        self.tracer.instant("block_repair", address=addr)

    def _send_sync_checkpoint(self, dst: int) -> None:
        # The shipped blob is read via the WORKING superblock's
        # references: an in-flight async flip must land first.
        self._ckpt_join()
        sb = self.superblock.working
        size = int(sb["checkpoint_size"])
        if size == 0:
            return
        # A full blob is many chunks; don't resend on every repair retry.
        last = self._sync_sent.get(dst, -(10**9))
        if self._ticks - last < 4 * REPAIR_RETRY_TICKS:
            return
        self._sync_sent[dst] = self._ticks
        blob = self._sync_wrap(self._read_grid(int(sb["checkpoint_offset"]), size))
        blob_checksum = wire.checksum(blob)
        commit_min_checksum = (
            int(sb["commit_min_checksum_lo"])
            | (int(sb["commit_min_checksum_hi"]) << 64)
        )
        chunk_size = self.config.message_body_size_max
        n_chunks = (len(blob) + chunk_size - 1) // chunk_size
        for i in range(n_chunks):
            chunk = blob[i * chunk_size : (i + 1) * chunk_size]
            h = wire.make_header(
                command=Command.sync_checkpoint, cluster=self.cluster,
                view=self.view, replica=self.replica,
                op=int(sb["commit_min"]), commit=self.commit_min,
                context=blob_checksum, checkpoint_id=commit_min_checksum,
                request=i, timestamp=len(blob),
            )
            wire.finalize_header(h, chunk)
            self.bus.send(dst, h, chunk)

    def _on_request_sync(self, header: np.ndarray, body: bytes) -> None:
        self._send_sync_checkpoint(int(header["replica"]))

    def _on_sync_checkpoint(self, header: np.ndarray, body: bytes) -> None:
        checkpoint_op = int(header["op"])
        if checkpoint_op <= self.commit_min:
            # Already past it; drop any partial chunk assembly for this
            # obsolete checkpoint.
            self._sync_chunks.pop(wire.u128(header, "context"), None)
            return
        blob_checksum = wire.u128(header, "context")
        total = int(header["timestamp"])
        chunk_size = self.config.message_body_size_max
        state = self._sync_chunks.setdefault(blob_checksum, {})
        state[int(header["request"])] = body
        assembled = b"".join(
            state.get(i, b"")
            for i in range((total + chunk_size - 1) // chunk_size)
        )
        if len(assembled) != total:
            return  # still incomplete
        if wire.checksum(assembled) != blob_checksum:
            del self._sync_chunks[blob_checksum]
            return
        self._install_sync_checkpoint(
            assembled, checkpoint_op, wire.u128(header, "checkpoint_id"),
            blob_checksum, int(header["commit"]),
        )

    def _install_sync_checkpoint(self, payload: bytes, checkpoint_op: int,
                                 commit_min_checksum: int, blob_checksum: int,
                                 remote_commit: int) -> None:
        assert checkpoint_op > self.commit_min  # guarded at receive
        self._ckpt_join()  # superblock writes serialize with async flips
        # Shipped grid blocks must land BEFORE restore: restoring a
        # spilled snapshot reads the LSM tier to rebuild directories.
        try:
            blob = self._sync_unwrap(payload)
        except (ValueError, KeyError, TypeError):
            # Malformed sync payload from a peer (SnapshotError is a
            # ValueError; geometry checks raise ValueError; missing
            # state keys raise KeyError; type-confused entries — e.g.
            # `blocks` encoded as an int — raise TypeError in the
            # len()/int() geometry code): drop it and retry later —
            # a buggy peer must not crash this replica.
            return
        self._restore_snapshot(blob)
        self.sm.prepare_timestamp = self.sm.commit_timestamp

        region = int(self.superblock.working["sequence"]) % 2
        offset = self._grid_region_offset(region, len(blob))
        self._write_grid(offset, blob)
        if self.forest is not None:
            self.forest.grid.flush_writes()
        self.storage.sync()
        self.superblock.checkpoint(
            commit_min=checkpoint_op,
            commit_min_checksum=commit_min_checksum,
            commit_max=max(self.commit_max, remote_commit),
            checkpoint_offset=offset,
            checkpoint_size=len(blob),
            checkpoint_checksum=wire.checksum(blob),
            view=self.view,
            # The shipped blob restored the source's committed
            # membership (_restore_snapshot); carrying the OLD fields
            # forward here would resurrect the pre-sync epoch on
            # restart.
            epoch=self.epoch,
            members=self.members,
            # Recomputed from the state the blob restored, so a
            # restart's recompute-and-assert covers synced
            # checkpoints too.
            state_root=(
                int.from_bytes(self.sm.state_root(), "little")
                if hasattr(self.sm, "state_root")
                else 0
            ),
        )
        self.checkpoint_op = checkpoint_op
        self.commit_min = checkpoint_op
        self.commit_max = max(self.commit_max, remote_commit)
        self.commit_parent = commit_min_checksum
        # State sync supersedes WAL repair only BELOW the new
        # checkpoint (reference: src/vsr/sync.zig).  A journal tail
        # above it — e.g. the canonical tail a new primary adopted via
        # DVC before syncing its lagging prefix — holds committed ops
        # that MUST survive: truncating to checkpoint_op here would
        # make the primary's start_view advertise the shorter log and
        # wipe the committed suffix cluster-wide (found by the VOPR
        # corruption nemesis, seed 8006).
        if self.op <= checkpoint_op:
            self.op = checkpoint_op
            self.parent_checksum = commit_min_checksum
            # The checkpoint's commit_min_checksum IS the authoritative
            # head anchor now — without this, a sync during anchor
            # resolution leaves every prepare path gated forever (the
            # pin it was waiting on is cleared below).
            self._anchor_pending = False
            self._repair_wanted.clear()
            self._stash.clear()
        else:
            for o in [o for o in self._repair_wanted if o <= checkpoint_op]:
                del self._repair_wanted[o]
            for o in [o for o in self._stash if o <= checkpoint_op]:
                del self._stash[o]
        self._canon_pending = False
        self._sync_chunks.clear()
        self._advance_commit(self.commit_max)
        if self._repair_wanted:
            self._send_repair_requests(force=True)

    # ------------------------------------------------------------------
    # View change.

    def _enter_view(self, view: int) -> None:
        """Adopt a higher view as a backup in normal status.

        Entering PASSIVELY (we missed the view change) means our
        uncommitted journal tail may hold superseded siblings of the
        canonical ops (same parent, different content) — commits are
        gated until the new primary's start_view installs the canonical
        tail (reference: Command.request_start_view)."""
        assert view > self.view
        self.view = view
        self.status = "normal"
        self.log_view = view
        # Passive entry: the new view's canonical is NOT installed, so
        # our tail above commit_min is unconfirmed — persisting it as
        # this log_view's claim would make a superseded-sibling tail
        # durable and top-cohort.  Claim only the committed prefix
        # (always within the recovered journal, so restart-neutral).
        self._ckpt_join()  # superblock writes serialize with async flips
        self.superblock.view_change(
            self.view, self.log_view, self.commit_max,
            op_claimed=self.commit_min,
            # The previously-installed canonical suffix is KEPT (not
            # cleared): it is still our best durable knowledge of the
            # uncommitted range, and clearing it would reopen the
            # stale-carrier crash window right here (crash after
            # passive entry, before this view's start_view arrives,
            # restarts vouching raw ring siblings at the freshest
            # log_view).  If this view's canonical replaced any of
            # those ops, its copies carry a higher prepare-view and
            # win the merge tie-break; ring entries prepared in this
            # view likewise outrank the kept suffix in _tail_headers.
        )
        self.pipeline.clear()
        if self._np is not None:
            self._np.reset()
        self.request_queue.clear()
        self._queue_tenants.clear()
        self._tenant_depth.clear()
        # The queue is empty: any open overload episode closes with it
        # (left latched, the new view's first drain would run WFQ
        # order with no shed since — breaking the differential
        # contract's strict-FIFO-outside-an-episode guarantee).
        self._qos_episode = False
        self._queued_keys.clear()
        self._svc_votes.clear()
        self._dvc.clear()
        # Old-view vouches above the commit frontier are void: the new
        # view may have chosen different siblings there.
        for k in [k for k in self._vouched if k > self.commit_min]:
            del self._vouched[k]
        self._last_primary_seen = self._ticks
        if self.op > self.commit_min and not self.is_primary:
            self._canon_pending = True
            self._request_start_view()

    def _request_start_view(self) -> None:
        h = wire.make_header(
            command=Command.request_start_view, cluster=self.cluster,
            view=self.view, replica=self.replica,
        )
        wire.finalize_header(h, b"")
        self.bus.send(self.primary_index(), h, b"")

    def _on_request_start_view(self, header: np.ndarray, body: bytes) -> None:
        if (
            int(header["view"]) == self.view
            and self.status == "normal"
            and self.is_primary
        ):
            self._send_start_view(dst=int(header["replica"]))

    def _start_view_change(self, view: int) -> None:
        for k in [k for k in self._vouched if k > self.commit_min]:
            del self._vouched[k]
        self._canon_pending = False  # the DVC/start_view round re-canonizes
        self.status = "view_change"
        self.view = view
        self._svc_votes.setdefault(view, set()).add(self.replica)
        self._broadcast_svc()

    def _broadcast_svc(self) -> None:
        self._vc_last_sent = self._ticks
        h = wire.make_header(
            command=Command.start_view_change, cluster=self.cluster,
            view=self.view, replica=self.replica,
        )
        wire.finalize_header(h, b"")
        for r in range(self.replica_count):
            if r != self.replica:
                self.bus.send(r, h, b"")

    def _on_start_view_change(self, header: np.ndarray, body: bytes) -> None:
        if self.standby:
            return  # non-voting; the start_view brings the outcome
        view = int(header["view"])
        if view < self.view:
            return
        if view > self.view or self.status == "normal":
            if view == self.view and self.status == "normal":
                # A replica re-running view change for OUR live view
                # (e.g. rejoining after a crash with an unconfirmed
                # tail): the primary hands it the canonical view state
                # (reference: request_start_view).
                if self.is_primary:
                    self._send_start_view(dst=int(header["replica"]))
                return
            self._start_view_change(max(view, self.view))
        self._svc_votes.setdefault(self.view, set()).add(int(header["replica"]))
        votes = self._svc_votes.get(self.view, set())
        if len(votes) >= self.quorum_view_change:
            self._send_do_view_change()

    def _send_do_view_change(self) -> None:
        if self.standby:
            return
        # Persist before participating (reference: superblock view_change).
        self._ckpt_join()  # superblock writes serialize with async flips
        self.superblock.view_change(
            self.view, self.log_view, self.commit_max,
            op_claimed=self.op,
        )
        payload = {
            "log_view": self.log_view,
            "op": self.op,
            "commit_min": self.commit_min,
            "headers": self._tail_headers(),
        }
        body = _encode_dvc(payload)
        h = wire.make_header(
            command=Command.do_view_change, cluster=self.cluster,
            view=self.view, replica=self.replica, op=self.op,
            commit=self.commit_min,
        )
        wire.finalize_header(h, body)
        target = self.primary_index()
        if target == self.replica:
            self._on_do_view_change(h, body)
        else:
            self.bus.send(target, h, body)

    def _tail_headers(self) -> list[bytes]:
        """Headers of EVERY op we know above commit_min — from the
        in-memory redundant ring, which recovery populates even for
        slots whose prepares are torn or corrupt.  A damaged replica
        thus still VOUCHES for committed ops it can no longer read:
        the new primary pins their checksums and repairs the bodies
        from peers instead of silently truncating them (the reference
        gets the same property from DVC headers + nacks; understating
        DVCs lost committed ops — VOPR seed 8018).

        The superblock's persisted canonical suffix overrides ring
        entries prepared BEFORE the log_view that installed it
        (vh_log_view): those are pre-merge siblings the install
        superseded (durable in our ring only because the crash beat
        the repair).  Ring entries prepared at the install point or
        later postdate it (that view's — or, after passive entries, a
        newer view's — own prepares) and win."""
        by_op: dict[int, np.ndarray] = {}
        for slot in range(self.journal.slot_count):
            h = self.journal.headers[slot]
            if int(h["command"]) != int(Command.prepare):
                continue
            op = int(h["op"])
            # Bounded by our head claim: ring leftovers ABOVE the
            # recovered head are stale garbage from older generations
            # and must not ride into the canonical merge (VOPR seed
            # 8005); everything within (commit_min, op] is our
            # knowledge of the current history — including ops whose
            # prepares are damaged, which the redundant header still
            # vouches (VOPR seeds 8006/8018).  Sub-commit_min ops are
            # deliberately absent: for them "later view wins" is
            # unsound (a dead-view sibling can outrank the committed
            # one — widening this bound to the checkpoint broke
            # deep-slice seeds 8000/8003); their immutability is
            # enforced receiver-side in _install_log instead.
            if not self.commit_min < op <= self.op:
                continue
            if not wire.verify_header(h):
                continue
            by_op[op] = h
        vh_log_view = int(self.superblock.working["vh_log_view"])
        vh_top = 0
        for raw in self.superblock.view_headers():
            h = wire.header_from_bytes(raw)
            if not wire.verify_header(h):
                continue
            op = int(h["op"])
            if not self.commit_min < op <= self.op:
                continue
            cur = by_op.get(op)
            if cur is None or int(cur["view"]) < vh_log_view:
                by_op[op] = h
            vh_top = max(vh_top, op)
        # Chain-consistency above the vouched canonical suffix: an
        # install truncates the old tail only IN MEMORY — the ring
        # still physically holds it, and a crash-restart resurrects it
        # into the recovered head.  A dead leftover both PREDATING the
        # install (view < vh_log_view) and NOT chaining from the
        # canonical would ship a MIXED chain; the receiving merge's
        # sanitize resolves the contradiction by dropping the TRUE
        # canonical op below it, and the dead suffix gets installed
        # and committed — replica divergence (soak seed 323928758).
        if vh_top and vh_top in by_op:
            expect = wire.u128(by_op[vh_top], "checksum")
            prev = vh_top
            for o in sorted(k for k in by_op if k > vh_top):
                h = by_op[o]
                if o != prev + 1:
                    expect = None  # gap: linkage unverifiable above it
                prev = o
                verified = expect is not None and (
                    wire.u128(h, "parent") == expect
                )
                if verified or int(h["view"]) >= vh_log_view:
                    # Chains from the canonical, or postdates the
                    # install (the new view's own prepare): keep, and
                    # it defines the verified frontier upward.
                    expect = wire.u128(h, "checksum")
                    continue
                # Predates the install and cannot be positively linked
                # (contradicts the frontier, or sits above a gap that
                # makes linkage unverifiable): dead leftover — do NOT
                # stop at the first one, later ring entries above a
                # gap are equally suspect.
                del by_op[o]
                expect = None
        return [by_op[op].tobytes() for op in sorted(by_op)]

    def _on_do_view_change(self, header: np.ndarray, body: bytes) -> None:
        view = int(header["view"])
        if view < self.view:
            return
        if view > self.view:
            self._start_view_change(view)
        if self.primary_index(view) != self.replica:
            return
        self._dvc[int(header["replica"])] = _decode_dvc(body)
        if self.replica not in self._dvc:
            self._ckpt_join()
            self.superblock.view_change(
                self.view, self.log_view, self.commit_max,
                op_claimed=self.op,
            )
            self._dvc[self.replica] = {
                "log_view": self.log_view, "op": self.op,
                "commit_min": self.commit_min, "headers": self._tail_headers(),
            }
        if len(self._dvc) < self.quorum_view_change:
            return
        if self.status != "view_change":
            return

        # Adopt the longest log of the highest log_view (VRR rule),
        # MERGING headers across the highest-log_view cohort: each DVC
        # vouches for every op its redundant ring knows, so the union
        # covers committed ops even when every cohort member's
        # chain-verified head understates (recovery truncation).
        # Same-op conflicts (a stale sibling surviving in one ring)
        # resolve to the header prepared in the later view.
        best_log_view = max(d["log_view"] for d in self._dvc.values())
        cohort = [
            d for d in self._dvc.values()
            if d["log_view"] == best_log_view
        ]
        op_claimed = max(d["op"] for d in cohort)
        commit_floor = max(d["commit_min"] for d in self._dvc.values())
        # Merge headers from EVERY DVC (not only the top cohort: a
        # cohort member can claim a canonical tail whose prepares it
        # never finished repairing, while an older-view replica still
        # holds the committed headers — truncating at the hole
        # re-prepared NEW ops at committed numbers, VOPR seed
        # 1064614514).  Same-op conflicts resolve by the CARRIER's
        # log_view (VRR): the copy carried by the DVC with the
        # freshest installed canonical wins; the header's own
        # prepare-view only tie-breaks equal carriers.  Resolving by
        # prepare-view alone let a dead higher-view sibling held by a
        # stale replica beat the committed lower-view copy, rewriting
        # committed slots and chain-breaking every journal (VOPR seed
        # 925761995).  A stale carrier additionally cannot nominate
        # content at or below the quorum's commit floor.  (The
        # reference closes the residual uncertainty with its DVC nack
        # quorum, src/vsr/replica.zig.)
        best: dict[int, tuple[int, np.ndarray]] = {}
        for d in self._dvc.values():
            for raw in d["headers"]:
                h = wire.header_from_bytes(raw)
                if not wire.verify_header(h):
                    continue
                op = int(h["op"])
                if op > op_claimed:
                    continue  # beyond the canonical claim: stale tail
                if d["log_view"] < best_log_view and op <= commit_floor:
                    continue
                cur = best.get(op)
                if cur is None or d["log_view"] > cur[0] or (
                    d["log_view"] == cur[0]
                    and int(h["view"]) > int(cur[1]["view"])
                ):
                    best[op] = (d["log_view"], h)
        canonical = [best[op][1] for op in sorted(best)]
        self._install_log(canonical, op_claimed, commit_floor)

        self.status = "normal"
        self.log_view = self.view
        self._ckpt_join()
        self.superblock.view_change(
            self.view, self.log_view, self.commit_max,
            op_claimed=self.op,
            view_headers=[
                h.tobytes() for h in self._installed_canonical
                if int(h["op"]) > self.commit_min
            ],
        )
        self._svc_votes.clear()
        self._dvc.clear()
        self._send_start_view()
        self._advance_commit(self.commit_max)
        self._primary_requeue_uncommitted()

    def _install_log(self, canonical: list[np.ndarray], op_claimed: int,
                     commit_floor: int,
                     head_checksum: int | None = None,
                     min_head: int = 0) -> None:
        """Make our journal match the canonical tail, requesting any
        prepares we don't hold.

        `op_claimed` is the sender's op; its header tail may stop short
        of it (journal holes skip headers), in which case only the ops
        we have headers for are adopted — anything above is uncommitted
        (committed ops always reach a quorum's journals) and truncates.

        `min_head` (same-view reinstalls): a delayed duplicate
        start_view must still install its canonical headers (repair
        pins for stale siblings) but must NOT regress our head below
        the same-view tail we already hold — our vouches and anchor
        above its coverage stand.
        """
        self._canon_pending = False  # the canonical tail is now known
        was_anchor_pending = self._anchor_pending
        # Sanitize: within a canonical chain the highest header is
        # authoritative downward via parent links.  An entry whose
        # checksum contradicts the entry above it is a provably stale
        # sibling that leaked into a merge (a committed op can be
        # invisible to every DVC, bounded by commit_min, while an old
        # sibling in someone's ring is not).  Adopting such an entry
        # rewrote the committed slot while KEEPING the op above that
        # vouches its replacement — permanently chain-breaking every
        # journal in the cluster (VOPR seed 925761995).  Dropping it
        # leaves a hole; receivers pin the true checksum from the op
        # above via the chain walk and repair from whoever holds it.
        by_op = {int(h["op"]): h for h in canonical}
        for op in sorted(by_op, reverse=True):
            above = by_op.get(op + 1)
            if above is not None and wire.u128(above, "parent") != wire.u128(
                by_op[op], "checksum"
            ):
                del by_op[op]
        canonical = [by_op[op] for op in sorted(by_op)]
        # Stash the sanitized canonical for durable persistence: the
        # caller records its suffix in the superblock atomically with
        # log_view (see superblock.view_headers) so a crash between
        # install and journal repair cannot resurrect pre-merge
        # siblings into our next DVC.
        self._installed_canonical = list(canonical)
        covered = max([int(h["op"]) for h in canonical] + [op_claimed])
        # The canonical headers vouch their checksums for the commit
        # gate; anything above commit_min not re-vouched here is stale
        # — except same-view tail ops beyond a duplicate's coverage.
        for k in [
            k for k in self._vouched
            if k > self.commit_min and (not min_head or k <= covered)
        ]:
            del self._vouched[k]
        # Checksum pins from the previous view are equally stale in
        # the covered range: a surviving pin is a standing order to
        # OVERWRITE its slot the moment a matching (dead-view) prepare
        # arrives — which clobbered a newly-prepared canonical op and
        # hijacked the head anchor (seed 460991023).  The install
        # re-pins below exactly what it still wants; the same-view-
        # reinstall branch below re-arms the pending-anchor pin it
        # depends on (the pin must not simply be EXEMPTED here — a
        # resolved-but-stale anchor pin surviving into a head-found
        # install would recreate the standing-overwrite hazard).
        for k in [
            k for k in self._repair_wanted
            if k > self.commit_min and (not min_head or k <= covered)
        ]:
            del self._repair_wanted[k]
        for h in canonical:
            if int(h["op"]) > self.commit_min:
                self._vouched[int(h["op"])] = wire.u128(h, "checksum")
        have_ops = [int(h["op"]) for h in canonical]
        # Never regress below our own commit frontier: committed ops
        # are immutable.
        op_head = max(
            max(have_ops) if have_ops else 0, commit_floor,
            self.commit_min, min_head,
        )
        for h in canonical:
            op = int(h["op"])
            if op > op_head:
                continue
            if op <= self.commit_min:
                # WE committed this op: its journal slot is immutable.
                # A canonical header that disagrees is a stale sibling
                # that leaked into the merge (a committed op can fall
                # out of its holder's DVC, bounded by commit_min) —
                # adopting it rewrote committed slots and left an
                # unserviceable chain break (VOPR seed 925761995).
                # Peers missing the op repair by the exact checksum
                # the op above vouches.
                continue
            checksum = wire.u128(h, "checksum")
            have = self.journal.read_prepare(op)
            if have is not None and wire.u128(have[0], "checksum") == checksum:
                continue
            self._repair_wanted[op] = checksum
        self.op = op_head
        self.commit_max = max(self.commit_max, commit_floor)
        head = next(
            (h for h in canonical if int(h["op"]) == op_head), None
        )
        self._anchor_pending = False
        if head is not None:
            self.parent_checksum = wire.u128(head, "checksum")
        elif min_head and op_head == min_head:
            # Same-view reinstall kept our head: the current anchor
            # (and its pending-resolution state, if any) stands.
            # Deliberately NOT adopting a sender-supplied checksum for
            # this op even when op_claimed matches: a delayed
            # duplicate's head claim can name a superseded sibling of
            # the tail we already vouch (empirically diverges state —
            # VOPR deep-slice seed 8000); the pin-resolution round
            # trip is the safe path for a genuinely pending anchor.
            self._anchor_pending = was_anchor_pending
            if was_anchor_pending and op_head not in self._repair_wanted:
                # The pin sweep above dropped the pending anchor's
                # pin; without it nothing requests anything and the
                # resolution round trip dies (the deep-lag state-sync
                # wedge).  Re-arm from 0 (re-resolve).
                self._repair_wanted[op_head] = 0
                self._anchor_pin_view = -1
        elif head_checksum is not None and op_head == op_claimed:
            # No header covers op_head (e.g. the sender state-synced and
            # its checkpoint op is not journaled): anchor on the
            # sender's explicit head checksum instead of a stale local
            # one — a wrong anchor would poison the chain-repair pins.
            self.parent_checksum = head_checksum
        else:
            # Unknown anchor: do not run the chain walk against a
            # possibly-stale parent_checksum — and do NOT prepare new
            # ops on it either.  Pin the head for header resolution
            # (want=0 resolves to a checksum via request_headers, then
            # the prepare repairs by checksum); _maybe_resolve_anchor
            # re-anchors once the head prepare is local.
            if op_head > 0:
                self._anchor_pending = True
                # Force 0 (re-resolve): a leftover nonzero pin from an
                # older view could name a superseded sibling.
                self._repair_wanted[op_head] = 0
                self._anchor_pin_view = -1
            if self._repair_wanted:
                self._send_repair_requests(force=True)
            return
        self._verify_chain_down()
        if self._repair_wanted:
            self._send_repair_requests(force=True)

    def _verify_chain_down(self) -> None:
        """Walk the journal from the canonical head toward commit_min,
        verifying each prepare's checksum against its successor's
        `parent`.  The first missing/mismatched op (a superseded
        sibling from an older view) is pinned for exact-checksum
        repair.  While the walk cannot reach commit_min, the whole
        uncommitted range is SUSPECT (deeper siblings may hide below
        the unverified op) and commits are gated (_advance_commit)."""
        if self._anchor_pending:
            # parent_checksum is stale while the canonical head is
            # unresolved: a walk from it derives GARBAGE pins (seed
            # 377174739: a pin for op N naming another op's checksum
            # gated commits forever).  Stay suspect; the walk re-runs
            # from the true anchor once it resolves.
            self._chain_suspect = True
            return
        expect = self.parent_checksum
        for op in range(self.op, self.commit_min, -1):
            read = self.journal.read_prepare(op)
            if read is None or wire.u128(read[0], "checksum") != expect:
                self._repair_wanted[op] = expect
                self._chain_suspect = True
                self._send_repair_requests()
                return
            # Verified against the canonical chain: any pin for this
            # op is obsolete (a different-sibling pin is stale garbage
            # that would gate commits forever; a matching pin is
            # simply satisfied) — drop it.
            self._repair_wanted.pop(op, None)
            expect = wire.u128(read[0], "parent")
        self._chain_suspect = False

    def _send_start_view(self, dst: int | None = None) -> None:
        body = _encode_dvc({
            "log_view": self.log_view, "op": self.op,
            "commit_min": self.commit_min, "headers": self._tail_headers(),
            # While the canonical head is unresolved, parent_checksum
            # is a stale pre-install value: advertising it would make
            # backups adopt it as their anchor (head_checksum=0
            # decodes to None — receivers run their own unknown-anchor
            # resolution instead).
            "head_checksum": 0 if self._anchor_pending
            else self.parent_checksum,
        })
        h = wire.make_header(
            command=Command.start_view, cluster=self.cluster, view=self.view,
            replica=self.replica, op=self.op, commit=self.commit_min,
        )
        wire.finalize_header(h, body)
        targets = (
            [dst] if dst is not None
            else [r for r in range(self.total_count) if r != self.replica]
        )
        for r in targets:
            self.bus.send(r, h, body)

    def _on_start_view(self, header: np.ndarray, body: bytes) -> None:
        view = int(header["view"])
        if view < self.view:
            return
        if view == self.view and int(header["op"]) < self.commit_min:
            # Stale/delayed start_view for the current view (e.g. a
            # rejoin-help reply that raced past newer commits): adopting
            # it would regress op below our commit frontier.
            return
        payload = _decode_dvc(body)
        # Within an installed view the primary's log only grows, so a
        # same-view start_view claiming less than our op is a delayed
        # duplicate (lossy-network reordering).  Its HEADERS still
        # carry canonical knowledge worth installing (pins for stale
        # siblings below the claim — dropping the message outright
        # regressed repairs, seed 8000), but our head must not regress
        # to its stale claim (a regressed head with a stale anchor
        # derived garbage pins, seed 377174739).
        same_view_reinstall = view == self.view and self.log_view == view
        self.view = view
        self.status = "normal"
        self.log_view = view
        canonical = [wire.header_from_bytes(raw) for raw in payload["headers"]]
        self._install_log(
            canonical, payload["op"], int(header["commit"]),
            head_checksum=payload.get("head_checksum"),
            min_head=self.op if same_view_reinstall else 0,
        )
        # Persist the installed canonical suffix with log_view.  A
        # same-view reinstall merges with the already-persisted set:
        # a delayed duplicate's shorter coverage must not shed the
        # durable vouch for tail ops we already installed.  Merge ONLY
        # when the persisted suffix was installed at THIS log_view —
        # after a passive entry (which keeps the older suffix) the
        # first start_view also matches same_view_reinstall, and
        # merging would re-stamp the older view's headers at the
        # current vh_log_view, elevating them above intermediate-view
        # ring entries in _tail_headers.
        vh: dict[int, bytes] = {}
        if same_view_reinstall and (
            int(self.superblock.working["vh_log_view"]) == self.log_view
        ):
            for raw in self.superblock.view_headers():
                prev = wire.header_from_bytes(raw)
                if wire.verify_header(prev):
                    vh[int(prev["op"])] = raw
        for ch in self._installed_canonical:
            vh[int(ch["op"])] = ch.tobytes()
        self._ckpt_join()
        self.superblock.view_change(
            self.view, self.log_view, self.commit_max,
            op_claimed=self.op,
            view_headers=[
                vh[op] for op in sorted(vh) if op > self.commit_min
            ],
        )
        self._svc_votes.clear()
        self._dvc.clear()
        self._last_primary_seen = self._ticks
        self._advance_commit(self.commit_max)


# ----------------------------------------------------------------------
# DVC/SV body codec: length-prefixed header list + scalars.


def _encode_dvc(payload: dict) -> bytes:
    import struct

    head = payload.get("head_checksum") or 0
    parts = [
        struct.pack(
            "<QQQQQI",
            payload["log_view"], payload["op"], payload["commit_min"],
            head & 0xFFFFFFFFFFFFFFFF, head >> 64,
            len(payload["headers"]),
        )
    ]
    parts.extend(payload["headers"])
    return b"".join(parts)


def _decode_dvc(body: bytes) -> dict:
    import struct

    log_view, op, commit_min, head_lo, head_hi, n = struct.unpack_from(
        "<QQQQQI", body, 0
    )
    off = 44
    headers = []
    from tigerbeetle_tpu.constants import HEADER_SIZE

    for _ in range(n):
        headers.append(body[off : off + HEADER_SIZE])
        off += HEADER_SIZE
    return {
        "log_view": log_view, "op": op, "commit_min": commit_min,
        "headers": headers,
        "head_checksum": (head_lo | (head_hi << 64)) or None,
    }
