"""SuperBlock: 4-copy quorum-written root of persistent state.

Keeps the reference's protocol (reference: src/vsr/superblock.zig:1-56,
superblock_quorums.zig): the superblock is written as 4 identical
copies; opening requires a quorum (2 of 4) of valid copies agreeing on
the highest sequence, so a crash mid-update can never lose both the
old and the new state.

State tracked (ours — the checkpoint reference is a grid-zone snapshot
blob instead of an LSM manifest):
- VSR state: view / log_view / commit_min / commit_max,
- checkpoint: op (`commit_min`), checksum of the prepare at that op,
  and the (offset, size, checksum) of the state snapshot in the grid
  zone (double-buffered A/B regions so a torn snapshot write leaves
  the previous checkpoint intact).
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.storage import (
    SUPERBLOCK_COPIES,
    SUPERBLOCK_COPY_SIZE,
    Storage,
)

VIEW_HEADERS_MAX = 14  # canonical-suffix headers the superblock holds

SUPERBLOCK_DTYPE = np.dtype(
    [
        ("checksum_lo", "<u8"), ("checksum_hi", "<u8"),
        ("cluster_lo", "<u8"), ("cluster_hi", "<u8"),
        ("sequence", "<u8"),
        ("replica", "<u2"), ("replica_count", "<u2"),
        ("view", "<u4"), ("log_view", "<u4"),
        ("version", "<u4"),
        ("commit_min", "<u8"),
        ("commit_max", "<u8"),
        ("commit_min_checksum_lo", "<u8"), ("commit_min_checksum_hi", "<u8"),
        ("checkpoint_offset", "<u8"),
        ("checkpoint_size", "<u8"),
        ("checkpoint_checksum_lo", "<u8"), ("checkpoint_checksum_hi", "<u8"),
        # Cluster membership (reconfiguration; reference:
        # src/vsr.zig:273-311): epoch + the slot->process permutation.
        # member_count == 0 means the identity default.
        ("epoch", "<u8"),
        ("member_count", "<u2"),
        ("members", "V64"),
        # Canonical log claim of the installed log_view: the highest
        # op the view's canonical said exists.  Restart must not
        # forget it — a recovering replica whose journal understates
        # the claim would send understating DVCs, and a view-change
        # quorum of understating DVCs truncated committed ops (VOPR
        # seed 1064614514; reference durably keeps its vsr_headers in
        # the superblock for the same reason).
        ("op_claimed", "<u8"),
        # Canonical suffix headers of the installed log_view (the
        # reference durably keeps `vsr_headers` in its superblock,
        # src/vsr/superblock.zig).  A replica that installed a
        # canonical tail but crashed before its journal ring durably
        # absorbed it would otherwise restart vouching the PRE-merge
        # siblings its ring still holds — at the freshest log_view,
        # where the merge trusts it most (the stale-carrier class,
        # VOPR seeds 925761995/941686528/199800160).  Persisting the
        # installed suffix atomically with log_view closes the gap:
        # restart re-vouches the canonical copies.
        ("vh_count", "<u2"),
        # The log_view at which the suffix was installed: passive view
        # entries advance log_view while KEEPING the suffix, so the
        # precedence rule in _tail_headers (ring entries prepared at
        # or after the install outrank the suffix) must compare
        # against the install point, not the current log_view.
        ("vh_log_view", "<u4"),
        ("view_headers", f"V{VIEW_HEADERS_MAX * HEADER_SIZE}"),
        # State root (state_machine/commitment.py): the 16-byte
        # incremental commitment of the account table at commit_min.
        # Recovery recomputes it from the restored snapshot and
        # asserts equality; the VOPR compares it cross-replica.  Zero
        # = no checkpoint taken yet / state machine without roots.
        # APPENDED (carved from reserved) so every pre-r15 field keeps
        # its offset: an old data file decodes root=0 here, which the
        # restore assert treats as "not recorded" and skips.
        ("state_root_lo", "<u8"), ("state_root_hi", "<u8"),
        ("reserved",
         f"V{SUPERBLOCK_COPY_SIZE - 224 - VIEW_HEADERS_MAX * HEADER_SIZE}"),
    ]
)
assert SUPERBLOCK_DTYPE.itemsize == SUPERBLOCK_COPY_SIZE

QUORUM_OPEN = 2  # of SUPERBLOCK_COPIES


class SuperBlock:
    def __init__(self, storage: Storage, cluster: int) -> None:
        self.storage = storage
        self.cluster = cluster
        self.working = np.zeros(1, SUPERBLOCK_DTYPE)[0]

    # ------------------------------------------------------------------

    def format(self, replica: int, replica_count: int) -> None:
        h = np.zeros(1, SUPERBLOCK_DTYPE)[0]
        h["cluster_lo"] = self.cluster & 0xFFFFFFFFFFFFFFFF
        h["cluster_hi"] = self.cluster >> 64
        h["sequence"] = 1
        h["replica"] = replica
        h["replica_count"] = replica_count
        h["version"] = wire.VERSION
        h["commit_min"] = 0
        h["commit_max"] = 0
        root = wire.root_prepare(self.cluster)
        h["commit_min_checksum_lo"] = root["checksum_lo"]
        h["commit_min_checksum_hi"] = root["checksum_hi"]
        self._write(h)

    def checkpoint(
        self,
        commit_min: int,
        commit_min_checksum: int,
        commit_max: int,
        checkpoint_offset: int,
        checkpoint_size: int,
        checkpoint_checksum: int,
        view: int | None = None,
        log_view: int | None = None,
        epoch: int | None = None,
        members: list[int] | None = None,
        state_root: int = 0,
    ) -> None:
        """Durably advance to a new checkpoint (snapshot must already
        be synced in the grid zone — write ordering is the caller's
        contract)."""
        h = self.working.copy()
        h["sequence"] = int(h["sequence"]) + 1
        if epoch is not None:
            h["epoch"] = epoch
        if members is not None:
            assert len(members) <= 64
            h["member_count"] = len(members)
            h["members"] = bytes(members).ljust(64, b"\x00")
        h["commit_min"] = commit_min
        h["commit_max"] = commit_max
        h["commit_min_checksum_lo"] = commit_min_checksum & 0xFFFFFFFFFFFFFFFF
        h["commit_min_checksum_hi"] = commit_min_checksum >> 64
        h["checkpoint_offset"] = checkpoint_offset
        h["checkpoint_size"] = checkpoint_size
        h["checkpoint_checksum_lo"] = checkpoint_checksum & 0xFFFFFFFFFFFFFFFF
        h["checkpoint_checksum_hi"] = checkpoint_checksum >> 64
        h["state_root_lo"] = state_root & 0xFFFFFFFFFFFFFFFF
        h["state_root_hi"] = state_root >> 64
        if view is not None:
            h["view"] = view
        if log_view is not None:
            h["log_view"] = log_view
        self._write(h)

    def view_change(self, view: int, log_view: int, commit_max: int,
                    op_claimed: int | None = None,
                    view_headers: list[bytes] | None = None) -> None:
        """Durably record a view change (required before participating
        in the new view — reference: superblock view_change trigger).
        `op_claimed` records the installed canonical log claim of
        log_view (overwrites — it belongs to that log_view).
        `view_headers` (raw 256-byte wire headers, ascending op)
        overwrites the persisted canonical suffix; None keeps the
        previous set (it still belongs to the unchanged log_view)."""
        h = self.working.copy()
        h["sequence"] = int(h["sequence"]) + 1
        h["view"] = view
        h["log_view"] = log_view
        h["commit_max"] = max(int(h["commit_max"]), commit_max)
        if op_claimed is not None:
            h["op_claimed"] = op_claimed
        if view_headers is not None:
            # Keep the HIGHEST ops when the suffix overflows: stale
            # siblings that no chain link can pin live only in the
            # uncommitted range above the merge's commit floor, which
            # the pipeline bounds at 8 ops (< VIEW_HEADERS_MAX).  Ops
            # further down are committed cluster-wide — a stale ring
            # sibling there is caught by the canonical chain walk and
            # repaired by the exact checksum the op above vouches.
            suffix = view_headers[-VIEW_HEADERS_MAX:]
            h["vh_count"] = len(suffix)
            h["vh_log_view"] = log_view
            h["view_headers"] = b"".join(suffix).ljust(
                VIEW_HEADERS_MAX * HEADER_SIZE, b"\x00"
            )
        self._write(h)

    def view_headers(self) -> list[bytes]:
        """The persisted canonical suffix of the current log_view."""
        n = int(self.working["vh_count"])
        raw = bytes(self.working["view_headers"])
        return [
            raw[i * HEADER_SIZE:(i + 1) * HEADER_SIZE] for i in range(n)
        ]

    def _write(self, h: np.ndarray) -> None:
        payload = h.tobytes()[16:]
        c = wire.checksum(payload)
        h["checksum_lo"] = c & 0xFFFFFFFFFFFFFFFF
        h["checksum_hi"] = c >> 64
        raw = h.tobytes()
        for copy in range(SUPERBLOCK_COPIES):
            self.storage.write(
                self.storage.layout.superblock_offset + copy * SUPERBLOCK_COPY_SIZE,
                raw,
            )
        self.storage.sync()
        self.working = h

    # ------------------------------------------------------------------

    def open(self) -> np.ndarray:
        """Quorum read: highest sequence with >= QUORUM_OPEN agreeing
        valid copies wins.

        With cluster=None the superblock adopts the cluster id found
        in the file (`tigerbeetle start` doesn't ask the operator to
        repeat what `format` already recorded — reference:
        src/tigerbeetle/main.zig start reads it from the superblock)."""
        copies = []
        for copy in range(SUPERBLOCK_COPIES):
            raw = self.storage.read(
                self.storage.layout.superblock_offset + copy * SUPERBLOCK_COPY_SIZE,
                SUPERBLOCK_COPY_SIZE,
            )
            h = np.frombuffer(raw, SUPERBLOCK_DTYPE)[0]
            if self._valid(h):
                copies.append(h)
        by_checksum: dict[int, list[np.ndarray]] = {}
        for h in copies:
            key = int(h["checksum_lo"]) | (int(h["checksum_hi"]) << 64)
            by_checksum.setdefault(key, []).append(h)
        quorums = [
            group[0]
            for group in by_checksum.values()
            if len(group) >= QUORUM_OPEN
        ]
        if not quorums:
            raise RuntimeError(
                "superblock: no quorum of valid copies"
                + (
                    f" for cluster {self.cluster} (data file formatted for"
                    " a different cluster?)"
                    if self.cluster is not None and self._any_other_cluster()
                    else ""
                )
            )
        self.working = max(quorums, key=lambda h: int(h["sequence"])).copy()
        if self.cluster is None:
            self.cluster = int(self.working["cluster_lo"]) | (
                int(self.working["cluster_hi"]) << 64
            )
        return self.working

    def _any_other_cluster(self) -> bool:
        """True if any copy is checksum-valid under SOME cluster id
        other than ours (diagnostic for the mismatch error; a copy
        valid under our OWN cluster means corruption, not mismatch)."""
        saved, self.cluster = self.cluster, None
        try:
            for copy in range(SUPERBLOCK_COPIES):
                raw = self.storage.read(
                    self.storage.layout.superblock_offset
                    + copy * SUPERBLOCK_COPY_SIZE,
                    SUPERBLOCK_COPY_SIZE,
                )
                h = np.frombuffer(raw, SUPERBLOCK_DTYPE)[0]
                if self._valid(h):
                    found = int(h["cluster_lo"]) | (
                        int(h["cluster_hi"]) << 64
                    )
                    if found != saved:
                        return True
            return False
        finally:
            self.cluster = saved

    def _valid(self, h: np.ndarray) -> bool:
        payload = h.tobytes()[16:]
        c = wire.checksum(payload)
        if int(h["checksum_lo"]) != c & 0xFFFFFFFFFFFFFFFF:
            return False
        if int(h["checksum_hi"]) != c >> 64:
            return False
        cluster = int(h["cluster_lo"]) | (int(h["cluster_hi"]) << 64)
        cluster_ok = self.cluster is None or cluster == self.cluster
        return cluster_ok and int(h["version"]) == wire.VERSION
