from tigerbeetle_tpu.vsr.wire import (  # noqa: F401
    HEADER_DTYPE,
    Command,
    VsrOperation,
    checksum,
    finalize_header,
    header_from_bytes,
    make_header,
    root_prepare,
    verify_header,
)
