"""VSR wire protocol: the 256-byte message header and checksums.

Re-designs the reference's `vsr.Header` (reference:
src/vsr/message_header.zig:17-103) as one flat little-endian layout
instead of per-command comptime unions: every command uses the same
field offsets, unused fields must be zero.  The 256-byte size, the
checksum/checksum_body/parent chaining discipline, and the command
vocabulary (reference: src/vsr.zig:273-311) are preserved.

Checksums: the reference uses AEGIS-128L MAC-as-checksum (reference:
src/vsr/checksum.zig:1-60, hardware AES).  This build is a standalone
framework — clients and replicas are ours — so we use SHA-256
truncated to 128 bits: available at C speed in both Python (hashlib)
and the C++ runtime, no key management, collision-resistant.  The
discipline is identical: `checksum` covers header bytes [16..256),
`checksum_body` covers the body, every header/body/disk block is
verified before any cast.
"""

from __future__ import annotations

import enum
import hashlib

import numpy as np

from tigerbeetle_tpu.constants import HEADER_SIZE

# reference: src/vsr.zig:273-311 (Command, 23 kinds)
class Command(enum.IntEnum):
    reserved = 0
    ping = 1
    pong = 2
    ping_client = 3
    pong_client = 4
    request = 5
    prepare = 6
    prepare_ok = 7
    reply = 8
    commit = 9
    start_view_change = 10
    do_view_change = 11
    start_view = 12
    request_start_view = 13
    request_headers = 14
    request_prepare = 15
    request_reply = 16
    headers = 17
    eviction = 18
    request_blocks = 19
    block = 20
    request_sync_checkpoint = 21
    sync_checkpoint = 22
    # Ours: typed admission-control shed (runtime/server.py).  Unlike
    # `eviction` it is NOT fatal to the session — the request was never
    # admitted, so the client may back off and retry under the same
    # request number.  Legacy clients that predate the command ignore
    # it and recover through their normal retransmission cadence.
    client_busy = 23


# reference: src/vsr.zig:318-411 — operations 0-127 are VSR-reserved;
# >=128 belong to the state machine (tigerbeetle_tpu.types.Operation).
class VsrOperation(enum.IntEnum):
    reserved = 0
    root = 1
    register = 2
    reconfigure = 3
    pulse = 4
    upgrade = 5
    # Admin scrape (ours): answered by the server loop directly from
    # its obs registry snapshot — read-only, sessionless, never enters
    # the consensus pipeline (obs/scrape.py).
    stats = 6
    # Proof-of-state query (ours): the 16-byte incremental state
    # commitment + commit_min, answered by the server loop from the
    # state machine's host twin — same sessionless, never-prepared
    # shape as `stats` (state_machine/commitment.py; the router folds
    # per-shard roots into one cluster commitment).
    state_root = 7


HEADER_DTYPE = np.dtype(
    [
        ("checksum_lo", "<u8"), ("checksum_hi", "<u8"),          # [0, 16)
        ("checksum_body_lo", "<u8"), ("checksum_body_hi", "<u8"),  # [16, 32)
        ("parent_lo", "<u8"), ("parent_hi", "<u8"),              # [32, 48)
        ("client_lo", "<u8"), ("client_hi", "<u8"),              # [48, 64)
        ("cluster_lo", "<u8"), ("cluster_hi", "<u8"),            # [64, 80)
        ("context_lo", "<u8"), ("context_hi", "<u8"),            # [80, 96)
        ("checkpoint_id_lo", "<u8"), ("checkpoint_id_hi", "<u8"),  # [96, 112)
        ("request", "<u4"), ("view", "<u4"),                     # [112, 120)
        ("op", "<u8"),                                           # [120, 128)
        ("commit", "<u8"),                                       # [128, 136)
        ("timestamp", "<u8"),                                    # [136, 144)
        ("size", "<u4"),                                         # [144, 148)
        ("release", "<u4"),                                      # [148, 152)
        ("replica", "u1"), ("command", "u1"),                    # [152, 154)
        ("operation", "u1"), ("version", "u1"),                  # [154, 156)
        # Trace context (ours): carved from the reserved region so
        # every hop of a sampled request carries its identity — the
        # request id, the origin CLOCK_MONOTONIC timestamp stamped at
        # client submit, and the sampled flag.  Zero everywhere for
        # untraced messages (the old all-reserved layout), so legacy
        # headers stay bit-identical.
        ("trace_id", "<u8"),                                     # [156, 164)
        ("trace_ts", "<u8"),                                     # [164, 172)
        ("trace_flags", "u1"),                                   # [172, 173)
        # Tenant key (ours, round 16): the LEDGER this request's
        # events belong to, stamped by tenant-aware clients so
        # admission/scheduling can key on it without touching the
        # body.  Zero (legacy clients, VSR-internal messages) means
        # "derive from the body's leading event" (tenant_of below) —
        # so legacy headers stay bit-identical, exactly like the
        # trace-context carve-out above.
        ("tenant", "<u4"),                                       # [173, 177)
        # Read attestation (ours, round 19): follower-served read
        # replies carry the 16-byte state commitment of the state they
        # were answered from plus the op it covers (`root_op` = the
        # follower's commit_min), so a client can verify integrity AND
        # staleness against the cluster commitment (the primary's
        # root-at-op ring).  Zero everywhere else — primary replies
        # and every legacy message stay bit-identical, exactly like
        # the trace/tenant carve-outs above.
        ("state_root_lo", "<u8"), ("state_root_hi", "<u8"),      # [177, 193)
        ("root_op", "<u8"),                                      # [193, 201)
        ("reserved", "V55"),                                     # [201, 256)
    ]
)
assert HEADER_DTYPE.itemsize == HEADER_SIZE, HEADER_DTYPE.itemsize

# trace_flags bits.
TRACE_SAMPLED = 1

# ----------------------------------------------------------------------
# Multi-tenant QoS (round 16): the tenant key is the LEDGER.

# Typed busy payload: a QoS shed carries WHO was shed and the rate the
# server observed for that tenant, so a client can size its backoff.
# Legacy (QoS-off) busy replies keep an empty body — bit-identical to
# the r12 wire contract; clients must treat both shapes as busy.
BUSY_BODY_DTYPE = np.dtype(
    [
        ("tenant", "<u4"),        # ledger the shed request belonged to
        ("queue_depth", "<u4"),   # tenant's queued requests at shed time
        ("observed_rps", "<u8"),  # tenant's arrival rate, requests/sec
    ]
)

# `ledger` offset inside the 128-byte Account AND Transfer wire rows
# (both place it at the same offset; asserted against types.py on
# first use so a layout change cannot silently break derivation).
_LEDGER_OFFSET: int | None = None
_LEDGER_OPS: tuple[int, int] = ()  # (create_accounts, create_transfers)


def _ledger_layout() -> tuple[int, tuple[int, int]]:
    global _LEDGER_OFFSET, _LEDGER_OPS
    if _LEDGER_OFFSET is None:
        from tigerbeetle_tpu import types

        off_a = types.ACCOUNT_DTYPE.fields["ledger"][1]
        off_t = types.TRANSFER_DTYPE.fields["ledger"][1]
        assert off_a == off_t, (off_a, off_t)
        _LEDGER_OFFSET = off_a
        _LEDGER_OPS = (
            int(types.Operation.create_accounts),
            int(types.Operation.create_transfers),
        )
    return _LEDGER_OFFSET, _LEDGER_OPS


def tenant_of(header: np.ndarray, body: bytes | memoryview | None = None,
              ) -> int:
    """The tenant (ledger) a client request belongs to.

    Precedence: the header's explicit `tenant` stamp (tenant-aware
    clients), else the `ledger` field of the body's first event for
    the create operations (legacy clients grouped by their actual
    ledger), else 0 — the shared best-effort class (lookups/filters
    carry no ledger on the wire)."""
    t = int(header["tenant"])
    if t:
        return t
    if body is None or len(body) == 0:
        return 0
    offset, ledger_ops = _ledger_layout()
    if int(header["operation"]) not in ledger_ops:
        return 0
    if len(body) < offset + 4:
        return 0
    return int.from_bytes(bytes(body[offset : offset + 4]), "little")


# ----------------------------------------------------------------------
# Root-attested follower serving (round 19; runtime/follower.py).


class FollowerRefuse(enum.IntEnum):
    """Typed reasons a follower declines a read (client_busy body).
    The split matters to routers: `lagging`/`overload` are transient
    (redirect to the primary, retry the follower later with backoff);
    `unattested`/`poisoned`/`corrupt`/`gap` mean the follower cannot
    currently PROVE its state and refuses rather than lie."""

    lagging = 1      # behind the staleness bound; primary has fresher
    unattested = 2   # no successful root cross-check yet
    poisoned = 3     # replayed root MISMATCHED the primary's — fatal
    overload = 4     # read admission (QoS) shed
    not_readable = 5  # write/unknown op sent to a read-only follower
    corrupt = 6      # tailed log failed checksum mid-file
    gap = 7          # op discontinuity in the tailed log
    incompatible = 8  # replayed record rejected by the state machine


# Typed follower refusal payload: WHY plus how far behind, so a
# client/router can decide between redirecting and backing off.
# Length-distinct from the 16-byte tenant BUSY_BODY_DTYPE, so
# parse_busy_body / parse_follower_busy disambiguate structurally.
FOLLOWER_BUSY_DTYPE = np.dtype(
    [
        ("reason", "<u4"),      # FollowerRefuse
        ("follower", "<u4"),    # follower id (operator-assigned)
        ("lag_ops", "<u8"),     # primary op estimate - follower commit_min
        ("commit_min", "<u8"),  # the follower's replayed-to op
    ]
)
assert FOLLOWER_BUSY_DTYPE.itemsize != BUSY_BODY_DTYPE.itemsize


def follower_busy_body(reason: int, follower: int, lag_ops: int,
                       commit_min: int) -> bytes:
    row = np.zeros(1, FOLLOWER_BUSY_DTYPE)[0]
    row["reason"] = int(reason)
    row["follower"] = follower & 0xFFFFFFFF
    row["lag_ops"] = max(0, lag_ops)
    row["commit_min"] = commit_min
    return row.tobytes()


def parse_follower_busy(body: bytes) -> tuple[int, int, int, int] | None:
    """(reason, follower, lag_ops, commit_min), or None for any other
    busy-body shape."""
    if len(body) != FOLLOWER_BUSY_DTYPE.itemsize:
        return None
    row = np.frombuffer(body, FOLLOWER_BUSY_DTYPE)[0]
    return (int(row["reason"]), int(row["follower"]),
            int(row["lag_ops"]), int(row["commit_min"]))


def stamp_attestation(h: np.ndarray, root: bytes, op: int) -> np.ndarray:
    """Stamp a follower reply's attestation fields.  Must run BEFORE
    finalize_header — the checksum covers them."""
    assert len(root) == 16, len(root)
    h["state_root_lo"] = int.from_bytes(root[:8], "little")
    h["state_root_hi"] = int.from_bytes(root[8:], "little")
    h["root_op"] = op
    return h


def attestation_of(h: np.ndarray) -> tuple[bytes, int] | None:
    """(root, op) when the reply carries an attestation, else None
    (primary-served / legacy replies are all-zero here; op 0 is the
    empty root prepare, never a servable state)."""
    op = int(h["root_op"])
    if not op:
        return None
    root = (
        int(h["state_root_lo"]).to_bytes(8, "little")
        + int(h["state_root_hi"]).to_bytes(8, "little")
    )
    return root, op


def busy_body(tenant: int, queue_depth: int, observed_rps: int) -> bytes:
    row = np.zeros(1, BUSY_BODY_DTYPE)[0]
    row["tenant"] = tenant & 0xFFFFFFFF
    row["queue_depth"] = min(queue_depth, 0xFFFFFFFF)
    row["observed_rps"] = observed_rps
    return row.tobytes()


def parse_busy_body(body: bytes) -> tuple[int, int, int] | None:
    """(tenant, queue_depth, observed_rps), or None for a legacy
    (empty / unknown-shape) busy body."""
    if len(body) != BUSY_BODY_DTYPE.itemsize:
        return None
    row = np.frombuffer(body, BUSY_BODY_DTYPE)[0]
    return int(row["tenant"]), int(row["queue_depth"]), int(row["observed_rps"])

# Wire-protocol version (ours, not the reference's).
VERSION = 1

_CHECKSUM_BODY_EMPTY = None  # computed lazily below


def checksum(data: bytes | memoryview | np.ndarray) -> int:
    """128-bit truncated SHA-256 (little-endian int)."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    return int.from_bytes(hashlib.sha256(data).digest()[:16], "little")


def checksum_pair(data) -> tuple[int, int]:
    c = checksum(data)
    return c & 0xFFFFFFFFFFFFFFFF, c >> 64


def make_header(**fields) -> np.ndarray:
    """A zeroed header record with the given fields set.

    u128-valued logical fields (parent, client, cluster, context,
    checkpoint_id) may be passed as plain ints and are split into
    limbs.
    """
    h = np.zeros(1, HEADER_DTYPE)[0]
    h["version"] = VERSION
    h["size"] = HEADER_SIZE
    for name, value in fields.items():
        if f"{name}_lo" in HEADER_DTYPE.names:
            h[f"{name}_lo"] = value & 0xFFFFFFFFFFFFFFFF
            h[f"{name}_hi"] = value >> 64
        else:
            h[name] = value
    return h


def u128(h: np.ndarray, name: str) -> int:
    return int(h[f"{name}_lo"]) | (int(h[f"{name}_hi"]) << 64)


def copy_trace(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """Propagate the trace context from `src` into `dst` (request ->
    prepare -> prepare_ok / reply).  Must run BEFORE finalize_header:
    the checksum covers the trace fields."""
    dst["trace_id"] = src["trace_id"]
    dst["trace_ts"] = src["trace_ts"]
    dst["trace_flags"] = src["trace_flags"]
    return dst


def trace_sampled(h: np.ndarray) -> int:
    """The header's trace id when it is sampled, else 0 — one check
    for every stage-recording call site."""
    if int(h["trace_flags"]) & TRACE_SAMPLED:
        return int(h["trace_id"])
    return 0


def finalize_header(
    h: np.ndarray, body: bytes = b"",
    checksum_body: tuple[int, int] | None = None,
) -> np.ndarray:
    """Set size + checksum_body + checksum.  Returns `h` for chaining.

    `checksum_body` is the hash-once reuse seam (round 23): a caller
    that already holds `body`'s digest — e.g. from a verified request
    header, whose checksum_body field the ingress verify pass proved
    equals SHA-256(body)[:16] — passes the (lo, hi) limb pair and the
    body pass is skipped.  The caller owns the invariant that the pair
    IS this body's digest; a wrong pair produces a frame that every
    verifier rejects (fail-closed, not silent corruption)."""
    h["size"] = HEADER_SIZE + len(body)
    cb_lo, cb_hi = (
        checksum_pair(body) if checksum_body is None else checksum_body
    )
    h["checksum_body_lo"] = cb_lo
    h["checksum_body_hi"] = cb_hi
    raw = bytearray(h.tobytes())
    c_lo, c_hi = checksum_pair(bytes(raw[16:]))
    h["checksum_lo"] = c_lo
    h["checksum_hi"] = c_hi
    return h


def header_from_bytes(raw: bytes) -> np.ndarray:
    assert len(raw) == HEADER_SIZE, len(raw)
    return np.frombuffer(raw, HEADER_DTYPE)[0].copy()


def headers_from_arena(arena: np.ndarray, offsets: np.ndarray,
                       n: int) -> np.ndarray:
    """Gather the leading 256 header bytes of `n` frames packed in a
    drain arena into one (n,) HEADER_DTYPE record array — a single
    vectorized fancy-index instead of n frombuffer/copy round trips.
    The result is a standalone copy (safe to retain past arena reuse).
    Frames shorter than a header must be excluded by the caller (the
    bus's size-field framing already guarantees >= HEADER_SIZE)."""
    if n == 0:
        return np.empty(0, HEADER_DTYPE)
    if n <= 4:
        # Small drains: direct per-frame casts beat building the
        # (n, 256) gather index (the fixed cost that showed up as a
        # fake per-event decode number on idle protocol rounds).
        out = np.empty(n, HEADER_DTYPE)
        for i in range(n):
            off = int(offsets[i])
            out[i] = np.frombuffer(
                arena, HEADER_DTYPE, count=1, offset=off
            )[0]
        return out
    idx = (
        offsets[:n, None].astype(np.int64)
        + np.arange(HEADER_SIZE, dtype=np.int64)[None, :]
    )
    flat = np.ascontiguousarray(arena[idx]).reshape(n * HEADER_SIZE)
    return flat.view(HEADER_DTYPE)


def finalize_headers_py(headers: np.ndarray, bodies: list) -> None:
    """Fallback batch reply finalize (hashlib per header) — same
    result bytes as the native tb_fp_finalize_headers pass."""
    for i, body in enumerate(bodies):
        finalize_header(headers[i], body)


def verify_header(h: np.ndarray, body: bytes | None = None) -> bool:
    """Checksum + structural validity; body checked when provided."""
    raw = h.tobytes()
    c_lo, c_hi = checksum_pair(raw[16:])
    if int(h["checksum_lo"]) != c_lo or int(h["checksum_hi"]) != c_hi:
        return False
    if int(h["version"]) != VERSION:
        return False
    if int(h["size"]) < HEADER_SIZE:
        return False
    if body is not None:
        if int(h["size"]) != HEADER_SIZE + len(body):
            return False
        cb_lo, cb_hi = checksum_pair(body)
        if int(h["checksum_body_lo"]) != cb_lo or int(h["checksum_body_hi"]) != cb_hi:
            return False
    return True


def root_prepare(cluster: int) -> np.ndarray:
    """The deterministic op=0 root prepare every data file starts with
    (reference: src/vsr/message_header.zig Header.Prepare.root)."""
    h = make_header(
        cluster=cluster,
        command=Command.prepare,
        operation=VsrOperation.root,
        op=0,
        commit=0,
        view=0,
        timestamp=0,
    )
    return finalize_header(h, b"")
