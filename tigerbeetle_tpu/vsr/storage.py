"""Storage: zoned, sector-aligned data-file I/O.

Re-designs the reference's storage stack (reference: src/storage.zig:
14-110 sector I/O; src/vsr/superblock.zig + journal.zig zone layout)
as one flat zone map over a single data file:

    [superblock x4][wal headers][wal prepares][client replies][grid]

Two interchangeable backends:
- `FileStorage`: a real file (pwrite/pread + fdatasync).  The C++
  runtime's io layer slots in underneath without changing callers.
- `MemoryStorage`: in-memory with seeded fault injection — the
  VOPR-style fake (reference: src/testing/storage.zig:1-25), used by
  the deterministic cluster tests.

All reads/writes are whole-sector (4096) multiples at sector-aligned
offsets, matching the reference's Direct-I/O discipline so the layout
is torn-write-aware by construction.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from tigerbeetle_tpu.constants import Config, HEADER_SIZE, SECTOR_SIZE


def _sectors(n: int) -> int:
    """Round up to a sector multiple."""
    return (n + SECTOR_SIZE - 1) // SECTOR_SIZE * SECTOR_SIZE


SUPERBLOCK_COPIES = 4  # reference: src/vsr/superblock.zig (4-copy quorum)
SUPERBLOCK_COPY_SIZE = SECTOR_SIZE  # one sector per copy: atomic-ish write


@dataclasses.dataclass(frozen=True)
class ZoneLayout:
    """Byte offsets of every zone, derived from the cluster config."""

    config: Config
    grid_size: int

    @property
    def superblock_offset(self) -> int:
        return 0

    @property
    def superblock_size(self) -> int:
        return SUPERBLOCK_COPIES * SUPERBLOCK_COPY_SIZE

    @property
    def wal_headers_offset(self) -> int:
        return self.superblock_offset + self.superblock_size

    @property
    def wal_headers_size(self) -> int:
        return _sectors(self.config.journal_slot_count * HEADER_SIZE)

    @property
    def wal_prepares_offset(self) -> int:
        return self.wal_headers_offset + self.wal_headers_size

    @property
    def wal_prepares_size(self) -> int:
        return self.config.journal_slot_count * _sectors(self.config.message_size_max)

    @property
    def client_replies_offset(self) -> int:
        return self.wal_prepares_offset + self.wal_prepares_size

    @property
    def client_replies_size(self) -> int:
        return self.config.clients_max * _sectors(self.config.message_size_max)

    @property
    def grid_offset(self) -> int:
        return self.client_replies_offset + self.client_replies_size

    @property
    def total_size(self) -> int:
        return self.grid_offset + self.grid_size

    def prepare_slot_offset(self, slot: int) -> int:
        assert 0 <= slot < self.config.journal_slot_count
        return self.wal_prepares_offset + slot * _sectors(self.config.message_size_max)

    def header_slot_offset(self, slot: int) -> int:
        """Sector-aligned offset of the header-ring sector holding `slot`."""
        return self.wal_headers_offset + slot * HEADER_SIZE

    def reply_slot_offset(self, slot: int) -> int:
        assert 0 <= slot < self.config.clients_max
        return self.client_replies_offset + slot * _sectors(self.config.message_size_max)


class FsyncCrash(RuntimeError):
    """Seeded fault point: the process dies INSIDE an fsync — the sync
    never completes, so nothing it would have covered may be acked
    (MemoryStorage.crash_at_fsync; the VOPR group-commit contract
    tests drive this)."""


class Storage:
    """Backend interface: aligned read/write/sync."""

    layout: ZoneLayout
    # Actual durability syscalls issued (one per fdatasync; the group
    # -commit and async-checkpoint benches grade against this).
    stat_fsyncs = 0
    # True when write_prepare(sync=False) + a later covering
    # sync_wal() is crash-equivalent to per-op syncs (FileStorage).
    # The fault-injecting MemoryStorage keeps it False so seeded
    # crash tests stay deterministic; tests opt in per-instance.
    supports_deferred_sync = False

    def read(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def write(self, offset: int, data: bytes) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def writeback_hint(self, offset: int, size: int) -> None:
        """START async writeback of a range without waiting (grid
        block writes: the next checkpoint's full sync then finds most
        pages already clean instead of stalling on an interval's worth
        of dirty data).  Purely advisory — default no-op."""

    def sync_wal(self) -> None:
        """Durably flush the control/WAL zones only (ack path).
        Backends without zone isolation flush everything."""
        self.sync()

    def close(self) -> None:
        pass

    def _check(self, offset: int, size: int) -> None:
        # The grid zone (last) may grow past the formatted size as
        # checkpoint snapshots grow; fixed zones are bounds-checked by
        # their own offset arithmetic.
        assert offset % SECTOR_SIZE == 0, offset
        assert size % SECTOR_SIZE == 0, size
        assert offset >= 0


# Linux sync_file_range(2) via libc (no Python binding exists).
_SFR_WAIT_BEFORE, _SFR_WRITE, _SFR_WAIT_AFTER = 1, 2, 4
_sync_file_range = None
try:
    import ctypes as _ctypes

    _libc = _ctypes.CDLL(None, use_errno=True)
    _raw_sfr = _libc.sync_file_range
    _raw_sfr.restype = _ctypes.c_int
    _raw_sfr.argtypes = [
        _ctypes.c_int, _ctypes.c_long, _ctypes.c_long, _ctypes.c_uint,
    ]
    _sync_file_range = _raw_sfr
except (OSError, AttributeError):
    _sync_file_range = None


class FileStorage(Storage):
    """Two files: `path` holds the control zones (superblock, WAL
    rings, client replies) and `path`.grid holds the grid zone.  The
    commit path's per-op fdatasync then flushes ONLY the WAL file —
    LSM spill/compaction writeback in the grid file never rides the
    ack latency (the isolation the reference gets from O_DIRECT; a
    fdatasync on a shared inode would flush everything).  sync()
    flushes both (checkpoint ordering barrier)."""

    supports_async_writeback = True  # grid writer thread (vsr/grid.py)
    supports_deferred_sync = True    # WAL group commit (vsr/journal.py)

    def __init__(self, path: str, layout: ZoneLayout, create: bool = False) -> None:
        self.layout = layout
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self._fd = os.open(path, flags, 0o644)
        try:
            self._fd_grid = os.open(path + ".grid", flags, 0o644)
        except FileNotFoundError:
            os.close(self._fd)
            raise RuntimeError(
                f"{path}.grid is missing: the data file's grid zone "
                "lives in a sibling .grid file (keep them together; "
                "re-run `format` to create a fresh pair)"
            ) from None
        if create:
            os.ftruncate(self._fd, layout.grid_offset)
        self._grid_off = layout.grid_offset
        self._grid_dirty = False
        self._wal_dirty = False
        # Dirty extent of the grid file since the last paced walk:
        # sync_grid_paced must scale with bytes WRITTEN, not file size
        # (a 32 GB mostly-clean grid must not cost 2k chunk-sleeps per
        # checkpoint).  Plain attributes: a racing write during the
        # walk at worst rides the next walk — durability always comes
        # from the fdatasync that follows.
        self._grid_ext_lo = None
        self._grid_ext_hi = 0
        # Write-amplification accounting (bench durable config reports
        # bytes/event; reference analog: devhub's datafile-size metric,
        # src/scripts/devhub.zig:36-41).  WAL counts only the journal
        # rings; superblock/client-reply traffic is "control" —
        # lumping checkpoint control writes into WAL framing would
        # misdirect the exact investigation this counter serves.
        self.stat_bytes_wal = 0
        self.stat_bytes_grid = 0
        self.stat_bytes_control = 0
        self.stat_fsyncs = 0
        self._wal_lo = layout.wal_headers_offset
        self._wal_hi = layout.wal_prepares_offset + layout.wal_prepares_size

    def _at(self, offset: int) -> tuple[int, int]:
        if offset >= self._grid_off:
            return self._fd_grid, offset - self._grid_off
        return self._fd, offset

    def read(self, offset: int, size: int) -> bytes:
        self._check(offset, size)
        fd, off = self._at(offset)
        data = os.pread(fd, size, off)
        if len(data) < size:  # reading past EOF in the grid zone
            data = data.ljust(size, b"\x00")
        return data

    def write(self, offset: int, data: bytes) -> None:
        self._check(offset, len(data))
        fd, off = self._at(offset)
        written = os.pwrite(fd, data, off)
        assert written == len(data)
        if fd == self._fd_grid:
            self._grid_dirty = True
            self.stat_bytes_grid += written
            if self._grid_ext_lo is None or off < self._grid_ext_lo:
                self._grid_ext_lo = off
            if off + written > self._grid_ext_hi:
                self._grid_ext_hi = off + written
        else:
            self._wal_dirty = True
            if self._wal_lo <= offset < self._wal_hi:
                self.stat_bytes_wal += written
            else:
                self.stat_bytes_control += written

    def sync(self) -> None:
        # Clear-then-sync ordering: a concurrent write landing after
        # the clear re-marks the file dirty, so the NEXT sync covers
        # it even if this fdatasync raced past it (sync_wal runs on
        # the replica's WAL worker thread).  On failure the flag is
        # restored — an error must not launder unsynced data as clean.
        if self._wal_dirty:
            self._wal_dirty = False
            try:
                self.stat_fsyncs += 1
                os.fdatasync(self._fd)
            except OSError:
                self._wal_dirty = True
                raise
        if self._grid_dirty:
            self._grid_dirty = False
            try:
                self.stat_fsyncs += 1
                os.fdatasync(self._fd_grid)
            except OSError:
                self._grid_dirty = True
                raise

    def sync_wal(self) -> None:
        """Flush the control/WAL file only (per-op ack durability)."""
        self._wal_dirty = False
        try:
            self.stat_fsyncs += 1
            os.fdatasync(self._fd)
        except OSError:
            self._wal_dirty = True
            raise

    def writeback_hint(self, offset: int, size: int) -> None:
        if _sync_file_range is not None:
            fd, off = self._at(offset)
            _sync_file_range(fd, off, size, _SFR_WRITE)

    def sync_grid_paced(self, chunk: int = 16 << 20,
                        pause_s: float = 0.001) -> None:
        """Push the grid file's dirty EXTENT to the device in bounded
        chunks with yields in between, so a concurrent WAL fdatasync
        (the ack path's per-op/per-drain sync) never queues behind one
        monolithic grid flush — the async-checkpoint finalize calls
        this BEFORE its covering storage.sync(), which is then left
        with little more than metadata.  Only the range written since
        the last walk is paced (cost scales with dirty bytes, not
        file size).  Purely a pacing optimization: sync_file_range
        does NOT flush the drive cache, so durability still comes
        from the fdatasync that follows.  No-op where sync_file_range
        is unavailable or nothing was written."""
        lo, hi = self._grid_ext_lo, self._grid_ext_hi
        self._grid_ext_lo, self._grid_ext_hi = None, 0
        if _sync_file_range is None or lo is None or hi <= lo:
            return
        import time as _time

        flags = _SFR_WAIT_BEFORE | _SFR_WRITE | _SFR_WAIT_AFTER
        for off in range(lo, hi, chunk):
            _sync_file_range(
                self._fd_grid, off, min(chunk, hi - off), flags
            )
            _time.sleep(pause_s)

    def close(self) -> None:
        os.close(self._fd)
        os.close(self._fd_grid)


class MemoryStorage(Storage):
    """Seeded fault-injecting in-memory backend.

    Faults (reference: src/testing/storage.zig:58-95):
    - `crash()` drops writes that were never `sync()`ed (with
      per-sector probability `p_lose_unsynced`), modeling torn writes
      and lost buffers on power failure.
    - `corrupt_sector(offset)` flips bytes to model latent sector
      errors.
    """

    _PAGE = 1 << 16

    def __init__(self, layout: ZoneLayout, seed: int = 0,
                 p_lose_unsynced: float = 1.0) -> None:
        self.layout = layout
        # Page-sparse images: only written pages materialize, so large
        # reserved regions (snapshot spans, the forest block zone) cost
        # nothing — mirroring a sparse file on a real filesystem.
        self._pages: dict[int, bytearray] = {}      # current contents
        self._spages: dict[int, bytearray] = {}     # last-synced contents
        self._dirty: set[int] = set()  # dirty sector indices
        self._rng = np.random.default_rng(seed)
        self._p_lose = p_lose_unsynced
        self.reads = 0
        self.writes = 0
        self.stat_fsyncs = 0
        # Fault point: the Nth sync() from now RAISES FsyncCrash
        # without persisting anything — the crash-at-fsync model the
        # group-commit contract seeds drive (None = disabled).
        self.crash_at_fsync: int | None = None

    def _read_range(self, pages: dict, offset: int, size: int) -> bytes:
        out = bytearray(size)
        at = 0
        while at < size:
            pi, po = divmod(offset + at, self._PAGE)
            n = min(self._PAGE - po, size - at)
            page = pages.get(pi)
            if page is not None:
                out[at : at + n] = page[po : po + n]
            at += n
        return bytes(out)

    def _write_range(self, pages: dict, offset: int, data) -> None:
        at = 0
        size = len(data)
        while at < size:
            pi, po = divmod(offset + at, self._PAGE)
            n = min(self._PAGE - po, size - at)
            page = pages.get(pi)
            if page is None:
                page = pages.setdefault(pi, bytearray(self._PAGE))
            page[po : po + n] = data[at : at + n]
            at += n

    def read(self, offset: int, size: int) -> bytes:
        self._check(offset, size)
        self.reads += 1
        return self._read_range(self._pages, offset, size)

    def write(self, offset: int, data: bytes) -> None:
        self._check(offset, len(data))
        self.writes += 1
        self._write_range(self._pages, offset, data)
        for s in range(offset // SECTOR_SIZE, (offset + len(data)) // SECTOR_SIZE):
            self._dirty.add(s)

    def sync(self) -> None:
        if self.crash_at_fsync is not None:
            self.crash_at_fsync -= 1
            if self.crash_at_fsync <= 0:
                self.crash_at_fsync = None
                # The sync never completed: nothing moves to the
                # synced image, and the caller must treat the process
                # as dead (crash() then models the power loss).
                raise FsyncCrash("seeded crash inside fsync")
        self.stat_fsyncs += 1
        for s in self._dirty:
            off = s * SECTOR_SIZE
            self._write_range(
                self._spages, off, self._read_range(self._pages, off, SECTOR_SIZE)
            )
        self._dirty.clear()

    def crash(self) -> None:
        """Simulate power loss: unsynced sectors independently either
        reach disk or revert to their last synced contents."""
        for s in self._dirty:
            off = s * SECTOR_SIZE
            if self._rng.random() < self._p_lose:
                self._write_range(
                    self._pages, off,
                    self._read_range(self._spages, off, SECTOR_SIZE),
                )
            else:
                self._write_range(
                    self._spages, off,
                    self._read_range(self._pages, off, SECTOR_SIZE),
                )
        self._dirty.clear()

    def corrupt_sector(self, offset: int) -> None:
        off = offset // SECTOR_SIZE * SECTOR_SIZE
        noise = self._rng.integers(0, 256, SECTOR_SIZE, np.uint8).tobytes()
        self._write_range(self._pages, off, noise)
        self._write_range(self._spages, off, noise)
