"""Journal: the write-ahead log, two on-disk rings.

Keeps the reference's core design (reference: src/vsr/journal.zig:
17-67): a prepares ring (full messages, slot = op % slot_count) plus a
redundant headers ring (256-byte headers, 16 per sector).  The
redundant ring is what makes torn prepare writes detectable: a prepare
whose own header is corrupt but whose redundant header is intact was
torn mid-write (and vice versa).

Recovery decision table per slot (simplification of the reference's
case matrix, same outcomes):

    prepare   redundant   =>
    valid     matching    ok
    valid     missing     ok (torn header write; header repaired)
    valid     different   the ring wrapped mid-update: trust the
                          higher op (both checksums are valid)
    torn      valid       faulty (data loss unless head: see below)
    torn      torn        unwritten (fresh slot)

After slot scan, the hash chain (prepare.parent == previous prepare's
checksum) is walked from the checkpoint op; the head is the last chain
-connected op.  A faulty slot above the checkpoint either truncates
the head (if nothing valid follows it) or is reported for repair
(multi-replica) / fatal (single replica).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu.constants import HEADER_SIZE, SECTOR_SIZE
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.storage import Storage, _sectors
from tigerbeetle_tpu.vsr.wire import Command, HEADER_DTYPE

HEADERS_PER_SECTOR = SECTOR_SIZE // HEADER_SIZE


@dataclasses.dataclass
class Recovery:
    op_head: int                 # highest chain-connected op
    headers: dict[int, np.ndarray]   # op -> prepare header (valid ops only)
    faulty_ops: list[int]        # ops lost to torn/corrupt slots (below head)
    truncated_ops: list[int]     # ops discarded as uncommitted head


class Journal:
    def __init__(self, storage: Storage, cluster: int) -> None:
        self.storage = storage
        self.layout = storage.layout
        self.config = storage.layout.config
        self.cluster = cluster
        self.slot_count = self.config.journal_slot_count
        # In-memory redundant header ring (mirrors the disk ring).
        self.headers = np.zeros(self.slot_count, HEADER_DTYPE)
        # Deferred-sync bookkeeping (group commit): WAL writes issued
        # with sync=False since the last covering sync_batch().
        self.unsynced_writes = 0
        from tigerbeetle_tpu.obs import anatomy as anatomy_mod
        from tigerbeetle_tpu.utils import tracer as tracer_mod

        self.tracer = tracer_mod.NULL
        # Per-request anatomy (obs/anatomy.py): the journal_write
        # stage timestamp is taken HERE, next to the WAL append, so a
        # sampled request's timeline shows exactly when its durability
        # write landed (the owning replica shares its recorder).
        self.anatomy = anatomy_mod.NULL
        # Private default registry until the owning replica shares its
        # own via set_metrics (standalone journals stay observable).
        from tigerbeetle_tpu import obs

        self.set_metrics(obs.Registry())

        # Native append framing (round 20): sector padding + redundant
        # ring update + redundant-sector build in one C call, handed
        # back as ready-to-write scratch buffers.  Byte-identical to
        # the Python framing below (differential-tested); requires the
        # ring to be sector-aligned (the C pass reads a whole sector's
        # worth of ring entries).
        from tigerbeetle_tpu import envcheck
        from tigerbeetle_tpu.runtime import fastpath

        self._native_frame = (
            envcheck.native_pipeline() == 1
            and fastpath.pipeline_available()
            and self.slot_count % HEADERS_PER_SECTOR == 0
        )
        if self._native_frame:
            self._scratch_prepare = np.zeros(self._prepare_size(), np.uint8)
            self._scratch_sector = np.zeros(SECTOR_SIZE, np.uint8)

    def set_metrics(self, registry) -> None:
        """Create this journal's handles on `registry` (the owning
        replica's, so one snapshot covers WAL write/sync latency)."""
        self.metrics = registry
        self._c_writes = registry.counter("journal.writes")
        self._c_sync_batches = registry.counter("journal.sync_batches")
        self._h_write = registry.histogram("journal.write_us")
        self._h_sync = registry.histogram("journal.sync_us")

    # ------------------------------------------------------------------

    def slot_for_op(self, op: int) -> int:
        return op % self.slot_count

    def _prepare_size(self) -> int:
        return _sectors(self.config.message_size_max)

    def write_prepare(self, header: np.ndarray, body: bytes, sync: bool = True) -> None:
        """Append one prepare: prepares ring first, then the redundant
        header sector (reference ordering — so a crash between the two
        writes is the 'valid prepare / missing redundant' case).

        Hash-once invariant (round 23): this path must NEVER hash the
        body — the header arrives finalized (checksum_body stamped by
        the build seam), and the size assertions below are the only
        integrity checks the write needs.  Disk bytes are re-verified
        on READ (read_prepare), where rehashing is the point."""
        assert int(header["command"]) == Command.prepare
        assert int(header["size"]) == HEADER_SIZE + len(body)
        op = int(header["op"])
        slot = self.slot_for_op(op)

        self._c_writes.inc()
        with self.tracer.span(
            "journal_write", op=op, bytes=len(body)
        ), self._h_write.time():
            if self._native_frame:
                # C builds the padded prepare, updates headers[slot]
                # in place, and builds the redundant sector — Python
                # only issues the two storage writes.
                from tigerbeetle_tpu.runtime import fastpath

                padded_len = fastpath.frame_prepare(
                    header, body, self.headers, slot,
                    HEADERS_PER_SECTOR, SECTOR_SIZE,
                    self._scratch_prepare, self._scratch_sector,
                )
                self.storage.write(
                    self.layout.prepare_slot_offset(slot),
                    memoryview(self._scratch_prepare)[:padded_len],
                )
                sector_index = slot // HEADERS_PER_SECTOR
                self.storage.write(
                    self.layout.wal_headers_offset
                    + sector_index * SECTOR_SIZE,
                    memoryview(self._scratch_sector),
                )
            else:
                msg = header.tobytes() + body
                padded = msg.ljust(_sectors(len(msg)), b"\x00")
                self.storage.write(self.layout.prepare_slot_offset(slot), padded)
                self.headers[slot] = header
                self._write_header_sector(slot)
            if sync:
                # ONE fdatasync of the WAL FILE covers both rings
                # (device cache flush included — scoped alternatives
                # like sync_file_range do NOT flush the drive cache).
                # Safe: the op is only acked after this returns; a
                # crash beforehand leaves torn states recovery already
                # classifies.  The grid lives in its own file
                # (storage.py FileStorage), so LSM spill/compaction
                # writeback never rides the ack latency.
                self.storage.sync_wal()
            else:
                # Deferred (group commit): the caller owns the covering
                # sync_batch() and must not ack this op before it.
                self.unsynced_writes += 1
        self.anatomy.stage_h(header, "journal_write")

    def write_prepare_framed(self, header: np.ndarray, body_len: int,
                             wal_view, slot: int, sector_view,
                             sector_index: int) -> None:
        """Append one ALREADY-FRAMED prepare (r22 drain loop): the
        sector-padded prepare buffer and redundant-header sector were
        built by the batch C call (which also wrote headers[slot] in
        place) — this issues the same two storage writes, counters,
        and spans as write_prepare(sync=False), per prepare, so the
        storage-visible sequence is identical to the per-item path."""
        assert int(header["command"]) == Command.prepare
        assert int(header["size"]) == HEADER_SIZE + body_len
        op = int(header["op"])
        self._c_writes.inc()
        with self.tracer.span(
            "journal_write", op=op, bytes=body_len
        ), self._h_write.time():
            self.storage.write(self.layout.prepare_slot_offset(slot), wal_view)
            self.storage.write(
                self.layout.wal_headers_offset + sector_index * SECTOR_SIZE,
                sector_view,
            )
            self.unsynced_writes += 1
        self.anatomy.stage_h(header, "journal_write")

    def sync_batch(self) -> bool:
        """One covering fdatasync for every deferred WAL write since
        the last batch — the group-commit seam: a whole poll-drain's
        prepares (and their redundant sectors, and any scrub heals)
        share one durability syscall.  No-op when nothing is deferred,
        so idle flush points cost nothing.  Returns True when a sync
        was actually issued."""
        if self.unsynced_writes == 0:
            return False
        self.unsynced_writes = 0
        self._c_sync_batches.inc()
        try:
            with self._h_sync.time():
                self.storage.sync_wal()
        except BaseException:
            # The covering sync did not complete: everything it would
            # have covered is still unsynced (acks must stay held).
            self.unsynced_writes += 1
            raise
        return True

    def header_sector_intact(self, slot: int) -> bool:
        """Does the DISK redundant-header sector for `slot` match the
        in-memory ring?  (Scrubber probe for latent sector errors.)"""
        sector_index = slot // HEADERS_PER_SECTOR
        first = sector_index * HEADERS_PER_SECTOR
        want = self.headers[first : first + HEADERS_PER_SECTOR].tobytes()
        want = want.ljust(SECTOR_SIZE, b"\x00")
        disk = self.storage.read(
            self.layout.wal_headers_offset + sector_index * SECTOR_SIZE,
            SECTOR_SIZE,
        )
        return disk == want

    def rewrite_header_sector(self, slot: int, sync: bool = True) -> None:
        """Self-heal a latent error in the redundant ring from the
        in-memory copy (authoritative while the process lives).  Only
        the WAL file is flushed (the grid has its own barriers); with
        sync=False the heal rides the caller's covering sync_batch()
        instead of paying its own fdatasync."""
        self._write_header_sector(slot)
        if sync:
            self.storage.sync_wal()
        else:
            self.unsynced_writes += 1

    def _write_header_sector(self, slot: int) -> None:
        sector_index = slot // HEADERS_PER_SECTOR
        first = sector_index * HEADERS_PER_SECTOR
        data = self.headers[first : first + HEADERS_PER_SECTOR].tobytes()
        data = data.ljust(SECTOR_SIZE, b"\x00")
        offset = self.layout.wal_headers_offset + sector_index * SECTOR_SIZE
        self.storage.write(offset, data)

    def read_prepare(self, op: int) -> tuple[np.ndarray, bytes] | None:
        """Read+verify the prepare for `op`; None if torn/overwritten."""
        slot = self.slot_for_op(op)
        raw = self.storage.read(
            self.layout.prepare_slot_offset(slot), self._prepare_size()
        )
        header = wire.header_from_bytes(raw[:HEADER_SIZE])
        if not wire.verify_header(header):
            return None
        if int(header["op"]) != op or int(header["command"]) != Command.prepare:
            return None
        if wire.u128(header, "cluster") != self.cluster:
            return None
        size = int(header["size"])
        body = raw[HEADER_SIZE:size]
        if not wire.verify_header(header, body):
            return None
        return header, bytes(body)

    # ------------------------------------------------------------------

    # Only ops this close to the newest redundant-ring op can have a
    # prepare newer than (or present without) their redundant header:
    # write_prepare issues prepare -> redundant -> fdatasync in order
    # and an op is acked only after the sync joins, so un-persisted
    # redundant headers are confined to the in-flight tail (pipeline
    # <= 8 prepares; 64 is a generous margin for crash-reordering of
    # unsynced sectors).
    RECOVER_HEAD_WINDOW = 64
    # Test hook: force the full prepares-ring scan so differential
    # tests can check the windowed scan classifies identically.
    RECOVER_PROBE_ALL = False

    def recover(self, commit_min: int) -> Recovery:
        """Scan both rings and reconstruct the log above `commit_min`
        (the checkpoint op).

        The prepares ring is NOT read in full: a slot whose redundant
        header is intact with op < commit_min is settled (its op was
        fdatasynced before the checkpoint and recovery skips it), and
        an all-zero redundant sector outside the head window means the
        slot was never written (prepares persist in issue order).
        Only slots that can still influence the result — op >=
        commit_min, op == 0, garbage redundant bytes, or within
        RECOVER_HEAD_WINDOW of the newest op — pay a prepare read.
        On this container's ~5 ms-per-IO disk that turns a 1024-slot
        x 1 MiB ring scan (~5.6 s, measured) into a few dozen reads.
        """
        # Load the redundant ring (one sequential read).
        raw = self.storage.read(
            self.layout.wal_headers_offset, self.layout.wal_headers_size
        )
        disk_headers = np.frombuffer(
            raw[: self.slot_count * HEADER_SIZE], HEADER_DTYPE
        ).copy()

        zero_header = bytes(HEADER_SIZE)
        r_valid_all: list[bool] = []
        settled: list[bool] = []  # classified from the redundant ring alone
        max_op = 0
        for slot in range(self.slot_count):
            redundant = disk_headers[slot]
            r_valid = wire.verify_header(redundant) and int(
                redundant["command"]
            ) == Command.prepare and wire.u128(redundant, "cluster") == self.cluster
            r_valid_all.append(r_valid)
            if r_valid:
                op = int(redundant["op"])
                max_op = max(max_op, op)
                settled.append(
                    op < commit_min
                    and op != 0
                    and self.slot_for_op(op) == slot
                )
            else:
                virgin = (
                    raw[slot * HEADER_SIZE : (slot + 1) * HEADER_SIZE]
                    == zero_header
                )
                settled.append(virgin)
        # Slots that may hold an op newer than their redundant header.
        # Both directions around max_op: un-fdatasynced sectors persist
        # in arbitrary order across a crash, so a slot in the in-flight
        # tail can expose a stale WRAPPED redundant (old op, valid
        # checksum) while its prepare already holds the new op — such a
        # slot sits below max_op, not above it.
        for op in range(
            max(0, max_op - self.RECOVER_HEAD_WINDOW),
            max_op + 1 + self.RECOVER_HEAD_WINDOW,
        ):
            settled[self.slot_for_op(op)] = False
        if self.RECOVER_PROBE_ALL:
            settled = [False] * self.slot_count

        slot_header: dict[int, np.ndarray] = {}
        slot_state: dict[int, str] = {}
        for slot in range(self.slot_count):
            redundant = disk_headers[slot]
            r_valid = r_valid_all[slot]
            if settled[slot]:
                if r_valid:
                    # Redundant header is byte-identical to the intact
                    # prepare's own header; recovery skips the op
                    # either way (op < commit_min).
                    slot_state[slot] = "ok"
                    slot_header[slot] = redundant
                    self.headers[slot] = redundant
                else:
                    slot_state[slot] = "unwritten"
                continue

            p = self._read_slot_prepare(slot)
            if p is not None:
                header, _ = p
                if r_valid and int(redundant["op"]) > int(header["op"]):
                    # Ring wrapped mid-update: redundant is newer but its
                    # prepare was torn — the slot's newest op is lost.
                    slot_state[slot] = "faulty"
                    slot_header[slot] = redundant
                else:
                    slot_state[slot] = "ok"
                    slot_header[slot] = header
                    self.headers[slot] = header
            elif r_valid:
                slot_state[slot] = "faulty"  # prepare torn, redundant intact
                slot_header[slot] = redundant
                self.headers[slot] = redundant
            else:
                slot_state[slot] = "unwritten"

        # Collect valid ops above the checkpoint.
        headers: dict[int, np.ndarray] = {}
        faulty_headers: dict[int, np.ndarray] = {}
        for slot, state in slot_state.items():
            h = slot_header.get(slot)
            if h is None:
                continue
            op = int(h["op"])
            if op < commit_min and op != 0:
                continue
            if state == "ok":
                headers[op] = h
            else:
                faulty_headers[op] = h

        # Walk the hash chain upward from the checkpoint.  When the
        # checkpoint op's own slot is gone (overwritten/faulty), its
        # state lives in the checkpoint snapshot; the chain then
        # starts unanchored just above it (parent None).
        op_head = commit_min
        chain_parent = (
            wire.u128(headers[commit_min], "checksum") if commit_min in headers else None
        )
        op = commit_min + 1
        faulty_ops: list[int] = []
        while True:
            if op in headers:
                h = headers[op]
                if chain_parent is not None and wire.u128(h, "parent") != chain_parent:
                    above = [o for o in headers if o > op]
                    if above:
                        # Chain break BELOW valid ops: one side of the
                        # break is a superseded sibling from an older
                        # view, and recovery alone cannot tell which.
                        # Keep everything and report the break op
                        # faulty — the VSR layer rejoins through a
                        # view change and resolves the true sibling by
                        # vouched checksum.  Truncating here erased
                        # COMMITTED durable ops whose headers then
                        # vanished from the DVC merge (VOPR seeds
                        # 170611267, 1064614514).
                        faulty_ops.append(op)
                        chain_parent = None
                        op += 1
                        continue
                    break  # chain break at the top: stale head, truncated
                chain_parent = wire.u128(h, "checksum")
                op_head = op
                op += 1
            elif op in faulty_headers:
                # A hole below newer valid ops = data loss; a hole at the
                # top = torn head, truncated.
                above = [o for o in headers if o > op]
                if above:
                    faulty_ops.append(op)
                    chain_parent = None  # chain unverifiable across hole
                    op_head = max(above)
                    op += 1
                else:
                    break
            else:
                break

        truncated = sorted(
            o for o in set(headers) | set(faulty_headers) if o > op_head
        )
        headers = {o: h for o, h in headers.items() if o <= op_head}
        return Recovery(
            op_head=op_head,
            headers=headers,
            faulty_ops=faulty_ops,
            truncated_ops=truncated,
        )

    def _read_slot_prepare(self, slot: int) -> tuple[np.ndarray, bytes] | None:
        raw = self.storage.read(
            self.layout.prepare_slot_offset(slot), self._prepare_size()
        )
        header = wire.header_from_bytes(raw[:HEADER_SIZE])
        if not wire.verify_header(header):
            return None
        if int(header["command"]) != Command.prepare:
            return None
        if wire.u128(header, "cluster") != self.cluster:
            return None
        if self.slot_for_op(int(header["op"])) != slot:
            return None
        size = int(header["size"])
        if size > len(raw):
            return None
        body = raw[HEADER_SIZE:size]
        if not wire.verify_header(header, body):
            return None
        return header, bytes(body)
