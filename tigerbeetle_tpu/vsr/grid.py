"""Grid: checksummed block store over the data file's grid zone.

reference: src/vsr/grid.zig:34-60 — fixed-size blocks addressed
1..block_count, allocated by the FreeSet, verified on every read, with
a set-associative block cache (utils/cache.py; reference:
src/lsm/set_associative_cache.zig — the policy is host-side and not
consensus-critical).

Block layout: [64B header][payload], header =
checksum u128 | address u64 | length u32 | block_type u8 | pad.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.utils.cache import SetAssociativeCache
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.free_set import FreeSet
from tigerbeetle_tpu.vsr.storage import Storage

BLOCK_HEADER_SIZE = 64

BLOCK_DTYPE = np.dtype(
    [
        ("checksum_lo", "<u8"), ("checksum_hi", "<u8"),
        ("address", "<u8"),
        ("length", "<u4"),
        ("block_type", "u1"),
        ("reserved", "V35"),
    ]
)
assert BLOCK_DTYPE.itemsize == BLOCK_HEADER_SIZE


class Grid:
    # Audited write-write sharing with the grid-write SerialWorker
    # (tbcheck worker-shared): _write_one (worker) and write_block /
    # _join_pending (callers) both mutate the _pending_writes
    # refcounts — every access holds _pending_lock.
    _WORKER_SHARED = frozenset({"_pending_writes"})

    def __init__(self, storage: Storage, *, block_size: int = 1 << 16,
                 block_count: int = 1 << 12, base_offset: int | None = None,
                 cache_blocks: int = 256) -> None:
        self.storage = storage
        self.block_size = block_size
        assert block_size % 4096 == 0
        self.block_count = block_count
        self.base = (
            storage.layout.grid_offset if base_offset is None else base_offset
        )
        self.free_set = FreeSet(block_count)
        # Round the operator-facing block budget up to a whole number
        # of 4-way sets (0 still means "smallest cache", not cache-off:
        # reads are checksum-verified either way).
        ways = 4
        capacity = max(ways, (cache_blocks + ways - 1) // ways * ways)
        self._cache = SetAssociativeCache(capacity=capacity, ways=ways)
        # Async block writeback: spill/compaction block writes queue to
        # one writer thread (the GIL drops during pwrite, so disk wall
        # time overlaps the merge CPU).  Reads hit the cache, which is
        # populated synchronously at write time; a cache miss on a
        # still-pending address joins the queue first.  Checkpoints
        # barrier via flush_writes() before any fsync.  Only enabled on
        # backends that declare it safe (FileStorage; the fault-
        # injecting MemoryStorage stays synchronous for determinism).
        self._writer = None
        self._pending_writes: dict[int, int] = {}  # address -> refcount
        if getattr(storage, "supports_async_writeback", False):
            import threading
            import weakref

            from tigerbeetle_tpu.utils.worker import SerialWorker

            self._writer = SerialWorker("grid-write")
            self._write_futures: list = []
            self._write_error: BaseException | None = None
            self._pending_lock = threading.Lock()
            # Discarded grids (crash-recovery loops) reclaim their
            # worker thread instead of leaking it.
            weakref.finalize(self, self._writer.close)

    @property
    def payload_size(self) -> int:
        return self.block_size - BLOCK_HEADER_SIZE

    def _offset(self, address: int) -> int:
        assert 1 <= address <= self.block_count
        return self.base + (address - 1) * self.block_size

    def write_block(self, address: int, payload: bytes,
                    block_type: int = 1) -> None:
        assert len(payload) <= self.payload_size
        self._cache.put(address, payload)
        if self._writer is not None:
            # Frame construction (header + checksum + padding) and the
            # pwrite both happen on the writer thread — the checksum is
            # ~1/3 of the main-thread block cost and overlaps cleanly.
            with self._pending_lock:
                self._pending_writes[address] = (
                    self._pending_writes.get(address, 0) + 1
                )
            self._write_futures.append(
                self._writer.submit(
                    self._write_one, address, payload, block_type
                )
            )
            if len(self._write_futures) > 512:  # bound queue memory
                self.flush_writes()
            return
        self._write_one(address, payload, block_type)

    def _write_one(self, address: int, payload: bytes,
                   block_type: int) -> None:
        try:
            h = np.zeros(1, BLOCK_DTYPE)[0]
            h["address"] = address
            h["length"] = len(payload)
            h["block_type"] = block_type
            c = wire.checksum(payload)
            h["checksum_lo"] = c & 0xFFFFFFFFFFFFFFFF
            h["checksum_hi"] = c >> 64
            # Trim the physical write to the sector-rounded frame: the
            # reader takes the payload length from the header and
            # checksums only that, so stale bytes from a previous
            # tenant of this address past the frame are never
            # interpreted (write-amplification lever — a half-full
            # block costs half the disk bandwidth).
            frame = h.tobytes() + payload
            size = (len(frame) + 4095) & ~4095
            block = frame.ljust(size, b"\x00")
            self.storage.write(self._offset(address), block)
            # Kick async writeback now so the next checkpoint's full
            # sync finds these pages already clean.
            self.storage.writeback_hint(self._offset(address), size)
        finally:
            if self._writer is not None:
                with self._pending_lock:
                    n = self._pending_writes.get(address, 0) - 1
                    if n <= 0:
                        self._pending_writes.pop(address, None)
                    else:
                        self._pending_writes[address] = n

    def flush_writes(self) -> None:
        """Join every queued block write (checkpoint/read barrier).

        A write failure is STICKY: once any queued write errors, every
        later flush re-raises — a checkpoint must never advance past a
        block the disk refused (storage failure is fatal here, as in
        the reference's storage fault model)."""
        if self._writer is None:
            return
        if self._write_error is not None:
            raise self._write_error
        futures, self._write_futures = self._write_futures, []
        first_exc = None
        for f in futures:
            try:
                f.result()
            # tbcheck: allow(broad-except): join EVERY queued write
            # before raising — the first error is sticky and re-raised
            # below; skipping the rest would leak unjoined futures.
            except BaseException as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            self._write_error = first_exc
            raise first_exc

    def _join_pending(self, address: int) -> None:
        """Barrier for ONE address: flush_writes alone is not enough
        when another thread (the async-checkpoint finalize) already
        swapped the futures list — its batch may still be mid-write.
        The pending refcount is decremented only after the pwrite, so
        spin on it (the writer thread is making progress)."""
        import time

        self.flush_writes()
        while address in self._pending_writes:
            time.sleep(0.0002)
            self.flush_writes()

    def read_block(self, address: int) -> bytes:
        cached = self._cache.get(address)
        if cached is not None:
            return cached
        if self._writer is not None and address in self._pending_writes:
            self._join_pending(address)
        raw = self.storage.read(self._offset(address), self.block_size)
        h = np.frombuffer(raw[:BLOCK_HEADER_SIZE], BLOCK_DTYPE)[0]
        length = int(h["length"])
        if int(h["address"]) != address or length > self.payload_size:
            raise RuntimeError(f"grid block {address} corrupt header")
        payload = raw[BLOCK_HEADER_SIZE : BLOCK_HEADER_SIZE + length]
        want = int(h["checksum_lo"]) | (int(h["checksum_hi"]) << 64)
        if wire.checksum(payload) != want:
            raise RuntimeError(f"grid block {address} corrupt payload")
        self._cache.put(address, payload)
        return payload

    def verify_block(self, address: int) -> bool:
        """Scrubber probe: is the on-disk block intact?  Reads the disk
        directly and leaves the cache alone — steady-state scrubbing
        must not churn hot entries (reference:
        src/vsr/grid_scrubber.zig)."""
        if self._writer is not None and address in self._pending_writes:
            self._join_pending(address)
        raw = self.storage.read(self._offset(address), self.block_size)
        return block_frame_valid(raw, address, self.payload_size)



def block_frame_valid(frame: bytes, address: int, payload_size: int) -> bool:
    """Self-consistency of a raw block frame (header address, length
    bound, payload checksum) — shared by the scrubber probe and the
    peer-repair serve/install paths, without touching any cache."""
    h = np.frombuffer(frame[:BLOCK_HEADER_SIZE], BLOCK_DTYPE)[0]
    length = int(h["length"])
    if int(h["address"]) != address or length > payload_size:
        return False
    payload = frame[BLOCK_HEADER_SIZE : BLOCK_HEADER_SIZE + length]
    want = int(h["checksum_lo"]) | (int(h["checksum_hi"]) << 64)
    return wire.checksum(payload) == want
