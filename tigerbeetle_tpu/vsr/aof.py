"""AOF: append-only file of committed prepares.

reference: src/aof.zig — an optional sequential log of every committed
prepare (header + body), written at commit time before the state
machine executes (reference: src/vsr/replica.zig:4136-4141).  Used for
external audit/replay pipelines AND (round 19) as the tail stream
read-only followers replay: entries are self-framing (the header
carries the size) and checksum-verified on read.

Tailing semantics (AofTail)
---------------------------
The writer appends each record with ONE os.write, so a crashed writer
leaves a *prefix* of its last record — never interior garbage of its
own making.  That gives the reader a clean decision rule at a record
that fails verification at absolute offset `at`:

- the bad record extends to end-of-file  -> TORN: a crash (or a still
  -in-flight append racing the reader) cut the record short.  The
  reader parks at `at` (the resume offset) and retries when the file
  grows — a completed append heals it in place.
- bytes exist BEYOND the bad record      -> CORRUPT: the writer only
  appends after complete records, so a bad record followed by more
  data is bit rot / a torn-then-appended-over tail, not a crash
  artifact.  The reader stops permanently and flags it; a follower
  must refuse to advance (refuse-not-lie), never skip ahead.

Reads are chunked (`chunk_bytes`), never one whole-file read — the AOF
of a long-lived primary outgrows memory.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.vsr import wire

# Upper bound on one framed record (header + body).  The header's size
# field is checksum-protected, so this only guards against reading a
# pathological frame into memory when the CHECKSUM itself is what the
# caller is about to discover is broken.
RECORD_SIZE_MAX = HEADER_SIZE + (1 << 24)


class _FileSource:
    """Byte source over a real file: the production tail target.  The
    file may not exist yet (follower started before the primary's
    first append) — treated as size 0."""

    def __init__(self, path: str) -> None:
        self.path = path

    def size(self) -> int:
        try:
            return os.stat(self.path).st_size
        except OSError:
            return 0

    def read_at(self, offset: int, n: int) -> bytes:
        try:
            with open(self.path, "rb") as f:
                f.seek(offset)
                return f.read(n)
        except OSError:
            return b""


class BytesSource:
    """Byte source over a caller-owned mutable buffer — the simulator
    /test seam (testing/cluster.py SimAof): torn tails are modeled by
    truncating the buffer, corruption by flipping bytes in place."""

    def __init__(self, buffer: bytearray) -> None:
        self.buffer = buffer

    def size(self) -> int:
        return len(self.buffer)

    def read_at(self, offset: int, n: int) -> bytes:
        return bytes(self.buffer[offset : offset + n])


class AofTail:
    """Offset-resumable, memory-bounded AOF reader.

    `poll()` returns every newly verified (header, body) entry since
    the last call and advances `offset` past them; a torn trailing
    record leaves `offset` AT the record (the resume point) and sets
    `torn` until the writer completes it; interior corruption sets
    `corrupt` permanently (`offset` parks at the first bad byte).
    Construction with a saved `offset` resumes an earlier tail — the
    caller owns checkpointing it.
    """

    def __init__(self, path_or_source, *, offset: int = 0,
                 chunk_bytes: int = 1 << 20) -> None:
        if isinstance(path_or_source, str):
            self.source = _FileSource(path_or_source)
        else:
            self.source = path_or_source
        assert chunk_bytes >= HEADER_SIZE, chunk_bytes
        self.offset = int(offset)
        self.chunk_bytes = int(chunk_bytes)
        self.torn = False
        self.corrupt = False
        self.corrupt_reason: str | None = None
        # Chunk cache, persisted ACROSS poll() calls: a driver that
        # consumes a few records per poll (the follower server bounds
        # its replay burst) must not re-read the same chunk from disk
        # every call — memory stays bounded by one chunk + one record.
        self._buf = b""
        self._buf_at = 0

    def _fail(self, reason: str) -> None:
        self.corrupt = True
        self.corrupt_reason = reason

    def poll(self, limit: int | None = None) -> list[tuple[np.ndarray, bytes]]:
        """Verified entries appended since the last poll (up to
        `limit`).  Never raises on bad bytes — see the class
        docstring for the torn/corrupt contract."""
        if self.corrupt:
            return []
        out: list[tuple[np.ndarray, bytes]] = []
        size = self.source.size()
        self.torn = False
        if size < self.offset:
            # The file shrank below our resume point: the writer
            # crashed and its repair truncated a torn tail we had
            # already read past.  Recovery gap-fill re-appends the
            # SAME committed records byte-for-byte (prepare headers
            # and bodies are deterministic), so the resume offset
            # becomes a valid record boundary again once the writer
            # catches up — wait, exactly like a torn tail.  The cached
            # chunk may hold pre-truncation bytes: drop it.
            self._buf = b""
            self.torn = True
            return out
        buf = self._buf
        buf_at = self._buf_at  # absolute offset of buf[0]
        while limit is None or len(out) < limit:
            at = self.offset
            avail = size - at
            if avail < HEADER_SIZE:
                self.torn = avail > 0
                break
            # Refill the chunk buffer so the header (and, usually, the
            # whole record) is in memory exactly once.
            rel = at - buf_at
            if rel < 0 or rel + HEADER_SIZE > len(buf):
                buf = self.source.read_at(
                    at, min(self.chunk_bytes, avail)
                )
                buf_at = at
                rel = 0
                if len(buf) < HEADER_SIZE:
                    self.torn = True  # raced a concurrent truncate
                    break
            header = wire.header_from_bytes(
                buf[rel : rel + HEADER_SIZE]
            )
            if not wire.verify_header(header):
                # Full header bytes present but invalid: torn only if
                # nothing follows this (partial) record — a complete
                # header always precedes any later append.
                if at + HEADER_SIZE >= size:
                    self.torn = True
                else:
                    self._fail(f"bad header at offset {at}")
                break
            rec_size = int(header["size"])
            if rec_size < HEADER_SIZE or rec_size > RECORD_SIZE_MAX:
                self._fail(f"implausible record size {rec_size} at {at}")
                break
            if avail < rec_size:
                self.torn = True
                break
            if rel + rec_size > len(buf):
                # Record crosses the chunk boundary: refill from `at`
                # (one record is bounded by RECORD_SIZE_MAX).
                buf = self.source.read_at(at, max(rec_size, min(
                    self.chunk_bytes, avail
                )))
                buf_at = at
                rel = 0
                if len(buf) < rec_size:
                    self.torn = True
                    break
            body = buf[rel + HEADER_SIZE : rel + rec_size]
            if not wire.verify_header(header, body):
                if at + rec_size >= size:
                    self.torn = True
                else:
                    self._fail(f"bad body checksum at offset {at}")
                break
            out.append((header, body))
            self.offset = at + rec_size
        self._buf = buf
        self._buf_at = buf_at
        return out


class AOF:
    """Append-only writer.  `repair=True` (the default for reopened
    files) scans the existing file on open, truncates a torn trailing
    record, and records `last_op` — the highest prepare op already on
    disk — so recovery replay (vsr/replica.py) can re-append exactly
    the committed ops a crash erased from the unsynced tail, keeping
    the op stream gap-free for followers."""

    def __init__(self, path: str, *, repair: bool = True) -> None:
        self.path = path
        self.last_op = 0
        if repair and os.path.exists(path):
            self._repair()
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)

    def _repair(self) -> None:
        tail = AofTail(self.path)
        while True:
            entries = tail.poll(limit=4096)
            if not entries:
                break
            for header, _body in entries:
                if int(header["command"]) == int(wire.Command.prepare):
                    self.last_op = max(self.last_op, int(header["op"]))
        size = os.stat(self.path).st_size
        if tail.offset < size:
            # Torn (or corrupt) tail from a previous incarnation:
            # truncate to the verified prefix so the next append
            # starts a clean record — appending after garbage would
            # permanently corrupt the stream for every tailer.
            fd = os.open(self.path, os.O_WRONLY)
            try:
                os.ftruncate(fd, tail.offset)
            finally:
                os.close(fd)

    def write(self, header: np.ndarray, body: bytes) -> None:
        # Hash-once invariant (round 23): the append reuses the
        # committed prepare's already-stamped header verbatim — no
        # body hash here, ever.  Tailers re-verify on read (AofTail),
        # which is where rehashing belongs.
        os.write(self._fd, header.tobytes() + body)
        if int(header["command"]) == int(wire.Command.prepare):
            self.last_op = max(self.last_op, int(header["op"]))

    def sync(self) -> None:
        os.fdatasync(self._fd)

    def close(self) -> None:
        os.close(self._fd)


def iterate(path: str) -> Iterator[tuple[np.ndarray, bytes]]:
    """Yield verified (header, body) entries; stops at the first torn
    or corrupt entry (a crash mid-append truncates the log there).
    Chunked via AofTail — never loads the whole file."""
    tail = AofTail(path)
    while True:
        entries = tail.poll(limit=4096)
        if not entries:
            return
        yield from entries


def replay(path: str, state_machine, *, cluster: int | None = None) -> int:
    """Re-execute an AOF through a fresh state machine; returns the
    number of ops applied (deterministic replay — same guarantee as
    WAL recovery)."""
    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.vsr.wire import Command

    applied = 0
    last_op = 0
    for header, body in iterate(path):
        if int(header["command"]) != Command.prepare:
            continue
        if cluster is not None and wire.u128(header, "cluster") != cluster:
            continue
        # A crash-recovered writer's protocol catch-up re-appends ops
        # whose earlier records the repair scan kept (the AOF is
        # gap-free, not duplicate-free) — replay them once.
        if int(header["op"]) <= last_op:
            continue
        last_op = int(header["op"])
        operation = int(header["operation"])
        if operation < types.Operation.pulse:
            continue  # VSR-internal ops (register, ...)
        timestamp = int(header["timestamp"])
        state_machine.prepare_timestamp = timestamp
        sm_op = types.Operation(operation)
        # Logically-batched prepare (vsr/multi.py): context carries
        # the sub count, the body ends in a demux trailer — commit the
        # event bytes, like the replica commit path does.
        n_subs = wire.u128(header, "context")
        if n_subs:
            from tigerbeetle_tpu.state_machine import demuxer

            if demuxer.batch_logical_allowed(sm_op):
                body, _subs = demuxer.decode_trailer(body, n_subs)
        state_machine.prefetch(sm_op, body, prefetch_timestamp=timestamp)
        state_machine.commit(
            0, int(header["op"]), timestamp, sm_op, body
        )
        applied += 1
    return applied
