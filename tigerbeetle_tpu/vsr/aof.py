"""AOF: append-only file of committed prepares.

reference: src/aof.zig — an optional sequential log of every committed
prepare (header + body), written at commit time before the state
machine executes (reference: src/vsr/replica.zig:4136-4141).  Used for
external audit/replay pipelines; entries are self-framing (the header
carries the size) and checksum-verified on read.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.vsr import wire


class AOF:
    def __init__(self, path: str) -> None:
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)

    def write(self, header: np.ndarray, body: bytes) -> None:
        os.write(self._fd, header.tobytes() + body)

    def sync(self) -> None:
        os.fdatasync(self._fd)

    def close(self) -> None:
        os.close(self._fd)


def iterate(path: str) -> Iterator[tuple[np.ndarray, bytes]]:
    """Yield verified (header, body) entries; stops at the first torn
    or corrupt entry (a crash mid-append truncates the log there)."""
    with open(path, "rb") as f:
        data = f.read()
    at = 0
    while at + HEADER_SIZE <= len(data):
        header = wire.header_from_bytes(data[at : at + HEADER_SIZE])
        size = int(header["size"])
        if size < HEADER_SIZE or at + size > len(data):
            return
        body = data[at + HEADER_SIZE : at + size]
        if not wire.verify_header(header, body):
            return
        yield header, body
        at += size


def replay(path: str, state_machine, *, cluster: int | None = None) -> int:
    """Re-execute an AOF through a fresh state machine; returns the
    number of ops applied (deterministic replay — same guarantee as
    WAL recovery)."""
    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.vsr.wire import Command

    applied = 0
    for header, body in iterate(path):
        if int(header["command"]) != Command.prepare:
            continue
        if cluster is not None and wire.u128(header, "cluster") != cluster:
            continue
        operation = int(header["operation"])
        if operation < types.Operation.pulse:
            continue  # VSR-internal ops (register, ...)
        timestamp = int(header["timestamp"])
        state_machine.prepare_timestamp = timestamp
        sm_op = types.Operation(operation)
        state_machine.prefetch(sm_op, body, prefetch_timestamp=timestamp)
        state_machine.commit(
            0, int(header["op"]), timestamp, sm_op, body
        )
        applied += 1
    return applied
