"""Cluster clock synchronization: Marzullo interval intersection.

Re-expresses the reference's clock stack (reference: src/vsr/clock.zig,
src/vsr/marzullo.zig) for this runtime: each replica samples every
peer's wall clock over ping/pong round trips, turns each sample into an
offset interval [offset - error, offset + error] (error = half the
round-trip time plus tolerance), and intersects the intervals with
Marzullo's algorithm to find the smallest window agreed on by a
majority of the cluster.  The primary then assigns prepare timestamps
from `realtime_synchronized()` — its own wall clock clamped into the
agreed window — so a primary with a skewed clock cannot poison the
cluster's strictly-monotonic timestamp stream (reference:
src/vsr/replica.zig:5762-5772 uses clock.realtime_synchronized()).

Time bases follow the reference: sample round trips are measured on the
local MONOTONIC clock (immune to wall-clock steps), while offsets
relate wall clocks (reference: src/vsr/clock.zig Epoch
monotonic/realtime capture).
"""

from __future__ import annotations

from dataclasses import dataclass

# reference: src/config.zig clock_offset_tolerance_max (10ms) and
# clock_epoch_max (60s) — the tolerance pads each sample's error bound;
# the epoch bound expires stale samples.
OFFSET_TOLERANCE_NS = 10_000_000
EPOCH_MAX_NS = 60_000_000_000
# reference: src/config.zig clock_synchronization_window_min/max — a
# sample's round trip must be sane before it is admitted.
RTT_MAX_NS = 2_000_000_000


def marzullo_smallest_interval(
    tuples: list[tuple[int, int]],
) -> tuple[int, int, int]:
    """Smallest interval consistent with the largest number of sources.

    `tuples` is [(offset, error), ...]; each source asserts the true
    offset lies in [offset - error, offset + error].  Returns
    (lo, hi, sources_true) — the reference's Marzullo.Interval
    (reference: src/vsr/marzullo.zig:12-60).  Touching endpoints count
    as overlapping, matching the reference's edge ordering (a lower
    edge sorts before an equal upper edge).
    """
    if not tuples:
        return (0, 0, 0)
    edges: list[tuple[int, int]] = []
    for offset, error in tuples:
        assert error >= 0, error
        edges.append((offset - error, 0))  # 0 = lower edge
        edges.append((offset + error, 1))  # 1 = upper edge
    edges.sort()
    best = 0
    count = 0
    lo = hi = edges[0][0]
    for i, (value, kind) in enumerate(edges):
        if kind == 0:
            count += 1
            if count > best:
                best = count
                lo = value
                hi = edges[i + 1][0]
        else:
            count -= 1
    return (lo, hi, best)


@dataclass
class _Sample:
    offset: int
    error: int
    learned_at: int  # local monotonic ns


class Clock:
    """Per-replica clock synchronizer.

    All methods take explicit (monotonic_ns, realtime_ns) "now" values
    so the deterministic simulator can drive virtual time (reference
    clock.zig is parameterized over Time for the same reason).
    """

    def __init__(self, replica: int, replica_count: int) -> None:
        self.replica = replica
        self.replica_count = replica_count
        # Best (lowest-error) sample per peer in the current epoch.
        self._samples: dict[int, _Sample] = {}
        self.window_lo = 0
        self.window_hi = 0
        self.synchronized = replica_count == 1
        self.sources_true = 1

    # -- sampling ------------------------------------------------------

    def learn(
        self,
        peer: int,
        m0: int,
        t1: int,
        m2: int,
        *,
        realtime_now: int,
    ) -> None:
        """Admit one ping/pong sample: ping sent at local monotonic
        `m0`, peer's wall clock read `t1`, pong received at local
        monotonic `m2` with local wall clock `realtime_now`
        (reference: src/vsr/clock.zig Clock.learn)."""
        if peer == self.replica:
            return
        if m2 < m0:
            return  # monotonic went backwards across a restart
        rtt = m2 - m0
        if rtt > RTT_MAX_NS:
            return  # saturated link; sample error too large to help
        # The peer read t1 somewhere inside [m0, m2]; assume the
        # midpoint and bound the error by half the round trip.
        error = rtt // 2 + OFFSET_TOLERANCE_NS
        offset = t1 + rtt // 2 - realtime_now
        best = self._samples.get(peer)
        # `<=` so a steady-RTT stream keeps refreshing learned_at —
        # otherwise every sample would age out together at EPOCH_MAX
        # and the cluster clock would flap unsynchronized periodically.
        if best is None or error <= best.error:
            self._samples[peer] = _Sample(offset, error, m2)
        self._synchronize(m2)

    def expire(self, monotonic_now: int) -> None:
        """Drop samples older than the epoch bound (reference:
        src/vsr/clock.zig epoch expiry)."""
        stale = [
            p
            for p, s in self._samples.items()
            if monotonic_now - s.learned_at > EPOCH_MAX_NS
        ]
        for p in stale:
            del self._samples[p]
        if stale:
            self._synchronize(monotonic_now)

    # -- synchronization ----------------------------------------------

    def _synchronize(self, monotonic_now: int) -> None:
        # Our own clock is a source with zero offset and zero error.
        tuples = [(0, 0)]
        tuples += [(s.offset, s.error) for s in self._samples.values()]
        lo, hi, sources = marzullo_smallest_interval(tuples)
        quorum = self.replica_count // 2 + 1
        if sources >= quorum:
            self.window_lo = lo
            self.window_hi = hi
            self.synchronized = True
            self.sources_true = sources
        elif self.replica_count > 1:
            self.synchronized = False

    def realtime_synchronized(self, realtime_now: int) -> int | None:
        """The local wall clock clamped into the cluster-agreed offset
        window, or None when unsynchronized (the caller falls back or
        defers — reference: src/vsr/replica.zig on_request's
        realtime_synchronized gate)."""
        if not self.synchronized:
            return None
        # True time ~ realtime_now + offset for offset in [lo, hi];
        # our own reading (offset 0) is clamped into the window.
        if 0 < self.window_lo:
            return realtime_now + self.window_lo
        if 0 > self.window_hi:
            return realtime_now + self.window_hi
        return realtime_now
