"""Grid scrubber: background read-verify of allocated grid blocks.

reference: src/vsr/grid_scrubber.zig — cycles ("tours") through every
allocated block proactively so latent sector errors are found (and
repaired from peers) before the data is needed.  Design carried over
from the reference's tour machinery:

- A tour SNAPSHOTS the allocated set once per cycle and walks that
  snapshot to completion; blocks allocated mid-cycle are picked up by
  the next tour (a moving target would skip or double-scrub blocks as
  the free set churns — the old per-tick re-listing did exactly that,
  and cost O(grid) work per tick).
- Blocks freed after the snapshot are skipped at probe time: a
  released block's frame legitimately goes stale the moment the free
  set forfeits it (reference: grid_scrubber cancels reads for freed
  blocks at checkpoint).
- Pacing targets a TOUR DURATION rather than a fixed per-tick count:
  each tick probes just enough blocks to finish the snapshot within
  ``cycle_ticks``, bounded by ``blocks_per_tick_max`` so a huge grid
  never turns one tick into an I/O storm (reference:
  grid_scrubber.zig cycle pacing against constants.grid_scrubber_*).
- Stats (`blocks_verified`, `faults_found`, `cycles`, `progress`)
  feed the replica's StatsD/tracer surfacing.

Corrupt addresses route into the replica's block-repair machinery
(`request_blocks`/`block`, vsr/multi.py) one block at a time.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.vsr.grid import Grid


class GridScrubber:
    def __init__(self, grid: Grid, *, cycle_ticks: int = 1024,
                 blocks_per_tick_max: int = 32) -> None:
        self.grid = grid
        self.cycle_ticks = max(1, cycle_ticks)
        self.blocks_per_tick_max = max(1, blocks_per_tick_max)
        # Current tour: a stable snapshot of allocated addresses and a
        # cursor into it; ticks remaining drive the pacing.
        self._tour: np.ndarray = np.zeros(0, np.int64)
        self._cursor = 0
        self._ticks_left = 0
        # Known-corrupt addresses (a set: a block that stays corrupt
        # across tours is ONE fault, reported once until repaired).
        self.corrupt: set[int] = set()
        self.cycles = 0
        self.blocks_verified = 0
        self.faults_found = 0

    @property
    def progress(self) -> float:
        """Fraction of the current tour completed (0..1)."""
        if len(self._tour) == 0:
            return 1.0
        return self._cursor / len(self._tour)

    # Empty-grid snapshot retry cadence: bounded O(grid) rescans while
    # still picking up the first allocations promptly.
    EMPTY_RETRY_TICKS = 16

    def _begin_tour(self) -> None:
        self._tour = np.flatnonzero(~self.grid.free_set.free) + 1
        self._cursor = 0
        self._ticks_left = (
            self.cycle_ticks
            if len(self._tour)
            else min(self.EMPTY_RETRY_TICKS, self.cycle_ticks)
        )

    def repaired(self, address: int) -> None:
        """Forget a healed block so a relapse counts as a new fault."""
        self.corrupt.discard(address)

    def tick(self) -> list[int]:
        """Verify the next paced chunk of the tour; returns newly-found
        corrupt addresses."""
        if self._cursor >= len(self._tour):
            if len(self._tour):
                self.cycles += 1
            elif self._ticks_left > 1:
                # Empty grid: retry the snapshot on the tour cadence,
                # not every tick (the snapshot scan is O(grid)).
                self._ticks_left -= 1
                return []
            self._begin_tour()
            if len(self._tour) == 0:
                return []
        remaining = len(self._tour) - self._cursor
        quota = -(-remaining // max(1, self._ticks_left))  # ceil
        quota = min(quota, self.blocks_per_tick_max, remaining)
        self._ticks_left = max(1, self._ticks_left - 1)
        found: list[int] = []
        chunk = self._tour[self._cursor : self._cursor + quota]
        # Blocks leaving the live set since the snapshot are skipped
        # rather than flagged (their frames may legitimately go stale,
        # and peers that checkpointed no longer serve them — the same
        # predicate the repair filter uses).  Indexed per chunk, not a
        # full-grid mask.
        dead = self.grid.free_set.leaving_live_set(chunk)
        for address, is_dead in zip(chunk, dead):
            if is_dead:
                continue
            address = int(address)
            self.blocks_verified += 1
            if not self.grid.verify_block(address) and (
                address not in self.corrupt
            ):
                found.append(address)
        self._cursor += quota
        self.faults_found += len(found)
        self.corrupt.update(found)
        return found
