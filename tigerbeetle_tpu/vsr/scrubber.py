"""Grid scrubber: background read-verify of allocated grid blocks.

reference: src/vsr/grid_scrubber.zig — cycles ("tours") through every
allocated block proactively so latent sector errors are found (and
repaired from peers) before the data is needed.  Design carried over
from the reference's tour machinery:

- A tour SNAPSHOTS the allocated set once per cycle and walks that
  snapshot to completion; blocks allocated mid-cycle are picked up by
  the next tour (a moving target would skip or double-scrub blocks as
  the free set churns — the old per-tick re-listing did exactly that,
  and cost O(grid) work per tick).
- Blocks freed after the snapshot are skipped at probe time: a
  released block's frame legitimately goes stale the moment the free
  set forfeits it (reference: grid_scrubber cancels reads for freed
  blocks at checkpoint).
- Pacing targets a TOUR DURATION rather than a fixed per-tick count:
  each tick probes just enough blocks to finish the snapshot within
  ``cycle_ticks``, bounded by ``blocks_per_tick_max`` so a huge grid
  never turns one tick into an I/O storm (reference:
  grid_scrubber.zig cycle pacing against constants.grid_scrubber_*).
- Stats (`blocks_verified`, `faults_found`, `cycles`, `progress`)
  feed the replica's StatsD/tracer surfacing.

Corrupt addresses route into the replica's block-repair machinery
(`request_blocks`/`block`, vsr/multi.py) one block at a time.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.vsr.grid import Grid


class GridScrubber:
    def __init__(self, grid: Grid, *, cycle_ticks: int = 1024,
                 blocks_per_tick_max: int = 32) -> None:
        self.grid = grid
        self.cycle_ticks = max(1, cycle_ticks)
        self.blocks_per_tick_max = max(1, blocks_per_tick_max)
        # Current tour: a stable snapshot of allocated addresses and a
        # cursor into it; ticks remaining drive the pacing.
        self._tour: np.ndarray = np.zeros(0, np.int64)
        self._cursor = 0
        self._ticks_left = 0
        self.corrupt: list[int] = []
        self.cycles = 0
        self.blocks_verified = 0
        self.faults_found = 0

    @property
    def progress(self) -> float:
        """Fraction of the current tour completed (0..1)."""
        if len(self._tour) == 0:
            return 1.0
        return self._cursor / len(self._tour)

    def _begin_tour(self) -> None:
        self._tour = np.flatnonzero(~self.grid.free_set.free) + 1
        self._cursor = 0
        self._ticks_left = self.cycle_ticks

    def tick(self) -> list[int]:
        """Verify the next paced chunk of the tour; returns newly-found
        corrupt addresses."""
        if self._cursor >= len(self._tour):
            if len(self._tour):
                self.cycles += 1
            self._begin_tour()
            if len(self._tour) == 0:
                return []
        remaining = len(self._tour) - self._cursor
        quota = -(-remaining // max(1, self._ticks_left))  # ceil
        quota = min(quota, self.blocks_per_tick_max, remaining)
        self._ticks_left = max(1, self._ticks_left - 1)
        found: list[int] = []
        fs = self.grid.free_set
        chunk = self._tour[self._cursor : self._cursor + quota]
        # Freed — or staged for release — since the snapshot: the
        # block is leaving the live set, and a peer that already
        # checkpointed may not serve it for repair anymore.  Skip
        # rather than flag (reference: grid_scrubber cancels reads of
        # released blocks).  Indexed per chunk, not a full-grid mask.
        dead = fs.free[chunk - 1] | fs.staging[chunk - 1]
        for address, is_dead in zip(chunk, dead):
            if is_dead:
                continue
            address = int(address)
            self.blocks_verified += 1
            if not self.grid.verify_block(address):
                found.append(address)
        self._cursor += quota
        self.faults_found += len(found)
        self.corrupt.extend(found)
        return found
