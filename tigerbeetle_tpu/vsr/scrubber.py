"""Grid scrubber: background read-verify of allocated grid blocks.

reference: src/vsr/grid_scrubber.zig:1-21 — cycles through every
allocated block proactively so latent sector errors are found (and
repaired from peers) before the data is needed.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.vsr.grid import Grid


class GridScrubber:
    def __init__(self, grid: Grid, blocks_per_tick: int = 4) -> None:
        self.grid = grid
        self.blocks_per_tick = blocks_per_tick
        self._cursor = 0
        self.corrupt: list[int] = []
        self.cycles = 0

    def tick(self) -> list[int]:
        """Verify the next few allocated blocks; returns newly-found
        corrupt addresses."""
        found: list[int] = []
        allocated = np.flatnonzero(~self.grid.free_set.free)
        if len(allocated) == 0:
            return found
        for _ in range(self.blocks_per_tick):
            if self._cursor >= len(allocated):
                self._cursor = 0
                self.cycles += 1
            address = int(allocated[self._cursor]) + 1
            self._cursor += 1
            if not self.grid.verify_block(address):
                found.append(address)
        self.corrupt.extend(found)
        return found
