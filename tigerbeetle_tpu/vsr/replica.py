"""Replica: the durable commit pipeline around a state machine.

This module carries the single-replica slice of the reference's
`ReplicaType` (reference: src/vsr/replica.zig): format, crash
recovery (superblock quorum -> snapshot restore -> WAL replay),
timestamp assignment, the prepare -> journal -> commit -> reply chain,
pulse injection, client sessions with at-most-once dedupe, and
checkpointing every `vsr_checkpoint_interval` ops (reference:
src/vsr/replica.zig:3886-4039).  Multi-replica consensus (prepare_ok
quorums, view change, repair) layers on top in vsr/multi.py via the
message bus — the commit pipeline here is shared by both.

Recovery = re-execution: timestamps are assigned at prepare time and
stored in the prepare header, so replaying the WAL through the state
machine is bit-deterministic (reference: deterministic state machine
requirement, docs/about/vopr.md).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.obs import stat_property
from tigerbeetle_tpu.state_machine import demuxer
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.storage import Storage, _sectors
from tigerbeetle_tpu.vsr.superblock import SuperBlock
from tigerbeetle_tpu.vsr.wire import Command, VsrOperation


def format(storage: Storage, cluster: int, replica: int = 0,
           replica_count: int = 1) -> None:
    """Initialize a data file (reference: src/vsr/replica_format.zig):
    superblock (sequence 1) + the root prepare in WAL slot 0."""
    sb = SuperBlock(storage, cluster)
    sb.format(replica, replica_count)
    journal = Journal(storage, cluster)
    journal.write_prepare(wire.root_prepare(cluster), b"")


# Fixed A/B checkpoint-snapshot reservation when an LSM forest shares
# the grid zone (spilling bounds the blob well below this; asserted at
# checkpoint).  Without a forest the regions size dynamically as before.
SNAPSHOT_SPAN = 1 << 28
FOREST_BLOCK_COUNT = 1 << 12


@dataclasses.dataclass
class Session:
    """Client session entry (reference: src/vsr/client_sessions.zig)."""

    session: int            # op of the register prepare
    request: int            # latest request number seen
    reply_header: bytes     # serialized header of the latest reply
    slot: int               # client_replies zone slot


class Replica:
    # Audited write-write sharing with the ckpt SerialWorker (tbcheck
    # worker-shared): the async checkpoint flip publishes checkpoint_op
    # from the worker thread, while open()/recovery set it on the
    # foreground thread — serialized by the _ckpt_join barrier, which
    # runs before any foreground read or write of checkpoint state.
    _WORKER_SHARED = frozenset({"checkpoint_op"})

    def __init__(self, storage: Storage, cluster: int, state_machine,
                 replica: int = 0, replica_count: int = 1, aof=None,
                 forest_block_count: int = FOREST_BLOCK_COUNT) -> None:
        self.storage = storage
        self.cluster = cluster
        self.sm = state_machine
        self.aof = aof  # optional vsr.aof.AOF (reference: src/aof.zig)
        # One daemon worker overlaps each op's WAL fdatasync (disk
        # wait) with its commit-stage CPU work; _prepare_and_commit
        # joins before replying, preserving the durability-before-ack
        # contract.  Only on backends whose sync is thread-safe
        # against concurrent writes (FileStorage); the fault-injecting
        # MemoryStorage keeps the synchronous path so its seeded crash
        # model stays deterministic.
        self._wal_sync_worker = None
        self._wal_sync_inflight = None
        # Asynchronous checkpoints (TB_CKPT_ASYNC, default on): the
        # commit-visible part of checkpoint() is only the freeze
        # (spill residue + snapshot encode + buffered blob write); the
        # disk barriers (grid writeback join, fdatasync, superblock
        # flip) run on a background worker and the NEXT checkpoint (or
        # close()) joins them.  Only on FileStorage — MemoryStorage
        # keeps the synchronous path so seeded crash tests stay
        # deterministic.
        self._ckpt_worker = None
        self._ckpt_job = None         # non-None while a flip is in flight
        self._ckpt_last_op = 0        # commit_min of the latest freeze
        # Metrics registry (obs/registry.py): every stat_* counter on
        # this replica is a registry handle behind a compatibility
        # property; latency histograms ride the same registry and the
        # whole tree is scrapeable via the `stats` wire op.
        from tigerbeetle_tpu import obs

        self.metrics = obs.Registry()
        self._stats = {
            "stat_ckpt_async": self.metrics.counter("ckpt.async"),
            "stat_ckpt_sync": self.metrics.counter("ckpt.sync"),
        }
        self._c_commits = self.metrics.counter("commits")
        self._h_commit = self.metrics.histogram("commit_us")
        self._h_request = self.metrics.histogram("request_us")
        # Hash-once commit path (round 23).  hash.bytes_hashed counts
        # BODY bytes actually SHA-256'd on this replica (ingress
        # verify, build rehashes under TB_HASH_REUSE=0, and the
        # coalesce finalize — header hashes are fixed 240-byte costs
        # and excluded by definition); hash.reuse_hits counts build
        # seams that consumed a cached digest instead of rehashing;
        # hash.committed_body_bytes is the ratio denominator the TCP
        # smoke asserts against (bytes_hashed / committed_body_bytes
        # <= 1.0 per role with reuse on).  hash.dup_body_bytes charges
        # duplicate DELIVERIES — a retransmitted prepare or request
        # must be verified before it can be recognized as a duplicate,
        # so its ingress pass is unavoidable in any design and the
        # smoke's exact bound is bytes_hashed <= committed + dup.
        # Created here so the single-replica server scrapes the same
        # vsr.hash.* names the VSR subclass feeds.
        from tigerbeetle_tpu import envcheck as _envcheck

        self._hash_reuse = _envcheck.hash_reuse() == 1
        self._c_hash_bytes = self.metrics.counter("hash.bytes_hashed")
        self._c_hash_reuse = self.metrics.counter("hash.reuse_hits")
        self._c_hash_commit = self.metrics.counter(
            "hash.committed_body_bytes"
        )
        self._c_hash_dup = self.metrics.counter("hash.dup_body_bytes")
        # Batched-reply encode pass (one vectorized header build + one
        # batch checksum finalize per committed batch).  The owning
        # server re-points this at its own `server.reply_encode_us`
        # histogram so the drain-loop instruments sit together.
        self.h_reply_encode = self.metrics.histogram("reply_encode_us")
        self._h_ckpt_freeze = self.metrics.histogram("ckpt.freeze_us")
        self._h_ckpt_finalize = self.metrics.histogram("ckpt.finalize_us")
        self.metrics.gauge_fn("commit_min", lambda: self.commit_min)
        # Per-request anatomy (obs/anatomy.py): stage timelines for
        # sampled requests, keyed by the wire trace context.  Enabled
        # iff metrics are; the owning server attaches the flight ring.
        from tigerbeetle_tpu.obs.anatomy import AnatomyRecorder

        self.anatomy = AnatomyRecorder(self.metrics.scope("anatomy"))
        if hasattr(state_machine, "anatomy"):
            state_machine.anatomy = self.anatomy
        if getattr(storage, "supports_async_writeback", False):
            import weakref

            from tigerbeetle_tpu import envcheck
            from tigerbeetle_tpu.utils.worker import SerialWorker

            self._wal_sync_worker = SerialWorker("wal-sync")
            weakref.finalize(self, self._wal_sync_worker.close)
            if envcheck.ckpt_async():
                self._ckpt_worker = SerialWorker("ckpt")
                weakref.finalize(self, self._ckpt_worker.close)
        # Optional testing.hash_log.HashLog: per-commit chained digests
        # for determinism-divergence pinpointing (reference:
        # src/testing/hash_log.zig).
        self.hash_log = None
        # Root ring (round 19): op -> 16-byte state root recorded after
        # each commit, serving the `state_root` at-op query followers
        # attest against (runtime/follower.py).  None = off (zero
        # cost); the owning server/harness enables it by assigning a
        # size via enable_root_ring().  Requires a state machine with
        # state_root().
        self.root_ring: dict[int, bytes] | None = None
        self.root_ring_max = 0
        # Span tracer (utils/tracer.py; reference: src/tracer.zig
        # hooked in the commit path) — NULL until set_tracer().
        from tigerbeetle_tpu.utils import tracer as tracer_mod

        self.tracer = tracer_mod.NULL
        self.config = storage.layout.config
        self.replica = replica
        self.replica_count = replica_count
        # Reconfiguration (reference: src/vsr.zig:273-311): the FIXED
        # process identity (index into the operator's address list) vs
        # the protocol slot (`self.replica`) the process currently
        # fills.  `members[slot] = process`; epoch bumps per change.
        self.process_index = replica
        # COMMITTED epoch/membership: advanced only by executing the
        # replicated reconfigure op (or restoring a checkpoint), so
        # reconfigure replies are a pure function of the op stream.
        self.epoch = 0
        self.members: list[int] | None = None
        # ADOPTED epoch/roles: may run AHEAD of committed via the
        # heartbeat advertisement (a crashed process must re-learn the
        # slot it fills to be reachable at all), but never influences
        # the committed validation — conflating them made a replica
        # that heartbeat-adopted epoch N reply "stale" to the
        # intermediate epochs it later replayed, while live replicas
        # had replied "ok": reply divergence (VOPR reconfigure
        # nemesis, seed 44).
        self.epoch_adopted = 0
        self.members_adopted: list[int] | None = None
        # epoch -> members actually applied (replay idempotency).
        self._reconfig_history: dict[int, list[int]] = {}

        self.superblock = SuperBlock(storage, cluster)
        self.journal = Journal(storage, cluster)
        self.journal.set_metrics(self.metrics)
        self.journal.anatomy = self.anatomy

        # LSM forest over the grid zone's block region (state machines
        # that support it spill frozen state there, so checkpoints stay
        # O(RAM tail) and durable state scales past host RAM —
        # reference: src/lsm/forest.zig:31).  The A/B snapshot regions
        # get a fixed reservation ahead of the block region; the file
        # is sparse, so unused reservation costs nothing on disk.
        self.forest = None
        if hasattr(state_machine, "attach_forest"):
            from tigerbeetle_tpu.lsm.forest import Forest

            self.forest = Forest(
                storage,
                base_offset=storage.layout.grid_offset + 2 * SNAPSHOT_SPAN,
                block_count=forest_block_count,
            )
            state_machine.attach_forest(self.forest)

        self.op = 0                  # highest prepared op
        self._ckpt_interval_observed = 0  # ops between checkpoints
        self.commit_min = 0          # highest committed op
        self.commit_parent = None    # checksum of last committed prepare
        self.view = 0
        self.parent_checksum = 0     # checksum of prepare at self.op
        self.checkpoint_op = 0
        self.sessions: dict[int, Session] = {}
        # (client, reply_header_bytes, reply_body) per sub-request of
        # the most recently committed batched prepare (see
        # _commit_prepare_impl; the primary pipeline drains it).
        self._batch_replies: list[tuple[int, bytes, bytes]] = []
        self._next_reply_slot = 0
        self.realtime = 0
        # Multiversion upgrades (multi.py drives these; the base
        # pipeline honors Operation.upgrade commits).
        self.release = 1
        self.upgrade_target: int | None = None

    # Compatibility: migrated stat_* counters live in the metrics
    # registry (obs/registry.py); reads and writes route to handles.
    stat_ckpt_async = stat_property("stat_ckpt_async")
    stat_ckpt_sync = stat_property("stat_ckpt_sync")

    # ------------------------------------------------------------------
    # Open / recovery.

    def open(self, *, replay_tail: bool | None = None) -> None:
        """Recover: superblock quorum -> checkpoint snapshot -> WAL.

        `replay_tail` controls whether the WAL above the checkpoint is
        EXECUTED during recovery.  Single-replica: yes — every recorded
        prepare was committed.  Multi-replica: no — the tail may hold
        speculative prepares that never reached quorum and were
        superseded after a view change; executing them would diverge
        this replica's state from the cluster permanently.  The tail
        stays in the journal as candidates, and the consensus layer
        re-commits it through the parent-checksum-verified chain as
        commit_max is learned from the cluster (the reference keeps
        recovering replicas from committing ahead of the cluster the
        same way — src/vsr/replica.zig:44-49 .recovering_head)."""
        if replay_tail is None:
            replay_tail = self.replica_count == 1
        sb = self.superblock.open()
        if self.cluster is None:
            # cluster=None = adopt the id `format` recorded (see
            # SuperBlock.open); the journal shares it for prepare
            # checksum verification.
            self.cluster = self.superblock.cluster
            self.journal.cluster = self.cluster
        if int(sb["member_count"]):
            members = list(
                bytes(sb["members"])[: int(sb["member_count"])]
            )
            self._install_committed(int(sb["epoch"]), members)
        self.view = int(sb["view"])
        self.checkpoint_op = int(sb["commit_min"])

        # Restore the checkpoint snapshot (if one was ever taken).
        size = int(sb["checkpoint_size"])
        if size:
            blob = self._read_grid(int(sb["checkpoint_offset"]), size)
            want = (
                int(sb["checkpoint_checksum_lo"])
                | (int(sb["checkpoint_checksum_hi"]) << 64)
            )
            if wire.checksum(blob) != want:
                raise RuntimeError("checkpoint snapshot corrupt")
            self._restore_snapshot(blob)
            # State-root recompute-and-assert: the restored state
            # machine re-derives its incremental commitment from
            # scratch; it must match the root the checkpoint recorded
            # — a blob that passes its checksum but decodes to
            # different table content (codec drift, partial restore)
            # dies HERE, not at the next cross-replica divergence.
            root_stored = int(sb["state_root_lo"]) | (
                int(sb["state_root_hi"]) << 64
            )
            if root_stored and hasattr(self.sm, "state_root"):
                root_now = int.from_bytes(self.sm.state_root(), "little")
                if root_now != root_stored:
                    raise RuntimeError(
                        "checkpoint state root mismatch after restore: "
                        f"recorded {root_stored:#034x}, recomputed "
                        f"{root_now:#034x}"
                    )

        recovery = self.journal.recover(self.checkpoint_op)
        if recovery.faulty_ops and self.replica_count == 1:
            raise RuntimeError(f"WAL data loss at ops {recovery.faulty_ops}")

        # Walk the readable prefix above the checkpoint.  When a tail
        # replay is requested (single-replica recovery, restart-replay
        # checkers) a gap truncates the head there — execution needs
        # the bodies.  A multi-replica open PRESERVES the full
        # recovered head instead: the ops above a damaged slot are
        # still vouched by the redundant ring, and the VSR repair
        # protocol refetches the missing prepares from peers —
        # truncating here made a damaged replica understate its DVC
        # and let a view-change quorum of damaged replicas discard
        # committed ops (VOPR corruption nemesis, seed 8006).
        op_head = recovery.op_head
        for op in range(self.checkpoint_op + 1, recovery.op_head + 1):
            read = self.journal.read_prepare(op)
            if read is None:
                assert self.replica_count > 1
                if replay_tail:
                    op_head = op - 1
                break
            if replay_tail:
                header, body = read
                self._commit_prepare(header, body, replay=True)
        self.op = op_head
        self.commit_min = op_head if replay_tail else self.checkpoint_op
        # Commit-chain anchor: checksum of the last committed prepare
        # (consensus verifies each next commit links to it).
        anchor = recovery.headers.get(self.commit_min)
        if anchor is not None:
            self.commit_parent = wire.u128(anchor, "checksum")
        elif self.commit_min == 0:
            self.commit_parent = wire.u128(
                wire.root_prepare(self.cluster), "checksum"
            )
        else:
            self.commit_parent = None  # unknown; verified from repair
        head = recovery.headers.get(op_head)
        self.parent_checksum = (
            wire.u128(head, "checksum") if head is not None
            else wire.u128(wire.root_prepare(self.cluster), "checksum")
        )

    # ------------------------------------------------------------------
    # The request path (single-replica: prepare+commit are synchronous).

    def on_request(self, operation: int, body: bytes, *, client: int = 0,
                   request: int = 0, realtime: int | None = None) -> bytes:
        """Execute one client request end-to-end; returns the reply body.

        Handles dedupe: a repeat of the client's latest request returns
        the stored reply without re-executing (reference:
        src/vsr/replica.zig:5035-5100)."""
        if realtime is not None:
            self.realtime = realtime
        if client:
            entry = self.sessions.get(client)
            if entry is not None and request == entry.request and request > 0:
                return self._read_reply(entry)

        if operation != types.Operation.pulse:
            self._tick_pulses()
        # request_us covers the whole prepare -> WAL -> commit chain
        # (what a single-replica client waits for); commit_us inside
        # it isolates the state-machine commit stage.
        with self._h_request.time():
            reply = self._prepare_and_commit(operation, body, client, request)
        return reply

    def register_client(self, client: int) -> None:
        """Session registration (reference: Operation.register)."""
        self._prepare_and_commit(
            VsrOperation.register, b"", client, 0, vsr_operation=True
        )

    def _tick_pulses(self) -> None:
        while True:
            self._advance_prepare_timestamp()
            if not self.sm.pulse_needed():
                return
            before = self.sm.pulse_next_timestamp
            self._prepare_and_commit(types.Operation.pulse, b"", 0, 0)
            if self.sm.pulse_next_timestamp == before:
                return

    def _advance_prepare_timestamp(self) -> None:
        # reference: src/vsr/replica.zig:5762-5772
        self.sm.prepare_timestamp = max(
            max(self.sm.prepare_timestamp, self.sm.commit_timestamp) + 1,
            self.realtime,
        )

    def _prepare_and_commit(self, operation: int, body: bytes, client: int,
                            request: int, vsr_operation: bool = False) -> bytes:
        assert len(body) <= self.config.message_body_size_max
        self._advance_prepare_timestamp()
        if not vsr_operation:
            self.sm.prepare(types.Operation(operation), body)
        timestamp = self.sm.prepare_timestamp

        op = self.op + 1
        header = wire.make_header(
            command=Command.prepare,
            operation=operation,
            cluster=self.cluster,
            client=client,
            request=request,
            view=self.view,
            op=op,
            commit=self.commit_min,
            timestamp=timestamp,
            parent=self.parent_checksum,
        )
        wire.finalize_header(header, body)
        # Single-replica role: bodies originate at the caller (no
        # ingress frame, no prior digest), so this finalize is the one
        # hash pass the hash-once contract budgets for the role.
        self._c_hash_bytes.inc(len(body))

        # WAL append is THE durability point — but the fdatasync (disk
        # wait, ~8ms on this container) overlaps the commit stage's CPU
        # work: the reply is only returned after the sync JOINS, so the
        # contract (no ack before WAL durability) is unchanged
        # (reference: the prepare pipeline overlaps journal writes with
        # commit execution the same way, src/vsr/replica.zig pipeline).
        if self._wal_sync_worker is not None:
            self.journal.write_prepare(header, body, sync=False)
            self.op = op
            self.parent_checksum = wire.u128(header, "checksum")
            self._wal_sync_inflight = self._wal_sync_worker.submit(
                self.storage.sync_wal
            )
            try:
                reply = self._commit_prepare(header, body)
            finally:
                self._join_wal_sync()
        else:
            self.journal.write_prepare(header, body)
            self.op = op
            self.parent_checksum = wire.u128(header, "checksum")
            reply = self._commit_prepare(header, body)

        # Checkpoint cadence (reference: src/constants.zig:55-81) — must
        # run before the WAL ring wraps over the previous checkpoint.
        if self._checkpoint_due():
            self.checkpoint()
        return reply

    def _checkpoint_due(self) -> bool:
        """Interval crossed since the latest FREEZE (an async flip
        still in flight counts — re-freezing against it would just
        serialize every commit on the join)."""
        return (
            self.commit_min - max(self.checkpoint_op, self._ckpt_last_op)
            >= self.config.vsr_checkpoint_interval
        )

    def _join_wal_sync(self) -> None:
        if self._wal_sync_inflight is not None:
            self._wal_sync_inflight.result()
            self._wal_sync_inflight = None

    def _aof_barrier(self) -> None:
        """WAL durability barrier before an AOF append (VsrReplica
        extends this to force the group-commit covering sync)."""
        self._join_wal_sync()

    def set_tracer(self, tracer) -> None:
        """Attach a utils.tracer.Tracer to this replica's hot paths
        (commit stages, checkpoint, journal writes, device engine
        lifecycle)."""
        self.tracer = tracer
        self.journal.tracer = tracer
        dev = getattr(self.sm, "_dev", None)
        if dev is not None and hasattr(dev, "tracer"):
            dev.tracer = tracer

    def _commit_prepare(self, header: np.ndarray, body: bytes,
                        replay: bool = False) -> bytes:
        """The commit stage chain (reference: src/vsr/replica.zig:
        3456-3535): prefetch -> commit -> reply store.  Wrapped whole
        in the `commit` span + commit_us histogram so per-op commit
        latency is scrapeable (bench sources its commit percentiles
        from this, not from re-derived timings)."""
        with self.tracer.span(
            "commit", op=int(header["op"])
        ), self._h_commit.time():
            reply = self._commit_prepare_impl(header, body, replay)
        self._c_commits.inc()
        # Ratio denominator for the hash-once contract: every body
        # byte this replica commits.  The TCP smoke asserts
        # bytes_hashed / committed_body_bytes <= 1.0 per role with
        # reuse on (coalescing excluded — see DESIGN.md r23).
        self._c_hash_commit.inc(len(body))
        if self.root_ring is not None:
            self._record_root(int(header["op"]))
        self.anatomy.stage_h(header, "commit")
        return reply

    def enable_root_ring(self, size: int) -> None:
        """Keep the state root of the last `size` committed ops so the
        `state_root` query can answer AT a requested op — the follower
        attestation primitive.  Backfills the current commit point so
        a follower already caught up can attest immediately."""
        assert size > 0 and hasattr(self.sm, "state_root")
        self.root_ring = {}
        self.root_ring_max = int(size)
        if self.commit_min > 0:
            self._record_root(self.commit_min)

    def _record_root(self, op: int) -> None:
        ring = self.root_ring
        ring[op] = self.sm.state_root()
        while len(ring) > self.root_ring_max:
            ring.pop(next(iter(ring)))

    def root_at(self, op: int) -> bytes | None:
        """Ring lookup: the state root AFTER committing `op`, if still
        retained."""
        return None if self.root_ring is None else self.root_ring.get(op)

    def _commit_prepare_impl(self, header: np.ndarray, body: bytes,
                             replay: bool = False) -> bytes:
        op = int(header["op"])
        operation = int(header["operation"])
        timestamp = int(header["timestamp"])
        client = wire.u128(header, "client")
        if hasattr(self.sm, "anatomy_trace"):
            # Stamp the current prepare's trace id so the state
            # machine can attribute its device-window hop.
            self.sm.anatomy_trace = wire.trace_sampled(header)

        if replay:
            # Timestamps replay from the header, not the clock
            # (prepare() only assigns timestamps, so setting the stored
            # value reproduces the live prepare exactly).
            self.sm.prepare_timestamp = timestamp
            if self.aof is not None and op > self.aof.last_op:
                # Gap fill (round 19): a crash can erase the AOF's
                # unsynced tail while the ops it held stay committed
                # cluster-wide (WAL recovery replays them with
                # replay=True, which historically skipped the AOF
                # entirely).  Re-appending exactly the missing ops
                # keeps the AOF's op stream gap-free — the contract
                # followers tail under.  No durability barrier needed:
                # a replayed op is already covered by the WAL.
                self.aof.write(header, body)
        elif self.aof is not None:
            # reference: src/vsr/replica.zig:4136-4141 — AOF before
            # apply, and never ahead of the WAL's durability: the AOF
            # must not record an op a crash could erase from the WAL.
            self._aof_barrier()
            self.aof.write(header, body)

        if operation == int(VsrOperation.register):
            reply = b""
            self.sessions[client] = Session(
                session=op, request=0, reply_header=b"",
                slot=self._alloc_reply_slot(),
            )
            assert len(self.sessions) <= self.config.clients_max
        elif operation == int(VsrOperation.reconfigure):
            # Replicated membership change (reference:
            # src/vsr.zig:273-311): epoch bump + slot->process
            # permutation; reply is a 4-byte result code.  The
            # prepare's view rides along so the primary-displacement
            # check is deterministic across replicas (the header is
            # replicated bit-exact; live view state is not).
            reply = self._commit_reconfigure(body, int(header["view"]))
        elif operation == int(VsrOperation.upgrade):
            # Cluster-coordinated release switch (reference:
            # src/vsr/replica.zig:4298 replica_release_execute): the
            # committed target release takes effect when the process
            # re-executes into the new binary (harness restart).
            reply = b""
            target = int.from_bytes(body[:8], "little")
            # Replay of an old upgrade op (already running >= target)
            # must not latch a stale target and block future upgrades.
            if target > self.release:
                self.upgrade_target = target
        else:
            sm_op = types.Operation(operation)
            n_subs = wire.u128(header, "context")
            if n_subs:
                # Logically-batched prepare: commit the combined event
                # batch once, then demux + store each sub-request's
                # reply slice (state_machine/demuxer.py).
                events, subs = demuxer.decode_trailer(body, n_subs)
                with self.tracer.span("state_machine_prefetch"):
                    self.sm.prefetch(
                        sm_op, events, prefetch_timestamp=timestamp
                    )
                with self.tracer.span(
                    "state_machine_commit", op=op, bytes=len(events)
                ):
                    reply = self.sm.commit(
                        client, op, timestamp, sm_op, events
                    )
                dm = demuxer.Demuxer(sm_op, reply)
                offset = 0
                pieces = []
                for _sub_client, _sub_request, count in subs:
                    pieces.append(dm.decode(offset, count))
                    offset += count
                # Per-sub replies captured AT commit: a session stores
                # only its LATEST reply, so when one batch multiplexes
                # several requests of the SAME client (open-loop
                # sessions keep many in flight), sending the stored
                # reply N times would answer every sub with the last
                # request's bytes — earlier subs would never resolve.
                # The pipeline sends these captured pairs instead.
                #
                # Coalesced encode (columnar ingest, round 14): ALL sub
                # reply headers are built in one vectorized pass and
                # checksummed in one batch finalize — replacing per-sub
                # make_header + 2 hashlib calls — then scattered to
                # sessions in sub order (bit-identical bytes to the
                # old per-sub path).
                with self.h_reply_encode.time():
                    rhdrs = self._encode_sub_replies(header, subs, pieces)
                self._batch_replies = []
                for i, (sub_client, sub_request, _count) in enumerate(subs):
                    if not sub_client:
                        continue
                    entry = self.sessions.get(sub_client)
                    if entry is None:  # un-registered (tests drive raw)
                        continue
                    piece = pieces[i]
                    entry.request = sub_request
                    entry.reply_header = rhdrs[i].tobytes()
                    msg = entry.reply_header + piece
                    self.storage.write(
                        self.storage.layout.reply_slot_offset(entry.slot),
                        msg.ljust(_sectors(len(msg)), b"\x00"),
                    )
                    self._batch_replies.append(
                        (sub_client, entry.reply_header, piece)
                    )
                self._compact_beat()
                self.commit_min = op
                if self.hash_log is not None and not replay:
                    self.hash_log.record(op, header.tobytes(), reply)
                return reply
            with self.tracer.span("state_machine_prefetch"):
                self.sm.prefetch(sm_op, body, prefetch_timestamp=timestamp)
            with self.tracer.span(
                "state_machine_commit", op=op, bytes=len(body)
            ):
                reply = self.sm.commit(client, op, timestamp, sm_op, body)

        self._compact_beat()
        self.commit_min = op
        # Replayed commits are not recorded: a recovered WAL tail may
        # include speculative ops that never reached quorum and are
        # later superseded (two-step repair corrects the state).
        if self.hash_log is not None and not replay:
            self.hash_log.record(op, header.tobytes(), reply)
        if client and operation != int(VsrOperation.register):
            self._store_reply(header, reply)
        return reply

    # ------------------------------------------------------------------
    # Reconfiguration (reference: src/vsr.zig:273-311).

    @staticmethod
    def decode_reconfigure(body: bytes) -> tuple[int, list[int]] | None:
        """None = malformed (a poison body must fail with a result
        code, never crash the commit path of every replica)."""
        if len(body) < 9:
            return None
        epoch = int.from_bytes(body[:8], "little")
        count = body[8]
        if count == 0 or count > 64 or len(body) < 9 + count:
            return None
        return epoch, list(body[9 : 9 + count])

    @staticmethod
    def encode_reconfigure(epoch: int, members: list[int]) -> bytes:
        return (
            epoch.to_bytes(8, "little")
            + bytes([len(members)])
            + bytes(members)
        )

    def validate_reconfigure(
        self, epoch: int, members: list[int], view: int = 0
    ) -> int:
        """-> 0 ok; 1 stale/skipped epoch; 2 malformed membership;
        3 would displace the primary that committed it (an accepted
        self-demotion would orphan the in-flight pipeline — the slot
        of `view`'s primary must keep its process)."""
        if epoch != self.epoch + 1:
            return 1
        if sorted(members) != list(range(self._member_total())):
            return 2
        current = self.members or list(range(self._member_total()))
        primary_slot = view % self.replica_count
        if members[primary_slot] != current[primary_slot]:
            return 3
        return 0

    def _member_total(self) -> int:
        return self.replica_count  # multi.py adds standbys

    def _commit_reconfigure(self, body: bytes, view: int = 0) -> bytes:
        decoded = self.decode_reconfigure(body)
        if decoded is None:
            return (2).to_bytes(4, "little")
        epoch, members = decoded
        if self._reconfig_history.get(epoch) == members:
            # Idempotent replay: a replica whose committed install of
            # this epoch came from a checkpoint (open/state sync)
            # rather than live execution replays the op with the same
            # success code every live replica recorded.  (History
            # covers only the restored epoch, not intermediates — an
            # acceptable residual: clients retry reconfigure against
            # the session reply only within one epoch.)
            return (0).to_bytes(4, "little")
        code = self.validate_reconfigure(epoch, members, view)
        if code == 0:
            self._install_committed(epoch, members)
        return code.to_bytes(4, "little")

    def _install_committed(self, epoch: int, members: list[int]) -> None:
        """Install a committed membership: the single sequence the
        op-stream execution, superblock restore, and state-sync
        restore must all share — divergence between these paths is
        exactly the reply-nondeterminism class of seeds 44 and
        300661417."""
        self.epoch = epoch
        self.members = list(members)
        self._reconfig_history[epoch] = list(members)
        self._adopt_roles(epoch, members)

    def _adopt_roles(self, epoch: int, members: list[int]) -> None:
        """Adopt the runtime identity for `members` unless a NEWER
        membership was already adopted out-of-band (heartbeat): roles
        follow the freshest known epoch, while self.epoch/self.members
        stay the committed-prefix state that deterministic reconfigure
        replies validate against."""
        if epoch < self.epoch_adopted:
            return
        self.epoch_adopted = epoch
        self.members_adopted = list(members)
        self._apply_membership(members)

    def _apply_membership(self, members: list[int]) -> None:
        """Adopt the slot this process fills under `members`
        (single-replica base: bookkeeping only; multi.py re-derives
        roles, ring, and clock)."""
        self.replica = members.index(self.process_index)

    def _compact_beat(self) -> None:
        """One beat of paced LSM work per commit (reference:
        src/vsr/replica.zig:3847 .compact_state_machine stage,
        src/lsm/compaction.zig beats): spill a bounded chunk of frozen
        state into the LSM and advance a bounded slice of merge debt,
        so checkpoints only settle a small residue instead of stalling
        on a whole interval's worth."""
        if self.forest is None:
            return
        # Spill/compaction beats keep running through an async flip
        # window: allocation is safe because the FreeSet quarantines
        # the frozen checkpoint's released blocks from reuse until the
        # flip lands (the previous superblock — still the durable
        # recovery root — may reference them), and beats stay a pure
        # function of commit count either way (cluster-deterministic).
        spilled = 0
        if hasattr(self.sm, "spill_beat"):
            spilled = self.sm.spill_beat()
        if spilled or self.forest.compaction_pending():
            # Escalate the budget as the next checkpoint nears so
            # in-flight merges land BEFORE the barrier instead of
            # draining inside it as one latency spike (the p100 tail).
            # The cadence is learned from the PREVIOUS interval
            # (operators may checkpoint more often than
            # vsr_checkpoint_interval — the durable benchmark does);
            # op-count-driven, so replicas stay deterministic.
            interval = min(
                self.config.vsr_checkpoint_interval,
                self._ckpt_interval_observed or (1 << 30),
            )
            left = self.checkpoint_op + interval - self.op
            budget = 64 if left > 8 else 64 * (10 - max(left, 0))
            with self.tracer.span("lsm_compact_beat", rows=spilled):
                self.forest.compact_beat(budget)

    # ------------------------------------------------------------------
    # Client replies (reference: src/vsr/client_replies.zig).

    def _alloc_reply_slot(self) -> int:
        """A free reply slot — evicting the oldest session when the
        table is full (reference: src/vsr/client_sessions.zig evict +
        Command.eviction, src/vsr.zig:301).  The eviction choice (the
        lowest register op) is deterministic, so every replica evicts
        the same client at the same commit."""
        if self._next_reply_slot < self.config.clients_max:
            slot = self._next_reply_slot
            self._next_reply_slot += 1
            return slot
        victim = min(self.sessions, key=lambda c: self.sessions[c].session)
        slot = self.sessions.pop(victim).slot
        self._notify_eviction(victim)
        return slot

    def _notify_eviction(self, client: int) -> None:
        """Hook: networked replicas send Command.eviction (multi.py)."""

    def _store_reply(self, prepare: np.ndarray, reply_body: bytes) -> None:
        client = wire.u128(prepare, "client")
        entry = self.sessions.get(client)
        if entry is None:  # un-registered client (tests drive directly)
            return
        reply = wire.make_header(
            command=Command.reply,
            operation=int(prepare["operation"]),
            cluster=self.cluster,
            client=client,
            request=int(prepare["request"]),
            view=self.view,
            op=int(prepare["op"]),
            commit=int(prepare["op"]),
            timestamp=int(prepare["timestamp"]),
            context=wire.u128(prepare, "checksum"),
        )
        # The reply carries the request's trace context back to the
        # client (origin timestamp included), closing the loop: the
        # client can compute wire-to-wire latency from its own clock.
        wire.copy_trace(reply, prepare)
        wire.finalize_header(reply, reply_body)
        entry.request = int(prepare["request"])
        entry.reply_header = reply.tobytes()
        msg = reply.tobytes() + reply_body
        self.storage.write(
            self.storage.layout.reply_slot_offset(entry.slot),
            msg.ljust(_sectors(len(msg)), b"\x00"),
        )

    def _encode_sub_replies(self, prepare: np.ndarray, subs, pieces):
        """One encode pass for a batched prepare's sub replies: an
        (n,) HEADER_DTYPE array built vectorized (shared fields
        broadcast from the prepare, per-sub client/request scattered
        in) and finalized in one native batch checksum call
        (runtime/fastpath.py; hashlib loop fallback).  Field-for-field
        the same header _store_reply builds per sub."""
        from tigerbeetle_tpu.runtime import fastpath

        n = len(subs)
        rh = np.zeros(n, wire.HEADER_DTYPE)
        rh["version"] = wire.VERSION
        rh["command"] = int(Command.reply)
        rh["operation"] = int(prepare["operation"])
        rh["cluster_lo"] = self.cluster & 0xFFFFFFFFFFFFFFFF
        rh["cluster_hi"] = self.cluster >> 64
        rh["view"] = self.view
        rh["op"] = prepare["op"]
        rh["commit"] = prepare["op"]
        rh["timestamp"] = prepare["timestamp"]
        # context = the prepare's checksum (reply provenance).
        rh["context_lo"] = prepare["checksum_lo"]
        rh["context_hi"] = prepare["checksum_hi"]
        # The reply carries the request's trace context back to the
        # client (copy_trace semantics — the batch shares the
        # prepare's context, exactly as the per-sub header.copy() did).
        rh["trace_id"] = prepare["trace_id"]
        rh["trace_ts"] = prepare["trace_ts"]
        rh["trace_flags"] = prepare["trace_flags"]
        rh["client_lo"] = np.array(
            [c & 0xFFFFFFFFFFFFFFFF for c, _r, _n in subs], np.uint64
        )
        rh["client_hi"] = np.array(
            [c >> 64 for c, _r, _n in subs], np.uint64
        )
        rh["request"] = np.array([r for _c, r, _n in subs], np.uint32)
        if not fastpath.finalize_headers(rh, pieces):
            wire.finalize_headers_py(rh, pieces)
        return rh

    def _read_reply(self, entry: Session) -> bytes:
        header = wire.header_from_bytes(entry.reply_header)
        size = int(header["size"])
        raw = self.storage.read(
            self.storage.layout.reply_slot_offset(entry.slot), _sectors(size)
        )
        body = raw[HEADER_SIZE:size]
        stored = wire.header_from_bytes(raw[:HEADER_SIZE])
        if stored.tobytes() != entry.reply_header or not wire.verify_header(
            stored, body
        ):
            raise RuntimeError("stored reply corrupt")
        return bytes(body)

    # ------------------------------------------------------------------
    # Checkpointing.

    def checkpoint(self) -> None:
        """Freeze a snapshot of the committed state, then make it the
        durable recovery root.  The freeze (spill residue into LSM
        memtables, snapshot encode, buffered blob write) runs inline;
        the disk barriers + superblock flip run on the checkpoint
        worker when async checkpointing is on — commits keep flowing
        while they land, and the next checkpoint (or close()) joins.
        Write ordering guarantees the previous checkpoint survives a
        torn snapshot write either way."""
        self._ckpt_join()
        # Learn the operator's checkpoint cadence for compaction
        # pacing (_compact_beat escalates toward the next barrier).
        base = max(self.checkpoint_op, self._ckpt_last_op)
        if self.op > base:
            self._ckpt_interval_observed = self.op - base
        with self.tracer.span("checkpoint", op=self.commit_min):
            with self.tracer.span(
                "ckpt_freeze", op=self.commit_min
            ), self._h_ckpt_freeze.time():
                args = self._checkpoint_freeze()
            self._ckpt_last_op = self.commit_min
            if self._ckpt_worker is not None:
                self._stats["stat_ckpt_async"].inc()
                self._ckpt_job = self._ckpt_worker.submit(
                    self._checkpoint_finalize, *args
                )
            else:
                self._stats["stat_ckpt_sync"].inc()
                self._checkpoint_finalize(*args)

    def _ckpt_join(self) -> None:
        """Barrier: wait for the in-flight async flip (if any).  Must
        run before anything that reads or writes the superblock, and
        before the next freeze."""
        job, self._ckpt_job = self._ckpt_job, None
        if job is not None:
            job.result()

    def close(self) -> None:
        """Join in-flight background work (async checkpoint flip, WAL
        sync) and stop the workers.  Idempotent."""
        self._ckpt_join()
        self._join_wal_sync()
        if self._ckpt_worker is not None:
            self._ckpt_worker.close()
        if self._wal_sync_worker is not None:
            self._wal_sync_worker.close()

    def _checkpoint_freeze(self):
        """Foreground half: bring the LSM tier + snapshot blob to a
        consistent image of commit_min and stage it in the grid zone
        (buffered writes).  Returns the finalize args — everything the
        background flip needs, captured now so later commits cannot
        skew it."""
        head = self.journal.read_prepare(self.commit_min)
        if head is not None:
            head_checksum = wire.u128(head[0], "checksum")
        else:
            # Latent sector error on the checkpoint-head slot, found
            # before the paced scrubber reached it: the in-memory
            # redundant ring still holds the committed header — use
            # its checksum (peer repair heals the slot asynchronously).
            slot = self.journal.slot_for_op(self.commit_min)
            mem = self.journal.headers[slot]
            assert int(mem["op"]) == self.commit_min and int(
                mem["command"]
            ) == wire.Command.prepare, "checkpoint head unrecoverable"
            head_checksum = wire.u128(mem, "checksum")

        if self.forest is not None:
            # Spill frozen state into LSM grid blocks first so the
            # snapshot blob covers only the RAM tail (O(delta)).
            with self.tracer.span("lsm_spill"):
                self.sm.checkpoint_spill()

        blob = self._take_snapshot()
        # The state root is part of the frozen image: captured here —
        # the snapshot encode drained the state machine, so the
        # incremental commitment is exactly commit_min's — and flipped
        # into the superblock with the rest of the checkpoint
        # references (recovery recomputes-and-asserts it; the VOPR
        # compares it cross-replica).
        state_root = (
            int.from_bytes(self.sm.state_root(), "little")
            if hasattr(self.sm, "state_root")
            else 0
        )
        region = int(self.superblock.working["sequence"]) % 2
        offset = self._grid_region_offset(region, len(blob))
        self._write_grid(offset, blob)
        return (
            self.commit_min, head_checksum, offset, len(blob),
            wire.checksum(blob), self.view, self.epoch,
            list(self.members) if self.members is not None else None,
            state_root,
        )

    def _checkpoint_finalize(self, commit_min, head_checksum, offset,
                             size, blob_checksum, view, epoch,
                             members, state_root) -> None:
        """Disk half (checkpoint worker in async mode): everything the
        new superblock references must be durable before the flip."""
        with self._h_ckpt_finalize.time():
            self._checkpoint_finalize_impl(
                commit_min, head_checksum, offset, size, blob_checksum,
                view, epoch, members, state_root,
            )

    def _checkpoint_finalize_impl(self, commit_min, head_checksum, offset,
                                  size, blob_checksum, view, epoch,
                                  members, state_root) -> None:
        if self.aof is not None:
            # The AOF is a recovery stream: make it durable at least as
            # often as checkpoints (reference: src/aof.zig fsyncs).
            self.aof.sync()
        if self.forest is not None:
            # Outstanding async block writes must be on disk before
            # the sync that the new superblock's references rely on.
            self.forest.grid.flush_writes()
            # Paced chunked writeback first — ASYNC MODE ONLY: one
            # monolithic grid fdatasync monopolizes the device and the
            # ack path's WAL fsyncs queue behind it for its whole
            # duration (the commit-p99 spike this worker exists to
            # remove).  Inline (TB_CKPT_ASYNC=0) there is no
            # concurrent WAL fsync to protect, and the chunk pauses
            # would just lengthen the commit-loop stall.  Durability
            # still comes from sync().
            paced = getattr(self.storage, "sync_grid_paced", None)
            if paced is not None and self._ckpt_worker is not None:
                paced()
        self.storage.sync()

        self.superblock.checkpoint(
            commit_min=commit_min,
            commit_min_checksum=head_checksum,
            commit_max=commit_min,
            checkpoint_offset=offset,
            checkpoint_size=size,
            checkpoint_checksum=blob_checksum,
            view=view,
            epoch=epoch,
            members=members,
            state_root=state_root,
        )
        self.checkpoint_op = commit_min
        # Deliberately NOT releasing the free-set quarantine here: the
        # flip lands at a nondeterministic WALL time, and letting it
        # steer allocation would diverge grid layouts across replicas
        # (beat allocation must stay a pure function of the commit
        # stream — block-level peer repair relies on byte-identical
        # grids).  The quarantine clears at the NEXT freeze instead
        # (FreeSet.checkpoint replaces it), which the _ckpt_join
        # barrier guarantees is after this flip is durable.

    def _grid_region_offset(self, region: int, blob_len: int) -> int:
        if self.forest is not None:
            # Fixed reservation: the forest's block region starts at
            # 2 * SNAPSHOT_SPAN (spilling keeps blobs bounded).
            assert blob_len <= SNAPSHOT_SPAN, "snapshot exceeds reservation"
            return self.storage.layout.grid_offset + region * SNAPSHOT_SPAN
        # Region B starts past the largest blob either region has held;
        # sized live from the current blob and the previous checkpoint.
        prev = int(self.superblock.working["checkpoint_size"])
        span = _sectors(max(blob_len, prev, 1 << 20))
        return self.storage.layout.grid_offset + region * span

    def _take_snapshot(self) -> bytes:
        from tigerbeetle_tpu.utils import snapshot as snapcodec

        sessions = self.sessions
        cl = np.zeros((len(sessions), 2), np.uint64)  # u128 client ids
        meta = np.zeros((len(sessions), 4), np.uint64)
        headers = []
        for i, (client, s) in enumerate(sessions.items()):
            cl[i, 0] = client & ((1 << 64) - 1)
            cl[i, 1] = client >> 64
            # meta[3]: registered-but-unreplied sessions carry an empty
            # reply_header; encode presence explicitly.
            meta[i] = (s.session, s.request, s.slot, 1 if s.reply_header else 0)
            assert len(s.reply_header) in (0, HEADER_SIZE)
            headers.append(
                s.reply_header if s.reply_header else bytes(HEADER_SIZE)
            )
        return snapcodec.encode(
            {
                "sm": self.sm.snapshot(),
                "clients": cl,
                "session_meta": meta,
                "reply_headers": b"".join(headers),
                "next_reply_slot": self._next_reply_slot,
                # Committed membership is part of the checkpoint state:
                # a state-synced replica jumps commit_min past the
                # reconfigure ops themselves, and without the epoch it
                # would reject every later epoch as stale — diverging
                # reconfigure replies cluster-wide (VOPR reconfigure
                # nemesis, seed 300661417).
                "epoch": self.epoch,
                "members": bytes(self.members or []),
            }
        )

    def _restore_snapshot(self, blob: bytes) -> None:
        from tigerbeetle_tpu.utils import snapshot as snapcodec

        state = snapcodec.decode(blob)
        self.sm.restore(state["sm"])
        self.sessions = {}
        headers = state["reply_headers"]
        for i in range(len(state["clients"])):
            client = int(state["clients"][i, 0]) | (
                int(state["clients"][i, 1]) << 64
            )
            self.sessions[client] = Session(
                session=int(state["session_meta"][i, 0]),
                request=int(state["session_meta"][i, 1]),
                reply_header=(
                    headers[i * HEADER_SIZE : (i + 1) * HEADER_SIZE]
                    if int(state["session_meta"][i, 3])
                    else b""
                ),
                slot=int(state["session_meta"][i, 2]),
            )
        self._next_reply_slot = state["next_reply_slot"]
        epoch = int(state.get("epoch", 0))
        members = list(state.get("members", b""))
        if epoch and members:
            self._install_committed(epoch, members)

    def _write_grid(self, offset: int, blob: bytes) -> None:
        self.storage.write(offset, blob.ljust(_sectors(len(blob)), b"\x00"))

    def _read_grid(self, offset: int, size: int) -> bytes:
        return self.storage.read(offset, _sectors(size))[:size]
