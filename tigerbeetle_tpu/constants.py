"""Configuration constants.

Mirrors the reference's three-tier config system (reference:
src/config.zig:66-347, src/constants.zig) with the presets we need:
``production`` and ``test_min``. Consensus-critical cluster values keep
the reference's numbers so wire/disk artifacts stay compatible.
"""

from __future__ import annotations

import dataclasses

SECTOR_SIZE = 4096  # reference: src/constants.zig sector_size
HEADER_SIZE = 256  # reference: src/vsr/message_header.zig:17 (@sizeOf(Header))

# reference: src/constants.zig:47
VSR_OPERATIONS_RESERVED = 128

# Event-loop tick length (ns): the simulator's wall-clock step, the
# replica's virtual monotonic increment, and the server's tick cadence
# all share this so clock-sync RTT arithmetic is consistent
# (reference: src/constants.zig tick_ms).
TICK_NS = 10_000_000


@dataclasses.dataclass(frozen=True)
class Config:
    """Cluster-critical + process config (subset used by this build)."""

    name: str
    # reference: src/config.zig:153
    message_size_max: int
    # reference: src/config.zig:158
    lsm_batch_multiple: int
    # reference: src/config.zig:149
    pipeline_prepare_queue_max: int
    # reference: src/config.zig journal_slot_count
    journal_slot_count: int
    # reference: src/config.zig:151
    clients_max: int = 64
    # Hot RAM tail retained across checkpoints: spill beats keep the
    # durable store at most this many rows ahead of the LSM tier, and
    # checkpoints spill only the excess — so checkpoint latency is
    # O(one beat), not O(interval).  0 = spill everything at
    # checkpoint (the small-state test configs).
    spill_keep_rows: int = 0
    quorum_replication_max: int = 3

    @property
    def message_body_size_max(self) -> int:
        # reference: src/constants.zig:220
        return self.message_size_max - HEADER_SIZE

    def batch_max(self, event_size: int, result_size: int = 8) -> int:
        # reference: src/state_machine.zig:75-81
        return self.message_body_size_max // max(event_size, result_size)

    @property
    def batch_max_create_transfers(self) -> int:
        return self.batch_max(128)

    @property
    def vsr_checkpoint_interval(self) -> int:
        # reference: src/constants.zig:55-57
        m = self.lsm_batch_multiple
        p = self.pipeline_prepare_queue_max
        return self.journal_slot_count - m - m * ((p + m - 1) // m)


# reference: src/config.zig:66-175 (default/production values)
PRODUCTION = Config(
    name="production",
    message_size_max=1 * 1024 * 1024,
    lsm_batch_multiple=32,
    pipeline_prepare_queue_max=8,
    journal_slot_count=1024,
    spill_keep_rows=16_384,
)

# reference: src/config.zig:256-286 (config=test_min)
TEST_MIN = Config(
    name="test_min",
    message_size_max=4096,
    lsm_batch_multiple=4,
    pipeline_prepare_queue_max=4,
    journal_slot_count=32,
    clients_max=4,
)

assert PRODUCTION.batch_max_create_transfers == 8190
assert PRODUCTION.vsr_checkpoint_interval == 960
