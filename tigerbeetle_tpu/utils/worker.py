"""Serial background worker on a daemon thread.

A minimal stand-in for ThreadPoolExecutor(max_workers=1) whose thread
is a DAEMON: replicas and grids are constructed/discarded freely in
crash-recovery loops and fuzz harnesses, and must not leak non-daemon
threads that pin the process (or the storage objects) alive.
"""

from __future__ import annotations

import queue
import threading


class _Job:
    __slots__ = ("fn", "args", "_done", "_exc")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self._done = threading.Event()
        self._exc: BaseException | None = None

    def result(self) -> None:
        self._done.wait()
        if self._exc is not None:
            raise self._exc


class SerialWorker:
    """FIFO execution of submitted jobs on one daemon thread.

    `close()` stops the thread (idempotent); owners should either call
    it or register it with `weakref.finalize` so discarded owners
    (replicas/grids in crash-recovery loops) reclaim their thread
    instead of leaking one blocked in q.get() per construction."""

    _STOP = object()

    def __init__(self, name: str) -> None:
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    def submit(self, fn, *args) -> _Job:
        assert not self._closed, "submit on closed SerialWorker"
        job = _Job(fn, args)
        self._q.put(job)
        return job

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._q.put(self._STOP)

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is self._STOP:
                return
            try:
                job.fn(*job.args)
            # tbcheck: allow(broad-except): the worker thread must
            # survive any job failure — the exception is stored and
            # re-raised at job.result() on the submitting thread.
            except BaseException as e:
                job._exc = e
            finally:
                job._done.set()
