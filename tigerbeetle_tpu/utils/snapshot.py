"""Fixed-layout, versioned, checksummed snapshot encoding.

Replaces pickle for every durable blob (state-machine checkpoints,
client sessions, forest manifests): pickle is version-fragile — which
undercuts multiversion upgrades — and `pickle.loads` on bytes read from
disk or shipped by peers (state sync) is an arbitrary-code-execution
surface.  This codec can only produce numpy arrays of allowlisted plain
dtypes, unsigned ints (u128 max), and raw bytes — nothing executable.

The encoding is canonical: equal inputs give byte-equal blobs (the
convergence checkers compare snapshot bytes across replicas), entries
are emitted in the caller-provided order, and every blob carries a
SHA-256 of its payload verified before any parsing.

Discipline follows the reference's CheckpointState approach: explicit
layout, size asserts, verify-before-use (reference:
src/vsr/superblock.zig:1-56, src/vsr/checksum.zig:1-10).

Wire layout (little-endian):
    magic   8B  b"TBSNAP\\x01\\x00"
    count   u32  number of entries
    paylen  u64  byte length of the entry stream that follows
    sha256 32B  digest of the entry stream
    entries, each:
        key_len u16 | key utf-8 | kind u8 | meta | data_len u64 | data
    kind 0 ndarray: meta = dtype_len u16, dtype ascii, ndim u8, dims u64*
    kind 1 uint (<= 2^128-1): no meta, data = 16B LE
    kind 2 bytes: no meta
"""

from __future__ import annotations

import hashlib
import re
import struct

import numpy as np

MAGIC = b"TBSNAP\x01\x00"

# Plain data dtypes only — no objects, no structured records.
_DTYPE_RE = re.compile(r"^(\||<)([buif][1248]|V16|V8)$")


class SnapshotError(ValueError):
    pass


def _check_dtype(dtype: np.dtype) -> str:
    s = dtype.str
    if not _DTYPE_RE.match(s):
        raise SnapshotError(f"dtype not allowlisted: {s!r}")
    return s


def encode(entries: dict) -> bytes:
    """entries: ordered mapping key -> np.ndarray | int | bytes."""
    parts = []
    for key, value in entries.items():
        kb = key.encode("utf-8")
        head = struct.pack("<H", len(kb)) + kb
        if isinstance(value, np.ndarray):
            ds = _check_dtype(value.dtype).encode("ascii")
            value = np.ascontiguousarray(value)
            meta = struct.pack("<BH", 0, len(ds)) + ds
            meta += struct.pack("<B", value.ndim)
            meta += struct.pack(f"<{value.ndim}Q", *value.shape)
            data = value.tobytes()
        elif isinstance(value, (int, np.integer)):
            value = int(value)
            if not 0 <= value < (1 << 128):
                raise SnapshotError(f"int out of u128 range: {key}")
            meta = struct.pack("<B", 1)
            data = value.to_bytes(16, "little")
        elif isinstance(value, (bytes, bytearray, memoryview)):
            meta = struct.pack("<B", 2)
            data = bytes(value)
        else:
            raise SnapshotError(f"unsupported type for {key}: {type(value)}")
        parts.append(head + meta + struct.pack("<Q", len(data)) + data)
    payload = b"".join(parts)
    header = (
        MAGIC
        + struct.pack("<IQ", len(entries), len(payload))
        + hashlib.sha256(payload).digest()
    )
    return header + payload


def decode(blob: bytes) -> dict:
    """-> dict key -> np.ndarray | int | bytes.  Raises SnapshotError on
    any structural or checksum violation; never executes content."""
    if len(blob) < len(MAGIC) + 4 + 8 + 32:
        raise SnapshotError("snapshot truncated (header)")
    if blob[: len(MAGIC)] != MAGIC:
        raise SnapshotError("bad snapshot magic/version")
    at = len(MAGIC)
    count, paylen = struct.unpack_from("<IQ", blob, at)
    at += 12
    digest = blob[at : at + 32]
    at += 32
    payload = blob[at : at + paylen]
    if len(payload) != paylen:
        raise SnapshotError("snapshot truncated (payload)")
    if hashlib.sha256(payload).digest() != digest:
        raise SnapshotError("snapshot checksum mismatch")

    out: dict = {}
    at = 0

    def take(n: int) -> bytes:
        nonlocal at
        if at + n > len(payload):
            raise SnapshotError("snapshot truncated (entry)")
        piece = payload[at : at + n]
        at += n
        return piece

    for _ in range(count):
        (key_len,) = struct.unpack("<H", take(2))
        try:
            key = take(key_len).decode("utf-8")
        except UnicodeDecodeError as e:
            raise SnapshotError("key not utf-8") from e
        if key in out:
            raise SnapshotError(f"duplicate key {key}")
        (kind,) = struct.unpack("<B", take(1))
        if kind == 0:
            (dtype_len,) = struct.unpack("<H", take(2))
            try:
                dtype_str = take(dtype_len).decode("ascii")
            except UnicodeDecodeError as e:
                raise SnapshotError("dtype not ascii") from e
            if not _DTYPE_RE.match(dtype_str):
                raise SnapshotError(f"dtype not allowlisted: {dtype_str!r}")
            dtype = np.dtype(dtype_str)
            (ndim,) = struct.unpack("<B", take(1))
            if ndim > 4:
                raise SnapshotError("ndarray rank too large")
            shape = struct.unpack(f"<{ndim}Q", take(8 * ndim))
            (data_len,) = struct.unpack("<Q", take(8))
            # Python-int product: no u64 wrap for hostile dims.
            n_items = 1
            for dim in shape:
                n_items *= int(dim)
            expect = dtype.itemsize * n_items
            if data_len != expect:
                raise SnapshotError(f"array size mismatch for {key}")
            data = take(data_len)
            out[key] = np.frombuffer(data, dtype).reshape(shape).copy()
        elif kind == 1:
            (data_len,) = struct.unpack("<Q", take(8))
            if data_len != 16:
                raise SnapshotError("int entry must be 16 bytes")
            out[key] = int.from_bytes(take(16), "little")
        elif kind == 2:
            (data_len,) = struct.unpack("<Q", take(8))
            out[key] = take(data_len)
        else:
            raise SnapshotError(f"unknown entry kind {kind}")
    if at != len(payload):
        raise SnapshotError("trailing bytes after last entry")
    return out


def encode_tree(tree: dict, prefix: str = "") -> bytes:
    """Encode a nested dict by flattening keys with '/'."""
    return encode(flatten(tree, prefix))


def flatten(tree: dict, prefix: str = "") -> dict:
    flat: dict = {}
    for k, v in tree.items():
        assert "/" not in str(k), k
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(flatten(v, f"{key}/"))
        else:
            flat[key] = v
    return flat


def unflatten(flat: dict) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def decode_tree(blob: bytes) -> dict:
    return unflatten(decode(blob))
