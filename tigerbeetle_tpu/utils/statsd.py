"""StatsD UDP metrics emitter.

reference: src/statsd.zig:12-46 — fire-and-forget UDP datagrams in
StatsD line format, used by the benchmark load generator
(reference: src/tigerbeetle/benchmark_load.zig:360-364).
"""

from __future__ import annotations

import socket


class StatsD:
    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "tigerbeetle") -> None:
        self.address = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(payload.encode(), self.address)
        except OSError:
            pass  # fire-and-forget

    def gauge(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}.{name}:{value}|g")

    def count(self, name: str, value: int = 1) -> None:
        self._send(f"{self.prefix}.{name}:{value}|c")

    def timing(self, name: str, ms: float) -> None:
        self._send(f"{self.prefix}.{name}:{ms}|ms")

    def close(self) -> None:
        self._sock.close()
