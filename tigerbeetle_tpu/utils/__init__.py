from tigerbeetle_tpu.utils.hashindex import HashIndex, RunIndex

__all__ = ["HashIndex", "RunIndex"]
