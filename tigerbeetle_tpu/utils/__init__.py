from tigerbeetle_tpu.utils.hashindex import HashIndex

__all__ = ["HashIndex"]
