"""Set-associative cache (reference: src/lsm/set_associative_cache.zig).

The reference caches grid blocks and objects in N-way set-associative
tables rather than LRU maps: memory use is exactly bounded up front
(static allocation), lookup cost is O(ways), and eviction needs no
linked-list bookkeeping — a clock bit per way approximates LRU.  Same
design here: `ways` slots per set, sets chosen by key hash, clock
second-chance eviction within the set.
"""

from __future__ import annotations


class SetAssociativeCache:
    """key (int) -> value, N-way set associative with clock eviction."""

    def __init__(self, capacity: int = 256, ways: int = 4) -> None:
        assert capacity % ways == 0 and capacity > 0
        self.ways = ways
        self.sets = capacity // ways
        # Per-slot parallel arrays: key (None = empty), value, clock bit.
        n = capacity
        self._keys: list[int | None] = [None] * n
        self._values: list[object] = [None] * n
        self._clock: list[bool] = [False] * n
        self._hand: list[int] = [0] * self.sets
        self.hits = 0
        self.misses = 0

    def _set_base(self, key: int) -> int:
        # Fibonacci hash of the key selects the set.
        h = (key * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        return (h % self.sets) * self.ways

    def get(self, key: int):
        base = self._set_base(key)
        for i in range(base, base + self.ways):
            if self._keys[i] == key:
                self._clock[i] = True
                self.hits += 1
                return self._values[i]
        self.misses += 1
        return None

    def put(self, key: int, value) -> None:
        base = self._set_base(key)
        empty = -1
        for i in range(base, base + self.ways):
            if self._keys[i] == key:
                self._values[i] = value
                self._clock[i] = True
                return
            if empty < 0 and self._keys[i] is None:
                empty = i
        if empty >= 0:
            slot = empty
        else:
            # Clock second-chance within the set (reference eviction).
            s = base // self.ways
            while True:
                i = base + self._hand[s]
                self._hand[s] = (self._hand[s] + 1) % self.ways
                if self._clock[i]:
                    self._clock[i] = False
                else:
                    slot = i
                    break
        self._keys[slot] = key
        self._values[slot] = value
        self._clock[slot] = True

    def remove(self, key: int) -> None:
        base = self._set_base(key)
        for i in range(base, base + self.ways):
            if self._keys[i] == key:
                self._keys[i] = None
                self._values[i] = None
                self._clock[i] = False
                return

    def __contains__(self, key: int) -> bool:
        base = self._set_base(key)
        return any(
            self._keys[i] == key for i in range(base, base + self.ways)
        )
