"""Tracer: span tree with slot-based start/end discipline.

reference: src/tracer.zig:1-70 — events are started/ended on fixed
slots (so nesting bugs assert immediately), and emitted to a backend
selected at init: `none` (no-op, zero overhead) or `json` (Chrome
trace-event format, loadable in chrome://tracing / Perfetto — the
tracy backend analog for this build).

Hooked in the hot paths (the reference hooks tracer.zig directly in
src/state_machine.zig:610-614,1124-1143 and src/io/linux.zig:31-33):
replica commit stages, checkpoint, journal writes, LSM spill/seal, and
the device flush — see Replica.tracer.  Backend "none" costs one
attribute check per site.

Beyond spans, the tracer carries counter series (`count()`, Chrome
"C" events: queue depths, batch sizes, repair counts) and instant
markers (`instant()`).  The buffer is bounded: oldest spans drop first
and the drop total is reported in the dump, so a long-running server
can leave tracing on.
"""

from __future__ import annotations

import collections
import json
import time


# Event vocabulary (reference: src/tracer.zig:48-70), extended with
# the cross-replica drain timeline (prepare -> covering fsync ->
# prepare_ok -> commit -> reply) and the server/device seams.  The
# list is documentation — spans are keyed by name, not index.
EVENTS = (
    "commit", "checkpoint",
    "state_machine_prefetch", "state_machine_commit", "state_machine_compact",
    "tree_compaction", "lsm_spill", "grid_read", "grid_write",
    "io_read", "io_write", "replica_on_message", "journal_write",
    "device_flush", "wal_scrub", "block_repair",
    "prepare", "prepare_ok", "gc_covering_sync", "reply",
    "ckpt_freeze", "ckpt_finalize", "poll_drain", "device_link",
    "wave_dispatch",
)

BUFFER_MAX = 200_000  # events kept before oldest-first dropping


class Tracer:
    def __init__(self, backend: str = "none", process_id: int = 0,
                 clock=time.perf_counter_ns,
                 buffer_max: int = BUFFER_MAX) -> None:
        assert backend in ("none", "json")
        self.backend = backend
        self.enabled = backend != "none"
        self.process_id = process_id
        self.clock = clock
        self.buffer_max = buffer_max
        # Optional obs.flight.FlightRecorder sink: instants (and span
        # ends) are mirrored into its bounded ring EVEN when the
        # backend is "none" — the flight recorder is the always-on
        # postmortem buffer, the backend the opt-in full trace.
        self.flight = None
        self._open: dict[tuple[str, int], tuple[int, dict | None]] = {}
        # deque(maxlen) drops oldest in O(1); a list shift per event
        # would make every traced hot-path op O(buffer_max) once full.
        self._spans: collections.deque[dict] = collections.deque(
            maxlen=buffer_max
        )
        self.dropped = 0

    # -- spans ---------------------------------------------------------

    def start(self, event: str, slot: int = 0, **args) -> None:
        """Open span `event` on `slot`.  One slot holds one open span
        of a given name — double-start asserts immediately (the
        reference's slot discipline); concurrent same-name spans use
        distinct slots (e.g. op number % k)."""
        if not self.enabled:
            return
        key = (event, slot)
        assert key not in self._open, f"span {event}[{slot}] already open"
        self._open[key] = (self.clock(), args or None)

    def stop(self, event: str, slot: int = 0) -> None:
        if not self.enabled:
            return
        key = (event, slot)
        # Unbalanced end asserts immediately (the reference's slot
        # discipline), instead of surfacing as a bare KeyError.
        assert key in self._open, f"span {event}[{slot}] not open"
        begin, args = self._open.pop(key)
        now = self.clock()
        span = {
            "name": event, "ph": "X", "pid": self.process_id, "tid": slot,
            "ts": begin / 1e3, "dur": (now - begin) / 1e3,
        }
        if args:
            span["args"] = args
        self._push(span)

    def span(self, event: str, slot: int = 0, **args):
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, event, slot, args)

    # -- counters + instants -------------------------------------------

    def count(self, series: str, value: float, **extra) -> None:
        """Counter sample (Chrome 'C' event): queue depth, batch size,
        repair totals — graphed as a time series by the viewer."""
        if not self.enabled:
            return
        values = {"value": value}
        values.update(extra)
        self._push(
            {
                "name": series, "ph": "C", "pid": self.process_id,
                "tid": 0, "ts": self.clock() / 1e3, "args": values,
            }
        )

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (view change, crash recovery, …).
        Mirrored into the flight ring even with backend "none" — the
        postmortem buffer must not depend on full tracing being on."""
        if self.flight is not None:
            self.flight.note(name, **args)
        if not self.enabled:
            return
        ev = {
            "name": name, "ph": "i", "s": "p", "pid": self.process_id,
            "tid": 0, "ts": self.clock() / 1e3,
        }
        if args:
            ev["args"] = args
        self._push(ev)

    # -- output --------------------------------------------------------

    def _push(self, event: dict) -> None:
        if len(self._spans) == self.buffer_max:
            self.dropped += 1
        self._spans.append(event)

    def dump(self) -> str:
        assert not self._open, f"open spans at dump: {list(self._open)}"
        return json.dumps(
            {
                "traceEvents": list(self._spans),
                "otherData": {"dropped_events": self.dropped},
            }
        )

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dump())

    @classmethod
    def from_env(cls, process_id: int = 0) -> "Tracer":
        """Backend from the TB_TRACE knob (envcheck-validated)."""
        from tigerbeetle_tpu import envcheck

        return cls(envcheck.trace_backend(), process_id=process_id)


class _Span:
    __slots__ = ("_tracer", "_event", "_slot", "_args")

    def __init__(self, tracer: Tracer, event: str, slot: int, args: dict):
        self._tracer = tracer
        self._event = event
        self._slot = slot
        self._args = args

    def __enter__(self):
        self._tracer.start(self._event, self._slot, **self._args)

    def __exit__(self, *exc):
        self._tracer.stop(self._event, self._slot)
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


# One shared no-op context manager: disabled-tracer spans on the hot
# path cost an attribute check and this constant return.
_NOOP_SPAN = _NoopSpan()

# Shared no-op instance for call sites whose owner never enabled
# tracing (enabled=False short-circuits every method).
NULL = Tracer("none")
