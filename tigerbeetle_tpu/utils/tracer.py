"""Tracer: span tree with slot-based start/end discipline.

reference: src/tracer.zig:1-70 — events are started/ended on fixed
slots (so nesting bugs assert immediately), and emitted to a backend
selected at init: `none` (no-op, zero overhead) or `json` (Chrome
trace-event format, loadable in chrome://tracing / Perfetto — the
tracy backend analog for this build).
"""

from __future__ import annotations

import json
import time


# Event vocabulary (reference: src/tracer.zig:48-70).
EVENTS = (
    "commit", "checkpoint",
    "state_machine_prefetch", "state_machine_commit", "state_machine_compact",
    "tree_compaction", "grid_read", "grid_write", "io_read", "io_write",
    "replica_on_message", "journal_write",
)


class Tracer:
    def __init__(self, backend: str = "none", process_id: int = 0,
                 clock=time.perf_counter_ns) -> None:
        assert backend in ("none", "json")
        self.backend = backend
        self.process_id = process_id
        self.clock = clock
        self._open: dict[str, int] = {}   # slot -> start ns
        self._spans: list[dict] = []

    def start(self, event: str, **args) -> None:
        if self.backend == "none":
            return
        assert event not in self._open, f"span {event} already open"
        self._open[event] = self.clock()
        if args:
            self._open_args = {event: args}

    def stop(self, event: str) -> None:
        if self.backend == "none":
            return
        begin = self._open.pop(event)
        now = self.clock()
        self._spans.append(
            {
                "name": event, "ph": "X", "pid": self.process_id, "tid": 0,
                "ts": begin / 1e3, "dur": (now - begin) / 1e3,
            }
        )

    def span(self, event: str):
        tracer = self

        class _Span:
            def __enter__(self):
                tracer.start(event)

            def __exit__(self, *exc):
                tracer.stop(event)
                return False

        return _Span()

    def dump(self) -> str:
        assert not self._open, f"open spans at dump: {list(self._open)}"
        return json.dumps({"traceEvents": self._spans})

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dump())
