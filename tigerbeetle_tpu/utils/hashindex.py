"""Vectorized open-addressing hash index: u128 key -> u64 value.

The host-side id directories (account id -> slot, transfer id -> row;
the reference's IdTree role, src/lsm/groove.zig:136-176) sit on the
commit hot path with 3 batch lookups + 1 batch insert per commit.
Sorted-run searches over 16-byte void keys are memcmp-bound; this
table keeps keys as native uint64 limb pairs and does linear probing
with whole-batch numpy steps — each probe round is a handful of SIMD
ops over the still-unresolved lanes, and rounds shrink geometrically
(load factor is capped at ~0.5).

Deletions (create_accounts chain rollback only) leave tombstones:
lookups probe through them, inserts do not reuse them (rare enough
that reclaiming happens on the next growth rehash).
"""

from __future__ import annotations

import numpy as np

_M1 = np.uint64(0x9E3779B97F4A7C15)
_M2 = np.uint64(0xC2B2AE3D27D4EB4F)
_M3 = np.uint64(0xFF51AFD7ED558CCD)


class HashIndex:
    def __init__(self, capacity: int = 1 << 16) -> None:
        assert capacity & (capacity - 1) == 0
        self._cap = capacity
        self._mask = np.uint64(capacity - 1)
        self.k_lo = np.zeros(capacity, np.uint64)
        self.k_hi = np.zeros(capacity, np.uint64)
        self.val = np.zeros(capacity, np.uint64)
        self.used = np.zeros(capacity, bool)
        self.dead = np.zeros(capacity, bool)
        self.count = 0
        self._tombstones = 0

    def _hash(self, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
        h = lo * _M1 + hi * _M2
        h ^= h >> np.uint64(33)
        h *= _M3
        h ^= h >> np.uint64(29)
        return h & self._mask

    def _grow(self, need: int) -> None:
        while (self.count + self._tombstones + need) * 2 >= self._cap:
            self._cap *= 2
        live = np.flatnonzero(self.used & ~self.dead)
        k_lo, k_hi, val = self.k_lo[live], self.k_hi[live], self.val[live]
        self._mask = np.uint64(self._cap - 1)
        self.k_lo = np.zeros(self._cap, np.uint64)
        self.k_hi = np.zeros(self._cap, np.uint64)
        self.val = np.zeros(self._cap, np.uint64)
        self.used = np.zeros(self._cap, bool)
        self.dead = np.zeros(self._cap, bool)
        self.count = 0
        self._tombstones = 0
        self.insert(k_lo, k_hi, val)

    def insert(self, lo: np.ndarray, hi: np.ndarray, values: np.ndarray) -> None:
        """Batch insert; keys must be unique and not already present."""
        n = len(lo)
        if n == 0:
            return
        if (self.count + self._tombstones + n) * 2 >= self._cap:
            self._grow(n)
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        values = np.asarray(values, np.uint64)
        pos = self._hash(lo, hi)
        pending = np.arange(n)
        one = np.uint64(1)
        while len(pending):
            p = pos[pending]
            occ = self.used[p]
            free = pending[~occ]
            if len(free):
                # Scatter all candidates; colliding writes resolve
                # last-writer-wins, and a read-back identifies the one
                # winner per bucket (keys are unique) — no sort needed.
                fp = pos[free]
                self.used[fp] = True
                self.k_lo[fp] = lo[free]
                self.k_hi[fp] = hi[free]
                self.val[fp] = values[free]
                placed = (self.k_lo[fp] == lo[free]) & (self.k_hi[fp] == hi[free])
                losers = free[~placed]
            else:
                losers = free
            stepped = np.concatenate([pending[occ], losers])
            pos[stepped] = (pos[stepped] + one) & self._mask
            pending = stepped
        self.count += n

    def lookup(self, lo: np.ndarray, hi: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Batch get -> (found bool array, values uint64)."""
        n = len(lo)
        found = np.zeros(n, bool)
        values = np.zeros(n, np.uint64)
        if n == 0 or self.count == 0:
            return found, values
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        pos = self._hash(lo, hi)
        active = np.arange(n)
        one = np.uint64(1)
        while len(active):
            p = pos[active]
            occ = self.used[p]
            match = (
                occ
                & ~self.dead[p]
                & (self.k_lo[p] == lo[active])
                & (self.k_hi[p] == hi[active])
            )
            hit = active[match]
            found[hit] = True
            values[hit] = self.val[p[match]]
            cont = occ & ~match
            active = active[cont]
            pos[active] = (pos[active] + one) & self._mask
        return found, values

    def remove(self, lo: np.ndarray, hi: np.ndarray) -> None:
        """Tombstone existing keys (chain-rollback un-create)."""
        n = len(lo)
        if n == 0:
            return
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        pos = self._hash(lo, hi)
        active = np.arange(n)
        one = np.uint64(1)
        removed = 0
        while len(active):
            p = pos[active]
            occ = self.used[p]
            match = (
                occ
                & ~self.dead[p]
                & (self.k_lo[p] == lo[active])
                & (self.k_hi[p] == hi[active])
            )
            mp = p[match]
            self.dead[mp] = True
            removed += len(mp)
            cont = occ & ~match
            active = active[cont]
            pos[active] = (pos[active] + one) & self._mask
        self.count -= removed
        self._tombstones += removed
