"""Vectorized open-addressing hash index: u128 key -> u64 value.

The host-side id directories (account id -> slot, transfer id -> row;
the reference's IdTree role, src/lsm/groove.zig:136-176) sit on the
commit hot path with 3 batch lookups + 1 batch insert per commit.
Sorted-run searches over 16-byte void keys are memcmp-bound; this
table keeps keys as native uint64 limb pairs and does linear probing
with whole-batch numpy steps — each probe round is a handful of SIMD
ops over the still-unresolved lanes, and rounds shrink geometrically
(load factor is capped at ~0.5).

Deletions (create_accounts chain rollback only) leave tombstones:
lookups probe through them, inserts do not reuse them (rare enough
that reclaiming happens on the next growth rehash).
"""

from __future__ import annotations

import numpy as np

_M1 = np.uint64(0x9E3779B97F4A7C15)
_M2 = np.uint64(0xC2B2AE3D27D4EB4F)
_M3 = np.uint64(0xFF51AFD7ED558CCD)


class HashIndex:
    def __init__(self, capacity: int = 1 << 16) -> None:
        assert capacity & (capacity - 1) == 0
        self._cap = capacity
        self._mask = np.uint64(capacity - 1)
        self.k_lo = np.zeros(capacity, np.uint64)
        self.k_hi = np.zeros(capacity, np.uint64)
        self.val = np.zeros(capacity, np.uint64)
        self.used = np.zeros(capacity, bool)
        self.dead = np.zeros(capacity, bool)
        self.count = 0
        self._tombstones = 0

    def _hash(self, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
        h = lo * _M1 + hi * _M2
        h ^= h >> np.uint64(33)
        h *= _M3
        h ^= h >> np.uint64(29)
        return h & self._mask

    def _grow(self, need: int) -> None:
        # Grow 4x: rehash work is the dominant insert cost, and quadrupling
        # keeps total rehash work ~1.33N instead of ~2N.
        while (self.count + self._tombstones + need) * 2 >= self._cap:
            self._cap *= 4
        live = np.flatnonzero(self.used & ~self.dead)
        k_lo, k_hi, val = self.k_lo[live], self.k_hi[live], self.val[live]
        self._mask = np.uint64(self._cap - 1)
        self.k_lo = np.zeros(self._cap, np.uint64)
        self.k_hi = np.zeros(self._cap, np.uint64)
        self.val = np.zeros(self._cap, np.uint64)
        self.used = np.zeros(self._cap, bool)
        self.dead = np.zeros(self._cap, bool)
        self.count = 0
        self._tombstones = 0
        self.insert(k_lo, k_hi, val)

    def insert(self, lo: np.ndarray, hi: np.ndarray, values: np.ndarray) -> None:
        """Batch insert; keys must be unique and not already present."""
        n = len(lo)
        if n == 0:
            return
        if (self.count + self._tombstones + n) * 2 >= self._cap:
            self._grow(n)
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        values = np.asarray(values, np.uint64)
        pos = self._hash(lo, hi)
        pending = np.arange(n)
        one = np.uint64(1)
        while len(pending):
            p = pos[pending]
            occ = self.used[p]
            free = pending[~occ]
            if len(free):
                # Scatter all candidates; colliding writes resolve
                # last-writer-wins, and a read-back identifies the one
                # winner per bucket (keys are unique) — no sort needed.
                fp = pos[free]
                self.used[fp] = True
                self.k_lo[fp] = lo[free]
                self.k_hi[fp] = hi[free]
                self.val[fp] = values[free]
                placed = (self.k_lo[fp] == lo[free]) & (self.k_hi[fp] == hi[free])
                losers = free[~placed]
            else:
                losers = free
            stepped = np.concatenate([pending[occ], losers])
            pos[stepped] = (pos[stepped] + one) & self._mask
            pending = stepped
        self.count += n

    def lookup(self, lo: np.ndarray, hi: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Batch get -> (found bool array, values uint64)."""
        n = len(lo)
        found = np.zeros(n, bool)
        values = np.zeros(n, np.uint64)
        if n == 0 or self.count == 0:
            return found, values
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        pos = self._hash(lo, hi)
        active = np.arange(n)
        one = np.uint64(1)
        while len(active):
            p = pos[active]
            occ = self.used[p]
            match = (
                occ
                & ~self.dead[p]
                & (self.k_lo[p] == lo[active])
                & (self.k_hi[p] == hi[active])
            )
            hit = active[match]
            found[hit] = True
            values[hit] = self.val[p[match]]
            cont = occ & ~match
            active = active[cont]
            pos[active] = (pos[active] + one) & self._mask
        return found, values

    def remove(self, lo: np.ndarray, hi: np.ndarray) -> None:
        """Tombstone existing keys (chain-rollback un-create)."""
        n = len(lo)
        if n == 0:
            return
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        pos = self._hash(lo, hi)
        active = np.arange(n)
        one = np.uint64(1)
        removed = 0
        while len(active):
            p = pos[active]
            occ = self.used[p]
            match = (
                occ
                & ~self.dead[p]
                & (self.k_lo[p] == lo[active])
                & (self.k_hi[p] == hi[active])
            )
            mp = p[match]
            self.dead[mp] = True
            removed += len(mp)
            cont = occ & ~match
            active = active[cont]
            pos[active] = (pos[active] + one) & self._mask
        self.count -= removed
        self._tombstones += removed


class RunIndex:
    """Id directory with run-length compression over sequential ids.

    TigerBeetle recommends (and its benchmark default generates)
    sequential ids (reference: src/tigerbeetle/cli.zig:80-101
    `id_order=sequential`; docs/coding/data-modeling.md time-based ids).
    Rows in the columnar stores are assigned in insert order, so a batch
    of contiguous ids maps to a contiguous row range — representable as
    one (start_id, len, start_val) run instead of 8190 hash entries.

    Same contract as HashIndex (insert keys unique & absent; remove keys
    present). Non-contiguous batches fall back to the hash; lookups
    consult both. Runs are grouped by the high limb (virtually always a
    single group, id_hi == 0 or a fixed template prefix) and kept sorted
    by start for a vectorized searchsorted probe.
    """

    def __init__(self, capacity: int = 1 << 16) -> None:
        self._hash = HashIndex(capacity)
        # hi (int) -> [starts u64 sorted, lens u64, vals u64]
        self._runs: dict[int, list[np.ndarray]] = {}
        self._run_count = 0

    @property
    def count(self) -> int:
        return self._hash.count + self._run_count

    def _try_run(self, lo, hi, values) -> bool:
        n = len(lo)
        if n < 2 or hi[0] != hi[-1] or (hi != hi[0]).any():
            return False
        # lo[-1] >= lo[0] rejects uint64 wraparound, which the modular
        # diff check alone would mistake for contiguity.
        if lo[-1] < lo[0] or values[-1] < values[0]:
            return False
        one = np.uint64(1)
        if ((lo[1:] - lo[:-1]) != one).any():
            return False
        if ((values[1:] - values[:-1]) != one).any():
            return False
        h = int(hi[0])
        start, val = lo[0], values[0]
        g = self._runs.get(h)
        if g is None:
            self._runs[h] = [
                np.array([start], np.uint64),
                np.array([n], np.uint64),
                np.array([val], np.uint64),
            ]
            self._run_count += n
            return True
        starts, lens, vals = g
        i = int(np.searchsorted(starts, start))
        # Merge with predecessor when ids AND rows are both contiguous.
        if (
            i > 0
            and starts[i - 1] + lens[i - 1] == start
            and vals[i - 1] + lens[i - 1] == val
        ):
            lens[i - 1] += np.uint64(n)
            # May now abut the successor too.
            if (
                i < len(starts)
                and starts[i - 1] + lens[i - 1] == starts[i]
                and vals[i - 1] + lens[i - 1] == vals[i]
            ):
                lens[i - 1] += lens[i]
                g[0] = np.delete(starts, i)
                g[1] = np.delete(lens, i)
                g[2] = np.delete(vals, i)
        elif (
            i < len(starts)
            and start + np.uint64(n) == starts[i]
            and val + np.uint64(n) == vals[i]
        ):
            starts[i] = start
            lens[i] += np.uint64(n)
            vals[i] = val
        else:
            g[0] = np.insert(starts, i, start)
            g[1] = np.insert(lens, i, np.uint64(n))
            g[2] = np.insert(vals, i, val)
        self._run_count += n
        return True

    def insert(self, lo: np.ndarray, hi: np.ndarray, values: np.ndarray) -> None:
        if len(lo) == 0:
            return
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        values = np.asarray(values, np.uint64)
        if not self._try_run(lo, hi, values):
            self._hash.insert(lo, hi, values)

    def lookup(self, lo: np.ndarray, hi: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        found, values = self._hash.lookup(lo, hi)
        if not self._runs:
            return found, values
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        for h, (starts, lens, vals) in self._runs.items():
            if not len(starts):
                continue
            lane = ~found & (hi == np.uint64(h))
            if not lane.any():
                continue
            ls = lo[lane]
            idx = np.searchsorted(starts, ls, side="right") - 1
            ic = np.maximum(idx, 0)
            hit = (idx >= 0) & (ls - starts[ic] < lens[ic])
            if not hit.any():
                continue
            li = np.flatnonzero(lane)[hit]
            off = lo[li] - starts[ic[hit]]
            found[li] = True
            values[li] = vals[ic[hit]] + off
        return found, values

    def remove(self, lo: np.ndarray, hi: np.ndarray) -> None:
        n = len(lo)
        if n == 0:
            return
        lo = np.asarray(lo, np.uint64)
        hi = np.asarray(hi, np.uint64)
        in_hash, _ = self._hash.lookup(lo, hi)
        if in_hash.any():
            self._hash.remove(lo[in_hash], hi[in_hash])
        # Run splitting: rare (create_accounts chain rollback only).
        for k in np.flatnonzero(~in_hash):
            g = self._runs.get(int(hi[k]))
            assert g is not None, "remove of absent key"
            starts, lens, vals = g
            i = int(np.searchsorted(starts, lo[k], side="right")) - 1
            off = lo[k] - starts[i]
            assert 0 <= off < lens[i], "remove of absent key"
            tail = lens[i] - off - np.uint64(1)
            if off == 0 and tail == 0:
                if len(starts) == 1:
                    del self._runs[int(hi[k])]
                else:
                    g[0] = np.delete(starts, i)
                    g[1] = np.delete(lens, i)
                    g[2] = np.delete(vals, i)
            elif off == 0:
                starts[i] += np.uint64(1)
                vals[i] += np.uint64(1)
                lens[i] = tail
            elif tail == 0:
                lens[i] = off
            else:
                new_val = vals[i] + off + np.uint64(1)
                lens[i] = off
                g[0] = np.insert(starts, i + 1, lo[k] + np.uint64(1))
                g[1] = np.insert(lens, i + 1, tail)
                g[2] = np.insert(vals, i + 1, new_val)
            self._run_count -= 1
