"""tbcheck: AST-level invariant linter for the determinism / money /
wire / exception / lock contracts (round 17).

Entry points:
- ``python -m tigerbeetle_tpu lint [--json] [paths...]`` (cli.py)
- :func:`run_lint` — the tier-1 test surface (tests/test_tbcheck.py)
"""

from tigerbeetle_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintResult,
    Rule,
    SourceFile,
    main,
    run_lint,
)
