"""Static import graph over the package (tbcheck reachability).

The determinism rule's scope is "code the deterministic simulation can
execute", computed from the import graph rooted at testing/cluster.py
and testing/vopr.py rather than a filename exemption list (the r16
lesson: lists rot, graphs don't).  Edges follow EVERY static import —
module-level and function-level alike — because the sim does execute
lazily-imported modules (flight recorder, chaos shims, commitment);
the result is a safe over-approximation, and genuinely process-facing
modules that land in it (the real-TCP server loop, the scrape client)
carry reasoned per-line or per-file suppressions instead of silently
escaping the rule.
"""

from __future__ import annotations

import ast
import os

PACKAGE = "tigerbeetle_tpu"

#: Roots of the sim-reachable set: the deterministic cluster harness
#: and the VOPR driver.  Everything they can import is code a seeded
#: simulation may execute, and must not read wall clocks or unseeded
#: entropy.
SIM_ROOTS = (
    f"{PACKAGE}.testing.cluster",
    f"{PACKAGE}.testing.vopr",
)


def module_name(path: str, pkg_root: str) -> str:
    """Dotted module name of `path` relative to the directory that
    CONTAINS the package root (so vsr/wire.py ->
    tigerbeetle_tpu.vsr.wire)."""
    rel = os.path.relpath(os.path.abspath(path),
                          os.path.dirname(os.path.abspath(pkg_root)))
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = rel.split(os.sep)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _resolve_from(node: ast.ImportFrom, importer: str,
                  is_pkg: bool) -> str | None:
    """Absolute dotted module an ImportFrom names (None when the
    import is relative past the package top).  `is_pkg`: the importer
    is an __init__.py, whose dotted name already IS its package (one
    relative level strips nothing from it)."""
    if node.level == 0:
        return node.module
    base = importer.split(".")
    # one level strips the module's own name; further levels strip
    # parents (an __init__ importer already IS its package).
    if not is_pkg:
        base = base[:-1]
    drop = node.level - 1
    if drop >= len(base):
        return None
    if drop:
        base = base[:-drop]
    return ".".join(base + ([node.module] if node.module else []))


def build_graph(files: dict[str, ast.Module], pkg_root: str,
                ) -> dict[str, set[str]]:
    """files: path -> parsed module.  Returns module -> set(imported
    modules), edges restricted to modules inside the package."""
    known = {module_name(p, pkg_root) for p in files}
    graph: dict[str, set[str]] = {m: set() for m in known}

    def add(importer: str, target: str | None) -> None:
        if not target or not target.startswith(PACKAGE):
            return
        # `from pkg.mod import Symbol`: the target is the module if it
        # exists, else the containing package (whose __init__ runs).
        while target and target not in known:
            target = target.rpartition(".")[0]
        if target and target != importer:
            graph[importer].add(target)

    for path, tree in files.items():
        importer = module_name(path, pkg_root)
        is_pkg = os.path.basename(path) == "__init__.py"
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    add(importer, alias.name)
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_from(node, importer, is_pkg)
                if base is None:
                    continue
                add(importer, base)
                for alias in node.names:
                    add(importer, f"{base}.{alias.name}")
    return graph


def reachable(graph: dict[str, set[str]], roots=SIM_ROOTS) -> set[str]:
    """Transitive closure from `roots` (roots included when present)."""
    seen: set[str] = set()
    stack = [r for r in roots if r in graph]
    while stack:
        mod = stack.pop()
        if mod in seen:
            continue
        seen.add(mod)
        stack.extend(graph.get(mod, ()))
        # importing pkg.sub implies pkg.__init__ ran too
        parent = mod.rpartition(".")[0]
        if parent and parent in graph and parent not in seen:
            stack.append(parent)
    return seen
