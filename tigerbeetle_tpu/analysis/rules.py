"""tbcheck rules: the project's contracts, encoded once.

Each rule is an AST visitor over one module; scoping (sim-reachable
set, exempt modules) comes from the Context.  Rule ids are stable —
they are the keys suppressions name.

Catalog:
  determinism   no wall clocks / unseeded entropy in sim-reachable code
  envcheck      TB_*/BENCH_* reads must go through envcheck.py
  money         u128 money math must never touch floats or `/`
  wire-layout   header carve-outs derived + overlap/annotation checked
  broad-except  broad handlers must re-raise, classify, or be reasoned
  worker-shared attrs mutated by worker closures AND methods must be
                declared in the class's _WORKER_SHARED set
  no-print      core modules talk through logging/tracer, not stdout
"""

from __future__ import annotations

import ast
import os

from tigerbeetle_tpu.analysis.core import Context, Rule, SourceFile
from tigerbeetle_tpu.analysis import layout as layout_mod

# ----------------------------------------------------------------------
# determinism


#: Canonical call paths that break deterministic simulation.  perf
#: counters are deliberately absent: metrics timing is observability,
#: never fed back into state-machine decisions.
NONDETERMINISTIC = {
    "time.time": "wall clock",
    "time.time_ns": "wall clock",
    "time.monotonic": "wall clock",
    "time.monotonic_ns": "wall clock",
    "os.urandom": "kernel entropy",
    "uuid.uuid1": "wall clock + MAC",
    "uuid.uuid4": "kernel entropy",
    "secrets.token_bytes": "kernel entropy",
    "secrets.token_hex": "kernel entropy",
    "secrets.randbits": "kernel entropy",
}
#: Module-level RNG functions = the unseeded global generator.  A
#: seeded `random.Random(seed)` / `np.random.default_rng(seed)`
#: instance is the sanctioned alternative.
_GLOBAL_RNG_FNS = (
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "getrandbits", "seed", "random_sample", "rand",
    "randn", "permutation", "bytes",
    # distribution draws (stdlib random and numpy global state alike)
    "gauss", "normalvariate", "expovariate", "betavariate",
    "triangular", "lognormvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "gammavariate",
    "normal", "standard_normal", "exponential", "poisson", "binomial",
    "gamma", "beta", "chisquare", "integers",
)
for _fn in _GLOBAL_RNG_FNS:
    NONDETERMINISTIC[f"random.{_fn}"] = "unseeded global RNG"
    NONDETERMINISTIC[f"numpy.random.{_fn}"] = "unseeded global RNG"
del _fn


class DeterminismRule(Rule):
    id = "determinism"
    doc = ("sim-reachable modules (import graph rooted at "
           "testing/cluster.py + testing/vopr.py) must not read wall "
           "clocks or unseeded entropy")

    def check(self, sf: SourceFile, ctx: Context):
        if not ctx.is_sim_reachable(sf):
            return
        # func nodes of zero-argument calls (unseeded default_rng()).
        bare_calls = {
            id(c.func) for c in ast.walk(sf.tree)
            if isinstance(c, ast.Call) and not c.args and not c.keywords
        }
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            path = sf.aliases.resolve(node)
            if path is None:
                continue
            why = NONDETERMINISTIC.get(path)
            if why is not None:
                yield self.finding(
                    sf, node,
                    f"{path} ({why}) in sim-reachable code — inject a "
                    "clock / use a seeded Generator",
                )
            elif path in ("numpy.random.default_rng", "random.Random"):
                if id(node) in bare_calls:
                    yield self.finding(
                        sf, node,
                        f"{path}() without a seed in sim-reachable "
                        "code — pass an explicit seed",
                    )


# ----------------------------------------------------------------------
# envcheck discipline


class EnvcheckRule(Rule):
    id = "envcheck"
    doc = ("TB_*/BENCH_* environment reads outside envcheck.py bypass "
           "validation and hide knobs from the envcheck surface tests")

    _EXEMPT = ("envcheck.py",)
    _PREFIXES = ("TB_", "BENCH_")

    def _knob(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value.startswith(self._PREFIXES):
                return node.value
        return None

    def check(self, sf: SourceFile, ctx: Context):
        if os.path.basename(sf.path) in self._EXEMPT:
            return
        for node in ast.walk(sf.tree):
            knob = None
            if isinstance(node, ast.Call):
                path = sf.aliases.resolve(node.func)
                if path in ("os.getenv", "os.environ.get",
                            "os.environ.pop", "os.environ.setdefault"):
                    knob = self._knob(node.args[0]) if node.args else None
            elif isinstance(node, ast.Subscript):
                path = sf.aliases.resolve(node.value)
                if path == "os.environ":
                    knob = self._knob(node.slice)
            if knob is not None:
                yield self.finding(
                    sf, node,
                    f"raw environment read of {knob} — route it "
                    "through envcheck.py (validated, named errors)",
                )


# ----------------------------------------------------------------------
# money-path integer safety


_MONEY_TOKENS = ("amount", "debit", "credit")
# Bare `float` covers both float(x) casts and astype(float) dtype use.
_FLOAT_DTYPES = {"float", "float16", "float32", "float64", "float_",
                 "double", "half", "single"}


def _simple_units(tree: ast.AST):
    """Yield the smallest statement-ish expression units: simple
    statements whole, compound statements by their header expressions
    only (so a `for` loop body's unrelated float math is not blamed on
    a money name in the iterator)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Return, ast.Expr, ast.Assert,
                             ast.Delete, ast.Raise)):
            yield node
        elif isinstance(node, (ast.If, ast.While)):
            yield node.test
        elif isinstance(node, ast.For):
            yield node.iter
        elif isinstance(node, ast.comprehension):
            yield node.iter
            for cond in node.ifs:
                yield cond


def _identifiers(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id.lower()
        elif isinstance(n, ast.Attribute):
            yield n.attr.lower()
        elif isinstance(n, ast.keyword) and n.arg:
            yield n.arg.lower()


class MoneyRule(Rule):
    id = "money"
    doc = ("expressions over amounts/debits/credits are u128 limb "
           "math: no float literals, no true division, no float "
           "dtypes — go through ops/u128.py")

    def _is_money(self, unit: ast.AST) -> bool:
        return any(
            any(tok in ident for tok in _MONEY_TOKENS)
            for ident in _identifiers(unit)
        )

    def check(self, sf: SourceFile, ctx: Context):
        for unit in _simple_units(sf.tree):
            if not self._is_money(unit):
                continue
            # Type annotations are declarations, not computation —
            # `fee_rate: float` on an AnnAssign must not be blamed on
            # the money name in its value.
            scan = ([unit.target, unit.value]
                    if isinstance(unit, ast.AnnAssign)
                    else [unit])
            for root in scan:
                if root is None:
                    continue
                for n in ast.walk(root):
                    if isinstance(n, ast.BinOp) and isinstance(
                        n.op, ast.Div
                    ):
                        yield self.finding(
                            sf, n,
                            "true division in a money expression — "
                            "u128 balances use integer/limb ops only",
                        )
                    elif isinstance(n, ast.Constant) and isinstance(
                        n.value, float
                    ):
                        yield self.finding(
                            sf, n,
                            f"float literal {n.value!r} in a money "
                            "expression — amounts are u128 integers",
                        )
                    elif isinstance(n, (ast.Attribute, ast.Name)):
                        leaf = (n.attr if isinstance(n, ast.Attribute)
                                else n.id)
                        if leaf in _FLOAT_DTYPES:
                            yield self.finding(
                                sf, n,
                                f"float type `{leaf}` in a money "
                                "expression (cast, dtype, or astype) "
                                "— amounts are u128 limb pairs",
                            )


# ----------------------------------------------------------------------
# wire layout


class WireLayoutRule(Rule):
    id = "wire-layout"
    doc = ("every byte-range carve-out of the 256-byte header is "
           "derived from the dtype declaration and checked for "
           "overlap/gaps/lying annotations")

    def _expected_total(self, ctx: Context) -> int | None:
        path = os.path.join(ctx.pkg_root, "constants.py")
        try:
            with open(path, encoding="utf-8") as fh:
                return layout_mod.header_size_of(fh.read())
        except OSError:
            return None

    def check(self, sf: SourceFile, ctx: Context):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not any(n.endswith("HEADER_DTYPE") for n in names):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            if sf.aliases.resolve(node.value.func) not in (
                "numpy.dtype", "np.dtype"
            ):
                continue
            layout = layout_mod.parse_dtype_layout(node.value)
            if layout is None:
                yield self.finding(
                    sf, node,
                    "HEADER_DTYPE declaration is not statically "
                    "parseable — tbcheck cannot prove the carve-outs",
                )
                continue
            for line, msg in layout_mod.check_layout(
                layout, sf.lines, self._expected_total(ctx)
            ):
                yield self.finding(sf, line, msg)


# ----------------------------------------------------------------------
# exception discipline


class BroadExceptRule(Rule):
    id = "broad-except"
    doc = ("bare/broad `except` must re-raise, funnel into "
           "classify_link_error, or carry an allow-comment naming why")

    _BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        types = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(
            isinstance(x, ast.Name) and x.id in self._BROAD
            for x in types
        )

    def _handler_escapes(self, handler: ast.ExceptHandler) -> bool:
        """True when the handler re-raises or classifies (a nested
        function body does not count — it runs later, if ever)."""
        stack = list(handler.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                fn = n.func
                leaf = (fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name) else "")
                if leaf == "classify_link_error":
                    return True
            stack.extend(ast.iter_child_nodes(n))
        return False

    def check(self, sf: SourceFile, ctx: Context):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if self._handler_escapes(node):
                continue
            what = ("bare except" if node.type is None
                    else "broad except")
            yield self.finding(
                sf, node,
                f"{what} swallows typed errors (DeviceLostError "
                "classification, EnvVarError) — re-raise, route "
                "through classify_link_error, or annotate why",
            )


# ----------------------------------------------------------------------
# worker-shared (lock discipline)


class _AttrWrites(ast.NodeVisitor):
    """Attribute names of `self` mutated in a function body: stores,
    aug-assigns, deletes, item-stores (self.x[k] = v), and calls to
    known mutating container methods (self.x.append(...))."""

    _MUTATORS = {"append", "pop", "clear", "add", "remove", "update",
                 "extend", "put", "setdefault", "discard", "insert"}

    def __init__(self):
        self.writes: set[str] = set()
        self.submits: list[ast.Call] = []
        self.self_calls: set[str] = set()

    def _self_attr(self, node: ast.AST) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def visit_Attribute(self, node: ast.Attribute) -> None:
        name = self._self_attr(node)
        if name is not None and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            self.writes.add(name)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            name = self._self_attr(node.value)
            if name is not None:
                self.writes.add(name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr == "submit":
                self.submits.append(node)
            target = self._self_attr(fn.value)
            if target is not None and fn.attr in self._MUTATORS:
                self.writes.add(target)
            name = self._self_attr(fn)
            if name is not None:
                self.self_calls.add(name)
        self.generic_visit(node)


class WorkerSharedRule(Rule):
    id = "worker-shared"
    doc = ("attributes mutated both from a SerialWorker-submitted "
           "closure and from instance methods must be declared in the "
           "class's _WORKER_SHARED set — a cheap static write-write "
           "race detector for the background-worker seams")

    def _declared(self, cls: ast.ClassDef) -> set[str] | None:
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_WORKER_SHARED"
                for t in stmt.targets
            ):
                v = stmt.value
                elts = []
                if isinstance(v, (ast.Set, ast.Tuple, ast.List)):
                    elts = v.elts
                elif isinstance(v, ast.Call) and isinstance(
                    v.func, ast.Name
                ) and v.func.id == "frozenset" and v.args:
                    inner = v.args[0]
                    if isinstance(inner, (ast.Set, ast.Tuple, ast.List)):
                        elts = inner.elts
                return {
                    e.value for e in elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
        return None

    def check(self, sf: SourceFile, ctx: Context):
        # Cheap pre-filter: a class can hit this rule by constructing
        # a SerialWorker OR by calling .submit() on an injected one —
        # an injected worker must not walk past the tripwire.
        if "SerialWorker" not in sf.text and ".submit(" not in sf.text:
            return
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            yield from self._check_class(sf, cls)

    def _check_class(self, sf: SourceFile, cls: ast.ClassDef):
        methods = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        analyses = {}
        for name, m in methods.items():
            a = _AttrWrites()
            a.visit(m)
            analyses[name] = a
        constructs_worker = any(
            isinstance(n, ast.Call) and (
                (isinstance(n.func, ast.Name)
                 and n.func.id == "SerialWorker")
                or (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "SerialWorker")
            )
            for n in ast.walk(cls)
        )
        submits_anything = any(a.submits for a in analyses.values())
        if not constructs_worker and not submits_anything:
            return

        # Worker entry points: self-method references (or local defs /
        # lambdas, analyzed inline) passed as a submit() first arg.
        entry_methods: set[str] = set()
        inline_writes: set[str] = set()
        for name, a in analyses.items():
            for call in a.submits:
                if not call.args:
                    continue
                fn = call.args[0]
                if (isinstance(fn, ast.Attribute)
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "self"
                        and fn.attr in methods):
                    entry_methods.add(fn.attr)
                elif isinstance(fn, ast.Lambda):
                    w = _AttrWrites()
                    w.visit(fn)
                    inline_writes |= w.writes
                elif isinstance(fn, ast.Name):
                    # a local `def job(): ...` in the same method
                    for d in ast.walk(methods[name]):
                        if isinstance(d, ast.FunctionDef) and (
                            d.name == fn.id
                        ):
                            w = _AttrWrites()
                            w.visit(d)
                            inline_writes |= w.writes
        if not entry_methods and not inline_writes:
            return

        # Transitive closure over self-method calls: everything a
        # submitted method can reach runs on the worker thread.
        worker_set: set[str] = set()
        stack = list(entry_methods)
        while stack:
            m = stack.pop()
            if m in worker_set or m not in analyses:
                continue
            worker_set.add(m)
            stack.extend(analyses[m].self_calls)

        worker_writes = set(inline_writes)
        for m in worker_set:
            worker_writes |= analyses[m].writes
        method_writes: set[str] = set()
        for name, a in analyses.items():
            if name in worker_set or name == "__init__":
                continue
            method_writes |= a.writes

        shared = sorted(worker_writes & method_writes)
        declared = self._declared(cls)
        for attr in shared:
            if declared is None or attr not in declared:
                yield self.finding(
                    sf, cls,
                    f"class {cls.name}: attribute '{attr}' is mutated "
                    "both from a SerialWorker closure and from "
                    "instance methods but is not declared in "
                    "_WORKER_SHARED — declare it (and say what "
                    "serializes the writes) or stop sharing it",
                )


# ----------------------------------------------------------------------
# no-print


class NoPrintRule(Rule):
    id = "no-print"
    doc = ("core modules must not print; stdout belongs to CLIs and "
           "benches (file-level allows with reasons), everything else "
           "talks through logging or the tracer")

    def check(self, sf: SourceFile, ctx: Context):
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and sf.aliases.resolve(node.func) == "print"):
                yield self.finding(
                    sf, node,
                    "print() in a core module — use logging or the "
                    "tracer (CLIs carry a file-level allow)",
                )


def all_rules() -> list[Rule]:
    return [
        DeterminismRule(),
        EnvcheckRule(),
        MoneyRule(),
        WireLayoutRule(),
        BroadExceptRule(),
        WorkerSharedRule(),
        NoPrintRule(),
    ]
