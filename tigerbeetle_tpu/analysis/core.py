"""tbcheck core: AST lint framework for the repo's invariants.

The reference enforces its contracts mechanically (src/tidy.zig bans
patterns repo-wide); tbcheck is our equivalent grown past regexes: a
per-rule AST visitor pass over the whole package with import-alias
resolution (a ``from os import environ as E`` cannot walk past a rule),
reasoned per-line / per-file suppressions, and machine-readable JSON
output.  Wired as ``python -m tigerbeetle_tpu lint`` and as a tier-1
test (tests/test_tbcheck.py) that asserts zero findings.

Suppression grammar (every form REQUIRES a reason string, and unused
suppressions are themselves findings so they cannot rot):

    x = time.monotonic()  # tbcheck: allow(determinism): <why>
    # tbcheck: allow(determinism): <why>        <- covers the NEXT line
    # tbcheck: allow-file(no-print): <why>      <- covers the whole file
"""
# tbcheck: allow-file(no-print): main() IS the lint CLI — findings and
# the summary line go to stdout by contract.

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

_ALLOW_RE = re.compile(
    r"#\s*tbcheck:\s*(allow|allow-file)\(([a-z0-9_,\s-]*)\)"
    r"(?::\s*(\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class _Allow:
    __slots__ = ("rules", "reason", "line", "file_wide", "used_rules")

    def __init__(self, rules, reason, line, file_wide):
        self.rules = rules
        self.reason = reason
        self.line = line
        self.file_wide = file_wide
        # Used-ness is PER RULE: a multi-rule allow whose rules don't
        # all still fire has stale halves, and stale halves rot.
        self.used_rules: set = set()


class AliasResolver(ast.NodeVisitor):
    """Canonical dotted names for imported bindings, module-wide.

    ``import time as _time`` makes ``_time.monotonic`` resolve to
    ``time.monotonic``; ``from os import environ as E`` makes
    ``E.get`` resolve to ``os.environ.get``.  Function-level imports
    are included (the module executes them too); shadowing by
    assignment is not tracked — rules treat resolution as "what this
    name most plausibly denotes", which is the right polarity for a
    linter (prefer a spurious finding + reasoned allow over a silent
    escape).
    """

    def __init__(self, tree: ast.Module) -> None:
        self.aliases: dict[str, str] = {}
        self.visit(tree)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            target = alias.name if alias.asname else alias.name.split(".")[0]
            self.aliases[name] = target

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level or not node.module:
            return  # package-relative: never stdlib time/os/random
        for alias in node.names:
            name = alias.asname or alias.name
            self.aliases[name] = f"{node.module}.{alias.name}"

    def resolve(self, node: ast.AST) -> str | None:
        """Dotted canonical path of a Name/Attribute chain, or None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + list(reversed(parts)))


class SourceFile:
    """One parsed module: source, AST, alias map, allow-comments."""

    def __init__(self, path: str, repo_root: str, text: str | None = None):
        self.path = os.path.abspath(path)
        self.rel = os.path.relpath(self.path, repo_root)
        if text is None:
            with open(self.path, encoding="utf-8") as fh:
                text = fh.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.rel)
        self.aliases = AliasResolver(self.tree)
        self.allows: dict[int, list[_Allow]] = {}  # line -> allows
        self.file_allows: dict[str, _Allow] = {}  # rule -> allow
        self.bad_allows: list[Finding] = []       # malformed suppressions
        self._collect_allows()

    def _collect_allows(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline
            ))
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ALLOW_RE.search(tok.string)
            if m is None:
                if "tbcheck:" in tok.string:
                    self.bad_allows.append(Finding(
                        "suppression", self.rel, tok.start[0],
                        "unparseable tbcheck directive "
                        "(expected `tbcheck: allow(<rule>): <reason>`)",
                    ))
                continue
            kind, rules_raw, reason = m.group(1), m.group(2), m.group(3)
            rules = tuple(
                r.strip() for r in rules_raw.split(",") if r.strip()
            )
            line = tok.start[0]
            if not rules or not (reason or "").strip():
                self.bad_allows.append(Finding(
                    "suppression", self.rel, line,
                    "suppression without a rule id and reason string "
                    "(`tbcheck: allow(<rule>): <reason>`)",
                ))
                continue
            allow = _Allow(rules, reason.strip(), line, kind == "allow-file")
            if allow.file_wide:
                for r in rules:
                    self.file_allows[r] = allow
            else:
                # A standalone comment covers the next non-comment,
                # non-blank line (so a multi-line reason block works,
                # and stacked allows for different rules merge); a
                # trailing comment covers its own line.
                standalone = self.lines[line - 1].lstrip().startswith("#")
                target = line
                if standalone:
                    target = line + 1
                    while target <= len(self.lines) and (
                        not self.lines[target - 1].strip()
                        or self.lines[target - 1].lstrip().startswith("#")
                    ):
                        target += 1
                self.allows.setdefault(target, []).append(allow)

    def suppressed(self, rule: str, line: int) -> bool:
        allow = self.file_allows.get(rule)
        if allow is not None:
            allow.used_rules.add(rule)
            return True
        for allow in self.allows.get(line, ()):
            if rule in allow.rules:
                allow.used_rules.add(rule)
                return True
        return False

    def unused_allow_findings(self, active_rules: set[str],
                              ) -> list[Finding]:
        """Stale suppressions — per rule id, so the dead half of an
        `allow-file(a, b)` is reported even while the live half still
        earns its keep.  Only rules that actually ran count (a
        single-rule invocation must not call another rule's allows
        stale)."""
        out = []
        seen = set()
        line_allows = [a for allows in self.allows.values()
                       for a in allows]
        for allow in line_allows + list(self.file_allows.values()):
            if id(allow) in seen:
                continue
            seen.add(id(allow))
            stale = [r for r in allow.rules
                     if r in active_rules and r not in allow.used_rules]
            if stale:
                out.append(Finding(
                    "suppression", self.rel, allow.line,
                    "unused suppression for "
                    f"{','.join(stale)} — delete it (suppressions "
                    "must not outlive the finding they justified)",
                ))
        return out


class Context:
    """Everything rules may consult: all files, the import graph, the
    sim-reachable module set, and the package root."""

    def __init__(self, files: list[SourceFile], pkg_root: str,
                 sim_modules: set[str], repo_root: str) -> None:
        self.files = files
        self.pkg_root = pkg_root
        self.repo_root = repo_root
        self.sim_modules = sim_modules

    def is_sim_reachable(self, sf: SourceFile) -> bool:
        from tigerbeetle_tpu.analysis import imports as imp

        return imp.module_name(sf.path, self.pkg_root) in self.sim_modules


class Rule:
    """Base: subclasses set `id`/`doc` and implement check()."""

    id = "base"
    doc = ""

    def check(self, sf: SourceFile, ctx: Context):
        raise NotImplementedError

    def finding(self, sf: SourceFile, node_or_line, message: str,
                ) -> Finding:
        line = (node_or_line if isinstance(node_or_line, int)
                else node_or_line.lineno)
        return Finding(self.id, sf.rel, line, message)


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    suppressed: int
    checked_files: int
    sim_modules: set[str]

    def as_json(self) -> str:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return json.dumps({
            "version": 1,
            "tool": "tbcheck",
            "checked_files": self.checked_files,
            "suppressed": self.suppressed,
            "counts": counts,
            "findings": [f.as_dict() for f in self.findings],
        }, indent=2, sort_keys=True)


def default_rules() -> list[Rule]:
    from tigerbeetle_tpu.analysis import rules as rules_mod

    return rules_mod.all_rules()


def run_lint(pkg_root: str | None = None, *,
             files: list[str] | None = None,
             rules: list[Rule] | None = None,
             assume_sim: bool = False) -> LintResult:
    """Lint the package (or an explicit file/directory list).

    With an explicit `files` subset, the import graph — and therefore
    the determinism rule's sim-reachable set — is still computed over
    the WHOLE package: linting one file must report exactly what the
    full run reports for it (a file has the same graph position either
    way).  `assume_sim=True` instead treats every linted file as
    sim-reachable — for fixture snippets outside the package, which
    have no graph position."""
    from tigerbeetle_tpu.analysis import imports as imp

    if pkg_root is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg_root = os.path.abspath(pkg_root)
    repo_root = os.path.dirname(pkg_root)

    def walk_py(root: str) -> list[str]:
        out = []
        for dirpath, dirs, names in os.walk(root):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out.extend(
                os.path.join(dirpath, n) for n in sorted(names)
                if n.endswith(".py")
            )
        return sorted(out)

    pkg_files = walk_py(pkg_root)
    if files is None:
        lint_files = pkg_files
    else:
        # Directory arguments expand to their .py files.
        lint_files = []
        for p in files:
            lint_files.extend(walk_py(p) if os.path.isdir(p) else [p])

    # A file the linter cannot read or parse is a FINDING, not a
    # crash: the machine-readable surface must stay machine-readable
    # when handed a broken path (rule id "parse").
    load_errors: list[Finding] = []

    def load(path: str) -> SourceFile | None:
        rel = os.path.relpath(os.path.abspath(path), repo_root)
        try:
            return SourceFile(path, repo_root)
        except SyntaxError as exc:
            load_errors.append(Finding(
                "parse", rel, exc.lineno or 1,
                f"not parseable as Python: {exc.msg}",
            ))
        except OSError as exc:
            load_errors.append(Finding(
                "parse", rel, 1,
                f"unreadable: {exc.strerror or exc}",
            ))
        return None

    by_path = {}
    for p in lint_files:
        sf = load(p)
        if sf is not None:
            by_path[os.path.abspath(p)] = sf
    sources = list(by_path.values())
    if assume_sim:
        sim = {imp.module_name(sf.path, pkg_root) for sf in sources}
    else:
        graph_sources = []
        for p in pkg_files:
            sf = by_path.get(os.path.abspath(p)) or load(p)
            if sf is not None:
                graph_sources.append(sf)
        graph = build_graph_from_sources(graph_sources, pkg_root)
        sim = imp.reachable(graph)
    ctx = Context(sources, pkg_root, sim, repo_root)

    findings: list[Finding] = []
    suppressed = 0
    active = rules if rules is not None else default_rules()
    for rule in active:
        for sf in sources:
            for f in rule.check(sf, ctx):
                if sf.suppressed(f.rule, f.line):
                    suppressed += 1
                else:
                    findings.append(f)
    active_ids = {r.id for r in active}
    for sf in sources:
        findings.extend(sf.bad_allows)
        findings.extend(sf.unused_allow_findings(active_ids))
    findings.extend(load_errors)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings, suppressed, len(sources), sim)


def build_graph_from_sources(sources: list[SourceFile], pkg_root: str):
    from tigerbeetle_tpu.analysis import imports as imp

    return imp.build_graph({sf.path: sf.tree for sf in sources}, pkg_root)


def main(argv: list[str]) -> int:
    """`python -m tigerbeetle_tpu lint [--json] [paths...]`."""
    import sys

    as_json = False
    paths = []
    for a in argv:
        if a == "--json":
            as_json = True
        elif a.startswith("--"):
            # Same contract as flags.py: unknown flags are fatal, not
            # silently dropped (a typo'd --json must not quietly flip
            # a CI consumer to the human-readable format).
            print(f"error: unknown lint flag {a!r}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
    result = run_lint(files=paths or None)
    if as_json:
        print(result.as_json())
    else:
        for f in result.findings:
            print(str(f))
        print(
            f"tbcheck: {len(result.findings)} finding(s) across "
            f"{result.checked_files} files ({result.suppressed} "
            "suppressed with reasons)"
        )
    return 1 if result.findings else 0
