"""Header-layout derivation for the wire-layout rule.

vsr/wire.py packs reserved-byte carve-outs (trace context, tenant key)
into the 256-byte header as numpy dtype fields, each annotated with
its intended byte range (``# [156, 164)``).  The rule re-derives the
REAL offsets from the dtype declaration itself — width per format
string, cumulative for list-form dtypes, explicit for dict-form — and
cross-checks every annotated range against them, so the next reserved
byte claim cannot silently collide with an existing carve-out: an
overlap, a gap, a wrong total, or a comment that lies about its bytes
is a finding, derived from wire.py, never hardcoded in the rule.
"""

from __future__ import annotations

import ast
import dataclasses
import re

_FMT_RE = re.compile(r"^[<>|=]?([uif])([0-9]+)$")
_VOID_RE = re.compile(r"^V([0-9]+)$")
_RANGE_RE = re.compile(r"\[\s*(\d+)\s*,\s*(\d+)\s*\)")


@dataclasses.dataclass
class Field:
    name: str
    offset: int
    size: int
    line: int

    @property
    def end(self) -> int:
        return self.offset + self.size


@dataclasses.dataclass
class Layout:
    fields: list[Field]
    problems: list[tuple[int, str]]  # (line, message)
    line: int  # the declaration's first line

    @property
    def total(self) -> int:
        return max((f.end for f in self.fields), default=0)

    def span_of(self, *names: str) -> tuple[int, int] | None:
        """[start, end) covered by the named fields, or None when any
        is missing."""
        picked = [f for f in self.fields if f.name in names]
        if len(picked) != len(names):
            return None
        return min(f.offset for f in picked), max(f.end for f in picked)


def _fmt_size(fmt: str) -> int | None:
    m = _FMT_RE.match(fmt)
    if m:
        return int(m.group(2))
    m = _VOID_RE.match(fmt)
    if m:
        return int(m.group(1))
    return None


def _const(node: ast.AST):
    return node.value if isinstance(node, ast.Constant) else None


def parse_dtype_layout(call: ast.Call) -> Layout | None:
    """Field layout of an ``np.dtype([...])`` / ``np.dtype({...})``
    call node, or None when the argument shape is not a dtype spec."""
    if not call.args:
        return None
    arg = call.args[0]
    problems: list[tuple[int, str]] = []
    fields: list[Field] = []
    if isinstance(arg, (ast.List, ast.Tuple)):
        at = 0
        for el in arg.elts:
            if not isinstance(el, ast.Tuple) or len(el.elts) < 2:
                problems.append((el.lineno, "unparseable dtype field"))
                continue
            name = _const(el.elts[0])
            fmt = _const(el.elts[1])
            size = _fmt_size(fmt) if isinstance(fmt, str) else None
            if not isinstance(name, str) or size is None:
                problems.append((
                    el.lineno,
                    f"dtype field {name!r}: width of format {fmt!r} "
                    "is not statically derivable",
                ))
                continue
            fields.append(Field(name, at, size, el.lineno))
            at += size
    elif isinstance(arg, ast.Dict):
        spec: dict[str, list] = {}
        for k, v in zip(arg.keys, arg.values):
            key = _const(k)
            if isinstance(key, str) and isinstance(v, (ast.List, ast.Tuple)):
                spec[key] = v.elts
        names = spec.get("names")
        formats = spec.get("formats")
        offsets = spec.get("offsets")
        if names is None or formats is None or offsets is None:
            return None
        for n, f, o in zip(names, formats, offsets):
            name, fmt, off = _const(n), _const(f), _const(o)
            size = _fmt_size(fmt) if isinstance(fmt, str) else None
            if not isinstance(name, str) or size is None or not isinstance(
                off, int
            ):
                problems.append((n.lineno, "unparseable dtype field"))
                continue
            fields.append(Field(name, off, size, n.lineno))
    else:
        return None
    return Layout(fields, problems, call.lineno)


def check_layout(layout: Layout, source_lines: list[str],
                 expected_total: int | None) -> list[tuple[int, str]]:
    """Structural checks + annotation cross-check.  Returns (line,
    message) problems."""
    problems = list(layout.problems)
    # No two carve-outs may claim the same byte.
    ordered = sorted(layout.fields, key=lambda f: (f.offset, f.end))
    for a, b in zip(ordered, ordered[1:]):
        if b.offset < a.end:
            problems.append((
                b.line,
                f"field '{b.name}' [{b.offset}, {b.end}) overlaps "
                f"'{a.name}' [{a.offset}, {a.end}) — reserved-byte "
                "carve-outs must never collide",
            ))
        elif b.offset > a.end:
            problems.append((
                b.line,
                f"gap of {b.offset - a.end} byte(s) between "
                f"'{a.name}' (ends {a.end}) and '{b.name}' (starts "
                f"{b.offset}) — the header must be fully accounted",
            ))
    if expected_total is not None and layout.total != expected_total:
        problems.append((
            layout.line,
            f"layout covers {layout.total} bytes, header is "
            f"{expected_total}",
        ))
    # Every `# [a, b)` annotation near a field line must match the
    # DERIVED span of the fields declared on that line.
    by_line: dict[int, list[Field]] = {}
    for f in layout.fields:
        by_line.setdefault(f.line, []).append(f)
    for line, fs in sorted(by_line.items()):
        text = source_lines[line - 1] if line <= len(source_lines) else ""
        m = _RANGE_RE.search(text.partition("#")[2])
        if not m:
            continue
        lo, hi = int(m.group(1)), int(m.group(2))
        real_lo = min(f.offset for f in fs)
        real_hi = max(f.end for f in fs)
        if (lo, hi) != (real_lo, real_hi):
            problems.append((
                line,
                f"annotation claims [{lo}, {hi}) but the declared "
                f"fields occupy [{real_lo}, {real_hi}) — fix the "
                "comment or the layout",
            ))
    return problems


def header_size_of(constants_source: str) -> int | None:
    """HEADER_SIZE literal from constants.py (parsed, not imported)."""
    try:
        tree = ast.parse(constants_source)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "HEADER_SIZE":
                    v = _const(node.value)
                    if isinstance(v, int):
                        return v
    return None
