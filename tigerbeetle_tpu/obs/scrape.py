"""Live counter scrape over the wire: the `stats` admin operation.

A running ReplicaServer answers `Command.request` +
`VsrOperation.stats` directly from its registry snapshot — read-only,
no session, no consensus (each replica reports its OWN counters, which
is exactly what fsyncs-per-prepare accounting needs).  The reply is a
`Command.reply` whose body is the JSON-encoded snapshot dict.

bench.py's replicated config and the tier-1 TCP smoke test use this
instead of regex-parsing TB_STATS log tails; the log-tail parser
survives only as the counter-verified fallback for kill -9'd replicas
(which can't answer a scrape but did leave their last line behind).
"""
# tbcheck: allow-file(determinism): scrape clients poll a live TCP
# server with wall-clock deadlines; the sim never executes them.

from __future__ import annotations

import json
import time

from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.wire import Command, VsrOperation

# Fixed request id for scrape matching: scrapes are sessionless
# (client=0), so the request field is free for correlation.
SCRAPE_REQUEST = 0x57A7


def scrape_stats(address: str, cluster: int, timeout_ms: int = 10_000) -> dict:
    """One registry snapshot from the replica at `address`
    ("host:port").  Raises TimeoutError when the server never answers
    (dead replica — callers fall back to its log tail)."""
    from tigerbeetle_tpu.runtime.native import EV_MESSAGE, NativeBus

    host, _, port = address.rpartition(":")
    bus = NativeBus()
    try:
        conn = bus.connect(host or "127.0.0.1", int(port))
        h = wire.make_header(
            command=Command.request, operation=VsrOperation.stats,
            cluster=cluster, request=SCRAPE_REQUEST,
        )
        wire.finalize_header(h, b"")
        bus.send(conn, h.tobytes())
        deadline = time.monotonic() + timeout_ms / 1e3
        while time.monotonic() < deadline:
            for ev_type, _conn, payload in bus.poll(50):
                if ev_type != EV_MESSAGE or len(payload) < HEADER_SIZE:
                    continue
                header = wire.header_from_bytes(payload[:HEADER_SIZE])
                body = payload[HEADER_SIZE:]
                if not wire.verify_header(header, body):
                    continue
                if (
                    int(header["command"]) == int(Command.reply)
                    and int(header["operation"]) == int(VsrOperation.stats)
                    and int(header["request"]) == SCRAPE_REQUEST
                ):
                    return json.loads(body.decode())
    finally:
        bus.close()
    raise TimeoutError(f"stats scrape of {address} timed out")


def scrape_state_root(
    address: str, cluster: int, timeout_ms: int = 10_000,
    at_op: int | None = None,
) -> tuple[bytes, int]:
    """Proof-of-state query: the replica's 16-byte state commitment
    (state_machine/commitment.py) + the commit_min it covers.  Same
    sessionless shape as the stats scrape — read-only, answered by the
    server loop, never enters consensus.  `at_op` asks for the root AS
    OF a specific op (answered from the replica's root ring when
    retained — the follower attestation query); callers must check the
    returned op, since a server without that op answers current."""
    from tigerbeetle_tpu.runtime.native import EV_MESSAGE, NativeBus
    from tigerbeetle_tpu.state_machine import commitment

    host, _, port = address.rpartition(":")
    bus = NativeBus()
    try:
        conn = bus.connect(host or "127.0.0.1", int(port))
        h = wire.make_header(
            command=Command.request, operation=VsrOperation.state_root,
            cluster=cluster, request=SCRAPE_REQUEST,
        )
        qbody = b"" if at_op is None else commitment.root_query_body(at_op)
        wire.finalize_header(h, qbody)
        bus.send(conn, h.tobytes() + qbody)
        deadline = time.monotonic() + timeout_ms / 1e3
        while time.monotonic() < deadline:
            for ev_type, _conn, payload in bus.poll(50):
                if ev_type != EV_MESSAGE or len(payload) < HEADER_SIZE:
                    continue
                header = wire.header_from_bytes(payload[:HEADER_SIZE])
                body = payload[HEADER_SIZE:]
                if not wire.verify_header(header, body):
                    continue
                if (
                    int(header["command"]) == int(Command.client_busy)
                    and int(header["request"]) == SCRAPE_REQUEST
                ):
                    # The router runs this query through its admission
                    # bound (unlike stats, answered pre-admission): a
                    # shed under load replies client_busy.  Resend
                    # instead of burning the rest of the deadline.
                    bus.send(conn, h.tobytes() + qbody)
                    continue
                if (
                    int(header["command"]) == int(Command.reply)
                    and int(header["operation"])
                    == int(VsrOperation.state_root)
                    and int(header["request"]) == SCRAPE_REQUEST
                ):
                    return commitment.parse_root_body(bytes(body))
    finally:
        bus.close()
    raise TimeoutError(f"state_root scrape of {address} timed out")


def state_root_reply(root: bytes, commit_min: int, request_header) -> tuple:
    """Server side: (reply_header, body) answering a `state_root`
    request with the 24-byte root+commit_min body."""
    from tigerbeetle_tpu.state_machine import commitment

    body = commitment.root_body(root, commit_min)
    reply = wire.make_header(
        command=Command.reply, operation=VsrOperation.state_root,
        cluster=wire.u128(request_header, "cluster"),
        client=wire.u128(request_header, "client"),
        request=int(request_header["request"]),
    )
    wire.finalize_header(reply, body)
    return reply, body


def stats_reply(snapshot: dict, request_header) -> tuple:
    """Server side: (reply_header, body) answering `request_header`
    with `snapshot` (runtime/server.py sends it on the raw conn)."""
    body = json.dumps(snapshot, sort_keys=True).encode()
    reply = wire.make_header(
        command=Command.reply, operation=VsrOperation.stats,
        cluster=wire.u128(request_header, "cluster"),
        client=wire.u128(request_header, "client"),
        request=int(request_header["request"]),
    )
    wire.finalize_header(reply, body)
    return reply, body
