"""Per-request anatomy: wire-propagated stage timelines + tail exemplars.

The aggregate spine (obs/registry.py) answers "what are the commit
percentiles"; this module answers the question a p999 outlier raises:
*where did THIS request spend its time*.  A compact trace context
(trace_id + origin timestamp + sampled flag, vsr/wire.py) rides the
wire header from client submit through primary prepare, journal write,
group-commit covering sync, backup prepare_ok, commit, and reply; each
hop appends a (stage, CLOCK_MONOTONIC ns) pair to the per-request
record kept here.  Blockchain Machine (arXiv:2104.06968) attributes
its wins by decomposing the sequential commit path stage-by-stage —
this is the per-request instrument that makes that decomposition
possible on live traffic.

Tail exemplars: when a request finishes, its end-to-end latency feeds
the `anatomy.e2e_us` histogram; a request landing in the histogram's
TOP buckets (>= the current p99 bucket, or during warmup) retains its
full stage timeline in a bounded ring (TB_TRACE_EXEMPLARS), scrapeable
via the `stats` wire op and renderable as Perfetto spans
(exemplar_trace_events) — a p999 outlier comes with its own anatomy
attached instead of a number in a bucket.

Costs: disabled (TB_METRICS=0) every method is one attribute check;
enabled, a stage is a list append + dict lookup.  Unsampled requests
(trace_id 0 / flag clear) never reach the recorder — call sites gate
on wire.trace_sampled().
"""

from __future__ import annotations

import collections
import time

from tigerbeetle_tpu.vsr import wire

# Canonical stage vocabulary (documentation — records are keyed by
# name, and hops may repeat: one prepare_ok per backup).
STAGES = (
    "client_submit", "ingress", "queued", "prepare", "journal_write",
    "gc_covering_sync", "prepare_ok", "commit", "reply", "busy",
)

# Bound on concurrently-open (unfinished) records: requests that never
# finish on this replica (dropped duplicates, superseded prepares)
# must not leak — oldest evicts first, counted.
OPEN_MAX = 1024


class AnatomyRecorder:
    """Bounded per-request stage-timeline recorder for one replica.

    `registry` is an obs.Registry (or Scope); the recorder is enabled
    iff the registry is (TB_METRICS=0 disables both).  `flight` is an
    optional obs.flight.FlightRecorder: every stage recorded here also
    lands in the flight ring, so the postmortem dump carries the most
    recent per-request events.
    """

    def __init__(self, registry, *, exemplar_ring: int | None = None,
                 open_max: int = OPEN_MAX,
                 clock=time.perf_counter_ns, flight=None) -> None:
        if exemplar_ring is None:
            from tigerbeetle_tpu import envcheck

            exemplar_ring = envcheck.trace_exemplars()
        assert exemplar_ring > 0
        self.enabled = bool(getattr(registry, "enabled", True))
        self.clock = clock
        self.flight = flight
        self.open_max = open_max
        self.exemplar_ring = exemplar_ring
        # trace_id -> {"origin": ns, "stages": [[name, ns], ...]}.
        # Ordered so overflow evicts the oldest open record.
        self._open: collections.OrderedDict[int, dict] = (
            collections.OrderedDict()
        )
        self.exemplars: collections.deque[dict] = collections.deque(
            maxlen=exemplar_ring
        )
        self._h_e2e = registry.histogram("e2e_us")
        self._c_finished = registry.counter("finished")
        self._c_exemplars = registry.counter("exemplars_kept")
        self._c_evicted = registry.counter("open_evicted")
        registry.gauge_fn("open", lambda: len(self._open))
        registry.gauge_fn("exemplar_ring", lambda: self.exemplar_ring)

    # -- hot path ------------------------------------------------------

    def stage(self, trace_id: int, stage: str, origin_ts: int = 0,
              ts: int | None = None) -> None:
        """Append one stage timestamp to `trace_id`'s record, opening
        it if needed (`origin_ts` = the wire header's client-submit
        timestamp, kept from the first opening hop)."""
        if not self.enabled or not trace_id:
            return
        if ts is None:
            ts = self.clock()
        rec = self._open.get(trace_id)
        if rec is None:
            if len(self._open) >= self.open_max:
                self._open.popitem(last=False)
                self._c_evicted.inc()
            rec = {"origin": origin_ts, "stages": []}
            self._open[trace_id] = rec
        rec["stages"].append([stage, ts])
        if self.flight is not None:
            self.flight.note(stage, ts=ts, trace_id=trace_id)

    def stage_h(self, header, stage: str) -> None:
        """Record a stage straight off a wire header (no-op unless the
        header carries a sampled trace context)."""
        if not self.enabled:
            return
        tid = wire.trace_sampled(header)
        if tid:
            self.stage(tid, stage, origin_ts=int(header["trace_ts"]))

    def stage_many(self, trace_ids, stage: str) -> None:
        """One stage timestamp shared by many requests (the covering
        group-commit sync lands for a whole drain at once)."""
        if not self.enabled or not trace_ids:
            return
        ts = self.clock()
        for tid in trace_ids:
            self.stage(tid, stage, ts=ts)

    def finish(self, trace_id: int, stage: str | None = None) -> None:
        """Close `trace_id`'s record: optional final stage, end-to-end
        latency into the histogram, tail-exemplar retention."""
        if not self.enabled or not trace_id:
            return
        rec = self._open.pop(trace_id, None)
        if rec is None:
            return
        now = self.clock()
        if stage is not None:
            rec["stages"].append([stage, now])
            if self.flight is not None:
                self.flight.note(stage, ts=now, trace_id=trace_id)
        origin = rec["origin"] or (
            rec["stages"][0][1] if rec["stages"] else now
        )
        e2e_us = max(0.0, (now - origin) / 1e3)
        self._c_finished.inc()
        if self._keep_exemplar(e2e_us):
            self._c_exemplars.inc()
            self.exemplars.append(
                {
                    "trace_id": trace_id,
                    "origin_ns": origin,
                    "e2e_us": round(e2e_us, 3),
                    "stages": rec["stages"],
                }
            )
        self._h_e2e.observe(e2e_us)

    def finish_h(self, header, stage: str | None = None) -> None:
        if not self.enabled:
            return
        tid = wire.trace_sampled(header)
        if tid:
            self.finish(tid, stage)

    def _keep_exemplar(self, e2e_us: float) -> bool:
        """Tail criterion: the value's bucket is at (or above) the
        current p99 bucket — i.e. the request landed in the
        histogram's top buckets.  Early requests (warmup, count < 16)
        are kept so the ring is never empty on short runs.  Evaluated
        BEFORE this request's own observation so one slow request
        cannot raise the bar for itself."""
        h = self._h_e2e
        if h.count < 16:
            return True
        from tigerbeetle_tpu.obs.registry import Histogram

        return Histogram.quantize(e2e_us) >= h.percentile(0.99)

    # -- extraction ----------------------------------------------------

    def exemplar_snapshot(self) -> list[dict]:
        """JSON-ready copy of the exemplar ring (newest last) for the
        `stats` wire scrape."""
        return [dict(ex, stages=[list(s) for s in ex["stages"]])
                for ex in self.exemplars]


def exemplar_trace_events(exemplars, pid: int = 0) -> list[dict]:
    """Render scraped exemplars as Chrome-trace events (one track per
    process): per exemplar, one enclosing `request` span plus one span
    per stage GAP named after the stage that closed it — so the
    Perfetto row reads prepare | journal_write | gc_covering_sync |
    commit | reply left to right, each span's width the time that hop
    took.  Output merges with per-replica tracer dumps via
    testing/cluster.merge_traces."""
    events: list[dict] = []
    for slot, ex in enumerate(exemplars):
        stages = ex.get("stages", [])
        if not stages:
            continue
        tid = slot % 32
        t0 = ex.get("origin_ns") or stages[0][1]
        events.append(
            {
                "name": f"request {ex.get('trace_id', 0):#x}",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": t0 / 1e3,
                "dur": max(stages[-1][1] - t0, 1) / 1e3,
                "args": {"e2e_us": ex.get("e2e_us", 0.0)},
            }
        )
        prev = t0
        for name, ts in stages:
            events.append(
                {
                    "name": name, "ph": "X", "pid": pid, "tid": tid,
                    "ts": prev / 1e3, "dur": max(ts - prev, 1) / 1e3,
                }
            )
            prev = ts
    return events


class _NoopRecorder:
    """Shared disabled instance for components built without a
    registry: every method is one attribute check."""

    enabled = False
    flight = None
    exemplars: collections.deque = collections.deque()

    def stage(self, *a, **k) -> None:
        pass

    def stage_h(self, *a, **k) -> None:
        pass

    def stage_many(self, *a, **k) -> None:
        pass

    def finish(self, *a, **k) -> None:
        pass

    def finish_h(self, *a, **k) -> None:
        pass

    def exemplar_snapshot(self) -> list:
        return []


NULL = _NoopRecorder()
