"""Observability spine: typed metrics registry + tracer knobs.

One `Registry` per component (replica, state machine, device engine),
composed into a single tree by the owning process (ReplicaServer) and
rendered three ways from the SAME counters:

- `TB_STATS` log lines (runtime/server.py _print_stats),
- the `stats` wire operation (scrapeable over the TCP bus, obs.scrape),
- bench JSON sections (bench.py reads the scrape, not log tails).

Knobs (validated in envcheck.py):

- ``TB_METRICS=0|1`` — 1 (default) records latency histograms; 0 skips
  the clock reads (counters stay live: logic and bench depend on them).
- ``TB_TRACE=none|json`` — promotes the utils/tracer.py span tracer to
  a first-class backend choice; ``json`` writes a Chrome-trace file per
  process (``TB_TRACE_PATH`` or ``tb_trace_r<i>.json``), mergeable into
  one cross-replica Perfetto timeline by testing/cluster.merge_traces.
"""

from tigerbeetle_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    counts_delta,
    percentile_of_counts,
    stat_property,
)
