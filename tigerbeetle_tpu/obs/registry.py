"""Typed metrics registry: counters, gauges, fixed-bucket histograms.

Design rules (the Blockchain Machine lesson, arXiv:2104.06968: hot-path
accounting lives NEXT to the hot path, not in post-hoc log scraping):

- Handles are plain objects with one hot method (`inc`/`set`/`observe`)
  — a traced site costs one attribute access and one add.
- Every mutation bumps a shared version cell, so idle-dedup (TB_STATS
  printing) and scrape clients compare ONE integer instead of a
  hand-picked tuple that silently goes stale when counters are added.
- Histograms are HDR-style fixed buckets (16 linear sub-buckets per
  power of two, <=12.5% relative width) with EXACT nearest-rank bucket
  selection: `percentile(q)` returns the upper edge of the bucket that
  contains the q-quantile sample, bit-for-bit reproducible against a
  sorted-list oracle quantized by the same `quantize()` (fuzzed in
  tests/test_obs.py).
- Registries compose: `attach(prefix, child)` grafts a component's
  registry into the owner's snapshot under a dotted prefix;
  `gauge_fn(name, fn)` pulls values owned elsewhere (storage fsync
  counts, queue depths) at snapshot time.
- Snapshots are flat `{dotted.name: number}` dicts (histograms expand
  to `.count/.sum/.max/.p50/.p99/.p999`) — JSON-ready for the `stats`
  wire operation, greppable when rendered as a TB_STATS line.
"""

from __future__ import annotations

import math
import time


class Counter:
    """Monotonic counter (floats allowed: wall-time accumulators).

    `inc` is the hot-path method; `set` exists for the compatibility
    properties (benches reset forensics counters between timed arms).
    """

    __slots__ = ("name", "value", "_v")

    def __init__(self, name: str, vcell: list) -> None:
        self.name = name
        self.value = 0
        self._v = vcell

    def inc(self, n=1) -> None:
        self.value += n
        self._v[0] += 1

    def set(self, value) -> None:
        self.value = value
        self._v[0] += 1


class Gauge:
    """Last-write-wins sample (queue depth, window occupancy)."""

    __slots__ = ("name", "value", "_v")

    def __init__(self, name: str, vcell: list) -> None:
        self.name = name
        self.value = 0
        self._v = vcell

    def set(self, value) -> None:
        self.value = value
        self._v[0] += 1


class Histogram:
    """Fixed-bucket latency histogram (HDR layout, sparse storage).

    Values are non-negative numbers; by convention sites record
    MICROSECONDS (names end in `_us`).  Buckets: unit-width below 16,
    then 8 buckets per power of two (width 2^e), so relative bucket
    width is <=12.5% — plenty for latency percentiles — while any
    value up to ~17 minutes in µs needs <260 bucket slots.
    """

    SUB_BITS = 4
    SUBS = 1 << SUB_BITS  # 16

    __slots__ = ("name", "_v", "counts", "count", "total", "max",
                 "unit_scale")

    def __init__(self, name: str, vcell: list, unit_scale: int = 1) -> None:
        self.name = name
        self._v = vcell
        self.counts: dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        # Sub-unit floor widening: values are bucketed at
        # value*unit_scale resolution and percentiles divide back, so
        # a `_us` histogram with unit_scale=16 resolves 1/16-µs steps
        # below 1 µs (sub-µs p50s stop collapsing into bucket 0).
        # count/sum/max stay in raw units.
        self.unit_scale = unit_scale

    # -- bucket arithmetic (static: the oracle test uses these too) ----

    @classmethod
    def bucket_of(cls, value) -> int:
        n = int(value)
        if n < cls.SUBS:
            return n if n > 0 else 0
        e = n.bit_length() - cls.SUB_BITS
        return ((e - 1) << (cls.SUB_BITS - 1)) + (n >> e) + (cls.SUBS >> 1)

    @classmethod
    def upper_of(cls, index: int) -> int:
        """Exclusive upper edge of bucket `index` (the percentile
        representative: every sample in the bucket is < this)."""
        if index < cls.SUBS:
            return index + 1
        half = cls.SUBS >> 1
        e = (index - cls.SUBS) // half + 1
        m = (index - cls.SUBS) % half + half
        return (m + 1) << e

    @classmethod
    def quantize(cls, value) -> int:
        """The bucket representative `value` falls into — what
        `percentile` returns when `value` is the rank sample."""
        return cls.upper_of(cls.bucket_of(value))

    # -- hot path ------------------------------------------------------

    def observe(self, value) -> None:
        idx = self.bucket_of(value * self.unit_scale)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.count += 1
        self.total += value
        if value > self.max:
            self.max = value
        self._v[0] += 1

    def time(self) -> "_Timer":
        """Context manager: observe the elapsed µs of the with-block."""
        return _Timer(self)

    # -- extraction ----------------------------------------------------

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile, exact at bucket resolution: the
        upper edge of the bucket holding sample #ceil(q*count),
        descaled back to raw units."""
        return percentile_of_counts(self.counts, q) / self.unit_scale


def percentile_of_counts(counts: dict, q: float) -> float:
    """Nearest-rank percentile over a raw bucket-count dict (the same
    arithmetic as Histogram.percentile).  Lets callers window a
    monotonic histogram: snapshot `dict(h.counts)` before a timed
    region, subtract after, and extract percentiles of just the
    window — histograms themselves are never reset."""
    total = sum(counts.values())
    if not total:
        return 0.0
    rank = min(total, max(1, math.ceil(q * total)))
    acc = 0
    for idx in sorted(counts):
        acc += counts[idx]
        if acc >= rank:
            return float(Histogram.upper_of(idx))
    raise AssertionError("bucket counts disagree with total")


def counts_delta(after: dict, before: dict) -> dict:
    """Bucket counts accumulated between two `dict(h.counts)` copies."""
    return {
        idx: n - before.get(idx, 0)
        for idx, n in after.items()
        if n - before.get(idx, 0) > 0
    }


class _Timer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram) -> None:
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._hist.observe((time.perf_counter_ns() - self._t0) / 1e3)
        return False


class _NoopTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_TIMER = _NoopTimer()


class _NoopHistogram:
    """TB_METRICS=0 stand-in: a timed hot-path site costs one attribute
    check and a constant return — no clock read, no dict write."""

    __slots__ = ()
    name = "<noop>"
    count = 0
    total = 0.0
    max = 0.0
    counts: dict = {}
    unit_scale = 1

    def observe(self, value) -> None:
        pass

    def time(self) -> _NoopTimer:
        return _NOOP_TIMER

    def percentile(self, q: float) -> float:
        return 0.0


_NOOP_HIST = _NoopHistogram()


class Registry:
    """A component's named instruments + composition into one tree."""

    def __init__(self, enabled: bool | None = None) -> None:
        if enabled is None:
            from tigerbeetle_tpu import envcheck

            enabled = envcheck.metrics_enabled() == 1
        self.enabled = enabled
        self._v = [0]
        self._items: dict[str, object] = {}
        self._pulls: dict[str, object] = {}
        self._children: list[tuple[str, Registry]] = []

    # -- handle creation (idempotent per name) -------------------------

    def _make(self, name: str, cls):
        item = self._items.get(name)
        if item is None:
            item = cls(name, self._v)
            self._items[name] = item
        assert isinstance(item, cls), (
            f"{name} already registered as {type(item).__name__}"
        )
        return item

    def counter(self, name: str) -> Counter:
        return self._make(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._make(name, Gauge)

    def histogram(self, name: str, unit_scale: int = 1):
        """Latency histogram — the no-op instance when TB_METRICS=0
        (its sites then skip the clock reads entirely).  `unit_scale`
        widens the sub-unit floor (see Histogram.unit_scale); every
        registration of a name must agree on it."""
        if not self.enabled:
            return _NOOP_HIST
        item = self._items.get(name)
        if item is None:
            item = Histogram(name, self._v, unit_scale)
            self._items[name] = item
        assert isinstance(item, Histogram), (
            f"{name} already registered as {type(item).__name__}"
        )
        assert item.unit_scale == unit_scale, (
            f"{name} registered with unit_scale {item.unit_scale}, "
            f"re-requested with {unit_scale}"
        )
        return item

    def gauge_fn(self, name: str, fn) -> None:
        """Pull gauge: `fn()` evaluated at snapshot time — for values
        owned elsewhere (storage fsync counts, queue depths)."""
        self._pulls[name] = fn

    def attach(self, prefix: str, child: "Registry") -> None:
        """Graft `child`'s instruments under `prefix.` in snapshots."""
        assert child is not self
        self._children.append((prefix, child))

    def scope(self, prefix: str) -> "Scope":
        """A view that prefixes every name — one shared store, so the
        owner's snapshot covers the scoped component's counters."""
        return Scope(self, prefix)

    # -- reads ---------------------------------------------------------

    def value(self, name: str):
        return self._items[name].value

    def version(self) -> int:
        """Total mutation count (self + attached children): bumps on
        every inc/set/observe, so `snapshot()['version']` equality
        means NOTHING changed — no hand-picked tuples."""
        return self._v[0] + sum(c.version() for _, c in self._children)

    def snapshot(self) -> dict:
        out: dict = {}
        self._collect(out, "")
        out["version"] = self.version()
        return out

    def _collect(self, out: dict, prefix: str) -> None:
        for name, item in self._items.items():
            if isinstance(item, Histogram):
                base = prefix + name
                out[base + ".count"] = item.count
                out[base + ".sum"] = round(item.total, 3)
                out[base + ".max"] = round(item.max, 3)
                out[base + ".p50"] = item.percentile(0.50)
                out[base + ".p99"] = item.percentile(0.99)
                out[base + ".p999"] = item.percentile(0.999)
            else:
                v = item.value
                out[prefix + name] = round(v, 6) if isinstance(v, float) else v
        for name, fn in self._pulls.items():
            out[prefix + name] = fn()
        for cprefix, child in self._children:
            child._collect(out, prefix + cprefix + ".")


class Scope:
    """Prefix view over a Registry (shared store + version cell)."""

    __slots__ = ("_reg", "_prefix")

    def __init__(self, registry: Registry, prefix: str) -> None:
        self._reg = registry
        self._prefix = prefix + "."

    @property
    def enabled(self) -> bool:
        return self._reg.enabled

    def counter(self, name: str) -> Counter:
        return self._reg.counter(self._prefix + name)

    def gauge(self, name: str) -> Gauge:
        return self._reg.gauge(self._prefix + name)

    def histogram(self, name: str, unit_scale: int = 1):
        return self._reg.histogram(self._prefix + name, unit_scale)

    def gauge_fn(self, name: str, fn) -> None:
        self._reg.gauge_fn(self._prefix + name, fn)

    def scope(self, prefix: str) -> "Scope":
        return Scope(self._reg, self._prefix + prefix)


def stat_property(key: str) -> property:
    """Compatibility shim for migrated `stat_*` attributes: reads and
    writes route to the registry handle in `self._stats[key]`, so
    existing `sm.stat_x += n` sites (and bench resets) keep working
    while the canonical value lives in the registry."""

    def fget(self):
        return self._stats[key].value

    def fset(self, value):
        self._stats[key].set(value)

    return property(fget, fset)
