"""Flight recorder: a fixed-size in-memory ring of recent trace events.

The reference leaves tracing on in production because its backend is
cheap; this build's always-on equivalent is a bounded ring
(TB_FLIGHT_RING events) that costs a deque append per event and ZERO
file I/O — until something goes wrong.  The ring is dumped to disk:

- on demotion (the device engine's `device_demoted` instant is a
  trigger event — the dump captures the requests in flight when the
  link died),
- on assertion failure in the server loop (runtime/server.py wraps
  serve_forever),
- on SIGTERM (runtime/server.py installs the handler),
- on demand (`dump()` / `write()`).

The dump is a Chrome-trace JSON (instant events on one process track),
so `testing/cluster.merge_traces` stitches per-replica flight dumps —
or a flight dump plus live tracer dumps — into one Perfetto timeline
for the postmortem.
"""

from __future__ import annotations

import collections
import json
import time

# Event names that trigger an automatic dump when a dump_path is set.
TRIGGER_EVENTS = frozenset({"device_demoted", "assertion_failure"})


class FlightRecorder:
    def __init__(self, capacity: int | None = None, *, process_id: int = 0,
                 dump_path: str | None = None,
                 clock=time.perf_counter_ns, stats_fn=None) -> None:
        if capacity is None:
            from tigerbeetle_tpu import envcheck

            capacity = envcheck.flight_ring()
        assert capacity > 0
        self.capacity = capacity
        self.process_id = process_id
        self.dump_path = dump_path
        self.clock = clock
        # Registry-snapshot provider (owner-wired, e.g. the server's
        # `lambda: registry.snapshot()`): every dump then embeds the
        # counters alongside the event ring, so a demotion postmortem
        # carries the dev_wave.spec.* / link forensics that explain it.
        self.stats_fn = stats_fn
        self._ring: collections.deque[tuple] = collections.deque(
            maxlen=capacity
        )
        self.dropped = 0
        self.dumps = 0

    # -- hot path ------------------------------------------------------

    def note(self, name: str, ts: int | None = None, **args) -> None:
        """Record one event.  Names in TRIGGER_EVENTS flush the ring
        to dump_path immediately (demotion postmortem)."""
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append((ts if ts is not None else self.clock(),
                           name, args or None))
        if self.dump_path and name in TRIGGER_EVENTS:
            self.write(self.dump_path, reason=name)

    # -- output --------------------------------------------------------

    def events(self) -> list[dict]:
        out = []
        for ts, name, args in self._ring:
            ev = {
                "name": name, "ph": "i", "s": "p",
                "pid": self.process_id, "tid": 0, "ts": ts / 1e3,
            }
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def dump(self, reason: str = "on_demand") -> dict:
        other = {
            "flight_recorder": True,
            "reason": reason,
            "dropped_events": self.dropped,
            "capacity": self.capacity,
        }
        if self.stats_fn is not None:
            try:
                other["stats"] = self.stats_fn()
            # tbcheck: allow(broad-except): the dump may run inside a
            # signal handler — a stats-provider failure records its
            # error in place of the snapshot, never voids the
            # postmortem.
            except Exception as exc:
                other["stats_error"] = repr(exc)[:200]
        return {
            "traceEvents": self.events(),
            "otherData": other,
        }

    def write(self, path: str, reason: str = "on_demand") -> None:
        """Atomic-enough dump: write then rename, so a reader never
        sees a half-written file even when the dump runs inside a
        signal handler."""
        self.dumps += 1
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.dump(reason), f)
        import os

        os.replace(tmp, path)
