from tigerbeetle_tpu.ops import u128

__all__ = ["u128"]
