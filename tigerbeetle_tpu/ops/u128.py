"""u128 arithmetic on (lo, hi) uint64 limb pairs for JAX.

TPUs have no native 128-bit integers; all balances/amounts in the wire
format are u128 (reference: src/tigerbeetle.zig:8-12,83). We decompose
into two little-endian uint64 limbs and implement the handful of ops
the state machine needs: add/sub with overflow detection, comparison,
min, and saturating subtraction. No multiplication is ever required.

Requires jax_enable_x64 (enabled in tigerbeetle_tpu.state_machine.kernel).
"""

from __future__ import annotations

import jax.numpy as jnp

# A u128 value is a tuple (lo, hi) of uint64 arrays.
U128 = tuple


def u128(lo, hi) -> U128:
    return (jnp.asarray(lo, jnp.uint64), jnp.asarray(hi, jnp.uint64))


def zeros_like(x: U128) -> U128:
    return (jnp.zeros_like(x[0]), jnp.zeros_like(x[1]))


def add(a: U128, b: U128) -> tuple[U128, jnp.ndarray]:
    """(a + b) mod 2^128 and an overflow flag."""
    lo = a[0] + b[0]
    carry = (lo < a[0]).astype(jnp.uint64)
    hi_partial = a[1] + b[1]
    ov1 = hi_partial < a[1]
    hi = hi_partial + carry
    ov2 = hi < hi_partial
    return (lo, hi), ov1 | ov2


def sub(a: U128, b: U128) -> tuple[U128, jnp.ndarray]:
    """(a - b) mod 2^128 and an underflow (borrow-out) flag."""
    lo = a[0] - b[0]
    borrow = (a[0] < b[0]).astype(jnp.uint64)
    hi = a[1] - b[1] - borrow
    under = (a[1] < b[1]) | ((a[1] == b[1]) & (borrow == 1))
    return (lo, hi), under


def sub_sat(a: U128, b: U128) -> U128:
    """max(a - b, 0) — the reference's `-|` saturating subtraction
    (reference: src/state_machine.zig:1519,1525)."""
    (lo, hi), under = sub(a, b)
    zero = jnp.zeros_like(lo)
    return (jnp.where(under, zero, lo), jnp.where(under, zero, hi))


def eq(a: U128, b: U128) -> jnp.ndarray:
    return (a[0] == b[0]) & (a[1] == b[1])


def ne(a: U128, b: U128) -> jnp.ndarray:
    return ~eq(a, b)


def gt(a: U128, b: U128) -> jnp.ndarray:
    return (a[1] > b[1]) | ((a[1] == b[1]) & (a[0] > b[0]))


def lt(a: U128, b: U128) -> jnp.ndarray:
    return gt(b, a)


def is_zero(a: U128) -> jnp.ndarray:
    return (a[0] == 0) & (a[1] == 0)


def minimum(a: U128, b: U128) -> U128:
    a_gt = gt(a, b)
    return (jnp.where(a_gt, b[0], a[0]), jnp.where(a_gt, b[1], a[1]))


def select(pred, a: U128, b: U128) -> U128:
    """where(pred, a, b) elementwise on limb pairs."""
    return (jnp.where(pred, a[0], b[0]), jnp.where(pred, a[1], b[1]))


# ----------------------------------------------------------------------
# 32-bit limb lanes for wrap-free scatter accumulation: a u128 delta is
# spread over four uint64 lanes each holding a 32-bit limb, so summing
# up to 2^32 deltas cannot wrap a lane; one carry pass recombines.

_MASK32 = jnp.uint64(0xFFFFFFFF)


def limbs32(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """(K,) u128 limb pair -> (K, 4) little-endian 32-bit limbs."""
    return jnp.stack([lo & _MASK32, lo >> 32, hi & _MASK32, hi >> 32], axis=-1)


def from_limbs32(acc: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(..., 4) limb sums -> (lo, hi, carry_out); sum taken mod 2^128."""
    c0 = acc[..., 0]
    c1 = acc[..., 1] + (c0 >> 32)
    c2 = acc[..., 2] + (c1 >> 32)
    c3 = acc[..., 3] + (c2 >> 32)
    lo = (c0 & _MASK32) | ((c1 & _MASK32) << 32)
    hi = (c2 & _MASK32) | ((c3 & _MASK32) << 32)
    return lo, hi, c3 >> 32
