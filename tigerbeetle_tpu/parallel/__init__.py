from tigerbeetle_tpu.parallel.sharded import (  # noqa: F401
    build_apply_step,
    make_mesh,
)
