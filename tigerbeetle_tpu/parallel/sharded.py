"""Multi-chip sharded balance apply (SPMD over a jax.sharding.Mesh).

The reference's replication exists for fault tolerance, not throughput
— commit execution is single-core by design (reference:
docs/about/performance.md:66-78).  The TPU build keeps those commit
semantics but adds a genuinely parallel device path for the subset
that dominates real workloads: order-free `create_transfers` batches
(the same admission conditions as the single-chip fast path, see
tpu.py `_commit_fast`).

Sharding design (scaling-book style — pick a mesh, annotate, let XLA
insert collectives):

- Mesh axes ``("dp", "shard")``.
- The account-balance table — the only mutable device state
  (reference: src/tigerbeetle.zig:7-29) — is sharded **row-wise over
  "shard"** and replicated over "dp".  This is the tensor-parallel
  analog: state partitioning.
- Event batches are sharded **over "dp"**: each dp group ingests a
  slice of the batch.  This is the data-parallel analog.

One step, inside `shard_map`:

1. every (dp, shard) device accumulates candidate u128 deltas from its
   local event slice onto the rows it owns (32-bit limb lanes so sums
   cannot wrap — same trick as kernel_fast.py);
2. ``psum`` over **dp** combines the whole batch's deltas per row;
3. per-row overflow predicates are computed locally, folded back to
   per-event reject masks, and ``psum``-ed over **shard** so every
   device agrees on admission (conservative row-granularity check:
   a row that would overflow rejects all events touching it, which the
   host then routes through the exact single-chip scan kernel —
   mirroring the mirror-admission fallback in tpu.py);
4. admitted deltas are re-accumulated and applied to the local rows.
   Removing events only shrinks row sums, so admitted sums cannot
   overflow.

Collectives (all_gather-and-sum over "dp", all_gather-any over
"shard") ride ICI; no host round-trips inside the step.  u64
all-reduce doesn't lower on TPU, so exact sums are done locally after
gathering.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tigerbeetle_tpu.ops import u128 as w

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# Delta-column layout: 4 u128 columns per account row.
# debits_pending, debits_posted, credits_pending, credits_posted.
COL_DP, COL_DPO, COL_CP, COL_CPO = range(4)


def shard_map_kwargs() -> dict:
    """Version-compat kwargs disabling shard_map's replication check
    (its name moved check_rep -> check_vma across jax releases).  The
    SPMD bodies here compute replicated outputs deterministically from
    replicated inputs, which the checker cannot prove."""
    import inspect

    params = inspect.signature(shard_map).parameters
    if "check_vma" in params:
        return {"check_vma": False}
    if "check_rep" in params:
        return {"check_rep": False}
    return {}


def make_row_mesh(devices=None) -> Mesh:
    """1-D ("shard",) mesh over `devices` — the row-sharding axis the
    device engine's authoritative tables (and the sharded wave
    executors, waves.py) partition over."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), ("shard",))


def own_rows(slots, local_rows: int, axis: str = "shard"):
    """Row-ownership arithmetic INSIDE a shard_map body, the ONE
    definition of the contiguous row layout: for global row indices
    `slots`, returns (local, rel) — the mask of rows this shard owns
    and their clipped shard-local indices.  Readers (gather_rows) and
    writers (waves._ShardTableOps) both resolve ownership here, so
    they can never disagree about the layout."""
    row0 = (lax.axis_index(axis) * local_rows).astype(slots.dtype)
    local = (slots >= row0) & (slots < row0 + local_rows)
    rel = jnp.clip(slots - row0, 0, local_rows - 1)
    return local, rel


def gather_rows(local_table, slots, local_rows: int, axis: str = "shard"):
    """Cross-shard row gather INSIDE a shard_map body: every device
    gets the full (K, W) rows for global row indices `slots` (already
    clipped to [0, total_rows)).  Each shard contributes the rows it
    owns and zeros elsewhere; an all_gather + sum over `axis` (pure
    data movement over ICI — u64 all-REDUCE doesn't lower on TPU)
    recombines them exactly, since each row has exactly one owner."""
    local, rel = own_rows(slots, local_rows, axis)
    part = jnp.where(local[:, None], local_table[rel], 0)
    return lax.all_gather(part, axis).sum(axis=0)


def make_mesh(devices=None, dp: int | None = None) -> Mesh:
    """Mesh over `devices` shaped (dp, shard).

    Defaults: dp=2 when the device count allows (so both axes are
    exercised), else a pure "shard" mesh.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        dp = 2 if n % 2 == 0 and n >= 4 else 1
    assert n % dp == 0, (n, dp)
    grid = np.asarray(devices).reshape(dp, n // dp)
    return Mesh(grid, ("dp", "shard"))


def _accumulate(local_rows, row0, dr_slot, cr_slot, amount_lo, amount_hi,
                is_pending, mask):
    """Masked local-row limb accumulation of one event slice.

    Returns (local_rows, 4, 4) uint64 limb sums.  Non-local or masked
    events contribute zero (amounts zeroed, row clipped).
    """
    acc = jnp.zeros((local_rows, 4, 4), jnp.uint64)
    for slot, col_p, col_posted in (
        (dr_slot, COL_DP, COL_DPO),
        (cr_slot, COL_CP, COL_CPO),
    ):
        local = mask & (slot >= row0) & (slot < row0 + local_rows)
        row = jnp.clip(slot - row0, 0, local_rows - 1)
        col = jnp.where(is_pending, col_p, col_posted)
        lo = jnp.where(local, amount_lo, 0)
        hi = jnp.where(local, amount_hi, 0)
        acc = acc.at[row, col].add(w.limbs32(lo, hi))
    return acc


def build_apply_step(mesh: Mesh, table_rows: int):
    """Jitted sharded apply: (balances, events...) -> (balances, admitted).

    `balances` is (table_rows, 8) uint64 sharded P("shard", None);
    event arrays are (E,) sharded P("dp").  Returns the updated table
    (same sharding) and the per-event admitted mask (dp-sharded).
    """
    n_shard = mesh.shape["shard"]
    assert table_rows % n_shard == 0, (table_rows, n_shard)
    local_rows = table_rows // n_shard

    def local_step(balances, dr_slot, cr_slot, amount_lo, amount_hi, is_pending):
        shard_id = lax.axis_index("shard")
        row0 = (shard_id * local_rows).astype(dr_slot.dtype)
        ones = jnp.ones_like(dr_slot, bool)

        # 1-2. Candidate deltas for local rows, combined across dp.
        # u64 all-reduce doesn't lower on TPU, so combine as
        # all_gather (pure data movement over ICI) + exact local sum.
        def combine_dp(acc):
            return lax.all_gather(acc, "dp").sum(axis=0)

        acc = _accumulate(
            local_rows, row0, dr_slot, cr_slot, amount_lo, amount_hi,
            is_pending, ones,
        )
        acc = combine_dp(acc)
        d_lo, d_hi, d_carry = w.from_limbs32(acc)  # (local_rows, 4)

        # 3. Per-row overflow -> per-event reject, agreed across shards.
        old_lo = balances[:, 0::2]
        old_hi = balances[:, 1::2]
        (_, _), carry = w.add((old_lo, old_hi), (d_lo, d_hi))
        row_over = (carry | (d_carry != 0)).any(axis=1)  # (local_rows,)

        reject = jnp.zeros_like(dr_slot, bool)
        for slot in (dr_slot, cr_slot):
            local = (slot >= row0) & (slot < row0 + local_rows)
            row = jnp.clip(slot - row0, 0, local_rows - 1)
            reject |= local & row_over[row]
        reject = lax.all_gather(reject, "shard").any(axis=0)
        # Out-of-range slots belong to no shard: their deltas were
        # dropped above, so they must never read as admitted.
        for slot in (dr_slot, cr_slot):
            reject |= (slot < 0) | (slot >= n_shard * local_rows)
        admitted = ~reject

        # 4. Apply admitted deltas (monotone: subset sums cannot overflow).
        acc = _accumulate(
            local_rows, row0, dr_slot, cr_slot, amount_lo, amount_hi,
            is_pending, admitted,
        )
        acc = combine_dp(acc)
        a_lo, a_hi, _ = w.from_limbs32(acc)
        (new_lo, new_hi), _ = w.add((old_lo, old_hi), (a_lo, a_hi))
        new_balances = jnp.stack(
            [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
             new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]],
            axis=-1,
        )
        return new_balances, admitted

    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P("shard", None), P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
        out_specs=(P("shard", None), P("dp")),
        **shard_map_kwargs(),
    )
    return jax.jit(step, donate_argnums=(0,))


def shard_balances(mesh: Mesh, balances: np.ndarray):
    """Place a host balance table onto the mesh with the step's sharding."""
    return jax.device_put(
        jnp.asarray(balances), NamedSharding(mesh, P("shard", None))
    )


def shard_events(mesh: Mesh, *arrays):
    dp = mesh.shape["dp"]
    for a in arrays:
        assert len(a) % dp == 0, (len(a), dp)
    sharding = NamedSharding(mesh, P("dp"))
    return tuple(jax.device_put(jnp.asarray(a), sharding) for a in arrays)
