"""Bespoke CLI flag parser (no argparse), mirroring the reference's
src/flags.zig: `--flag=value` syntax only, typed by a spec dict,
`fatal()` on any error."""
# tbcheck: allow-file(no-print): flag errors go to stderr by
# contract (reference: src/flags.zig fatal()).

from __future__ import annotations

import sys


def fatal(message: str) -> "NoReturn":  # noqa: F821
    print(f"error: {message}", file=sys.stderr)
    raise SystemExit(1)


def parse(args: list[str], spec: dict[str, object]) -> tuple[dict, list[str]]:
    """`spec`: flag name -> default (type inferred; None means required
    string; bool flags accept bare `--flag`).  Returns (flags,
    positionals)."""
    out = {k: v for k, v in spec.items()}
    required = {k for k, v in spec.items() if v is None}
    positionals: list[str] = []
    for arg in args:
        if not arg.startswith("--"):
            positionals.append(arg)
            continue
        name, eq, value = arg[2:].partition("=")
        key = name.replace("-", "_")
        if key not in spec:
            fatal(f"unknown flag --{name}")
        default = spec[key]
        if isinstance(default, bool):
            out[key] = value.lower() not in ("false", "0") if eq else True
        elif isinstance(default, int):
            if not eq:
                fatal(f"--{name} requires a value")
            try:
                out[key] = int(value, 0)
            except ValueError:
                fatal(f"--{name}: invalid integer {value!r}")
        else:
            if not eq:
                fatal(f"--{name} requires a value")
            out[key] = value
        required.discard(key)
    for key in sorted(required):
        fatal(f"--{key.replace('_', '-')} is required")
    return out, positionals
