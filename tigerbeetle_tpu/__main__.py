from tigerbeetle_tpu.cli import main

main()
