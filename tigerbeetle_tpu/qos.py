"""Multi-tenant QoS primitives: token buckets, weighted-fair pick,
observed-rate windows.

The tenant key is the LEDGER (types.py `ledger` field): production
overload is never uniform, and the reference bounds every resource per
client session (reference: src/vsr/replica.zig client_sessions /
client_busy).  This build keys admission, scheduling, and shedding one
level up — per tenant — so one hot ledger cannot starve the rest.

Three primitives, shared by the replica's request queue
(vsr/multi.py), the router's admission + retry sweep
(runtime/router.py), and the bench graders:

- `TokenBucket`: classic rate limiter, refilled from a monotonic
  clock the CALLER supplies (deterministic in simulators, wall-clock
  in servers).
- `WeightedFair`: smooth weighted round-robin (the nginx algorithm):
  each pick raises every active tenant's credit by its weight, the
  richest tenant wins and pays the total back.  Starvation-free by
  construction — a tenant with weight w among total weight W is
  picked at least once every ceil(W/w) picks (its credit grows by w
  per pick and only the winner ever pays) — and deterministic: ties
  break on the lowest tenant id.
- `RateWindow`: per-tenant arrivals-per-second observation, carried
  back to the shed tenant inside the typed `client_busy` payload so a
  well-behaved client can see WHY it was shed.

Admission and scheduling state is plain Python with no RNG and no
wall-clock reads of its own: the deterministic simulators drive them
with tick-derived clocks and stay byte-reproducible.  (The one
exception is TenantQos.on_reply, which reads the real clock — it
feeds only the observability histograms, never admission or
scheduling decisions, so sim state stays byte-reproducible.)
"""

from __future__ import annotations


class TokenBucket:
    """Token bucket: `rate` tokens/second, capacity `burst` tokens.

    rate <= 0 disables the bucket (admit always) — the default, so
    QoS-on under non-overload stays bit-identical to QoS-off.
    """

    __slots__ = ("rate", "burst", "tokens", "last_ns")

    def __init__(self, rate: float, burst: float | None = None) -> None:
        self.rate = float(rate)
        # Default burst: one second's worth (and never < 1 token, or a
        # positive rate could never admit anything).
        self.burst = max(1.0, float(burst if burst is not None else rate))
        self.tokens = self.burst
        self.last_ns = 0

    def admit(self, now_ns: int, cost: float = 1.0) -> bool:
        """Take `cost` tokens if available.  `now_ns` must be
        monotonic non-decreasing (caller-supplied clock)."""
        if self.peek(now_ns, cost):
            self.take(cost)
            return True
        return False

    def peek(self, now_ns: int, cost: float = 1.0) -> bool:
        """Refill, then check WITHOUT consuming — the two-bucket
        admission (request count AND body bytes) must be atomic: a
        request one bucket refuses must not drain the other (the same
        no-unrefunded-charge rule as the global-bound-first check in
        VsrReplica._enqueue_request)."""
        if self.rate <= 0.0:
            return True
        if now_ns > self.last_ns:
            self.tokens = min(
                self.burst,
                self.tokens + (now_ns - self.last_ns) * 1e-9 * self.rate,
            )
            self.last_ns = now_ns
        return self.tokens >= cost

    def take(self, cost: float = 1.0) -> None:
        """Consume after a successful peek (no refill: peek just
        refilled at the same clock reading)."""
        if self.rate > 0.0:
            self.tokens -= cost


class WeightedFair:
    """Smooth weighted round-robin over a dynamic tenant set.

    `pick(active)` returns the next tenant to serve from `active` (an
    iterable of tenant ids with queued work).  Credit of tenants that
    leave the active set is dropped IMMEDIATELY (see _prune: an idle
    tenant must not hoard credit toward a post-idle burst), so the
    credit map never outgrows the set of tenants concurrently active
    — the proportional-share guarantee holds among continuously
    backlogged tenants; a tenant whose queue empties re-enters at
    zero credit.
    """

    __slots__ = ("weights", "_credit")

    def __init__(self, weights: dict[int, float] | None = None) -> None:
        self.weights = dict(weights or {})
        self._credit: dict[int, float] = {}

    def weight_of(self, tenant: int) -> float:
        w = self.weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def pick(self, active) -> int | None:
        tenants = sorted(set(active))
        if not tenants:
            return None
        if len(tenants) == 1:
            self._prune(active={tenants[0]})
            return tenants[0]
        total = 0.0
        best = None
        # tbcheck: allow(money): WRR scheduling credits are weights,
        # not balances — float by design, never touch u128 amounts.
        best_credit = 0.0
        for t in tenants:
            w = self.weight_of(t)
            total += w
            # tbcheck: allow(money): same scheduler credit accumulator.
            c = self._credit.get(t, 0.0) + w
            self._credit[t] = c
            # Deterministic tie-break: sorted iteration + strict `>`
            # keeps the lowest tenant id when credits tie.
            if best is None or c > best_credit:
                best, best_credit = t, c
        self._credit[best] = best_credit - total
        self._prune(active=set(tenants))
        return best

    def _prune(self, active: set[int]) -> None:
        """Drop credit for tenants with no queued work: an idle
        tenant must not hoard credit (a burst after a long absence
        would then monopolize the drain), and the credit map must
        never outgrow the set of tenants concurrently active."""
        dead = [t for t in self._credit if t not in active]
        for t in dead:
            del self._credit[t]


class RateWindow:
    """Arrivals/second over a rolling one-second window, per tenant.

    `observe(tenant, now_ns)` counts one arrival; `rate(tenant)`
    returns the last COMPLETED window's count (the current partial
    window would under-report early in a second).  Bounded: windows
    are two integers per tenant, pruned with the tenant map.
    """

    WINDOW_NS = 1_000_000_000

    __slots__ = ("_win", "cap")

    def __init__(self, cap: int | None = None) -> None:
        # tenant -> [window_start_ns, count_in_window, last_full_count]
        self._win: dict[int, list] = {}
        # Distinct-tenant bound: observe() runs for EVERY arrival —
        # including in the default rate=0 config, where the bucket
        # eviction path (the only other pruner) never fires — so an
        # uncapped map would let a tenant-id sweep grow server memory
        # without bound.
        self.cap = cap

    def observe(self, tenant: int, now_ns: int) -> None:
        w = self._win.get(tenant)
        if w is None:
            if self.cap is not None and len(self._win) >= self.cap:
                # Evict the stalest window (the tenant least recently
                # re-anchored — its rate figure is the most stale).
                del self._win[min(self._win, key=lambda t: self._win[t][0])]
            self._win[tenant] = [now_ns, 1, 0]
            return
        elapsed = now_ns - w[0]
        if elapsed >= self.WINDOW_NS:
            # Scale the finished window to a per-second figure when it
            # ran long (idle gaps must not inflate the rate).
            w[2] = int(w[1] * self.WINDOW_NS / max(elapsed, 1))
            w[0] = now_ns
            w[1] = 1
        else:
            w[1] += 1

    def rate(self, tenant: int) -> int:
        w = self._win.get(tenant)
        return 0 if w is None else int(w[2])

    def drop(self, tenant: int) -> None:
        self._win.pop(tenant, None)


class TenantQos:
    """Per-tenant admission + scheduling + accounting for one process
    (a replica's request queue or the router's open-request table).

    Bundles the three primitives and the per-tenant obs counters:

    - `admit(tenant, now_ns, queued)`: token bucket + per-tenant queue
      bound; False = shed (the caller sends the typed busy carrying
      `rate_of(tenant)`).
    - `pick(active)`: weighted-fair choice of the next tenant to
      drain.
    - per-tenant counters/histograms under `t<tenant>.` in the given
      registry scope (admit / shed / lat_us with p50/p99 extracted at
      snapshot) — scraped by the stats wire op like every other
      instrument.  Distinct tracked tenants are bounded
      (TENANTS_MAX); overflow tenants share the `tother.` scope so a
      tenant-id sweep cannot grow the registry without bound.
    """

    TENANTS_MAX = 64

    def __init__(self, *, rate: float = 0.0, rate_bytes: float = 0.0,
                 queue_bound: int = 0,
                 weights: dict[int, float] | None = None,
                 registry=None) -> None:
        self.rate = float(rate)
        # Byte accounting (round 19, TB_TENANT_RATE_BYTES): a second
        # bucket charged by request BODY BYTES, so mixed-size batches
        # cannot cheat the request-count bucket (one 8k-event batch
        # and one single-event request cost the same count token but
        # ~8000x the decode/replay work).  0 = off.
        self.rate_bytes = float(rate_bytes)
        self.queue_bound = int(queue_bound)
        self.wfq = WeightedFair(weights)
        self.window = RateWindow(cap=self.TENANTS_MAX)
        self._buckets: dict[int, TokenBucket] = {}
        self._byte_buckets: dict[int, TokenBucket] = {}
        self._registry = registry
        self._metrics: dict[int, tuple] = {}
        self.sheds = 0
        self.admits = 0

    # -- admission -----------------------------------------------------

    def observe(self, tenant: int, now_ns: int) -> None:
        """Count one arrival toward the tenant's observed rate —
        BEFORE admission, so the rate in the busy payload reflects the
        tenant's offered load, not just what survived the bucket."""
        self.window.observe(tenant, now_ns)

    def _bucket(self, store: dict, rate: float, tenant: int,
                now_ns: int) -> TokenBucket:
        bucket = store.get(tenant)
        if bucket is None:
            if len(store) >= self.TENANTS_MAX:
                # Bounded state WITHOUT eviction: tenants beyond
                # the cap share ONE overflow bucket (key -1, the
                # `tother` pattern).  Evicting + re-creating
                # instead would hand every returning tenant a
                # fresh full burst — the tenant key is
                # client-controlled (header stamp / body ledger),
                # so an id sweep could cycle a hot tenant through
                # eviction and sustain far above its configured
                # rate.  Sharing under-admits the sweep: the safe
                # direction for overload protection.
                bucket = store.get(-1)
                if bucket is not None:
                    return bucket
                tenant = -1
            bucket = TokenBucket(rate)
            bucket.last_ns = now_ns
            store[tenant] = bucket
        return bucket

    def admit(self, tenant: int, now_ns: int, queued: int,
              body_bytes: int = 0) -> bool:
        """True = enqueue; False = shed.  `queued` is the tenant's
        current queue depth (owned by the caller's queue);
        `body_bytes` charges the byte bucket when TB_TENANT_RATE_BYTES
        is configured.  Charging is ATOMIC across the two buckets:
        both are checked before either is drained, so a shed never
        leaves a half-charge behind."""
        if self.queue_bound > 0 and queued >= self.queue_bound:
            return False
        count_bucket = byte_bucket = None
        if self.rate > 0.0:
            count_bucket = self._bucket(
                self._buckets, self.rate, tenant, now_ns
            )
            if not count_bucket.peek(now_ns):
                return False
        if self.rate_bytes > 0.0:
            byte_bucket = self._bucket(
                self._byte_buckets, self.rate_bytes, tenant, now_ns
            )
            if not byte_bucket.peek(now_ns, float(body_bytes)):
                return False
        if count_bucket is not None:
            count_bucket.take()
        if byte_bucket is not None:
            byte_bucket.take(float(body_bytes))
        return True

    def rate_of(self, tenant: int) -> int:
        return self.window.rate(tenant)

    # -- scheduling ----------------------------------------------------

    def pick(self, active) -> int | None:
        return self.wfq.pick(active)

    # -- accounting ----------------------------------------------------

    def _m(self, tenant: int):
        m = self._metrics.get(tenant)
        if m is None:
            if self._registry is None:
                return None
            if len(self._metrics) >= self.TENANTS_MAX:
                tenant = -1  # shared overflow scope ("tother.")
                m = self._metrics.get(tenant)
                if m is not None:
                    return m
            name = "tother" if tenant == -1 else f"t{tenant}"
            m = (
                self._registry.counter(f"{name}.admit"),
                self._registry.counter(f"{name}.shed"),
                self._registry.histogram(f"{name}.lat_us"),
            )
            self._metrics[tenant] = m
        return m

    def on_admit(self, tenant: int) -> None:
        self.admits += 1
        m = self._m(tenant)
        if m is not None:
            m[0].inc()

    def on_shed(self, tenant: int) -> None:
        self.sheds += 1
        m = self._m(tenant)
        if m is not None:
            m[1].inc()

    def on_reply(self, tenant: int, header) -> None:
        """Per-tenant reply latency, measured from the wire trace
        context's client-submit timestamp (sampled requests only —
        the same origin the anatomy recorder uses)."""
        m = self._m(tenant)
        if m is None:
            return
        import time

        from tigerbeetle_tpu.vsr import wire

        if wire.trace_sampled(header):
            origin = int(header["trace_ts"])
            if origin:
                m[2].observe(
                    max(0.0, (time.perf_counter_ns() - origin) / 1e3)
                )


BUSY_BACKOFF_CAP = 16  # max multiple of the base backoff


def backoff_delay(client_id: int, request: int, streak: int,
                  base: int, cap: int = BUSY_BACKOFF_CAP) -> int:
    """Busy-backoff delay in units of `base` (ns for the TCP client,
    sim ticks for SimClient): base * 2^(streak-1) capped at `cap`
    multiples, plus jitter that is a pure function of
    (client, request, streak) — deterministic under seeded drivers,
    yet de-synchronized across a fleet of shed clients so their
    retransmits don't re-converge on one instant.  ONE formula shared
    by both clients: the sim client exists to model the production
    one, and two hand-maintained copies would drift."""
    mult = min(1 << (streak - 1), cap)
    jitter = (client_id * 1000003 + request * 10007 + streak * 101) % base
    return base * mult + jitter


def parse_weights(raw: str) -> dict[int, float]:
    """TB_TENANT_WEIGHTS syntax: "ledger:weight,ledger:weight"
    (e.g. "1:4,7:2").  Unlisted tenants weigh 1.  Raises ValueError on
    malformed entries — envcheck wraps this into its fail-fast error.
    """
    out: dict[int, float] = {}
    raw = raw.strip()
    if not raw:
        return out
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        tenant_s, _, weight_s = entry.partition(":")
        tenant = int(tenant_s)
        weight = float(weight_s) if weight_s else 1.0
        if tenant < 0:
            raise ValueError(f"tenant {tenant} must be >= 0")
        if not weight > 0:
            raise ValueError(f"weight for tenant {tenant} must be > 0")
        out[tenant] = weight
    return out
