"""Python client: typed API over the native C-ABI session.

The role of the reference's language clients (reference:
src/clients/python would be the analog; all funnel through the
tb_client C ABI — src/clients/c/tb_client.zig:1-142).  Batches are
encoded straight into the 128-byte wire layouts (numpy structured
arrays), so the bytes this client sends are exactly what the state
machine kernel consumes — the zero-copy "batch encoder feeds the
device" path.
"""

from __future__ import annotations

import time

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime.native import NativeClient
from tigerbeetle_tpu.types import (
    ACCOUNT_BALANCE_DTYPE,
    ACCOUNT_DTYPE,
    ACCOUNT_FILTER_DTYPE,
    CREATE_RESULT_DTYPE,
    TRANSFER_DTYPE,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
)


class Client:
    """Synchronous client for one cluster address.

    >>> c = Client("127.0.0.1:3001", cluster_id=0)
    >>> c.create_accounts([{"id": 1, "ledger": 1, "code": 1}])
    []
    """

    def __init__(self, address: str, cluster_id: int = 0, *,
                 client_id: int | None = None, timeout_ms: int = 10_000) -> None:
        # `address` may be a comma-separated cluster list: the first is
        # the initial target; retransmissions rotate through the rest
        # so view changes recover (reference: src/vsr/client.zig).
        addrs = address.split(",")
        host, _, port = addrs[0].rpartition(":")
        if client_id is None:
            client_id = int.from_bytes(__import__("os").urandom(8), "little") | 1
        self._native = NativeClient(
            host or "127.0.0.1", int(port), cluster_id, client_id
        )
        for extra in addrs[1:]:
            h, _, p = extra.rpartition(":")
            self._native.add_address(h or "127.0.0.1", int(p))
        self.timeout_ms = timeout_ms

    def close(self) -> None:
        self._native.close()

    # ------------------------------------------------------------------

    def _rows(self, dtype: np.dtype, events, u128_fields) -> bytes:
        arr = np.zeros(len(events), dtype=dtype)
        for i, ev in enumerate(events):
            if isinstance(ev, np.void):
                arr[i] = ev
                continue
            for key, value in ev.items():
                if key in u128_fields:
                    types.u128_set(arr[i], key, value)
                else:
                    arr[i][key] = value
        return arr.tobytes()

    def create_accounts(self, accounts) -> list[tuple[int, CreateAccountResult]]:
        body = self._rows(
            ACCOUNT_DTYPE, accounts,
            {"id", "debits_pending", "debits_posted", "credits_pending",
             "credits_posted", "user_data_128"},
        )
        reply = self._native.request(
            Operation.create_accounts, body, self.timeout_ms
        )
        out = np.frombuffer(reply, CREATE_RESULT_DTYPE)
        return [
            (int(r["index"]), CreateAccountResult(int(r["result"]))) for r in out
        ]

    def create_transfers(self, transfers) -> list[tuple[int, CreateTransferResult]]:
        body = self._rows(
            TRANSFER_DTYPE, transfers,
            {"id", "debit_account_id", "credit_account_id", "amount",
             "pending_id", "user_data_128"},
        )
        reply = self._native.request(
            Operation.create_transfers, body, self.timeout_ms
        )
        out = np.frombuffer(reply, CREATE_RESULT_DTYPE)
        return [
            (int(r["index"]), CreateTransferResult(int(r["result"]))) for r in out
        ]

    def _ids(self, ids) -> bytes:
        arr = np.zeros(len(ids), types.U128_PAIR_DTYPE)
        for i, v in enumerate(ids):
            arr[i]["lo"] = v & types.U64_MAX
            arr[i]["hi"] = v >> 64
        return arr.tobytes()

    def lookup_accounts(self, ids) -> np.ndarray:
        reply = self._native.request(
            Operation.lookup_accounts, self._ids(ids), self.timeout_ms
        )
        return np.frombuffer(reply, ACCOUNT_DTYPE)

    def lookup_transfers(self, ids) -> np.ndarray:
        reply = self._native.request(
            Operation.lookup_transfers, self._ids(ids), self.timeout_ms
        )
        return np.frombuffer(reply, TRANSFER_DTYPE)

    def _filter(self, account_id: int, *, timestamp_min=0, timestamp_max=0,
                limit=8190, flags=types.AccountFilterFlags.debits
                | types.AccountFilterFlags.credits) -> bytes:
        row = np.zeros(1, ACCOUNT_FILTER_DTYPE)[0]
        types.u128_set(row, "account_id", account_id)
        row["timestamp_min"] = timestamp_min
        row["timestamp_max"] = timestamp_max
        row["limit"] = limit
        row["flags"] = flags
        return row.tobytes()

    def get_account_transfers(self, account_id: int, **kw) -> np.ndarray:
        reply = self._native.request(
            Operation.get_account_transfers, self._filter(account_id, **kw),
            self.timeout_ms,
        )
        return np.frombuffer(reply, TRANSFER_DTYPE)

    def get_account_balances(self, account_id: int, **kw) -> np.ndarray:
        reply = self._native.request(
            Operation.get_account_balances, self._filter(account_id, **kw),
            self.timeout_ms,
        )
        return np.frombuffer(reply, ACCOUNT_BALANCE_DTYPE)


class OpenLoopSession:
    """Open-loop wire client: MANY requests in flight on one session.

    The synchronous `Client` is closed-loop (one request blocks until
    its reply) — it cannot generate the arrival pressure production
    traffic has.  This client submits without waiting: `submit()`
    stamps a wire trace context (trace_id + origin CLOCK_MONOTONIC ns
    + sampled flag, vsr/wire.py) and returns immediately; `poll()`
    drains completions — `reply` (committed) or `busy` (typed
    admission shed, Command.client_busy) — each with client-measured
    latency.  bench.py's --open-loop mode and the overload smoke test
    drive it.
    """

    BUSY_RETRIES_MAX = 6  # then the busy surfaces as a completion

    def __init__(self, address: str, cluster: int, client_id: int, *,
                 register_timeout_ms: int = 30_000) -> None:
        from tigerbeetle_tpu import envcheck
        from tigerbeetle_tpu.constants import HEADER_SIZE
        from tigerbeetle_tpu.runtime.native import EV_MESSAGE, NativeBus
        from tigerbeetle_tpu.vsr import wire

        self._wire = wire
        self._hs = HEADER_SIZE
        self._ev_message = EV_MESSAGE
        self.cluster = cluster
        self.id = client_id
        self.request_number = 0
        # request number -> (submit perf_counter_ns, operation, frame
        # bytes) — the frame is kept so a typed busy can be
        # retransmitted verbatim after backoff (same request number:
        # it is a RETRANSMIT, so the at-most-once gate still applies).
        self.inflight: dict[int, tuple[int, int, bytes]] = {}
        # (request_number, kind "reply"|"busy", latency_s, reply_body,
        #  operation, tier) — the operation rides along so a mixed-op
        # driver (the read-heavy open-loop bench) can grade reads and
        # writes separately; `tier` records WHO served the completion
        # (round 19): ("primary"|"follower", server id, claimed
        # commit_min, attested root bytes) — zero/empty for primary
        # replies, so the bench's write-p99-flat grade can attribute
        # interference and a client can verify follower attestations.
        self.completed: list[tuple[int, str, float, bytes, int, tuple]] = []
        self.busy_replies = 0
        # Busy backoff (TB_BUSY_BACKOFF_MS; round 16): a shed request
        # retransmits after base * 2^(streak-1) ms (capped 16x) plus
        # deterministic seeded jitter instead of completing
        # immediately — immediate retransmit re-offers the overload
        # that shed it and self-amplifies the storm.  0 disables
        # (busy surfaces as a completion at once, the legacy shape).
        self.busy_backoffs = 0
        self._backoff_base_ns = int(envcheck.busy_backoff_ms() * 1e6)
        self._busy_streak: dict[int, int] = {}   # request -> streak
        self._retry_at: dict[int, int] = {}      # request -> due ns
        host, _, port = address.rpartition(":")
        self.bus = NativeBus()
        self.conn = self.bus.connect(host or "127.0.0.1", int(port))
        self._register(register_timeout_ms)

    def _register(self, timeout_ms: int) -> None:
        wire = self._wire
        h = wire.make_header(
            command=wire.Command.request,
            operation=wire.VsrOperation.register,
            cluster=self.cluster, client=self.id, request=0,
        )
        wire.finalize_header(h, b"")
        deadline = time.monotonic() + timeout_ms / 1e3
        last_sent = 0.0
        while time.monotonic() < deadline:
            if time.monotonic() - last_sent >= 1.0:
                last_sent = time.monotonic()
                self.bus.send(self.conn, h.tobytes())
            for ev_type, _conn, payload in self.bus.poll(50):
                if ev_type != self._ev_message or len(payload) < self._hs:
                    continue
                rh = wire.header_from_bytes(payload[: self._hs])
                if not wire.verify_header(rh, payload[self._hs:]):
                    continue
                if int(rh["command"]) == int(wire.Command.reply) and (
                    int(rh["operation"]) == int(wire.VsrOperation.register)
                ):
                    return
        raise TimeoutError(f"open-loop register of client {self.id:#x}")

    def submit(self, operation, body: bytes, *, tenant: int = 0) -> int:
        """Fire one request (no waiting).  Returns its request number;
        the completion arrives via poll().  `tenant` stamps the wire
        tenant key (0 = legacy: the server derives it from the body's
        leading event)."""
        wire = self._wire
        self.request_number += 1
        now = time.perf_counter_ns()
        h = wire.make_header(
            command=wire.Command.request, operation=operation,
            cluster=self.cluster, client=self.id,
            request=self.request_number,
            tenant=tenant,
            trace_id=((self.id << 20) ^ self.request_number)
            & 0xFFFFFFFFFFFFFFFF,
            trace_ts=now,
            trace_flags=wire.TRACE_SAMPLED,
        )
        wire.finalize_header(h, body)
        frame = h.tobytes() + body
        self.inflight[self.request_number] = (now, int(operation), frame)
        self.bus.send(self.conn, frame)
        return self.request_number

    def poll(self, timeout_ms: int = 0) -> None:
        """Drain completions into `self.completed` — through the same
        columnar batch verify/decode the server drain uses (one arena
        copy + one checksum pass per poll) when the native bus
        supports it; per-frame otherwise."""
        wire = self._wire
        batch = self.bus.poll_drain(timeout_ms)
        if batch is None:
            for ev_type, _conn, payload in self.bus.poll(timeout_ms):
                if ev_type != self._ev_message or len(payload) < self._hs:
                    continue
                h = wire.header_from_bytes(payload[: self._hs])
                body = payload[self._hs:]
                if not wire.verify_header(h, body):
                    continue
                self._complete(h, bytes(body))
            self._flush_backoff(time.perf_counter_ns())
            return
        import numpy as np

        from tigerbeetle_tpu.runtime import fastpath

        n, ev_types, _conns, offsets, lens, arena = batch
        if not n:
            self._flush_backoff(time.perf_counter_ns())
            return
        is_msg = (ev_types[:n] == self._ev_message) & (lens[:n] > 0)
        midx = np.nonzero(is_msg)[0]
        if len(midx):
            moffs = offsets[midx]
            mlens = lens[midx]
            ok, hdrs, _native, _bytes = fastpath.verify_and_gather(
                arena, moffs, mlens
            )
            mv = memoryview(arena)
            for i in range(len(midx)):
                if not ok[i]:
                    continue
                off = int(moffs[i])
                self._complete(
                    hdrs[i],
                    bytes(mv[off + self._hs : off + int(mlens[i])]),
                )
        self._flush_backoff(time.perf_counter_ns())

    def _flush_backoff(self, now_ns: int) -> None:
        """Retransmit busy-shed requests whose backoff expired."""
        if not self._retry_at:
            return
        for req in [r for r, due in self._retry_at.items() if due <= now_ns]:
            del self._retry_at[req]
            entry = self.inflight.get(req)
            if entry is None:
                self._busy_streak.pop(req, None)
                continue
            self.bus.send(self.conn, entry[2])

    def _complete(self, h, body: bytes) -> None:
        wire = self._wire
        cmd = int(h["command"])
        req = int(h["request"])
        entry = self.inflight.get(req)
        if cmd == int(wire.Command.client_busy):
            if entry is not None:
                self.busy_replies += 1
                streak = self._busy_streak.get(req, 0) + 1
                if (
                    self._backoff_base_ns > 0
                    and streak <= self.BUSY_RETRIES_MAX
                    # A FOLLOWER refusal is a redirect, not overload:
                    # retransmitting at the same follower would just
                    # collect the same typed refusal — surface it so
                    # the driver re-routes to the primary.
                    and wire.parse_follower_busy(body) is None
                ):
                    # Hold the request in flight and retransmit after
                    # capped exponential backoff (qos.backoff_delay:
                    # deterministic seeded jitter, shared with
                    # SimClient).
                    from tigerbeetle_tpu import qos

                    self._busy_streak[req] = streak
                    self._retry_at[req] = (
                        time.perf_counter_ns() + qos.backoff_delay(
                            self.id, req, streak, self._backoff_base_ns,
                        )
                    )
                    self.busy_backoffs += 1
                    return
                del self.inflight[req]
                self._busy_streak.pop(req, None)
                self._retry_at.pop(req, None)
                t0, op, _frame = entry
                lat = (time.perf_counter_ns() - t0) / 1e9
                self.completed.append(
                    (req, "busy", lat, b"", op, self._tier_of(h, body))
                )
        elif cmd == int(wire.Command.reply):
            if entry is not None:
                del self.inflight[req]
                self._busy_streak.pop(req, None)
                self._retry_at.pop(req, None)
                t0, op, _frame = entry
                lat = (time.perf_counter_ns() - t0) / 1e9
                self.completed.append(
                    (req, "reply", lat, body, op, self._tier_of(h, b""))
                )
        elif cmd == int(wire.Command.eviction):
            raise RuntimeError(f"open-loop client {self.id:#x} evicted")

    def _tier_of(self, h, busy_body: bytes) -> tuple:
        """Serving-tier attribution of one completion: a reply with an
        attestation carve-out (or a typed follower busy) was follower-
        served; everything else is the primary path."""
        wire = self._wire
        att = wire.attestation_of(h)
        if att is not None:
            return ("follower", int(h["replica"]), att[1], att[0])
        fb = wire.parse_follower_busy(busy_body) if busy_body else None
        if fb is not None:
            return ("follower", fb[1], fb[3], b"")
        return ("primary", int(h["replica"]), 0, b"")

    def close(self) -> None:
        self.bus.close()
