"""Process-level JAX platform override.

The ambient environment routes JAX at the axon TPU tunnel through a
sitecustomize hook that BOTH sets the ``jax_platforms`` config
programmatically (so the ``JAX_PLATFORMS`` env var alone does not
win) AND registers a PJRT plugin whose discovery blocks while the
tunnel is wedged — observed hard enough that ``jnp.zeros(4)`` hangs
forever.  When a parent process has decided this process must not
touch the device (``TB_FORCE_CPU_JAX=1`` — set by bench.py's
``ensure_device_responsive`` fallback), both routes have to be cut
before the first backend initializes: override the config AND
unregister the plugin factory, exactly as tests/conftest.py does for
the test suite.

Called from ``tigerbeetle_tpu/__init__.py`` so every entry point that
imports the package (server, clients, bench subprocesses) honors the
marker without its own boilerplate.
"""

from __future__ import annotations

import os


def pin_cpu_backend() -> None:
    """Pin this process's JAX to the CPU backend, unconditionally.
    Must run before the first backend initializes.  The single home
    of the private-API plugin unregistration (tests/conftest.py uses
    this too)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except (ImportError, AttributeError):  # private API best-effort
        pass


def force_cpu_jax_if_requested() -> None:
    """If TB_FORCE_CPU_JAX=1, pin this process's JAX to the CPU
    backend before any device backend can initialize."""
    from tigerbeetle_tpu.envcheck import env_str

    if env_str("TB_FORCE_CPU_JAX") == "1":
        pin_cpu_backend()
