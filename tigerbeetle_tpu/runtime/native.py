"""ctypes bindings for the native host runtime (native/tb_runtime.cpp).

The C++ library provides the epoll event loop, the header-framed TCP
message bus, and the C-ABI client session (the reference's io /
message_bus / tb_client components, reference: src/io/linux.zig,
src/message_bus.zig, src/clients/c/tb_client.zig).  Python loads it
via ctypes; if it hasn't been built yet and a compiler exists, it is
built on first use (make -C native).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
from tigerbeetle_tpu.envcheck import env_str as _env_str
from tigerbeetle_tpu.envcheck import native_sanitize as _native_sanitize

# Sanitizer flavor (TB_NATIVE_SANITIZE=asan): libraries load from
# native/asan/ (same basenames) and `make` targets the asan build —
# shared by this loader and runtime/fastpath.py so one knob flips
# BOTH libraries to their sanitized builds.
_SANITIZE = _native_sanitize()
_MAKE_TARGET = _SANITIZE or "all"


def _lib_dir() -> str:
    return (os.path.join(_NATIVE_DIR, _SANITIZE) if _SANITIZE
            else _NATIVE_DIR)


_LIB_PATH = _env_str(
    "TB_RUNTIME_LIB", os.path.join(_lib_dir(), "libtb_runtime.so")
)

_lib = None
_lib_failed = False  # negative cache: don't re-make per failed load
_lib_lock = threading.Lock()
# Build-failure forensics: when `make -C native` fails we fall back to
# a prebuilt .so (or to None), but the failure must be VISIBLE — a
# silent pure-Python fallback let benches report fallback numbers as
# native.  The make error tail is kept here for build_error() and the
# one-shot warning below; obs gauges surface it to scrapes.
_build_error: str | None = None


def build_error() -> str | None:
    """Tail of the native build failure, or None when the build was
    clean (or not attempted yet)."""
    return _build_error


_make_attempted = False


def _run_make(lib_path: str) -> None:
    """Invoke make; record + warn ONCE on failure instead of silently
    swallowing it (the prebuilt-.so / pure-Python fallback still
    engages, but now visibly).  One `make` covers both libraries
    (Makefile `all:`), so the runtime and fastpath loaders share a
    single attempt — and a failing build warns once, not per caller."""
    global _build_error, _make_attempted
    if _make_attempted:
        return
    _make_attempted = True
    # Build-failure forensics name the sanitizer flavor attempted: a
    # failing `make asan` (no compiler-rt, say) must never read as a
    # failing release build — and vice versa.
    flavor = f"sanitizer={_SANITIZE or 'none'}"
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, _MAKE_TARGET], check=True,
            capture_output=True, timeout=120,
        )
    except subprocess.CalledProcessError as exc:
        tail = (exc.stderr or exc.stdout or b"")[-800:].decode(
            "utf-8", "replace"
        )
        _build_error = (
            f"make -C native {_MAKE_TARGET} failed ({flavor}, "
            f"rc={exc.returncode}): {tail}"
        )
    except (OSError, subprocess.SubprocessError) as exc:
        _build_error = (
            f"make -C native {_MAKE_TARGET} failed ({flavor}): {exc!r}"
        )
    if _build_error is not None:
        import warnings

        fallback = (
            "falling back to the prebuilt library"
            if os.path.exists(lib_path)
            else "no prebuilt library — pure-Python fallback"
        )
        warnings.warn(
            f"native build failed ({fallback}): {_build_error}",
            RuntimeWarning, stacklevel=3,
        )


class _Event(ctypes.Structure):
    _fields_ = [
        ("type", ctypes.c_int32),
        ("conn", ctypes.c_int32),
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("len", ctypes.c_uint32),
    ]


EV_ACCEPTED, EV_CONNECTED, EV_MESSAGE, EV_CLOSED = 1, 2, 3, 4


def _load():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        _lib_failed = True  # cleared on success below
        # Always invoke make: the Makefile's dependency tracking makes
        # this a no-op when the library is fresh, and it REBUILDS a
        # stale prebuilt .so whose symbols would otherwise fail the
        # argtypes registration below with an AttributeError.
        _run_make(_LIB_PATH)
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None

        lib.tb_bus_create.restype = ctypes.c_void_p
        lib.tb_bus_create.argtypes = [ctypes.c_uint32]
        lib.tb_bus_destroy.argtypes = [ctypes.c_void_p]
        lib.tb_bus_listen.restype = ctypes.c_int
        lib.tb_bus_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16]
        lib.tb_bus_listen_port.restype = ctypes.c_int
        lib.tb_bus_listen_port.argtypes = [ctypes.c_void_p]
        lib.tb_bus_connect.restype = ctypes.c_int
        lib.tb_bus_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16]
        lib.tb_bus_send.restype = ctypes.c_int
        lib.tb_bus_send.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.tb_bus_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tb_bus_poll.restype = ctypes.c_int
        lib.tb_bus_poll.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tb_bus_next_event.restype = ctypes.c_int
        lib.tb_bus_next_event.argtypes = [ctypes.c_void_p, ctypes.POINTER(_Event)]
        lib.tb_client_init.restype = ctypes.c_void_p
        lib.tb_client_init.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.tb_client_deinit.argtypes = [ctypes.c_void_p]
        lib.tb_client_add_address.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16,
        ]
        lib.tb_client_request.restype = ctypes.c_int64
        lib.tb_client_request.argtypes = [
            ctypes.c_void_p, ctypes.c_uint8, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int,
        ]
        lib.tb_checksum128.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64 * 2,
        ]
        # Columnar drain + scatter-gather send (may be absent from a
        # stale prebuilt .so when the rebuild failed — the bus then
        # reports unsupported and callers keep the per-event paths).
        try:
            lib.tb_bus_send2.restype = ctypes.c_int
            lib.tb_bus_send2.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
        except AttributeError:
            lib.tb_bus_send2 = None
        try:
            lib.tb_bus_sendv.restype = ctypes.c_int
            lib.tb_bus_sendv.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32,
            ]
        except AttributeError:
            lib.tb_bus_sendv = None
        try:
            lib.tb_bus_poll_drain.restype = ctypes.c_int
            lib.tb_bus_poll_drain.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int32,
            ]
        except AttributeError:
            lib.tb_bus_poll_drain = None
        _lib = lib
        _lib_failed = False
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_checksum128(data: bytes) -> int:
    lib = _load()
    out = (ctypes.c_uint64 * 2)()
    lib.tb_checksum128(data, len(data), out)
    return int(out[0]) | (int(out[1]) << 64)


class NativeBus:
    """Event-loop TCP bus: listen/connect/send/poll."""

    def __init__(self, message_size_max: int = 1 << 20) -> None:
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native runtime unavailable")
        self._bus = self._lib.tb_bus_create(message_size_max)
        if not self._bus:
            raise RuntimeError("tb_bus_create failed")
        self._message_size_max = message_size_max
        self._drain_bufs = None

    @property
    def supports_drain(self) -> bool:
        return getattr(self._lib, "tb_bus_poll_drain", None) is not None

    def poll_drain(self, timeout_ms: int = 0, max_events: int = 4096):
        """Columnar drain: one C call copies every ready event into a
        reusable arena — `(n, types, conns, offsets, lens, arena)`
        numpy views, valid until the NEXT poll_drain/poll call.
        Message payloads are `arena[offsets[i]: offsets[i]+lens[i]]`;
        non-message events have len 0.  Returns None when the loaded
        library predates the symbol (callers keep the per-event poll
        path)."""
        import numpy as np

        if not self.supports_drain:
            return None
        bufs = self._drain_bufs
        if bufs is None or len(bufs[0]) < max_events:
            cap = max(
                4 << 20, 2 * (self._message_size_max + 256)
            )
            bufs = self._drain_bufs = (
                np.empty(max(max_events, 4096), np.int32),   # types
                np.empty(max(max_events, 4096), np.int32),   # conns
                np.empty(max(max_events, 4096), np.uint64),  # offsets
                np.empty(max(max_events, 4096), np.uint32),  # lens
                np.empty(cap, np.uint8),                     # arena
            )
        types, conns, offsets, lens, arena = bufs
        u8p = ctypes.POINTER(ctypes.c_uint8)
        n = self._lib.tb_bus_poll_drain(
            self._bus, timeout_ms,
            arena.ctypes.data_as(u8p), arena.nbytes,
            types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            conns.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            min(max_events, len(types)),
        )
        return n, types, conns, offsets, lens, arena

    def listen(self, host: str, port: int) -> int:
        rc = self._lib.tb_bus_listen(self._bus, host.encode(), port)
        if rc != 0:
            raise OSError(f"listen {host}:{port} failed")
        return self._lib.tb_bus_listen_port(self._bus)

    def connect(self, host: str, port: int) -> int:
        conn = self._lib.tb_bus_connect(self._bus, host.encode(), port)
        if conn < 0:
            raise OSError(f"connect {host}:{port} failed")
        return conn

    def send(self, conn: int, data: bytes) -> None:
        self._lib.tb_bus_send(self._bus, conn, data, len(data))

    def send2(self, conn: int, head: bytes, body: bytes) -> None:
        """One queued message from two parts — no Python-side concat
        (a megabyte body saved one full copy per hop)."""
        if getattr(self._lib, "tb_bus_send2", None) is None:
            self.send(conn, head + body)
            return
        self._lib.tb_bus_send2(
            self._bus, conn, head, len(head), body, len(body)
        )

    def sendv(self, conn: int, frames: list[bytes]) -> None:
        """Queue a run of complete frames for one connection in a
        single crossing (r22 drain loop: the backup's per-drain
        prepare_ok burst).  Falls back to per-frame sends when the
        loaded library predates the symbol."""
        if getattr(self._lib, "tb_bus_sendv", None) is None:
            for f in frames:
                self.send(conn, f)
            return
        u8p = ctypes.POINTER(ctypes.c_uint8)
        k = len(frames)
        bufs = (u8p * k)(
            *[ctypes.cast(ctypes.c_char_p(f), u8p) for f in frames]
        )
        lens = (ctypes.c_uint32 * k)(*[len(f) for f in frames])
        self._lib.tb_bus_sendv(self._bus, conn, bufs, lens, k)

    def close_conn(self, conn: int) -> None:
        self._lib.tb_bus_close(self._bus, conn)

    def poll(self, timeout_ms: int = 0) -> list[tuple[int, int, bytes]]:
        """-> [(event_type, conn, payload)]; payload copied out."""
        self._lib.tb_bus_poll(self._bus, timeout_ms)
        events = []
        ev = _Event()
        while self._lib.tb_bus_next_event(self._bus, ctypes.byref(ev)):
            payload = b""
            if ev.type == EV_MESSAGE and ev.len:
                payload = ctypes.string_at(ev.data, ev.len)
            events.append((int(ev.type), int(ev.conn), payload))
        return events

    def close(self) -> None:
        if self._bus:
            self._lib.tb_bus_destroy(self._bus)
            self._bus = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        # tbcheck: allow(broad-except): __del__ during interpreter
        # teardown — the bus handle may already be torn down; any
        # raise here becomes an unraisable-exception warning storm.
        except Exception:
            pass


class NativeClient:
    """Synchronous C-ABI client session (the tb_client analog)."""

    def __init__(self, host: str, port: int, cluster: int, client_id: int,
                 reply_cap: int = 1 << 20) -> None:
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native runtime unavailable")
        self._client = self._lib.tb_client_init(
            host.encode(), port, cluster,
            client_id & 0xFFFFFFFFFFFFFFFF, client_id >> 64,
        )
        if not self._client:
            raise OSError(f"tb_client_init {host}:{port} failed")
        self._reply_buf = ctypes.create_string_buffer(reply_cap)

    def add_address(self, host: str, port: int) -> None:
        """Additional cluster replica: retransmissions rotate through
        every known address, so a view change (new primary without
        this client's connection) recovers."""
        self._lib.tb_client_add_address(
            self._client, host.encode(), port
        )

    def request(self, operation: int, body: bytes = b"",
                timeout_ms: int = 10_000) -> bytes:
        rc = self._lib.tb_client_request(
            self._client, operation, body, len(body),
            self._reply_buf, len(self._reply_buf), timeout_ms,
        )
        if rc < 0:
            raise OSError(
                {-2: "evicted", -3: "timeout", -4: "io error", -5: "reply too large"}
                .get(rc, f"error {rc}")
            )
        return self._reply_buf.raw[:rc]

    def close(self) -> None:
        if self._client:
            self._lib.tb_client_deinit(self._client)
            self._client = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        # tbcheck: allow(broad-except): same __del__-at-teardown story
        # as NativeBus above.
        except Exception:
            pass
