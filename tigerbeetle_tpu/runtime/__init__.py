from tigerbeetle_tpu.runtime.native import (  # noqa: F401
    NativeBus,
    NativeClient,
    native_available,
)
