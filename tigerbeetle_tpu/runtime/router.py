"""Account-sharded multi-cluster router with crash-safe cross-shard 2PC.

One VSR group caps the whole system at a single primary's pipeline;
this module serves N independent consensus groups ("shards"), each
owning the account range `types.shard_of_account` maps to it, behind
one client-facing router:

- Shard-local work (both accounts on one shard, lookups, queries) is
  FORWARDED on the client's own session: the router impersonates the
  client on each shard, reusing the client's request numbers, so the
  shards' at-most-once session dedupe keeps working across router
  crashes (a retransmitted request replays the stored sub-replies).
- A cross-shard transfer is a distributed transaction built from the
  state machine's own two-phase machinery (the idempotent commit
  primitive of the cross-shard atomic transfer protocols,
  arXiv:2102.09688 / arXiv:2503.04595): a pending hold debiting the
  client account into a coordinator-owned settlement account on the
  debit shard, a mirrored hold on the credit shard, then a coordinator
  post (commit) or void (abort) of both.

Crash safety is structural, not stateful — the router keeps NOTHING
durable of its own:

- Every 2PC artifact has a DETERMINISTIC id derived from the client's
  transfer id (`types.XShardIds`), so re-driving any leg after a crash
  is deduplicated by transfer-id uniqueness (`exists`), never
  double-applied.
- The COMMIT DECISION is itself a replicated op: posting the
  debit-side hold rides the debit shard's consensus log.  A recovered
  coordinator reads the decision back from hold state and finishes the
  credit side idempotently.
- ABORT decisions record the client-visible result code in the void
  record's `user_data_64`, so a retransmitted aborted transfer replays
  its original error.
- In-doubt DISCOVERY needs no coordinator state either: settlement
  accounts are enumerable through a ledger-registry trail (a posted
  registry transfer per (shard, ledger), amount = ledger number), and
  every 2PC row touches a settlement account, so
  `get_account_transfers` over the settlement accounts re-surfaces
  every transfer the old coordinator ever started.
- Holds carry the TB_COORD_TIMEOUT_S pending timeout: an orphaned hold
  (coordinator lost before any decision) is voided by the shard's own
  expiry pulse — a clean abort, never lost money.

`RouterCore` is sans-IO (generators yielding SubOp batches) and shared
by the TCP `RouterServer` below and the deterministic simulation
transport in `testing/cluster.py`.
"""

from __future__ import annotations

import time

import numpy as np

from tigerbeetle_tpu import envcheck, types
from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.types import (
    ACCOUNT_DTYPE,
    ACCOUNT_FILTER_DTYPE,
    CREATE_RESULT_DTYPE,
    TRANSFER_DTYPE,
    U128_PAIR_DTYPE,
    AccountFilterFlags,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
    TransferFlags,
    XShardIds,
    coord_account_id,
    shard_of_account,
    u128_get,
    u128_set,
    xleg_tag,
    xleg_untag,
)

CTR = CreateTransferResult
CAR = CreateAccountResult

# Result codes that mean "this row is (already) applied" for an
# idempotent re-drive: `exists` is the id-dedupe answer for a row the
# previous coordinator incarnation already committed.
_OKISH = (int(CTR.ok), int(CTR.exists))

_POST_VOID = int(TransferFlags.post_pending_transfer | TransferFlags.void_pending_transfer)

# Registry marker ids are derived like 2PC ids but keyed by ledger.
def ledger_marker_id(ledger: int) -> int:
    return XShardIds._derive(ledger, "ledger-marker")


# The coordinator's STABLE wire identity: one session per shard for the
# lifetime of the deployment, re-registered (an idempotent replay) by
# every router incarnation.  A fresh id per incarnation would grow the
# shards' session tables until an eviction hit a live client session.
COORD_CLIENT_ID = 0xC00D_1D00_0000_0001

# Request-number gap a recovering coordinator leaves above the
# session's last committed request (the register reply's resume hint):
# anything the dead incarnation still had in flight is both out of the
# new range and permanently fenced as stale once a new request commits.
COORD_RESUME_GAP = 1 << 16


def result_codes(n_rows: int, reply: bytes) -> list[int]:
    """Expand a create_* reply (nonzero results only) into a dense
    per-row code list (0 = ok)."""
    codes = [0] * n_rows
    for r in np.frombuffer(reply, CREATE_RESULT_DTYPE):
        codes[int(r["index"])] = int(r["result"])
    return codes


def pack_results(pairs: list[tuple[int, int]]) -> bytes:
    """(index, code) pairs (nonzero codes), sorted by index, to wire."""
    pairs = sorted(p for p in pairs if p[1] != 0)
    arr = np.zeros(len(pairs), dtype=CREATE_RESULT_DTYPE)
    for i, (idx, code) in enumerate(pairs):
        arr[i]["index"] = idx
        arr[i]["result"] = code
    return arr.tobytes()


def _transfer_row(id: int, *, debit: int = 0, credit: int = 0,
                  amount: int = 0, pending_id: int = 0, ledger: int = 0,
                  code: int = 0, flags: int = 0, timeout: int = 0,
                  user_data_128: int = 0, user_data_64: int = 0) -> np.ndarray:
    row = np.zeros(1, dtype=TRANSFER_DTYPE)[0]
    u128_set(row, "id", id)
    u128_set(row, "debit_account_id", debit)
    u128_set(row, "credit_account_id", credit)
    u128_set(row, "amount", amount)
    u128_set(row, "pending_id", pending_id)
    u128_set(row, "user_data_128", user_data_128)
    row["user_data_64"] = user_data_64
    row["ledger"] = ledger
    row["code"] = code
    row["flags"] = flags
    row["timeout"] = timeout
    return row


def _account_row(id: int, *, ledger: int, code: int = 1) -> np.ndarray:
    row = np.zeros(1, dtype=ACCOUNT_DTYPE)[0]
    u128_set(row, "id", id)
    row["ledger"] = ledger
    row["code"] = code
    return row


def _filter_body(account_id: int, *, timestamp_min: int = 0,
                 limit: int = 8190) -> bytes:
    row = np.zeros(1, dtype=ACCOUNT_FILTER_DTYPE)[0]
    u128_set(row, "account_id", account_id)
    row["timestamp_min"] = timestamp_min
    row["limit"] = limit
    row["flags"] = AccountFilterFlags.debits | AccountFilterFlags.credits
    return row.tobytes()


def _ids_body(ids: list[int]) -> bytes:
    arr = np.zeros(len(ids), dtype=U128_PAIR_DTYPE)
    for i, v in enumerate(ids):
        arr[i]["lo"] = v & types.U64_MAX
        arr[i]["hi"] = v >> 64
    return arr.tobytes()


class SubOp:
    """One shard-bound operation the transport must complete.

    kind "fwd":   impersonated forward on the CLIENT's session — the
                  transport must use the client's own id and the
                  client's request number (at-most-once dedupe).
    kind "coord": coordinator-session op — the transport picks request
                  numbers freely; idempotency is id-level.
    kind "root":  sessionless proof-of-state query (VsrOperation.
                  state_root) — answered by the shard's server loop
                  outside consensus, so the transport sends it with
                  no session at all (read-only, trivially idempotent).
    """

    __slots__ = ("shard", "kind", "operation", "body", "done", "reply",
                 "client", "request", "trace")

    def __init__(self, shard: int, kind: str, operation, body: bytes, *,
                 client: int = 0, request: int = 0,
                 trace: tuple[int, int, int] = (0, 0, 0)) -> None:
        self.shard = shard
        self.kind = kind
        self.operation = operation
        self.body = body
        self.client = client
        self.request = request
        self.trace = trace
        self.done = False
        self.reply: bytes | None = None

    def complete(self, reply: bytes) -> None:
        self.done = True
        self.reply = reply


class _Task:
    """A generator-driven multi-stage operation: the generator yields
    lists of SubOps; when all of a stage's subops complete, pump()
    resumes it.  The generator's final `return value` (StopIteration)
    becomes `.result`."""

    def __init__(self, gen) -> None:
        self._gen = gen
        self.subops: list[SubOp] = []
        self.done = False
        self.result = None
        self._advance()

    def _advance(self) -> None:
        while True:
            try:
                self.subops = next(self._gen) or []
            except StopIteration as stop:
                self.subops = []
                self.done = True
                self.result = stop.value
                return
            if self.subops:
                return  # a stage with nothing to wait for advances now

    def pump(self) -> list[SubOp]:
        """-> freshly issued subops (empty if waiting or done)."""
        if self.done or any(not s.done for s in self.subops):
            return []
        self._advance()
        return self.subops


class _XRow:
    """One cross-shard transfer row of a create_transfers batch."""

    __slots__ = ("index", "tid", "dr", "cr", "amount", "ledger", "code",
                 "dshard", "cshard", "ids", "commit", "client_code")

    def __init__(self, index, tid, dr, cr, amount, ledger, code,
                 dshard, cshard) -> None:
        self.index = index
        self.tid = tid
        self.dr = dr
        self.cr = cr
        self.amount = amount
        self.ledger = ledger
        self.code = code
        self.dshard = dshard
        self.cshard = cshard
        self.ids = XShardIds(tid)
        self.commit = False
        self.client_code = 0


class RouterCore:
    """Sans-IO router logic: batch splitting, cross-shard 2PC staging,
    reply merging, and crash recovery — all expressed as SubOp batches
    for a transport to execute."""

    def __init__(self, n_shards: int, *, coord_timeout_s: int | None = None,
                 registry=None) -> None:
        self.n_shards = n_shards
        self.coord_timeout_s = (
            coord_timeout_s if coord_timeout_s is not None
            else envcheck.coord_timeout_s()
        )
        # (shard, ledger) pairs whose settlement accounts this
        # incarnation has ensured.  Volatile by design: re-ensuring is
        # an idempotent create (`exists`).
        self._ensured: set[tuple[int, int]] = set()
        # Cross-shard tids owned by a LIVE open request of this
        # incarnation: the concurrent recovery scan must not
        # probe-void them — their open request makes the decision.
        self._active_tids: set[int] = set()
        # Optional flight recorder (obs/flight.py): 2PC stage instants
        # (holds issued, decision, credit post) land in the postmortem
        # ring tagged with the client's trace id, so one merge_traces
        # pass over router flight dump + shard traces reads
        # hold -> hold -> post end to end.
        self.flight = None
        from tigerbeetle_tpu import obs

        self.registry = registry if registry is not None else obs.Registry()
        self._c_requests = self.registry.counter("router.requests")
        self._c_cross = self.registry.counter("router.cross_shard_transfers")
        self._c_local = self.registry.counter("router.local_transfers")
        self._c_commits = self.registry.counter("router.2pc_commits")
        self._c_aborts = self.registry.counter("router.2pc_aborts")
        self._c_roundtrips = self.registry.counter("router.2pc_roundtrips")
        self._c_conflicts = self.registry.counter("router.2pc_conflicts")
        self._c_compensations = self.registry.counter(
            "router.2pc_compensations"
        )
        self._c_recovered = self.registry.counter("router.indoubt_recovered")

    # ------------------------------------------------------------------
    # Batch splitting.

    def _chain_groups(self, flags_col) -> list[list[int]]:
        """Partition row indices into linked-chain groups (singletons
        for unlinked rows).  A trailing open chain stays one group (the
        state machine answers linked_event_chain_open for it)."""
        groups: list[list[int]] = []
        current: list[int] = []
        for i, f in enumerate(flags_col):
            current.append(i)
            if not (int(f) & int(TransferFlags.linked)):
                groups.append(current)
                current = []
        if current:
            groups.append(current)
        return groups

    def _plan_create_transfers(self, body: bytes):
        """-> (fwd_rows per shard, broadcast row indices, xrows,
        router_rejects [(index, code)])."""
        rows = np.frombuffer(body, dtype=TRANSFER_DTYPE)
        fwd: dict[int, list[int]] = {}
        broadcast: list[int] = []
        xrows: list[_XRow] = []
        rejects: list[tuple[int, int]] = []
        for group in self._chain_groups(rows["flags"]):
            if len(group) > 1:
                # Chain: routed as a unit to the first member's debit
                # shard.  A chain whose accounts span shards fails
                # closed there (account_not_found aborts the whole
                # chain) — never partially applied.
                first = rows[group[0]]
                shard = shard_of_account(
                    u128_get(first, "debit_account_id"), self.n_shards
                )
                fwd.setdefault(shard, []).extend(group)
                continue
            i = group[0]
            row = rows[i]
            flags = int(row["flags"])
            if flags & _POST_VOID:
                # Post/void routes by its pending transfer's location,
                # which only the owning shard knows: broadcast; the
                # merge keeps the one non-not_found verdict.
                broadcast.append(i)
                continue
            dr = u128_get(row, "debit_account_id")
            cr = u128_get(row, "credit_account_id")
            dshard = shard_of_account(dr, self.n_shards)
            cshard = shard_of_account(cr, self.n_shards)
            if dshard == cshard or flags != 0:
                # Shard-local — or flagged (pending/balancing)
                # cross-shard, which is unsupported and fails closed on
                # the debit shard (credit_account_not_found).
                fwd.setdefault(dshard, []).append(i)
                continue
            if int(row["timeout"]) != 0:
                # The state machine would reject this; the 2PC holds
                # carry their own timeout, so reject router-side with
                # the exact code the oracle returns.
                rejects.append(
                    (i, int(CTR.timeout_reserved_for_pending_transfer))
                )
                continue
            xrows.append(_XRow(
                i, u128_get(row, "id"), dr, cr, u128_get(row, "amount"),
                int(row["ledger"]), int(row["code"]), dshard, cshard,
            ))
        return rows, fwd, broadcast, xrows, rejects

    def _fwd_bodies(self, rows, fwd: dict[int, list[int]],
                    broadcast: list[int]):
        """-> {shard: (body, index_map)} with broadcast rows appended
        to EVERY shard's sub-batch, original order preserved."""
        out = {}
        shards = set(fwd)
        if broadcast:
            shards.update(range(self.n_shards))
        for shard in sorted(shards):
            indices = sorted(set(fwd.get(shard, [])) | set(broadcast))
            out[shard] = (rows[indices].tobytes(), indices)
        return out

    # ------------------------------------------------------------------
    # Settlement-account provisioning.

    def _ensure_subops(self, needed: set[tuple[int, int]]):
        """Two coordinator stages creating settlement + registry
        accounts and the durable ledger-registry marker for every
        (shard, ledger) not yet ensured this incarnation."""
        todo = sorted(needed - self._ensured)
        if not todo:
            return
        by_shard: dict[int, list[int]] = {}
        for shard, ledger in todo:
            by_shard.setdefault(shard, []).append(ledger)
        accounts = {}
        for shard, ledgers in sorted(by_shard.items()):
            rows = [
                _account_row(types.COORD_REGISTRY_ACCOUNT,
                             ledger=types.COORD_REGISTRY_LEDGER),
                _account_row(types.COORD_REGISTRY_FUNDING,
                             ledger=types.COORD_REGISTRY_LEDGER),
            ]
            for ledger in ledgers:
                rows.append(_account_row(coord_account_id(ledger),
                                         ledger=ledger))
            accounts[shard] = SubOp(
                shard, "coord", Operation.create_accounts,
                np.stack(rows).tobytes(),
            )
        yield list(accounts.values())
        # A shard that rejected any provisioning row (e.g. account
        # table at capacity) must NOT be marked ensured — the next
        # request retries, and the failure is counted, not sticky.
        ok_shards = set()
        for shard, sub in accounts.items():
            codes = result_codes(2 + len(by_shard[shard]), sub.reply)
            if all(c in _OKISH for c in codes):
                ok_shards.add(shard)
            else:
                self._c_conflicts.inc()
                if self.flight is not None:
                    self.flight.note("ensure_failed", shard=shard,
                                     codes=[c for c in codes if c])
        markers = {}
        for shard, ledgers in sorted(by_shard.items()):
            if shard not in ok_shards:
                continue
            rows = [
                _transfer_row(
                    ledger_marker_id(ledger),
                    debit=types.COORD_REGISTRY_FUNDING,
                    credit=types.COORD_REGISTRY_ACCOUNT,
                    amount=ledger, ledger=types.COORD_REGISTRY_LEDGER,
                    code=1,
                )
                for ledger in ledgers
            ]
            markers[shard] = SubOp(
                shard, "coord", Operation.create_transfers,
                np.stack(rows).tobytes(),
            )
        yield list(markers.values())
        for shard, sub in markers.items():
            codes = result_codes(len(by_shard[shard]), sub.reply)
            if all(c in _OKISH for c in codes):
                self._ensured.update(
                    (shard, lg) for lg in by_shard[shard]
                )
            else:
                self._c_conflicts.inc()
                if self.flight is not None:
                    self.flight.note("ensure_failed", shard=shard,
                                     codes=[c for c in codes if c])

    # ------------------------------------------------------------------
    # Client requests.

    def open_request(self, client: int, request: int, operation,
                     body: bytes,
                     trace: tuple[int, int, int] = (0, 0, 0)) -> _Task:
        self._c_requests.inc()
        op = Operation(int(operation))
        if op == Operation.create_transfers:
            gen = self._run_create_transfers(client, request, body, trace)
        elif op == Operation.create_accounts:
            gen = self._run_create_accounts(client, request, body, trace)
        elif op == Operation.lookup_accounts:
            gen = self._run_lookup_accounts(client, request, body, trace)
        elif op == Operation.lookup_transfers:
            gen = self._run_lookup_transfers(client, request, body, trace)
        elif op in (Operation.get_account_transfers,
                    Operation.get_account_balances):
            gen = self._run_single_shard_query(client, request, op, body,
                                               trace)
        else:
            gen = self._run_noop()
        return _Task(gen)

    def _run_noop(self):
        return b""
        yield  # pragma: no cover

    def _run_create_accounts(self, client, request, body, trace):
        rows = np.frombuffer(body, dtype=ACCOUNT_DTYPE)
        fwd: dict[int, list[int]] = {}
        rejects: list[tuple[int, int]] = []
        for group in self._chain_groups(rows["flags"]):
            shards = {
                shard_of_account(u128_get(rows[i], "id"), self.n_shards)
                for i in group
            }
            if len(shards) > 1:
                # A linked account chain spanning shards cannot be
                # atomic across consensus groups; fail the whole chain
                # closed rather than place accounts off their shard.
                rejects.extend(
                    (i, int(CAR.linked_event_failed)) for i in group
                )
                continue
            fwd.setdefault(shards.pop(), []).extend(group)
        bodies = self._fwd_bodies(rows, fwd, [])
        subops = {
            shard: SubOp(shard, "fwd", Operation.create_accounts, b,
                         client=client, request=request, trace=trace)
            for shard, (b, _imap) in bodies.items()
        }
        yield list(subops.values())
        pairs = list(rejects)
        for shard, sub in subops.items():
            _body, imap = bodies[shard]
            for sub_idx, code in enumerate(result_codes(len(imap),
                                                        sub.reply)):
                if code:
                    pairs.append((imap[sub_idx], code))
        return pack_results(pairs)

    def _run_create_transfers(self, client, request, body, trace):
        rows, fwd, broadcast, xrows, rejects = (
            self._plan_create_transfers(body)
        )
        self._c_local.inc(sum(len(v) for v in fwd.values()))
        self._c_cross.inc(len(xrows))
        self._active_tids.update(x.tid for x in xrows)
        try:
            reply = yield from self._drive_create_transfers(
                client, request, rows, fwd, broadcast, xrows, rejects,
                trace,
            )
        finally:
            self._active_tids.difference_update(x.tid for x in xrows)
        return reply

    def _drive_create_transfers(self, client, request, rows, fwd,
                                broadcast, xrows, rejects, trace):
        needed = set()
        for x in xrows:
            needed.add((x.dshard, x.ledger))
            needed.add((x.cshard, x.ledger))
        yield from self._ensure_subops(needed)

        # Stage 1: impersonated forwards + both holds, in parallel.
        bodies = self._fwd_bodies(rows, fwd, broadcast)
        fwd_subs = {
            shard: SubOp(shard, "fwd", Operation.create_transfers, b,
                         client=client, request=request, trace=trace)
            for shard, (b, _imap) in bodies.items()
        }
        hold_batches: dict[int, list[tuple[_XRow, str]]] = {}
        for x in xrows:
            hold_batches.setdefault(x.dshard, []).append((x, "debit"))
            hold_batches.setdefault(x.cshard, []).append((x, "credit"))
        hold_subs: dict[int, tuple[SubOp, list[tuple[_XRow, str]]]] = {}
        for shard, legs in sorted(hold_batches.items()):
            hrows = []
            for x, leg in legs:
                if leg == "debit":
                    hrows.append(_transfer_row(
                        x.ids.hold_debit, debit=x.dr,
                        credit=coord_account_id(x.ledger),
                        amount=x.amount, ledger=x.ledger, code=x.code,
                        flags=int(TransferFlags.pending),
                        timeout=self.coord_timeout_s,
                        user_data_128=x.tid,
                        user_data_64=xleg_tag(types.XLEG_DEBIT, x.cshard),
                    ))
                else:
                    hrows.append(_transfer_row(
                        x.ids.hold_credit,
                        debit=coord_account_id(x.ledger), credit=x.cr,
                        amount=x.amount, ledger=x.ledger, code=x.code,
                        flags=int(TransferFlags.pending),
                        timeout=self.coord_timeout_s,
                        user_data_128=x.tid,
                        user_data_64=xleg_tag(types.XLEG_CREDIT, x.dshard),
                    ))
            sub = SubOp(shard, "coord", Operation.create_transfers,
                        np.stack(hrows).tobytes(), trace=trace)
            hold_subs[shard] = (sub, legs)
        if xrows:
            self._c_roundtrips.inc()
            if self.flight is not None:
                for x in xrows:
                    self.flight.note(
                        "x2pc_holds", tid=x.tid, trace_id=trace[0],
                        dshard=x.dshard, cshard=x.cshard,
                    )
        yield list(fwd_subs.values()) + [s for s, _ in hold_subs.values()]

        # Stage 2: decide per xrow — post the debit hold (the durable
        # commit decision) or void the surviving hold(s).
        hold_code: dict[tuple[int, str], int] = {}
        for shard, (sub, legs) in hold_subs.items():
            for (x, leg), code in zip(legs,
                                      result_codes(len(legs), sub.reply)):
                hold_code[(x.index, leg)] = code
        p1: dict[int, list[tuple[_XRow, str]]] = {}
        for x in xrows:
            cd = hold_code[(x.index, "debit")]
            cc = hold_code[(x.index, "credit")]
            if cd in _OKISH and cc in _OKISH:
                x.commit = True
                p1.setdefault(x.dshard, []).append((x, "post_debit"))
            else:
                # Minimum nonzero non-exists code reproduces the
                # oracle's descending-precedence ordering.
                fails = [c for c in (cd, cc) if c not in _OKISH]
                x.client_code = min(fails)
                self._c_aborts.inc()
                if cd in _OKISH:
                    p1.setdefault(x.dshard, []).append((x, "void_debit"))
                if cc in _OKISH:
                    p1.setdefault(x.cshard, []).append((x, "void_credit"))
        p1_subs = self._resolution_subops(p1, trace)
        if p1_subs:
            self._c_roundtrips.inc()
            if self.flight is not None:
                for x in xrows:
                    self.flight.note(
                        "x2pc_decide", tid=x.tid, trace_id=trace[0],
                        commit=x.commit,
                    )
        yield [s for s, _ in p1_subs.values()]

        # Stage 3: read decisions; committed rows drive the credit-side
        # post, freshly-aborted ones clean up the credit hold, and a
        # decision found already-voided (a recovery probe beat us)
        # replays its recorded client code.
        p2: dict[int, list[tuple[_XRow, str]]] = {}
        code_lookups: dict[int, list[_XRow]] = {}
        for shard, (sub, legs) in p1_subs.items():
            for (x, role), code in zip(legs,
                                       result_codes(len(legs), sub.reply)):
                if role == "post_debit":
                    if code in _OKISH:
                        p2.setdefault(x.cshard, []).append(
                            (x, "post_credit")
                        )
                    elif code == int(CTR.pending_transfer_already_posted):
                        self._c_conflicts.inc()
                        p2.setdefault(x.cshard, []).append(
                            (x, "post_credit")
                        )
                    elif code == int(CTR.pending_transfer_already_voided):
                        # Aborted by a concurrent/recovered coordinator:
                        # fetch the recorded client code off the void
                        # record.
                        x.commit = False
                        code_lookups.setdefault(x.dshard, []).append(x)
                        p2.setdefault(x.cshard, []).append(
                            (x, "void_credit")
                        )
                    else:
                        # Expired (or failed) before the decision: a
                        # clean abort.
                        x.commit = False
                        x.client_code = int(CTR.pending_transfer_expired)
                        self._c_aborts.inc()
                        p2.setdefault(x.cshard, []).append(
                            (x, "void_credit")
                        )
                elif code == int(CTR.pending_transfer_already_posted):
                    # Tried to void a hold that is posted: the durable
                    # decision says commit — follow it.
                    self._c_conflicts.inc()
                    if role == "void_debit":
                        x.commit = True
                        x.client_code = 0
                        p2.setdefault(x.cshard, []).append(
                            (x, "post_credit")
                        )
        lookup_subs = {
            shard: (SubOp(shard, "coord", Operation.lookup_transfers,
                          _ids_body([x.ids.void_debit for x in xs]),
                          trace=trace), xs)
            for shard, xs in sorted(code_lookups.items())
        }
        p2_subs = self._resolution_subops(p2, trace)
        if p2_subs:
            self._c_roundtrips.inc()
            if self.flight is not None:
                for shard, (_sub, legs) in p2_subs.items():
                    for x, role in legs:
                        if role == "post_credit":
                            self.flight.note(
                                "x2pc_post_credit", tid=x.tid,
                                trace_id=trace[0], shard=shard,
                            )
        yield ([s for s, _ in p2_subs.values()]
               + [s for s, _ in lookup_subs.values()])

        # Stage 4: credit-side outcomes; a posted decision whose credit
        # hold expired anyway (timeout budget violated) is compensated
        # — money returns to the debitor, flagged loudly, never parked.
        comp: dict[int, list[_XRow]] = {}
        for shard, (sub, xs) in lookup_subs.items():
            found = {}
            for row in np.frombuffer(sub.reply, dtype=TRANSFER_DTYPE):
                found[u128_get(row, "id")] = int(row["user_data_64"])
            for x in xs:
                x.client_code = found.get(
                    x.ids.void_debit, int(CTR.pending_transfer_expired)
                ) or int(CTR.pending_transfer_expired)
        for shard, (sub, legs) in p2_subs.items():
            for (x, role), code in zip(legs,
                                       result_codes(len(legs), sub.reply)):
                if role != "post_credit":
                    continue
                if code in _OKISH:
                    self._c_commits.inc()
                else:
                    # The decided commit cannot complete on the credit
                    # side (hold expired past the timeout budget, or —
                    # a flagged protocol conflict — voided by another
                    # actor): compensate, returning the posted money
                    # to the debitor.  Never silently parked.
                    if code != int(CTR.pending_transfer_expired):
                        self._c_conflicts.inc()
                    self._c_compensations.inc()
                    x.commit = False
                    x.client_code = int(CTR.pending_transfer_expired)
                    comp.setdefault(x.dshard, []).append(x)
        comp_subs = []
        for shard, xs in sorted(comp.items()):
            rows_c = [
                _transfer_row(
                    x.ids.comp, debit=coord_account_id(x.ledger),
                    credit=x.dr, amount=x.amount, ledger=x.ledger,
                    code=x.code or 1, user_data_128=x.tid,
                )
                for x in xs
            ]
            comp_subs.append(SubOp(shard, "coord",
                                   Operation.create_transfers,
                                   np.stack(rows_c).tobytes(),
                                   trace=trace))
        yield comp_subs

        pairs = list(rejects)
        pairs.extend((x.index, x.client_code) for x in xrows)
        for shard, sub in fwd_subs.items():
            _body, imap = bodies[shard]
            codes = result_codes(len(imap), sub.reply)
            for sub_idx, orig in enumerate(imap):
                if orig in broadcast:
                    continue  # merged below
                if codes[sub_idx]:
                    pairs.append((orig, codes[sub_idx]))
        not_found = int(CTR.pending_transfer_not_found)
        for orig in broadcast:
            verdicts = []
            for shard, sub in fwd_subs.items():
                _body, imap = bodies[shard]
                verdicts.append(result_codes(len(imap), sub.reply)[
                    imap.index(orig)
                ])
            if 0 in verdicts:
                continue  # some shard applied it
            real = [c for c in verdicts if c != not_found]
            pairs.append((orig, min(real) if real else not_found))
        return pack_results(pairs)

    def _resolution_subops(self, batches: dict[int, list[tuple[_XRow, str]]],
                           trace):
        """post/void batches per shard -> {shard: (SubOp, legs)}."""
        out = {}
        for shard, legs in sorted(batches.items()):
            rows = []
            for x, role in legs:
                if role == "post_debit":
                    rows.append(_transfer_row(
                        x.ids.post_debit, pending_id=x.ids.hold_debit,
                        flags=int(TransferFlags.post_pending_transfer),
                    ))
                elif role == "post_credit":
                    rows.append(_transfer_row(
                        x.ids.post_credit, pending_id=x.ids.hold_credit,
                        flags=int(TransferFlags.post_pending_transfer),
                    ))
                elif role == "void_debit":
                    rows.append(_transfer_row(
                        x.ids.void_debit, pending_id=x.ids.hold_debit,
                        flags=int(TransferFlags.void_pending_transfer),
                        user_data_64=x.client_code
                        or int(CTR.pending_transfer_expired),
                    ))
                else:
                    rows.append(_transfer_row(
                        x.ids.void_credit, pending_id=x.ids.hold_credit,
                        flags=int(TransferFlags.void_pending_transfer),
                        user_data_64=x.client_code
                        or int(CTR.pending_transfer_expired),
                    ))
            out[shard] = (
                SubOp(shard, "coord", Operation.create_transfers,
                      np.stack(rows).tobytes(), trace=trace),
                legs,
            )
        return out

    def _run_lookup_accounts(self, client, request, body, trace):
        arr = np.frombuffer(body, dtype=U128_PAIR_DTYPE)
        ids = [int(r["lo"]) | (int(r["hi"]) << 64) for r in arr]
        by_shard: dict[int, list[int]] = {}
        for v in ids:
            by_shard.setdefault(shard_of_account(v, self.n_shards),
                                []).append(v)
        subs = {
            shard: SubOp(shard, "fwd", Operation.lookup_accounts,
                         _ids_body(vs), client=client, request=request,
                         trace=trace)
            for shard, vs in sorted(by_shard.items())
        }
        yield list(subs.values())
        found: dict[int, bytes] = {}
        for sub in subs.values():
            for row in np.frombuffer(sub.reply, dtype=ACCOUNT_DTYPE):
                found[u128_get(row, "id")] = row.tobytes()
        return b"".join(found[v] for v in ids if v in found)

    # Chunk bound for derived-id chases on the coordinator session —
    # conservative against small-config shards' batch caps.
    _LOOKUP_CHUNK = 200

    def _run_lookup_transfers(self, client, request, body, trace):
        arr = np.frombuffer(body, dtype=U128_PAIR_DTYPE)
        ids = [int(r["lo"]) | (int(r["hi"]) << 64) for r in arr]
        # Stage 1 — broadcast the client's own ids on the client's
        # session: a transfer row lives on whichever shard executed it.
        subs = [
            SubOp(shard, "fwd", Operation.lookup_transfers, body,
                  client=client, request=request, trace=trace)
            for shard in range(self.n_shards)
        ]
        yield subs
        found: dict[int, np.void] = {}
        for sub in subs:
            for row in np.frombuffer(sub.reply, dtype=TRANSFER_DTYPE):
                found.setdefault(u128_get(row, "id"), row)
        # Stage 2 — ids with no direct row anywhere may be cross-shard
        # transfers (no row under the client id exists at all): chase
        # their 2PC legs on the coordinator session, chunked so the
        # 3x-derived expansion never exceeds a shard's batch cap.
        missing = [v for v in dict.fromkeys(ids) if v not in found]
        derived = {v: XShardIds(v) for v in missing}
        chase: list[SubOp] = []
        for i in range(0, len(missing), self._LOOKUP_CHUNK):
            chunk = missing[i:i + self._LOOKUP_CHUNK]
            query: list[int] = []
            for v in chunk:
                x = derived[v]
                query.extend((x.hold_debit, x.hold_credit, x.post_debit))
            for shard in range(self.n_shards):
                chase.append(SubOp(shard, "coord",
                                   Operation.lookup_transfers,
                                   _ids_body(query), trace=trace))
        yield chase
        for sub in chase:
            for row in np.frombuffer(sub.reply, dtype=TRANSFER_DTYPE):
                found.setdefault(u128_get(row, "id"), row)
        out = []
        for v in ids:
            if v in found:
                out.append(found[v].tobytes())
                continue
            x = derived.get(v)
            if x is None:
                continue
            if x.hold_debit in found and x.post_debit in found and (
                x.hold_credit in found
            ):
                hd, hc = found[x.hold_debit], found[x.hold_credit]
                pd = found[x.post_debit]
                row = _transfer_row(
                    v, debit=u128_get(hd, "debit_account_id"),
                    credit=u128_get(hc, "credit_account_id"),
                    amount=u128_get(pd, "amount"),
                    ledger=int(hd["ledger"]), code=int(hd["code"]),
                )
                row["timestamp"] = pd["timestamp"]
                out.append(row.tobytes())
        return b"".join(out)

    def _run_single_shard_query(self, client, request, op, body, trace):
        row = np.frombuffer(body, dtype=ACCOUNT_FILTER_DTYPE)[0]
        shard = shard_of_account(u128_get(row, "account_id"),
                                 self.n_shards)
        sub = SubOp(shard, "fwd", op, body, client=client,
                    request=request, trace=trace)
        yield [sub]
        return sub.reply

    # ------------------------------------------------------------------
    # Proof of state: fold per-shard roots into ONE deterministic
    # cluster commitment (commitment.fold_cluster).  Runs as a normal
    # task — the `state_root` client query and the recovery audit both
    # ride it.

    def _root_subops(self) -> list[SubOp]:
        from tigerbeetle_tpu.vsr.wire import VsrOperation

        return [
            SubOp(shard, "root", VsrOperation.state_root, b"")
            for shard in range(self.n_shards)
        ]

    def _fold_roots(self, subs: list[SubOp]) -> bytes:
        from tigerbeetle_tpu.state_machine import commitment

        roots = []
        for sub in subs:
            root, _commit_min = commitment.parse_root_body(sub.reply)
            roots.append(root)
        return commitment.fold_cluster(roots)

    def state_root(self) -> _Task:
        """Cluster-wide proof of state: query every shard's root,
        fold deterministically, reply with root_body(folded,
        n_shards)."""
        return _Task(self._run_state_root())

    def _run_state_root(self):
        from tigerbeetle_tpu.state_machine import commitment

        subs = self._root_subops()
        yield subs
        return commitment.root_body(self._fold_roots(subs), self.n_shards)

    # ------------------------------------------------------------------
    # Crash recovery.

    def recover(self) -> _Task:
        """In-doubt recovery for a restarted coordinator: rediscover
        every cross-shard transfer through the shards' own logs and
        re-drive each to a terminal state (post or void), idempotently.
        Returns a _Task; `.result` is {"indoubt": n, "scanned": n}."""
        return _Task(self._run_recovery())

    def _scan_account(self, shard: int, account: int):
        """Generator stage helper: paginated get_account_transfers of
        one account; yields SubOp stages, accumulates rows into the
        returned list."""
        rows: list[np.void] = []
        timestamp_min = 0
        while True:
            sub = SubOp(shard, "coord", Operation.get_account_transfers,
                        _filter_body(account, timestamp_min=timestamp_min))
            yield [sub]
            page = np.frombuffer(sub.reply, dtype=TRANSFER_DTYPE)
            if len(page) == 0:
                return rows
            rows.extend(page)
            timestamp_min = int(page[-1]["timestamp"]) + 1

    def _run_recovery(self):
        # Stage R1: enumerate ledgers per shard via the registry trail.
        ledgers: dict[int, set[int]] = {}
        for shard in range(self.n_shards):
            rows = yield from self._scan_account(
                shard, types.COORD_REGISTRY_ACCOUNT
            )
            ledgers[shard] = {
                int(u128_get(r, "amount")) for r in rows
                if int(r["ledger"]) == types.COORD_REGISTRY_LEDGER
            }
            self._ensured.update((shard, lg) for lg in ledgers[shard])
        # Stage R2: scan every settlement account; every 2PC row
        # touches one, so this re-surfaces all transfers ever started.
        evidence: dict[int, dict[str, np.void]] = {}
        amounts: dict[int, int] = {}
        meta: dict[int, dict] = {}
        for shard in sorted(ledgers):
            for ledger in sorted(ledgers[shard]):
                rows = yield from self._scan_account(
                    shard, coord_account_id(ledger)
                )
                for row in rows:
                    tid = u128_get(row, "user_data_128")
                    if tid == 0:
                        continue
                    ids = XShardIds(tid)
                    rid = u128_get(row, "id")
                    role = next(
                        (r for r in XShardIds._ROLES
                         if getattr(ids, r) == rid), None,
                    )
                    if role is None:
                        continue
                    ev = evidence.setdefault(tid, {})
                    ev[role] = row
                    m = meta.setdefault(tid, {"ledger": ledger})
                    if role == "hold_debit":
                        m["dshard"] = shard
                        _leg, m["cshard"] = xleg_untag(
                            int(row["user_data_64"])
                        )
                        amounts[tid] = u128_get(row, "amount")
                    elif role == "hold_credit":
                        m["cshard"] = shard
                        _leg, m["dshard"] = xleg_untag(
                            int(row["user_data_64"])
                        )
                        amounts.setdefault(tid, u128_get(row, "amount"))
        # Stage R3: classify and re-drive.
        probes: dict[int, list[tuple[int, XShardIds]]] = {}
        finish: dict[int, list[tuple[int, XShardIds, str, int]]] = {}
        indoubt = 0
        for tid in sorted(evidence):
            if tid in self._active_tids:
                # A live open request of THIS incarnation owns the
                # decision (a client retransmit racing recovery);
                # probing would abort a transfer it is re-driving.
                continue
            ev = evidence[tid]
            m = meta[tid]
            ids = XShardIds(tid)
            dshard, cshard = m.get("dshard"), m.get("cshard")
            if "comp" in ev:
                continue  # terminally compensated
            if "post_debit" in ev:
                if "post_credit" not in ev and cshard is not None:
                    indoubt += 1
                    finish.setdefault(cshard, []).append(
                        (tid, ids, "post_credit", 0)
                    )
                continue
            if "void_debit" in ev:
                # Abort decided: re-void the credit hold
                # unconditionally (a not_found answer for a hold that
                # never landed is harmless; gating on scan evidence
                # would miss a hold the scan raced).
                if "void_credit" not in ev and cshard is not None:
                    indoubt += 1
                    finish.setdefault(cshard, []).append(
                        (tid, ids, "void_credit",
                         int(ev["void_debit"]["user_data_64"]))
                    )
                continue
            if dshard is not None:
                # Undecided (debit hold pending, or only the credit
                # hold surfaced — the scan may have raced the debit
                # hold's commit): the DECISION must still be made on
                # the debit side.  Probe-void the debit hold: the void
                # itself IS the abort decision if it lands (and
                # answers not_found if the hold never existed); if the
                # hold turns out posted, the decision was commit.
                # Deciding the credit side unilaterally here once
                # half-posted a transfer whose debit hold the scan
                # missed (sharded-VOPR seed 4242).
                indoubt += 1
                probes.setdefault(dshard, []).append((tid, ids))
        probe_subs = {}
        for shard, items in sorted(probes.items()):
            rows_p = [
                _transfer_row(
                    ids.void_debit, pending_id=ids.hold_debit,
                    flags=int(TransferFlags.void_pending_transfer),
                    user_data_64=int(CTR.pending_transfer_expired),
                )
                for _tid, ids in items
            ]
            probe_subs[shard] = (
                SubOp(shard, "coord", Operation.create_transfers,
                      np.stack(rows_p).tobytes()),
                items,
            )
        yield [s for s, _ in probe_subs.values()]
        for shard, (sub, items) in probe_subs.items():
            codes = result_codes(len(items), sub.reply)
            for (tid, ids), code in zip(items, codes):
                m = meta[tid]
                cshard = m.get("cshard")
                if cshard is None:
                    continue
                if code == int(CTR.pending_transfer_already_posted):
                    finish.setdefault(cshard, []).append(
                        (tid, ids, "post_credit", 0)
                    )
                else:
                    # Abort decided (void landed / hold expired /
                    # hold never existed): void the credit hold
                    # unconditionally.
                    finish.setdefault(cshard, []).append(
                        (tid, ids, "void_credit",
                         int(CTR.pending_transfer_expired))
                    )
        finish_subs = {}
        for shard, items in sorted(finish.items()):
            rows_f = []
            for _tid, ids, role, code in items:
                if role == "post_credit":
                    rows_f.append(_transfer_row(
                        ids.post_credit, pending_id=ids.hold_credit,
                        flags=int(TransferFlags.post_pending_transfer),
                    ))
                else:
                    rows_f.append(_transfer_row(
                        ids.void_credit, pending_id=ids.hold_credit,
                        flags=int(TransferFlags.void_pending_transfer),
                        user_data_64=code
                        or int(CTR.pending_transfer_expired),
                    ))
            finish_subs[shard] = (
                SubOp(shard, "coord", Operation.create_transfers,
                      np.stack(rows_f).tobytes()),
                items,
            )
        yield [s for s, _ in finish_subs.values()]
        # Stage R4: a re-driven credit post that finds its hold expired
        # (timeout budget violated while the coordinator was down) is
        # compensated so the decided money is never parked.
        comp: dict[int, list[tuple[int, XShardIds]]] = {}
        for shard, (sub, items) in finish_subs.items():
            codes = result_codes(len(items), sub.reply)
            for (tid, ids, role, _code), code in zip(items, codes):
                if role != "post_credit":
                    continue
                if code not in _OKISH:
                    # Decided commit that cannot complete on the
                    # credit side: compensate (see the open-request
                    # path for rationale).
                    if code != int(CTR.pending_transfer_expired):
                        self._c_conflicts.inc()
                    self._c_compensations.inc()
                    m = meta[tid]
                    comp.setdefault(m["dshard"], []).append((tid, ids))
        # The compensation row needs the debit hold's fields; fetch any
        # the scan raced past (the hold exists — its post succeeded).
        fetch = {
            shard: [tid for tid, _ids in items
                    if "hold_debit" not in evidence[tid]]
            for shard, items in comp.items()
        }
        fetch_subs = {
            shard: SubOp(shard, "coord", Operation.lookup_transfers,
                         _ids_body([XShardIds(t).hold_debit for t in tids]))
            for shard, tids in fetch.items() if tids
        }
        yield list(fetch_subs.values())
        for shard, sub in fetch_subs.items():
            for row in np.frombuffer(sub.reply, dtype=TRANSFER_DTYPE):
                tid = u128_get(row, "user_data_128")
                if tid:
                    evidence.setdefault(tid, {})["hold_debit"] = row
                    amounts.setdefault(tid, u128_get(row, "amount"))
        comp_subs = []
        for shard, items in sorted(comp.items()):
            rows_c = []
            for tid, ids in items:
                m = meta[tid]
                hd = evidence[tid].get("hold_debit")
                if hd is None:
                    self._c_conflicts.inc()
                    continue  # next recovery run retries
                rows_c.append(_transfer_row(
                    ids.comp, debit=coord_account_id(m["ledger"]),
                    credit=u128_get(hd, "debit_account_id"),
                    amount=amounts[tid], ledger=m["ledger"],
                    code=int(hd["code"]) or 1, user_data_128=tid,
                ))
            if rows_c:
                comp_subs.append(SubOp(shard, "coord",
                                       Operation.create_transfers,
                                       np.stack(rows_c).tobytes()))
        yield comp_subs
        self._c_recovered.inc(indoubt)
        # Post-recovery audit point: fetch every shard's state root
        # through the proof-of-state query and record the folded
        # cluster commitment with the recovery result (flight note
        # "router_recovered" carries it into the postmortem ring).
        root_subs = self._root_subops()
        yield root_subs
        cluster_root = self._fold_roots(root_subs)
        return {
            "indoubt": indoubt,
            "scanned": len(evidence),
            "cluster_root": cluster_root.hex(),
        }


# ----------------------------------------------------------------------
# TCP transport: the router as a wire-protocol front-end process.


class RouterServer:
    """Client-facing TCP router over N shard clusters.

    Clients speak the normal wire protocol to the router exactly as
    they would to a replica; the router forwards/filters per the
    RouterCore plan over per-shard native-bus connections.  Volatile by
    design: `recover=True` (the default when restarting over existing
    shards) runs the in-doubt recovery scan before serving.
    """

    RETRY_NS_DEFAULT = 1_000_000_000

    def __init__(self, listen_address: str, shard_addresses: list[str],
                 *, cluster: int = 0, recover: bool = True,
                 message_size_max: int = 1 << 20,
                 incarnation: int | None = None,
                 follower_addresses: list[str] | None = None) -> None:
        from tigerbeetle_tpu.obs.flight import FlightRecorder
        from tigerbeetle_tpu.runtime.native import (
            EV_CLOSED, EV_MESSAGE, NativeBus,
        )
        from tigerbeetle_tpu.runtime.server import parse_address
        from tigerbeetle_tpu.vsr import wire

        self._wire = wire
        self._ev_message = EV_MESSAGE
        self._ev_closed = EV_CLOSED
        self.cluster = cluster
        # Shard address lists: each entry is a comma-joined replica
        # address list for one shard.
        self.shard_addrs = [
            [parse_address(a) for a in entry.split(",")]
            for entry in shard_addresses
        ]
        self.n_shards = len(self.shard_addrs)
        from tigerbeetle_tpu import obs

        self.registry = obs.Registry()
        self.core = RouterCore(self.n_shards, registry=self.registry)
        self.flight = FlightRecorder(
            process_id=0,
            dump_path=envcheck.env_str(
                "TB_FLIGHT_PATH", "tb_flight_router.json"
            ),
        )
        self.core.flight = self.flight
        self.admit_queue = envcheck.router_queue()
        # Multi-tenant QoS (round 16): the router keys its own
        # admission (open-request slots) and its retry sweep's drain
        # order by tenant (ledger), mirroring the replica-side
        # contract — one hot tenant cannot pin every open slot or
        # starve other tenants' retries.  TB_TENANT_QOS=0 pins the
        # legacy single-bound path.
        self.qos = None
        if envcheck.tenant_qos():
            from tigerbeetle_tpu.qos import TenantQos

            self.qos = TenantQos(
                rate=envcheck.tenant_rate(),
                rate_bytes=envcheck.tenant_rate_bytes(),
                queue_bound=envcheck.tenant_queue(self.admit_queue),
                weights=envcheck.tenant_weights(),
                registry=self.registry.scope("router.qos"),
            )
        self.retry_ns = envcheck.coord_retry_ms() * 1_000_000
        self._c_shed = self.registry.counter("router.shed")
        self._c_retries = self.registry.counter("router.retries")
        self._c_shard_busy = self.registry.counter("router.shard_busy")
        # Read steering (round 19): reads go to root-attested
        # followers under TB_READ_POLICY, falling back to the primary
        # path on refusal / timeout / death — a dead follower can slow
        # one read by TB_READ_FALLBACK_MS, never fail it.  Entries are
        # "shard:host:port" (or "host:port" = shard 0); a follower
        # tails ONE shard's AOF and serves only reads that resolve to
        # that shard (lookup_transfers additionally needs n_shards==1:
        # the sharded path merges 2PC legs across shards into
        # client-view rows, which a single shard's follower cannot).
        self.followers: list[dict] = []
        for fid, entry in enumerate(follower_addresses or []):
            shard_s, sep, addr = entry.partition(":")
            if sep and ":" in addr and shard_s.isdigit():
                shard = int(shard_s)
            else:
                shard, addr = 0, entry
            assert 0 <= shard < self.n_shards, entry
            self.followers.append({
                "id": fid, "shard": shard,
                "addr": parse_address(addr), "conn": None,
                "streak": 0, "not_before": 0,
            })
        self.read_policy = envcheck.read_policy()
        if self.read_policy == "auto":
            self.read_policy = (
                "follower" if self.followers else "primary"
            )
        self.read_fallback_ns = envcheck.read_fallback_ms() * 1_000_000
        self._conn_follower: dict[int, int] = {}  # conn -> follower idx
        # (client, request) of reads currently riding a follower.
        self._frd: dict[tuple[int, int], dict] = {}
        self._c_frd = self.registry.counter("router.follower_reads")
        self._c_frd_redirects = self.registry.counter(
            "router.follower_redirects"
        )
        self._c_frd_timeouts = self.registry.counter(
            "router.follower_timeouts"
        )
        self.registry.gauge_fn("router.open_requests",
                               lambda: len(self._open))
        self.registry.gauge_fn("router.admit_queue",
                               lambda: self.admit_queue)
        self.bus = NativeBus(message_size_max)
        host, port = parse_address(listen_address)
        self.port = self.bus.listen(host, port)
        # Coordinator identity: STABLE across incarnations (see
        # COORD_CLIENT_ID); request numbering resumes above the
        # session's last committed request via the register reply's
        # resume hint.  `incarnation` only labels flight dumps.
        self.coord_client = COORD_CLIENT_ID
        self.incarnation = incarnation if incarnation is not None else 0
        # Shard connection state.
        self._shard_conn: dict[int, int | None] = {
            s: None for s in range(self.n_shards)
        }
        self._shard_target: dict[int, int] = {
            s: 0 for s in range(self.n_shards)
        }
        self._conn_shard: dict[int, int] = {}
        self._client_conns: dict[int, int] = {}
        # Wire bookkeeping.
        self._coord_request = 0
        # Sessionless "root" subop numbering: client=0 frames, so the
        # request field alone correlates replies.  Starts high to stay
        # clear of register's request=0.
        self._root_request = 0x5A00_0000
        self._pending: dict[tuple[int, int, int], SubOp] = {}
        self._sent_at: dict[int, tuple] = {}  # id(subop) -> state
        self._registered: dict[int, set[int]] = {}  # client -> shards
        self._register_waiters: dict[tuple[int, int], list[SubOp]] = {}
        self._register_pending: dict[tuple[int, int], np.ndarray] = {}
        self._register_sent: dict[tuple[int, int], int] = {}
        self._client_register: dict[int, np.ndarray] = {}
        self._open: dict[tuple[int, int], dict] = {}
        # tenant -> open-request count, maintained incrementally at
        # every _open insert/remove: admission (and the busy payload)
        # reads a tenant's slot usage per incoming request, and a
        # full-table scan there would put O(TB_ROUTER_QUEUE) work on
        # the router's per-request hot path (same reasoning as
        # VsrReplica._tenant_depth).
        self._tenant_open: dict[int, int] = {}
        self._tasks: list[tuple[_Task, dict | None]] = []
        self._recovery: _Task | None = None
        if recover:
            self._recovery = self.core.recover()
            self._issue_subops(self._recovery.subops)
            self._tasks.append((self._recovery, None))

    # -- shard connections ---------------------------------------------

    def _connect_shard(self, shard: int) -> int | None:
        conn = self._shard_conn[shard]
        if conn is not None:
            return conn
        addrs = self.shard_addrs[shard]
        for _ in range(len(addrs)):
            host, port = addrs[self._shard_target[shard] % len(addrs)]
            try:
                conn = self.bus.connect(host, port)
            except OSError:
                self._shard_target[shard] += 1
                continue
            self._shard_conn[shard] = conn
            self._conn_shard[conn] = shard
            return conn
        return None

    def _drop_shard_conn(self, conn: int) -> None:
        shard = self._conn_shard.pop(conn, None)
        if shard is not None and self._shard_conn.get(shard) == conn:
            self._shard_conn[shard] = None
            self._shard_target[shard] += 1  # rotate replica on reconnect

    # -- follower read steering ----------------------------------------

    # Int view of the one shared read-op definition (types.py).
    _READ_OPS = frozenset(int(op) for op in types.READ_OPERATIONS)

    def _read_shard(self, operation: int, body: bytes) -> int | None:
        """The single shard a read resolves to, or None when it is not
        follower-servable (multi-shard id set; lookup_transfers in a
        sharded deployment — see __init__)."""
        try:
            if operation in (int(Operation.get_account_transfers),
                             int(Operation.get_account_balances)):
                if len(body) != ACCOUNT_FILTER_DTYPE.itemsize:
                    return None
                row = np.frombuffer(body, ACCOUNT_FILTER_DTYPE)[0]
                return shard_of_account(
                    u128_get(row, "account_id"), self.n_shards
                )
            if operation == int(Operation.lookup_accounts):
                if len(body) % U128_PAIR_DTYPE.itemsize or not body:
                    return None
                rows = np.frombuffer(body, U128_PAIR_DTYPE)
                shards = {
                    shard_of_account(
                        int(r["lo"]) | (int(r["hi"]) << 64),
                        self.n_shards,
                    )
                    for r in rows
                }
                return shards.pop() if len(shards) == 1 else None
            if operation == int(Operation.lookup_transfers):
                return 0 if self.n_shards == 1 else None
        except (ValueError, KeyError):
            return None
        return None

    def _pick_follower(self, shard: int, now: int) -> dict | None:
        """A healthy follower for `shard`: not inside its failure
        backoff window (qos.backoff_delay per consecutive failure, so
        a dead follower costs one timeout per backoff window, not one
        per read)."""
        best = None
        for f in self.followers:
            if f["shard"] != shard or now < f["not_before"]:
                continue
            if best is None or f["streak"] < best["streak"]:
                best = f
        return best

    def _connect_follower(self, f: dict) -> int | None:
        if f["conn"] is not None:
            return f["conn"]
        try:
            conn = self.bus.connect(*f["addr"])
        except OSError:
            return None
        f["conn"] = conn
        self._conn_follower[conn] = f["id"]
        return conn

    def _follower_failed(self, f: dict, now: int) -> None:
        f["streak"] = min(f["streak"] + 1, 16)
        from tigerbeetle_tpu import qos as qos_mod

        f["not_before"] = now + qos_mod.backoff_delay(
            f["id"] + 1, 0, f["streak"], self.read_fallback_ns
        )

    def _try_follower_read(self, ctx: dict, operation: int,
                           body: bytes, now: int) -> bool:
        """Steer one admitted read at a follower.  True = in flight
        (reply or fallback will finish it); False = use the primary
        path now."""
        if self.read_policy != "follower":
            return False
        shard = self._read_shard(operation, body)
        if shard is None:
            return False
        f = self._pick_follower(shard, now)
        if f is None:
            return False
        conn = self._connect_follower(f)
        if conn is None:
            self._follower_failed(f, now)
            return False
        wire = self._wire
        h = wire.make_header(
            command=wire.Command.request, operation=operation,
            cluster=self.cluster, client=ctx["client"],
            request=ctx["request"],
        )
        wire.copy_trace(h, ctx["header"])
        h["tenant"] = ctx["header"]["tenant"]
        wire.finalize_header(h, body)
        self.bus.send(conn, h.tobytes() + body)
        self._c_frd.inc()
        key = (ctx["client"], ctx["request"])
        self._frd[key] = {
            "ctx": ctx, "follower": f, "body": body,
            "operation": operation, "deadline": now + self.read_fallback_ns,
        }
        return True

    def _frd_fallback(self, key: tuple, *, timeout: bool) -> None:
        """Follower refused / timed out / died: re-drive the read
        through the primary path — reads never fail because a
        follower did."""
        state = self._frd.pop(key, None)
        if state is None:
            return
        # tbcheck: allow(determinism): RouterServer is the real-TCP
        # front-end; retry/observe cadence runs on wall time.  The
        # sim drives RouterCore, which takes injected ticks.
        now = time.monotonic_ns()
        self._follower_failed(state["follower"], now)
        (self._c_frd_timeouts if timeout
         else self._c_frd_redirects).inc()
        ctx = state["ctx"]
        self.flight.note(
            "follower_read_fallback", client=ctx["client"],
            request=ctx["request"], follower=state["follower"]["id"],
            timeout=int(timeout),
        )
        if self._open.get((ctx["client"], ctx["request"])) is not ctx:
            return  # request since completed/dropped elsewhere
        trace = (int(ctx["header"]["trace_id"]),
                 int(ctx["header"]["trace_ts"]),
                 int(ctx["header"]["trace_flags"]))
        task = self.core.open_request(
            ctx["client"], ctx["request"], state["operation"],
            state["body"], trace,
        )
        self._issue_subops(task.subops)
        self._tasks.append((task, ctx))

    def _on_follower_message(self, conn: int, header, body: bytes,
                             cmd: int) -> None:
        wire = self._wire
        f = self.followers[self._conn_follower[conn]]
        key = (wire.u128(header, "client"), int(header["request"]))
        state = self._frd.get(key)
        if state is None or state["follower"] is not f:
            return
        if cmd == int(wire.Command.reply):
            self._frd.pop(key)
            f["streak"] = 0
            ctx = state["ctx"]
            self._tenant_open_dec(self._open.pop(
                (ctx["client"], ctx["request"]), None
            ))
            if self.qos is not None and ctx.get("tenant") is not None:
                self.qos.on_reply(ctx["tenant"], ctx["header"])
            cconn = self._client_conns.get(ctx["client"])
            if cconn is None:
                return
            h = wire.make_header(
                command=wire.Command.reply, cluster=self.cluster,
                client=ctx["client"], request=ctx["request"],
                operation=int(ctx["operation"]),
                replica=int(header["replica"]),
            )
            wire.copy_trace(h, ctx["header"])
            # Relay the attestation untouched: the CLIENT verifies
            # (root, commit_min) against the cluster commitment — the
            # router must not launder an unattested reply into an
            # attested-looking one or vice versa.
            h["state_root_lo"] = header["state_root_lo"]
            h["state_root_hi"] = header["state_root_hi"]
            h["root_op"] = header["root_op"]
            wire.finalize_header(h, body)
            self.bus.send(cconn, h.tobytes() + body)
        elif cmd == int(wire.Command.client_busy):
            # Typed follower refusal (lagging / unattested / corrupt /
            # overload): redirect to the primary path.
            self._frd_fallback(key, timeout=False)

    # -- subop issue / retry -------------------------------------------

    def _issue_subops(self, subops: list[SubOp]) -> None:
        for sub in subops:
            self._send_subop(sub, first=True)

    def _send_subop(self, sub: SubOp, first: bool = False) -> None:
        wire = self._wire
        if sub.kind == "root":
            # Sessionless proof-of-state query: no registration, no
            # session — the shard's server loop answers it directly.
            self._root_request += 1
            request = self._root_request
            key = (sub.shard, 0, request)
            old_key = self._sent_at.get(id(sub))
            if old_key is not None:
                self._pending.pop(old_key[0], None)
            self._pending[key] = sub
            # tbcheck: allow(determinism): RouterServer is the real-TCP
            # front-end; retry/observe cadence runs on wall time.  The
            # sim drives RouterCore, which takes injected ticks.
            self._sent_at[id(sub)] = (key, time.monotonic_ns())
            h = wire.make_header(
                command=wire.Command.request,
                operation=wire.VsrOperation.state_root,
                cluster=self.cluster, client=0, request=request,
            )
            wire.finalize_header(h, b"")
            conn = self._connect_shard(sub.shard)
            if conn is not None:
                self.bus.send(conn, h.tobytes())
            if not first:
                self._c_retries.inc()
            return
        if sub.kind == "fwd":
            client, request = sub.client, sub.request
        else:
            client = self.coord_client
        # Sessions (the client's impersonated one AND the
        # coordinator's own) must exist shard-side before any request,
        # or the shard answers with an eviction.  Registering is
        # idempotent: an existing session just replays its register
        # reply.
        regset = self._registered.setdefault(client, set())
        if sub.shard not in regset:
            self._ensure_registered(client, sub.shard, sub)
            return
        if sub.kind != "fwd":
            self._coord_request += 1
            request = self._coord_request
        key = (sub.shard, client, request)
        old_key = self._sent_at.get(id(sub))
        if old_key is not None:
            self._pending.pop(old_key[0], None)
        self._pending[key] = sub
        # tbcheck: allow(determinism): RouterServer is the real-TCP
        # front-end; retry/observe cadence runs on wall time.  The
        # sim drives RouterCore, which takes injected ticks.
        self._sent_at[id(sub)] = (key, time.monotonic_ns())
        h = wire.make_header(
            command=wire.Command.request, operation=int(sub.operation),
            cluster=self.cluster, client=client, request=request,
            trace_id=sub.trace[0], trace_ts=sub.trace[1],
            trace_flags=sub.trace[2],
        )
        wire.finalize_header(h, sub.body)
        conn = self._connect_shard(sub.shard)
        if conn is not None:
            self.bus.send(conn, h.tobytes() + sub.body)
        if not first:
            self._c_retries.inc()
            self.flight.note("router_retry", shard=sub.shard,
                             request=request, kind=sub.kind)

    def _ensure_registered(self, client: int, shard: int,
                           waiter: SubOp | None) -> None:
        key = (client, shard)
        if waiter is not None:
            self._register_waiters.setdefault(key, []).append(waiter)
        if key in self._register_pending:
            return
        wire = self._wire
        h = wire.make_header(
            command=wire.Command.request,
            operation=wire.VsrOperation.register,
            cluster=self.cluster, client=client, request=0,
        )
        wire.finalize_header(h, b"")
        self._register_pending[key] = h
        self._pending[(shard, client, 0)] = SubOp(
            shard, "register", wire.VsrOperation.register, b"",
            client=client,
        )
        conn = self._connect_shard(shard)
        if conn is not None:
            # tbcheck: allow(determinism): RouterServer is the real-TCP
            # front-end; retry/observe cadence runs on wall time.  The
            # sim drives RouterCore, which takes injected ticks.
            self._register_sent[key] = time.monotonic_ns()
            self.bus.send(conn, h.tobytes())

    def _retry_sweep(self) -> None:
        # tbcheck: allow(determinism): RouterServer is the real-TCP
        # front-end; retry/observe cadence runs on wall time.  The
        # sim drives RouterCore, which takes injected ticks.
        now = time.monotonic_ns()
        due = []
        for sub in list(self._pending.values()):
            if sub.kind == "register":
                continue
            state = self._sent_at.get(id(sub))
            if state is not None and now - state[1] >= self.retry_ns:
                due.append(sub)
        if self.qos is None or len(due) <= 1:
            for sub in due:
                self._send_subop(sub)
        else:
            # Weighted-fair retry order: coordinator legs (2PC
            # decisions — cluster safety, never a tenant's fault)
            # re-drive first; forwarded client ops then drain across
            # tenant groups by WFQ pick, so a flooding tenant's retry
            # backlog cannot starve other tenants' re-drives.
            by_tenant: dict[int, list] = {}
            for sub in due:
                if sub.kind != "fwd":
                    self._send_subop(sub)
                    continue
                ctx = self._open.get((sub.client, sub.request))
                tenant = ctx.get("tenant", 0) if ctx else 0
                by_tenant.setdefault(tenant, []).append(sub)
            while by_tenant:
                t = self.qos.pick(by_tenant.keys())
                group = by_tenant[t]
                self._send_subop(group.pop(0))
                if not group:
                    del by_tenant[t]
        # Re-send pending registers on the same cadence (NOT every
        # poll — a shard mid-view-change must not be flooded).
        for key, h in list(self._register_pending.items()):
            last = self._register_sent.get(key, 0)
            if now - last < self.retry_ns:
                continue
            conn = self._connect_shard(key[1])
            if conn is not None:
                self._register_sent[key] = now
                self.bus.send(conn, h.tobytes())

    # -- main loop ------------------------------------------------------

    def poll_once(self, timeout_ms: int = 10) -> None:
        for ev_type, conn, payload in self.bus.poll(timeout_ms):
            if ev_type == self._ev_closed:
                self._drop_shard_conn(conn)
                self._drop_follower_conn(conn)
                self._client_conns = {
                    c: k for c, k in self._client_conns.items()
                    if k != conn
                }
            elif ev_type == self._ev_message:
                self._on_message(conn, payload)
        self._retry_sweep()
        self._frd_sweep()
        self._pump_tasks()

    def _drop_follower_conn(self, conn: int) -> None:
        fid = self._conn_follower.pop(conn, None)
        if fid is None:
            return
        f = self.followers[fid]
        if f["conn"] == conn:
            f["conn"] = None
        # Reads in flight on the dead follower fall back NOW (kill -9
        # redirect, not a fallback-timeout wait).
        for key in [k for k, s in self._frd.items()
                    if s["follower"] is f]:
            self._frd_fallback(key, timeout=False)

    def _frd_sweep(self) -> None:
        if not self._frd:
            return
        # tbcheck: allow(determinism): RouterServer is the real-TCP
        # front-end; retry/observe cadence runs on wall time.  The
        # sim drives RouterCore, which takes injected ticks.
        now = time.monotonic_ns()
        for key in [k for k, s in self._frd.items()
                    if now >= s["deadline"]]:
            self._frd_fallback(key, timeout=True)

    def serve_forever(self) -> None:
        while True:
            self.poll_once()

    def close(self) -> None:
        self.bus.close()

    def _pump_tasks(self) -> None:
        done = []
        for task, ctx in self._tasks:
            issued = task.pump()
            if issued:
                self._issue_subops(issued)
            if task.done:
                done.append((task, ctx))
        for task, ctx in done:
            self._tasks.remove((task, ctx))
            if ctx is not None:
                self._reply_client(ctx, task.result)
            elif task is self._recovery:
                self.flight.note("router_recovered", **(task.result or {}))

    def _reply_client(self, ctx: dict, body: bytes) -> None:
        wire = self._wire
        self._tenant_open_dec(self._open.pop(
            ctx.get("open_key", (ctx["client"], ctx["request"])), None
        ))
        if self.qos is not None and ctx.get("tenant") is not None:
            self.qos.on_reply(ctx["tenant"], ctx["header"])
        # Sessionless queries (state_root) reply to the requesting
        # CONNECTION — concurrent scrapers share client id 0, so the
        # per-client conn map would route every reply to whichever
        # scraper connected last.
        conn = ctx.get("conn")
        if conn is None:
            conn = self._client_conns.get(ctx["client"])
        if conn is None:
            return  # client gone; retransmission re-derives the reply
        h = wire.make_header(
            command=wire.Command.reply, cluster=self.cluster,
            client=ctx["client"], request=ctx["request"],
            operation=int(ctx["operation"]),
        )
        wire.copy_trace(h, ctx["header"])
        wire.finalize_header(h, body)
        self.bus.send(conn, h.tobytes() + body)

    # -- wire dispatch --------------------------------------------------

    def _on_message(self, conn: int, payload: bytes) -> None:
        wire = self._wire
        if len(payload) < HEADER_SIZE:
            return
        header = wire.header_from_bytes(payload[:HEADER_SIZE])
        body = payload[HEADER_SIZE:]
        if not wire.verify_header(header, body):
            return
        cmd = int(header["command"])
        if conn in self._conn_follower:
            self._on_follower_message(conn, header, body, cmd)
            return
        if conn in self._conn_shard:
            self._on_shard_message(conn, header, body, cmd)
            return
        if cmd == int(wire.Command.request):
            self._on_client_request(conn, header, body)

    def _on_shard_message(self, conn: int, header, body: bytes,
                          cmd: int) -> None:
        wire = self._wire
        shard = self._conn_shard[conn]
        client = wire.u128(header, "client")
        request = int(header["request"])
        key = (shard, client, request)
        if cmd == int(wire.Command.reply):
            sub = self._pending.pop(key, None)
            if sub is None:
                return
            if sub.kind == "register":
                self._register_pending.pop((client, shard), None)
                self._register_sent.pop((client, shard), None)
                self._registered.setdefault(client, set()).add(shard)
                if client == self.coord_client:
                    # Resume coordinator numbering above everything
                    # the previous incarnation committed (register
                    # reply's session-resume hint).
                    resume = wire.u128(header, "context")
                    if resume:
                        self._coord_request = max(
                            self._coord_request,
                            resume + COORD_RESUME_GAP,
                        )
                for waiter in self._register_waiters.pop(
                    (client, shard), []
                ):
                    self._send_subop(waiter, first=True)
                self._maybe_finish_client_register(client)
                return
            self._sent_at.pop(id(sub), None)
            sub.complete(bytes(body))
        elif cmd == int(wire.Command.client_busy):
            # Shard overload: coordinator ops just retry later; a
            # forwarded client op propagates the typed busy so the
            # client backs off and re-drives the whole request.
            self._c_shard_busy.inc()
            sub = self._pending.get(key)
            if sub is not None and sub.kind == "fwd":
                self._fail_open_request(client, sub.request)
        elif cmd == int(wire.Command.eviction):
            if client != self.coord_client:
                # The client's impersonated session on this shard is
                # gone: forward the (terminal) eviction and DROP the
                # client's open requests — retrying them against a
                # dead session would spin forever and pin admit-queue
                # slots until the router sheds everything.
                self._registered.get(client, set()).discard(shard)
                self._drop_client_requests(client)
                cconn = self._client_conns.get(client)
                if cconn is not None:
                    self.bus.send(cconn, header.tobytes() + bytes(body))
                return
            # The COORDINATOR's session was evicted on this shard (a
            # session-table overflow landed on it): re-register — the
            # identity is stable, the ops are id-idempotent — and
            # re-drive every coord subop bound for the shard, which
            # would otherwise be retried into the void forever.
            self.flight.note("coord_evicted", shard=shard)
            self._registered.get(self.coord_client, set()).discard(shard)
            for sub in list(self._pending.values()):
                if sub.kind == "coord" and sub.shard == shard:
                    state = self._sent_at.pop(id(sub), None)
                    if state is not None:
                        self._pending.pop(state[0], None)
                    self._send_subop(sub, first=True)

    def _drop_client_requests(self, client: int) -> None:
        """Remove every open request of `client` (no busy reply: the
        caller already delivered a terminal eviction)."""
        for key in [k for k in self._open if k[0] == client]:
            ctx = self._open.pop(key)
            self._frd.pop(key, None)
            self._tenant_open_dec(ctx)
            dead = [t for t, c in self._tasks if c is ctx]
            self._tasks = [(t, c) for t, c in self._tasks
                           if c is not ctx]
            for task in dead:
                for sub in task.subops:
                    state = self._sent_at.pop(id(sub), None)
                    if state is not None:
                        self._pending.pop(state[0], None)

    def _fail_open_request(self, client: int, request: int) -> None:
        ctx = self._open.pop((client, request), None)
        if ctx is None:
            return
        self._frd.pop((client, request), None)
        self._tenant_open_dec(ctx)
        # Drop the task AND every outstanding subop it owns (fwd and
        # coord alike) — an orphaned coord subop would otherwise stay
        # in the retry sweep forever.  Its holds, if any, expire: a
        # clean abort; the client's retried request re-drives them.
        dead = [t for t, c in self._tasks if c is ctx]
        self._tasks = [(t, c) for t, c in self._tasks if c is not ctx]
        for task in dead:
            for sub in task.subops:
                state = self._sent_at.pop(id(sub), None)
                if state is not None:
                    self._pending.pop(state[0], None)
        self._send_busy(ctx["header"], ctx.get("tenant"), admission=False)

    def _send_busy(self, req_header, tenant=None, *,
                   admission: bool = True) -> None:
        """`admission=False` marks a busy for an ALREADY-ADMITTED
        request (a shard shed its sub-op): the typed payload and the
        flight note still go out, but it must not count as a tenant
        admission shed — the t<ledger>.shed counter is the router's
        own admission discriminator, and mixing downstream shard
        overload into it would let shed+admit both increment for one
        request."""
        wire = self._wire
        client = wire.u128(req_header, "client")
        conn = self._client_conns.get(client)
        payload = b""
        if self.qos is not None and tenant is not None:
            payload = wire.busy_body(
                tenant, self._open_of_tenant(tenant),
                self.qos.rate_of(tenant),
            )
            if admission:
                self.qos.on_shed(tenant)
        busy = wire.make_header(
            command=wire.Command.client_busy, cluster=self.cluster,
            client=client, request=int(req_header["request"]),
        )
        wire.copy_trace(busy, req_header)
        wire.finalize_header(busy, payload)
        if conn is not None:
            self.bus.send(conn, busy.tobytes() + payload)
        self._c_shed.inc()
        self.flight.note("router_shed", client=client,
                         request=int(req_header["request"]),
                         open=len(self._open),
                         tenant=-1 if tenant is None else tenant)
        if tenant is not None:
            self.flight.note(f"shed.t{tenant}")

    def _open_of_tenant(self, tenant: int) -> int:
        return self._tenant_open.get(tenant, 0)

    def _tenant_open_dec(self, ctx: dict | None) -> None:
        """Bookkeeping for an _open removal (reply/fail/drop): ctxs
        without a tenant (QoS off, sessionless state_root queries)
        are not counted on insert and skip here too."""
        tenant = None if ctx is None else ctx.get("tenant")
        if tenant is None:
            return
        count = self._tenant_open.get(tenant, 0) - 1
        if count > 0:
            self._tenant_open[tenant] = count
        else:
            self._tenant_open.pop(tenant, None)

    def _on_client_request(self, conn: int, header, body: bytes) -> None:
        wire = self._wire
        client = wire.u128(header, "client")
        request = int(header["request"])
        operation = int(header["operation"])
        self._client_conns[client] = conn
        if operation == int(wire.VsrOperation.stats):
            from tigerbeetle_tpu.obs.scrape import stats_reply

            reply, rbody = stats_reply(self.registry.snapshot(), header)
            self.bus.send(conn, reply.tobytes() + rbody)
            return
        if operation == int(wire.VsrOperation.state_root):
            # Cluster proof of state: fan the sessionless query out to
            # every shard and fold — a normal task, so it shares the
            # retry sweep, the admission bound (a polling monitor with
            # fresh request numbers must not grow _open past the queue
            # while a shard is unreachable), and replies through
            # _reply_client.  Scrapers share one well-known (client=0,
            # SCRAPE_REQUEST) identity, so the open key and the reply
            # route carry the CONNECTION: two concurrent scrapes are
            # independent requests, not a retransmission.
            open_key = (client, request, conn)
            if open_key in self._open:
                return
            if len(self._open) >= self.admit_queue:
                self._send_busy(header)
                return
            ctx = {
                "client": client, "request": request,
                "operation": operation, "header": header.copy(),
                "conn": conn, "open_key": open_key,
            }
            self._open[open_key] = ctx
            task = self.core.state_root()
            self._issue_subops(task.subops)
            self._tasks.append((task, ctx))
            self._pump_tasks()
            return
        if operation == int(wire.VsrOperation.register):
            self._client_register[client] = header.copy()
            for shard in range(self.n_shards):
                if shard not in self._registered.setdefault(client, set()):
                    self._ensure_registered(client, shard, None)
            self._maybe_finish_client_register(client)
            return
        if operation < types.Operation.pulse:
            return  # VSR-internal ops are not routable
        if (client, request) in self._open:
            return  # retransmission of an in-flight request
        tenant = None
        if self.qos is not None:
            # Tenant-keyed admission (retransmissions of in-flight
            # requests returned above — shedding here never answers a
            # request the router is already driving): a rate-capped
            # or slot-hogging tenant is shed with its own typed
            # payload while other tenants' requests still fit.  The
            # GLOBAL slot bound checks first so a request the full
            # table sheds anyway never consumes one of its tenant's
            # tokens (the tenant still rides the busy payload).
            tenant = wire.tenant_of(header, body)
            # tbcheck: allow(determinism): RouterServer is the real-TCP
            # front-end; retry/observe cadence runs on wall time.  The
            # sim drives RouterCore, which takes injected ticks.
            now = time.monotonic_ns()
            self.qos.observe(tenant, now)
        if len(self._open) >= self.admit_queue:
            self._send_busy(header, tenant)
            return
        if self.qos is not None:
            if not self.qos.admit(tenant, now, self._open_of_tenant(tenant),
                                  body_bytes=len(body)):
                self._send_busy(header, tenant)
                return
            self.qos.on_admit(tenant)
        trace = (int(header["trace_id"]), int(header["trace_ts"]),
                 int(header["trace_flags"]))
        ctx = {
            "client": client, "request": request,
            "operation": operation, "header": header.copy(),
            "tenant": tenant,
        }
        self._open[(client, request)] = ctx
        if tenant is not None:
            self._tenant_open[tenant] = (
                self._tenant_open.get(tenant, 0) + 1
            )
        if operation in self._READ_OPS:
            # tbcheck: allow(determinism): RouterServer is the
            # real-TCP front-end; retry/observe cadence runs on wall
            # time.  The sim drives RouterCore, with injected ticks.
            frd_now = time.monotonic_ns()
            if self._try_follower_read(ctx, operation, bytes(body),
                                       frd_now):
                return  # reply/fallback finishes it
        task = self.core.open_request(client, request, operation, body,
                                      trace)
        self._issue_subops(task.subops)
        self._tasks.append((task, ctx))
        self._pump_tasks()

    def _maybe_finish_client_register(self, client: int) -> None:
        wire = self._wire
        req = self._client_register.get(client)
        if req is None:
            return
        if len(self._registered.get(client, ())) < self.n_shards:
            return
        del self._client_register[client]
        conn = self._client_conns.get(client)
        if conn is None:
            return
        h = wire.make_header(
            command=wire.Command.reply, cluster=self.cluster,
            client=client, request=0,
            operation=wire.VsrOperation.register,
        )
        wire.finalize_header(h, b"")
        self.bus.send(conn, h.tobytes())
