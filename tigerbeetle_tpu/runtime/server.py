"""TCP replica server: the `tigerbeetle start` process loop.

Bridges the native message bus (runtime/native.py) to a VsrReplica:
peers handshake with a `ping` carrying their replica index, clients
are identified by the `client` field of their requests, and the loop
alternates bus polling with replica ticks (reference:
src/tigerbeetle/main.zig:382-384 `replica.tick(); io.run_for_ns(...)`).

Peer connection rule: replica i initiates connections to every j < i
(one TCP connection per replica pair); reconnects are retried each
tick (reference: src/message_bus.zig reconnect w/ backoff).
"""
# tbcheck: allow-file(determinism, no-print): ReplicaServer is the
# real-TCP process loop — realtime stamps (replica.realtime),
# drain deadlines, and TB_STATS lines are wall-clock/stdout by
# design.  The deterministic sim drives VsrReplica through SimBus
# (testing/cluster.py), never through this module.

from __future__ import annotations

import os
import time

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.vsr import replica as vsr_format
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.multi import VsrReplica
from tigerbeetle_tpu.vsr.storage import FileStorage, ZoneLayout
from tigerbeetle_tpu.vsr.wire import Command
from tigerbeetle_tpu.runtime.native import (
    EV_CLOSED,
    EV_MESSAGE,
    NativeBus,
)

TICK_NS = cfg.TICK_NS


def parse_address(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class TcpBus:
    """VsrReplica-facing bus adapter over the native TCP bus."""

    def __init__(self, addresses: list[str], replica_index: int,
                 message_size_max: int) -> None:
        self.addresses = addresses
        self.index = replica_index
        self.native = NativeBus(message_size_max)
        host, port = parse_address(addresses[replica_index])
        self.port = self.native.listen(host, port)
        self.replica_conns: dict[int, int] = {}  # keyed by PROCESS index
        self.client_conns: dict[int, int] = {}
        self._conn_peer: dict[int, tuple[str, object]] = {}
        self._pending_connects: dict[int, int] = {}  # conn -> replica
        # Protocol slot -> process index (reconfiguration re-points
        # slots at different processes; connections stay per-process).
        self._slot_map: list[int] | None = None

    def set_slot_map(self, members: list[int]) -> None:
        self._slot_map = list(members)

    # -- VsrReplica interface --

    def send(self, dst_replica: int, header: np.ndarray, body: bytes) -> None:
        conn = self.replica_conns.get(self._to_process(dst_replica))
        if conn is None:
            return  # not connected yet; protocol retransmits
        self.native.send2(conn, header.tobytes(), body)

    def send_client(self, client: int, header: np.ndarray, body: bytes) -> None:
        conn = self.client_conns.get(client)
        if conn is None:
            return
        self.native.send2(conn, header.tobytes(), body)

    def send_frames(self, dst_replica: int,
                    frames: list[tuple[np.ndarray, bytes]]) -> None:
        """Vectored send of a whole run of frames to one replica (r22:
        a drain's deferred prepare_oks release in ONE native call —
        same frames, same order as the per-frame loop)."""
        conn = self.replica_conns.get(self._to_process(dst_replica))
        if conn is None:
            return  # not connected yet; protocol retransmits
        self.native.sendv(
            conn, [h.tobytes() + body for h, body in frames]
        )

    # -- connection management --

    def connect_peers(self, cluster: int, view: int) -> None:
        """(Re)connect to every lower-indexed peer we're missing."""
        for j in range(self.index):
            if j in self.replica_conns:
                continue
            if j in self._pending_connects.values():
                continue
            host, port = parse_address(self.addresses[j])
            try:
                conn = self.native.connect(host, port)
            except OSError:
                continue
            self._pending_connects[conn] = j
            self._announce(conn, cluster, view)

    # Transport-handshake marker: announce pings identify the sender
    # by PROCESS index (the stable address-list position), while
    # protocol pings carry the sender's SLOT — the request field
    # disambiguates so registration never mixes the two spaces.
    ANNOUNCE_REQUEST = 0xB0B0_B0B0

    def _announce(self, conn: int, cluster: int, view: int,
                  pong: bool = False) -> None:
        h = wire.make_header(
            command=Command.pong if pong else Command.ping,
            cluster=cluster, view=view,
            replica=self.index, request=self.ANNOUNCE_REQUEST,
        )
        wire.finalize_header(h, b"")
        self.native.send(conn, h.tobytes())

    def _to_process(self, slot: int) -> int:
        """Protocol SLOT -> process index (identity until reconfigured)."""
        if self._slot_map is not None and slot < len(self._slot_map):
            return self._slot_map[slot]
        return slot

    def register_peer(self, conn: int, replica_index: int,
                      is_process: bool = False) -> None:
        """Connections are keyed by PROCESS.  Announce handshakes carry
        the process index directly (is_process); protocol messages
        carry the sender's SLOT, translated through the slot map —
        otherwise a reconfigured peer's pings would overwrite another
        process's connection entry."""
        self._pending_connects.pop(conn, None)
        process = replica_index if is_process else self._to_process(
            replica_index
        )
        self.replica_conns[process] = conn
        self._conn_peer[conn] = ("replica", process)

    def register_client(self, conn: int, client: int) -> None:
        self.client_conns[client] = conn
        self._conn_peer[conn] = ("client", client)

    def drop_conn(self, conn: int) -> None:
        self._pending_connects.pop(conn, None)
        kind_id = self._conn_peer.pop(conn, None)
        if kind_id is None:
            return
        kind, peer = kind_id
        if kind == "replica":
            self.replica_conns.pop(peer, None)
        else:
            self.client_conns.pop(peer, None)


class ReplicaServer:
    def __init__(self, data_path: str, *, cluster: int | None = None,
                 addresses: list[str], replica_index: int,
                 state_machine_factory, config: cfg.Config = cfg.PRODUCTION,
                 grid_size: int = 1 << 20, aof_path: str | None = None,
                 trace_path: str | None = None,
                 standby_count: int = 0) -> None:
        layout = ZoneLayout(config=config, grid_size=grid_size)
        self.storage = FileStorage(data_path, layout)
        self.bus = TcpBus(addresses, replica_index, config.message_size_max)
        aof = None
        if aof_path:
            # Append-only file of every committed prepare (reference:
            # src/aof.zig, --aof flag): an independent audit/recovery
            # stream replayable via vsr.aof.replay.
            from tigerbeetle_tpu.vsr.aof import AOF

            aof = AOF(aof_path)
        # The address list covers actives THEN standbys; the last
        # `standby_count` processes replicate without voting.
        if not 0 <= standby_count < len(addresses):
            raise ValueError(
                f"standby_count {standby_count} must leave at least one "
                f"active replica among {len(addresses)} addresses"
            )
        self.replica = VsrReplica(
            self.storage, cluster, state_machine_factory(), self.bus,
            replica=replica_index,
            replica_count=len(addresses) - standby_count,
            standby_count=standby_count, aof=aof,
        )
        # Knob-controlled tracing (TB_TRACE=json): processes started
        # without an explicit --trace path still record the span
        # timeline, written to TB_TRACE_PATH (or tb_trace_r<i>.json)
        # at close — per-replica files merge into one Perfetto
        # timeline via testing/cluster.merge_traces.
        from tigerbeetle_tpu import envcheck

        if not trace_path and envcheck.trace_backend() == "json":
            trace_path = envcheck.env_str(
                "TB_TRACE_PATH", f"tb_trace_r{replica_index}.json"
            )
            if "{replica}" in trace_path:
                trace_path = trace_path.format(replica=replica_index)
            elif replica_index and envcheck.env_is_set("TB_TRACE_PATH"):
                # One exported TB_TRACE_PATH shared by a whole cluster
                # must not let replicas clobber each other's trace at
                # close: non-zero indices get a suffix.
                root, ext = os.path.splitext(trace_path)
                trace_path = f"{root}.r{replica_index}{ext}"
        self._trace_path = trace_path
        # Flight recorder (obs/flight.py): always-on bounded ring of
        # recent trace events, dumped on demotion / assertion failure /
        # SIGTERM for postmortems — no file I/O until then.
        from tigerbeetle_tpu.obs.flight import FlightRecorder

        flight_path = envcheck.env_str(
            "TB_FLIGHT_PATH", f"tb_flight_r{replica_index}.json"
        )
        if "{replica}" in flight_path:
            flight_path = flight_path.format(replica=replica_index)
        elif replica_index and envcheck.env_is_set("TB_FLIGHT_PATH"):
            root, ext = os.path.splitext(flight_path)
            flight_path = f"{root}.r{replica_index}{ext}"
        self._flight_path = flight_path
        self.flight = FlightRecorder(
            process_id=replica_index, dump_path=flight_path,
            # Late-bound: every flight dump embeds the full registry
            # snapshot (dev_wave.spec.*, link forensics, QoS counters)
            # next to the event ring — the postmortem carries the
            # numbers that explain it.
            stats_fn=lambda: self.registry.snapshot(),
        )
        # The tracer now exists unconditionally: backend "json" only
        # when a trace path is configured (spans cost nothing on
        # "none"), but its instants ALWAYS mirror into the flight ring
        # — so demotions/view changes are in the postmortem dump even
        # with full tracing off (utils/tracer.py).
        from tigerbeetle_tpu.utils.tracer import Tracer

        tracer = Tracer(
            "json" if trace_path else "none", process_id=replica_index
        )
        tracer.flight = self.flight
        self.replica.set_tracer(tracer)
        self.replica.anatomy.flight = self.flight
        # Unified registry tree (obs/registry.py): the replica's and
        # state machine's registries graft in under "vsr."/"sm.", the
        # storage's fsync/byte counters ride as pull gauges, and the
        # server's own drain-loop instruments live at the top.  ONE
        # source of truth rendered three ways: TB_STATS lines
        # (_print_stats), the `stats` wire scrape, bench JSON.
        from tigerbeetle_tpu import obs

        self.registry = obs.Registry()
        self.registry.attach("vsr", self.replica.metrics)
        sm_metrics = getattr(self.replica.sm, "metrics", None)
        if sm_metrics is not None:
            self.registry.attach("sm", sm_metrics)
        storage = self.storage
        self.registry.gauge_fn("replica", lambda: replica_index)
        self.registry.gauge_fn(
            "storage.fsyncs", lambda: storage.stat_fsyncs
        )
        self.registry.gauge_fn(
            "storage.bytes_wal", lambda: storage.stat_bytes_wal
        )
        self.registry.gauge_fn(
            "storage.bytes_grid", lambda: storage.stat_bytes_grid
        )
        self.registry.gauge_fn(
            "server.queue_depth", lambda: len(self.replica.request_queue)
        )
        # Drain-loop instruments: messages per drain, wire decode time
        # per message, drains that hit the round bound.
        self._h_drain = self.registry.histogram("server.drain_msgs")
        self._h_decode = self.registry.histogram("server.decode_us")
        self._c_drains = self.registry.counter("server.drains")
        self._c_drain_rounds = self.registry.counter("server.drain_rounds")
        # Columnar ingest fast path (round 14): TB_FASTPATH_DECODE=1
        # drains the bus through one arena copy + one batch checksum
        # pass per poll (native tb_fp_verify_frames, vectorized Python
        # fallback), and client requests enter the replica as one
        # columnar batch.  TB_FASTPATH_DECODE=0 forces the legacy
        # per-message path end to end for differential runs.
        self._fastpath_decode = (
            envcheck.fastpath_decode() == 1
            and self.bus.native.supports_drain
        )
        self._drain_batch_max = envcheck.drain_batch_max()
        # decode µs per EVENT (128-byte wire records in the drain's
        # bodies) — the honest amortized unit the bench grades.
        self._h_decode_ev = self.registry.histogram(
            "server.decode_us_per_event"
        )
        self._c_fp_hits = self.registry.counter("fastpath.batch_decode_hits")
        self._c_fp_fallbacks = self.registry.counter(
            "fastpath.batch_decode_fallbacks"
        )
        # Raw ingress-verify hash total (every frame body, protocol
        # included) — the engine-load view; the commit-path subset
        # feeds vsr.hash.bytes_hashed in _dispatch_drain.
        self._c_verify_bytes = self.registry.counter(
            "server.verify_body_bytes"
        )
        # Native availability is pinned at startup (the loader caches);
        # a build failure is VISIBLE here and in the warning
        # runtime/native.py emits — benches must not pass fallback
        # numbers off as native.
        from tigerbeetle_tpu.runtime import fastpath as fastpath_mod
        from tigerbeetle_tpu.runtime import native as native_mod

        self._fastpath = fastpath_mod
        fp_unavailable = 0 if fastpath_mod.batch_verify_available() else 1
        self.registry.gauge_fn(
            "fastpath.native_unavailable", lambda: fp_unavailable
        )
        if fp_unavailable and native_mod.build_error():
            print(
                "TB_WARN fastpath native unavailable: "
                + native_mod.build_error(),
                flush=True,
            )
        # Hash-once commit path (round 23): which SHA-256 engine serves
        # the hot path (scalar fallback warned once + gauged so no
        # bench can mistake a 225 MB/s run for a SHA-NI run), plus the
        # process-global pool stats.  hash.lanes_busy counts jobs that
        # actually ran on worker lanes — 0 under TB_HASH_THREADS=0 by
        # definition.
        self.registry.gauge_fn(
            "hash.engine_code",
            lambda: {"evp": 1, "sha256-legacy": 2, "scalar": 3}.get(
                fastpath_mod.hash_engine_name(), 0
            ),
        )
        self.registry.gauge_fn(
            "hash.scalar_fallback", fastpath_mod.hash_scalar_fallback
        )
        self.registry.gauge_fn(
            "hash.lanes_busy",
            lambda: fastpath_mod.hash_stats()["lane_jobs"],
        )
        self.registry.gauge_fn(
            "hash.table_hits",
            lambda: fastpath_mod.hash_stats()["table_hits"],
        )
        self.registry.gauge_fn(
            "hash.threads",
            lambda: fastpath_mod.hash_stats()["threads"],
        )
        if fastpath_mod.batch_verify_available():
            fastpath_mod.hash_scalar_fallback()  # one-time warning
        # Coalesced reply encode (vsr/replica.py _encode_sub_replies)
        # reports into the server's instrument tree.
        self._h_reply_encode = self.registry.histogram(
            "server.reply_encode_us"
        )
        self.replica.h_reply_encode = self._h_reply_encode
        # Admission control: fresh requests beyond TB_ADMIT_QUEUE
        # queued requests are shed with a typed Command.client_busy —
        # overload degrades visibly (shed counter, bounded queue)
        # instead of growing the tail unboundedly.  The bound lives in
        # the REPLICA's enqueue path, below the at-most-once gate, so
        # a retransmission of a committed request still gets its
        # stored reply under overload (never a busy).
        self.admit_queue = envcheck.admit_queue(
            config.pipeline_prepare_queue_max
        )
        self.registry.gauge_fn("server.admit_queue", lambda: self.admit_queue)
        self._c_shed = self.registry.counter("server.shed")
        self.replica.admit_queue = self.admit_queue
        self.replica.on_shed = self._on_shed
        # Multi-tenant QoS (round 16): admission, drain order, and
        # shedding keyed by tenant (ledger).  TB_TENANT_QOS=0 pins the
        # legacy single-queue path exactly (replica.qos stays None);
        # on, the per-tenant admit/shed/lat_us instruments land under
        # vsr.qos.t<ledger>.* in the registry tree, so the stats wire
        # op scrapes them like everything else.
        if envcheck.tenant_qos():
            from tigerbeetle_tpu.qos import TenantQos

            self.replica.qos = TenantQos(
                rate=envcheck.tenant_rate(),
                rate_bytes=envcheck.tenant_rate_bytes(),
                queue_bound=envcheck.tenant_queue(self.admit_queue),
                weights=envcheck.tenant_weights(),
                registry=self.replica.metrics.scope("qos"),
            )
            qos = self.replica.qos
            self.registry.gauge_fn("server.tenant_rate", lambda: qos.rate)
            self.registry.gauge_fn(
                "server.tenant_queue", lambda: qos.queue_bound
            )
        self.replica.open()
        # Root ring (round 19): retain the state root of recent
        # commits so the `state_root` at-op query can attest follower
        # replays.  TB_ROOT_RING=0 disables; costs one state_root()
        # read per commit (a 16-byte digest copy on the incremental-
        # commitment state machines).
        ring = envcheck.root_ring()
        if ring and hasattr(self.replica.sm, "state_root"):
            self.replica.enable_root_ring(ring)
        self._last_tick = 0
        self._last_stats = 0
        self._stats_snapshot: tuple | None = None

    @property
    def port(self) -> int:
        return self.bus.port

    # Bound on drain rounds per poll_once: each extra round is a
    # zero-timeout poll, so a chattering peer cannot starve ticks.
    DRAIN_ROUNDS_MAX = 16

    def poll_once(self, timeout_ms: int = 10) -> None:
        """One loop iteration: drain ALL ready bus events (so one
        group-commit sync covers a whole pipeline's worth of prepares
        and replies coalesce per drain), then tick on cadence, then
        flush the group commit — no ack leaves before its covering
        sync.  TB_GROUP_COMMIT_MAX_US bounds deferral inside a long
        drain.

        With TB_FASTPATH_DECODE=1 (default) each round is columnar:
        one C call copies every ready event into a contiguous arena,
        one batch pass verifies every frame's checksums, headers are
        gathered in one vectorized pass, and the round's client
        requests enter the replica as one batch
        (vsr/multi.py on_requests_batch) — no per-message Python on
        the hot path."""
        deadline_ns = self.replica.group_commit_max_us * 1_000
        drain_t0 = None
        rounds = 0
        drained = 0
        while True:
            t_poll = timeout_ms if rounds == 0 else 0
            rounds += 1
            if self._fastpath_decode:
                batch = self.bus.native.poll_drain(
                    t_poll, self._drain_batch_max
                )
                got = batch[0] > 0
                if got:
                    drained += self._dispatch_drain(*batch)
            else:
                events = self.bus.native.poll(t_poll)
                got = bool(events)
                for ev_type, conn, payload in events:
                    if ev_type == EV_CLOSED:
                        self.bus.drop_conn(conn)
                    elif ev_type == EV_MESSAGE:
                        drained += 1
                        self._on_raw_message(conn, payload)
            if self.replica._gc_pending and drain_t0 is None:
                drain_t0 = time.monotonic_ns()
            if drain_t0 is not None and (
                time.monotonic_ns() - drain_t0 >= deadline_ns
            ):
                # Deferral deadline inside a busy drain: sync + release
                # now; later messages start a fresh batch.
                self.replica.flush_group_commit()
                drain_t0 = None
            if not got or rounds >= self.DRAIN_ROUNDS_MAX:
                break
        if drained:
            # Drain-size distribution: how many messages one covering
            # sync amortizes over (the group-commit win, measured).
            self._c_drains.inc()
            self._c_drain_rounds.inc(rounds)
            self._h_drain.observe(drained)
        now = time.monotonic_ns()
        if now - self._last_tick >= TICK_NS:
            self._last_tick = now
            self.replica.realtime = time.time_ns()
            # Real elapsed time, not tick counts, so clock-sync RTT
            # error bounds reflect event-loop stalls.
            self.replica.monotonic_external = True
            self.replica.monotonic = now
            self.replica.tick()
            self.bus.connect_peers(self.replica.cluster, self.replica.view)
            if now - self._last_stats >= 100 * TICK_NS:  # ~1s cadence
                self._last_stats = now
                self._print_stats()
        self.replica.flush_group_commit()

    # TB_STATS line schema: legacy key -> registry snapshot key.  The
    # line is a RENDERING of the registry (one source of truth with
    # the `stats` scrape); it survives kill -9 in the log tail, which
    # is why bench keeps a log-tail parser as fallback.
    STATS_LINE_KEYS = (
        ("fsyncs", "storage.fsyncs"),
        ("prepares", "vsr.prepares_written"),
        ("gc_flushes", "vsr.gc_flushes"),
        ("commit_min", "vsr.commit_min"),
        ("ckpt_async", "vsr.ckpt.async"),
        ("commits", "vsr.commits"),
    )

    def _print_stats(self) -> None:
        """One greppable counters line per second of activity on
        stdout (the replica log), rendered from the registry snapshot.
        Idle-dedup compares the RENDERED values — derived from the
        same STATS_LINE_KEYS map that prints, so a key added to the
        line is automatically in the comparison (the old hand-picked
        tuple silently went stale instead).  The raw snapshot version
        deliberately stays out of the line: heartbeat decode samples
        bump it every tick, and keying the dedup on it would grow an
        idle cluster's log ~1 line/s forever."""
        snap = self.registry.snapshot()
        rendered = tuple(
            int(snap.get(key, 0)) for _legacy, key in self.STATS_LINE_KEYS
        )
        if rendered == self._stats_snapshot:
            return  # idle: don't grow the log
        self._stats_snapshot = rendered
        print(
            "TB_STATS " + " ".join(
                f"{legacy}={value}"
                for (legacy, _key), value in zip(
                    self.STATS_LINE_KEYS, rendered
                )
            ),
            flush=True,
        )

    def _dispatch_drain(self, n, ev_types, conns, offsets, lens,
                        arena) -> int:
        """Columnar round: verify every framed message in ONE batch
        checksum pass (native, or the vectorized Python fallback),
        gather all headers in one vectorized cast, then walk the
        events in arrival order — protocol messages dispatch inline
        (pre-verified), client requests collect into one columnar
        batch handed to the replica at the end of the round.  Bodies
        stay zero-copy views of the drain arena until a retention
        point (queue/prepare) forces the single necessary copy."""
        import numpy as np

        is_msg = (ev_types[:n] == EV_MESSAGE) & (lens[:n] > 0)
        midx = np.nonzero(is_msg)[0]
        hdrs = ok = None
        if len(midx):
            t0 = time.perf_counter_ns()
            moffs = offsets[midx]
            mlens = lens[midx]
            ok, hdrs, native, bytes_hashed = self._fastpath.verify_and_gather(
                arena, moffs, mlens
            )
            (self._c_fp_hits if native else self._c_fp_fallbacks).inc()
            # The verify pass is the ingress hash tier.  The replica's
            # hash.bytes_hashed tracks COMMIT-PATH body bytes only
            # (request + prepare frames that verified — the bodies
            # whose digests the reuse seams may consume), so the smoke
            # ratio against committed_body_bytes is exact; protocol
            # bodies (ping clock advertisements etc.) are control-plane
            # noise and land in server.verify_body_bytes, the raw
            # engine total.  bytes_hashed is None only on the
            # stale-.so corner — skip, never guess.
            if bytes_hashed is not None:
                self._c_verify_bytes.inc(bytes_hashed)
                cmds = hdrs["command"]
                ops = hdrs["operation"]
                # Sessionless admin queries (stats / state_root) are
                # request frames that never commit — excluded, or a
                # scrape-polling client would inflate the numerator.
                rel = np.asarray(ok, bool) & (
                    (
                        (cmds == int(Command.request))
                        & (ops != int(wire.VsrOperation.stats))
                        & (ops != int(wire.VsrOperation.state_root))
                    )
                    | (cmds == int(Command.prepare))
                )
                rel_bytes = (
                    int(mlens[rel].sum()) - HEADER_SIZE * int(rel.sum())
                )
                if rel_bytes > 0:
                    self.replica._c_hash_bytes.inc(rel_bytes)
            # Amortized decode cost per 128-byte event record, sampled
            # only for rounds that actually carry event bodies —
            # protocol-only rounds (heartbeats, prepare_oks) would
            # otherwise report the fixed per-drain setup cost as a
            # bogus "per event" number.
            n_events = (int(mlens.sum()) - HEADER_SIZE * len(midx)) // 128
            if n_events > 0:
                self._h_decode_ev.observe(
                    (time.perf_counter_ns() - t0) / 1e3 / n_events
                )
        mv = memoryview(arena)
        msgs = 0
        pos = 0
        req_hdrs: list = []
        req_bodies: list = []
        # Contiguous same-command runs of prepare / prepare_ok frames
        # collect here and hand off as ONE batch call (vsr/multi.py
        # on_prepares_batch / on_prepare_oks_batch) — the r22
        # C-resident drain seam.  Any other event flushes the pending
        # run first, so relative order against non-run messages is
        # exactly the per-message walk's; requests still defer to the
        # end of the round (r14 behavior), AFTER the final flush.
        run_kind = 0
        run_hdrs: list = []
        run_bodies: list = []

        def flush_run() -> None:
            nonlocal run_kind, run_hdrs, run_bodies
            if not run_hdrs:
                return
            if run_kind == int(Command.prepare):
                self.replica.on_prepares_batch(run_hdrs, run_bodies)
            else:
                self.replica.on_prepare_oks_batch(run_hdrs)
            run_kind = 0
            run_hdrs = []
            run_bodies = []

        for j in range(n):
            et = int(ev_types[j])
            conn = int(conns[j])
            if et == EV_CLOSED:
                flush_run()
                self.bus.drop_conn(conn)
                continue
            if et != EV_MESSAGE or not lens[j]:
                continue
            i = pos
            pos += 1
            if not ok[i]:
                continue
            msgs += 1
            header = hdrs[i]
            off = int(offsets[j])
            end = off + int(lens[j])
            cmd = int(header["command"])
            if cmd == int(Command.request):
                if int(header["operation"]) == int(wire.VsrOperation.stats):
                    # Scrapes answer from live state: flush so they
                    # observe everything that arrived before them.
                    flush_run()
                    self._send_stats_reply(conn, header)
                    continue
                if int(header["operation"]) == int(
                    wire.VsrOperation.state_root
                ):
                    flush_run()
                    self._send_state_root_reply(
                        conn, header, mv[off + HEADER_SIZE : end]
                    )
                    continue
                self.replica.anatomy.stage_h(header, "ingress")
                self.bus.register_client(conn, wire.u128(header, "client"))
                req_hdrs.append(header)
                req_bodies.append(mv[off + HEADER_SIZE : end])
            elif cmd in (int(Command.prepare), int(Command.prepare_ok)):
                # Learn peer identity at collection time, exactly as
                # _dispatch_message would per message (ack routing in
                # the batch path needs the conn registered).
                if int(header["replica"]) != self.replica.replica:
                    if self.bus._conn_peer.get(conn) is None:
                        self.bus.register_peer(conn, int(header["replica"]))
                if run_kind != cmd:
                    flush_run()
                    run_kind = cmd
                run_hdrs.append(header)
                if cmd == int(Command.prepare):
                    run_bodies.append(mv[off + HEADER_SIZE : end])
            else:
                flush_run()
                self._dispatch_message(
                    conn, header, bytes(mv[off + HEADER_SIZE : end]),
                    verified=True,
                )
        flush_run()
        if req_hdrs:
            self.replica.on_requests_batch(req_hdrs, req_bodies)
        return msgs

    def _send_stats_reply(self, conn: int, header) -> None:
        # Admin scrape (obs/scrape.py): answered from the registry
        # snapshot right here — read-only, sessionless, and never
        # enters the consensus pipeline.  Tail exemplars (the slow
        # requests' stage timelines) ride along as a structured key
        # next to the flat counters.
        from tigerbeetle_tpu.obs.scrape import stats_reply

        snap = self.registry.snapshot()
        snap["anatomy.exemplars"] = (
            self.replica.anatomy.exemplar_snapshot()
        )
        reply, body = stats_reply(snap, header)
        self.bus.native.send(conn, reply.tobytes() + body)

    def _send_state_root_reply(self, conn: int, header,
                               query: bytes = b"") -> None:
        # Proof-of-state hook (state_machine/commitment.py): the
        # 16-byte incremental state commitment + the commit_min it is
        # current to — read-only, sessionless, answered here so it can
        # never enter consensus.  Replicas without a commitment-aware
        # state machine answer zeros (the client treats an all-zero
        # root as "not supported / empty").  A query body naming an op
        # answers from the root ring (the follower attestation
        # primitive) when that op is still retained; otherwise the
        # current root goes out and the caller sees the op mismatch.
        from tigerbeetle_tpu.obs.scrape import state_root_reply
        from tigerbeetle_tpu.state_machine import commitment

        sm = self.replica.sm
        at_op = commitment.parse_root_query(bytes(query))
        root = at_op_root = None
        if at_op is not None:
            at_op_root = self.replica.root_at(at_op)
        if at_op_root is not None:
            root, commit_min = at_op_root, at_op
        else:
            root = sm.state_root() if hasattr(sm, "state_root") else bytes(16)
            commit_min = self.replica.commit_min
        reply, body = state_root_reply(root, commit_min, header)
        self.bus.native.send(conn, reply.tobytes() + body)

    def _on_raw_message(self, conn: int, payload: bytes) -> None:
        if len(payload) < HEADER_SIZE:
            return
        # Wire decode cost (header cast + checksum verify) — the
        # per-message cost the columnar ingest path replaces; measured
        # here so the legacy arm reports its µs honestly, including
        # the SAME per-event amortized instrument the columnar drain
        # feeds (the TB_FASTPATH_DECODE=0/1 bench arms compare it).
        t0 = time.perf_counter_ns()
        header = wire.header_from_bytes(payload[:HEADER_SIZE])
        body = payload[HEADER_SIZE:]
        ok = wire.verify_header(header, body)
        decode_us = (time.perf_counter_ns() - t0) / 1e3
        self._h_decode.observe(decode_us)
        n_events = len(body) // 128
        if n_events > 0:
            self._h_decode_ev.observe(decode_us / n_events)
        if not ok:
            return
        self._dispatch_message(conn, header, body, verified=True)

    def _dispatch_message(self, conn: int, header, body: bytes,
                          verified: bool = False) -> None:
        cmd = int(header["command"])
        if cmd == int(Command.request) and (
            int(header["operation"]) == int(wire.VsrOperation.stats)
        ):
            self._send_stats_reply(conn, header)
            return
        if cmd == int(Command.request) and (
            int(header["operation"]) == int(wire.VsrOperation.state_root)
        ):
            self._send_state_root_reply(conn, header, body)
            return
        if cmd in (Command.ping, Command.pong):
            announce = int(header["request"]) == TcpBus.ANNOUNCE_REQUEST
            self.bus.register_peer(
                conn, int(header["replica"]), is_process=announce
            )
            if announce:
                # Transport-only handshake: the replica field is a
                # PROCESS index, which the protocol layer would misread
                # as a slot (polluting slot-keyed release/clock maps) —
                # answer with a reciprocal announce so the connector
                # registers this side too, and stop here.
                if cmd == int(Command.ping):
                    # Pong-flavored so the reciprocal doesn't echo.
                    self.bus._announce(
                        conn, self.replica.cluster, self.replica.view,
                        pong=True,
                    )
                return
            # Protocol ping/pong: carries clock-sync samples
            # (vsr/clock.py); the reply rides the registered conn.
            self.replica.on_message(header, body, verified=verified)
            return
        if cmd == Command.request:
            # Ingress stage for sampled requests (trace context is
            # CLIENT-owned: the server never mints one — a minted id
            # would alter prepare checksums and break the recorded
            # wire contract for legacy clients; unsampled requests
            # stay byte-identical end to end).  Admission shedding
            # happens in the replica's enqueue path, AFTER dedupe.
            self.replica.anatomy.stage_h(header, "ingress")
            self.bus.register_client(conn, wire.u128(header, "client"))
        elif int(header["replica"]) != self.replica.replica:
            # Learn peer identity from any replica-sourced message.
            kind = self.bus._conn_peer.get(conn)
            if kind is None and cmd not in (
                int(Command.reply), int(Command.eviction),
            ):
                self.bus.register_peer(conn, int(header["replica"]))
        self.replica.on_message(header, body, verified=verified)

    def _on_shed(self, header, tenant=None) -> None:
        """Replica shed callback: count + flight-note (the replica
        already sent the typed busy on the client's connection).  The
        tenant rides the note so a postmortem flight dump shows WHO
        was shed during an overload window — and a per-tenant shed
        instant (`shed.t<ledger>`) makes the per-tenant timeline
        greppable without parsing note args."""
        self._c_shed.inc()
        self.flight.note(
            "shed", client=wire.u128(header, "client"),
            request=int(header["request"]),
            queue=len(self.replica.request_queue),
            tenant=-1 if tenant is None else tenant,
        )
        if tenant is not None:
            self.flight.note(f"shed.t{tenant}")

    def install_flight_handlers(self) -> None:
        """Dump the flight ring on SIGTERM, then die with the default
        disposition (exit code intact for supervisors).  Main-thread
        only — in-process test servers (threaded loops) skip it."""
        import signal

        def on_sigterm(signum, frame):
            try:
                self.flight.write(self._flight_path, reason="sigterm")
            finally:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        try:
            signal.signal(signal.SIGTERM, on_sigterm)
        except ValueError:
            pass  # not the main thread: no signal-based dump

    def serve_forever(self) -> None:
        self.install_flight_handlers()
        while True:
            try:
                self.poll_once()
            except AssertionError as exc:
                # Invariant violation: capture the last moments before
                # the crash.  The `assertion_failure` event is a flight
                # trigger, so note() flushes the ring to disk.
                self.flight.note("assertion_failure", error=repr(exc)[:500])
                raise

    def close(self) -> None:
        # Device-engine end-of-life barrier first: every outstanding
        # reply future must resolve (host replay if the link is gone)
        # or fail typed before the process tears down its I/O.
        sm = getattr(self.replica, "sm", None)
        dev = getattr(sm, "_dev", None)
        if dev is not None and hasattr(dev, "close"):
            dev.close()
        # Release any held acks, then join background durability work
        # (in-flight async checkpoint flip, WAL sync) BEFORE any fd
        # closes — the checkpoint worker's finalize calls aof.sync()
        # and storage.sync(), and closing those fds first would turn
        # the join into an EBADF (or worse, an fdatasync on a reused
        # fd number).
        self.replica.flush_group_commit()
        self.replica.close()
        if self.replica.aof is not None:
            self.replica.aof.close()
        if self._trace_path:
            self.replica.tracer.write(self._trace_path)
        self.bus.native.close()
        self.storage.close()


def format_data_file(path: str, *, cluster: int, replica_index: int = 0,
                     replica_count: int = 1,
                     config: cfg.Config = cfg.PRODUCTION,
                     grid_size: int = 1 << 20) -> None:
    layout = ZoneLayout(config=config, grid_size=grid_size)
    storage = FileStorage(path, layout, create=True)
    vsr_format.format(storage, cluster, replica_index, replica_count)
    storage.close()
