"""ctypes bindings for the native commit fast path (tb_fastpath.cpp).

The host side of the TPU commit pipeline — wire decode, static ladder,
account resolution, duplicate detection, u128 overflow admission — runs
in C++ at memcpy-like speed; Python keeps orchestration, the columnar
stores, and the device queue.  The balance mirror memory is OWNED by
the native library and wrapped zero-copy as numpy arrays, so exact-path
(JAX kernel) commits and expiry mutations are immediately visible to
the native admission checks and vice versa.

Falls back to None (pure-Python path) when no compiler/library exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
from tigerbeetle_tpu import envcheck

def _default_lib_path() -> str:
    # Shares the sanitizer-flavor knob with runtime/native.py: under
    # TB_NATIVE_SANITIZE=asan both libraries load their sanitized
    # builds from native/asan/.
    from tigerbeetle_tpu.runtime import native as native_mod

    return os.path.join(native_mod._lib_dir(), "libtb_fastpath.so")


_LIB_PATH = envcheck.env_str("TB_FASTPATH_LIB", _default_lib_path())

_lib = None
_lib_failed = False  # negative cache: never retry (or re-make) per call
_lib_lock = threading.Lock()

_U64P = ctypes.POINTER(ctypes.c_uint64)
_U32P = ctypes.POINTER(ctypes.c_uint32)
_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)
_U8P = ctypes.POINTER(ctypes.c_uint8)

FALLBACK = 1


def kway_merge(streams, value_size: int):
    """Native k-way merge of sorted-unique (keys V16, flags u8, vals
    (n, value_size) u8) streams, newest first.  Returns merged arrays
    or None when the native library is unavailable."""
    import numpy as np

    lib = _load()
    if lib is None:
        return None
    k = len(streams)
    total = sum(len(s[0]) for s in streams)
    keys_c = [np.ascontiguousarray(s[0]) for s in streams]
    flags_c = [np.ascontiguousarray(s[1]) for s in streams]
    vals_c = [np.ascontiguousarray(s[2]) for s in streams]
    key_ptrs = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[a.ctypes.data_as(_U8P) for a in keys_c]
    )
    flag_ptrs = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[a.ctypes.data_as(_U8P) for a in flags_c]
    )
    val_ptrs = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[a.ctypes.data_as(_U8P) for a in vals_c]
    )
    lens = (ctypes.c_int64 * k)(*[len(s[0]) for s in streams])
    out_keys = np.empty(total, dtype="V16")
    out_flags = np.empty(total, np.uint8)
    out_vals = np.empty((total, value_size), np.uint8)
    n = lib.tb_lsm_kway_merge(
        k, key_ptrs, flag_ptrs, val_ptrs, lens, value_size,
        out_keys.ctypes.data_as(_U8P) if total else None,
        out_flags.ctypes.data_as(_U8P) if total else None,
        out_vals.ctypes.data_as(_U8P) if total else None,
    )
    return out_keys[:n], out_flags[:n], out_vals[:n]


def _load():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            # This round's drain/commit hot paths probe availability
            # per call: without this, a host where the build fails
            # would fork a `make` per server drain instead of
            # degrading to the pure-Python fallback.
            return None
        if envcheck.env_is_set("TB_FASTPATH_DISABLE"):
            return None
        _lib_failed = True  # cleared on success below
        # Always invoke make: a no-op when fresh, and it rebuilds a
        # stale prebuilt .so whose missing symbols would fail the
        # argtypes registration below.  Build failures are recorded +
        # warned (runtime/native.py _run_make), never silently eaten —
        # a bench must not report pure-Python fallback numbers as
        # native.
        from tigerbeetle_tpu.runtime import native as native_mod

        native_mod._run_make(_LIB_PATH)
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None

        lib.tb_fp_create.restype = ctypes.c_void_p
        lib.tb_fp_create.argtypes = [ctypes.c_uint64]
        lib.tb_fp_destroy.argtypes = [ctypes.c_void_p]
        lib.tb_fp_balances_lo.restype = _U64P
        lib.tb_fp_balances_lo.argtypes = [ctypes.c_void_p]
        lib.tb_fp_balances_hi.restype = _U64P
        lib.tb_fp_balances_hi.argtypes = [ctypes.c_void_p]
        lib.tb_fp_add_accounts.argtypes = [
            ctypes.c_void_p, _U64P, _U64P, _U32P, _U32P,
            ctypes.c_uint32, ctypes.c_uint64,
        ]
        lib.tb_fp_remove_accounts.argtypes = [
            ctypes.c_void_p, _U64P, _U64P, ctypes.c_uint32,
        ]
        lib.tb_fp_add_transfer_ids.argtypes = [
            ctypes.c_void_p, _U64P, _U64P, ctypes.c_uint64, ctypes.c_uint32,
        ]
        lib.tb_fp_remove_transfer_ids.argtypes = [
            ctypes.c_void_p, _U64P, _U64P, ctypes.c_uint32,
        ]
        lib.tb_fp_commit_transfers.restype = ctypes.c_int
        lib.tb_fp_commit_transfers.argtypes = [
            ctypes.c_void_p, _U8P, ctypes.c_uint32, ctypes.c_uint64,
            _U32P, _I32P, _I32P, _I64P, _I64P, _U64P, _U64P, _U32P,
        ]
        lib.tb_fp_commit_linked.restype = ctypes.c_int
        lib.tb_fp_commit_linked.argtypes = [
            ctypes.c_void_p, _U8P, ctypes.c_uint32, ctypes.c_uint64,
            _U32P, _I32P, _I32P, _I64P, _I64P, _U64P, _U64P, _U32P,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tb_fp_commit_two_phase.restype = ctypes.c_int
        lib.tb_fp_commit_two_phase.argtypes = [
            ctypes.c_void_p, _U8P, ctypes.c_uint32, ctypes.c_uint64,
            # durable-target join
            _I64P, _U32P, _I32P, _I32P, _U64P, _U64P, _U32P, _U32P,
            _U64P, _U64P, _U64P, _U32P, _U32P, _U32P,
            # outputs
            _U32P, _I32P, _I32P, _U64P, _U64P, _U64P, _U64P, _U64P,
            _U32P, _U32P, _U32P, _U32P,
            _I64P, _U32P, _U32P,
            _I64P, _I64P, _U64P, _U64P, _U32P,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tb_fp_commit_exact.restype = ctypes.c_int
        lib.tb_fp_commit_exact.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_uint32, _U32P, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_uint32,
            _U64P, _I64P, _I64P, _U64P, _U64P, _U32P,
        ]
        lib.tb_lsm_kway_merge.restype = ctypes.c_int64
        lib.tb_lsm_kway_merge.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(_U8P), ctypes.POINTER(_U8P),
            ctypes.POINTER(_U8P), _I64P, ctypes.c_int32,
            _U8P, _U8P, _U8P,
        ]
        lib.tb_fp_decode_store.argtypes = [
            _U8P, ctypes.c_uint32, ctypes.c_uint64,
            _U64P, _U64P, _U64P, _U64P, _U64P, _U64P,
            _U64P, _U64P, _U64P,
            _U32P, _U32P, _U32P, _U32P, _U32P, _U64P, _U8P,
        ]
        # Columnar ingest (absent from a stale prebuilt .so when the
        # rebuild failed: callers fall back per-call).
        try:
            lib.tb_fp_verify_frames.argtypes = [
                _U8P, ctypes.POINTER(ctypes.c_uint64), _U32P,
                ctypes.c_uint32, _U8P,
            ]
            lib.tb_fp_finalize_headers.argtypes = [
                _U8P, ctypes.c_uint32, ctypes.POINTER(_U8P), _U32P,
            ]
        except AttributeError:
            lib.tb_fp_verify_frames = None
            lib.tb_fp_finalize_headers = None
        # r23 hash family: counted verify + the hash-pool / engine
        # controls.  Absent from a stale prebuilt .so whose rebuild
        # failed: callers degrade to the r20 symbols (uncounted) or
        # the Python fallback — the pipeline ABI check reports the
        # staleness loudly either way.
        try:
            lib.tb_fp_verify_frames2.restype = ctypes.c_uint64
            lib.tb_fp_verify_frames2.argtypes = [
                _U8P, ctypes.POINTER(ctypes.c_uint64), _U32P,
                ctypes.c_uint32, _U8P,
            ]
            lib.tb_hash_configure.argtypes = [
                ctypes.c_int32, ctypes.c_int32,
            ]
            lib.tb_hash_engine.restype = ctypes.c_int32
            lib.tb_hash_engine.argtypes = []
            lib.tb_hash_stats.argtypes = [_U64P]
        except AttributeError:
            lib.tb_fp_verify_frames2 = None
            lib.tb_hash_configure = None
            lib.tb_hash_engine = None
            lib.tb_hash_stats = None
        # Native commit pipeline (round 20).  Absent symbols mean a
        # stale prebuilt .so whose rebuild failed: pipeline_available()
        # reports False with a rebuild hint instead of letting an
        # AttributeError fire mid-drain.
        try:
            lib.tb_pl_abi_version.restype = ctypes.c_uint32
            lib.tb_pl_abi_version.argtypes = []
            lib.tb_pl_create.restype = ctypes.c_void_p
            lib.tb_pl_create.argtypes = []
            lib.tb_pl_destroy.argtypes = [ctypes.c_void_p]
            lib.tb_pl_reset.argtypes = [ctypes.c_void_p]
            lib.tb_pl_size.restype = ctypes.c_uint32
            lib.tb_pl_size.argtypes = [ctypes.c_void_p]
            lib.tb_pl_build_prepare.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32, _U8P,
            ]
            lib.tb_pl_build_prepare_ok.argtypes = [
                ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32, _U8P,
            ]
            lib.tb_pl_frame_prepare.restype = ctypes.c_uint64
            lib.tb_pl_frame_prepare.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
                _U8P, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                _U8P, _U8P,
            ]
            lib.tb_pl_note_prepare.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                ctypes.c_uint32,
            ]
            lib.tb_pl_on_ack.restype = ctypes.c_int
            lib.tb_pl_on_ack.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.tb_pl_mark_all_synced.argtypes = [ctypes.c_void_p]
            lib.tb_pl_set_synced.restype = ctypes.c_int
            lib.tb_pl_set_synced.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
            ]
            lib.tb_pl_drop.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.tb_pl_commit_ready.restype = ctypes.c_int
            lib.tb_pl_commit_ready.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
            ]
            lib.tb_pl_votes.restype = ctypes.c_uint32
            lib.tb_pl_votes.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            # C-resident drain loop (round 22; flags added r23, ABI
            # 3).  Grouped with the r20 symbols on purpose: a stale
            # .so missing ANY of them disables the whole pipeline (and
            # reports ABI != 3 anyway), never a mixed old/new symbol
            # set.
            lib.tb_pl_build_prepares.restype = ctypes.c_int64
            lib.tb_pl_build_prepares.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(_U8P),
                _U64P, _U64P, _U64P, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_int, ctypes.c_uint32, _U8P,
                _U8P, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                _U8P, ctypes.c_uint64, _U64P, _U64P, _U64P, _U8P, _U64P,
            ]
            lib.tb_pl_accept_prepares.restype = ctypes.c_int64
            lib.tb_pl_accept_prepares.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(_U8P), _U64P,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_int, _U8P,
                _U8P, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                _U8P, ctypes.c_uint64, _U64P, _U64P, _U64P, _U8P, _U64P,
            ]
            lib.tb_pl_on_acks.restype = ctypes.c_int64
            lib.tb_pl_on_acks.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32, _I64P,
            ]
            lib.tb_pl_commit_ready_run.restype = ctypes.c_uint64
            lib.tb_pl_commit_ready_run.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
            ]
        except AttributeError:
            lib.tb_pl_abi_version = None
        _lib = lib
        _lib_failed = False
        # Push the envcheck-validated pool sizing down at load: C
        # never reads the environment itself (the tbcheck envcheck
        # rule), and every later crossing inherits the lanes.
        if lib.tb_hash_configure is not None:
            lib.tb_hash_configure(envcheck.hash_threads(), 0)
        return _lib


def decode_store(events: np.ndarray, n: int, ts_base: int,
                 cols: dict, lo: int) -> None:
    """One C pass: wire Transfer records -> contiguous store columns
    written in place at cols[name][lo:lo+n] (tpu.py _STORE_FIELDS
    minus dr/cr slots).  PRECONDITION: every event applied — callers
    with failures take the shared slow path.  `events` is the
    contiguous wire-record array (read-only frombuffer views are fine
    — the C side only reads)."""
    lib = _load()
    assert lib is not None
    assert events.flags["C_CONTIGUOUS"]

    def at(name, ptype):
        arr = cols[name]
        return ctypes.cast(
            arr.ctypes.data + lo * arr.dtype.itemsize, ptype
        )

    lib.tb_fp_decode_store(
        ctypes.cast(events.__array_interface__["data"][0], _U8P),
        n, ts_base,
        at("id_lo", _U64P), at("id_hi", _U64P),
        at("amount_lo", _U64P), at("amount_hi", _U64P),
        at("pending_lo", _U64P), at("pending_hi", _U64P),
        at("ud128_lo", _U64P), at("ud128_hi", _U64P), at("ud64", _U64P),
        at("ud32", _U32P), at("timeout", _U32P), at("ledger", _U32P),
        at("code", _U32P), at("flags", _U32P), at("timestamp", _U64P),
        at("status", _U8P),
    )


def _p(arr: np.ndarray, ptype):
    return arr.ctypes.data_as(ptype)


class _OwnedView(np.ndarray):
    """ndarray view that keeps its native owner alive (lifetime tie),
    propagated to any derived view via __array_finalize__."""

    _owner = None

    def __array_finalize__(self, obj):
        if obj is not None:
            self._owner = getattr(obj, "_owner", None)


class NativeFastpath:
    """One native fast-path instance per TpuStateMachine."""

    def __init__(self, account_capacity: int) -> None:
        lib = _load()
        assert lib is not None
        self._lib = lib
        self._fp = lib.tb_fp_create(account_capacity)
        self.capacity = account_capacity
        # Zero-copy numpy views over the native balance mirror.  The
        # views hold a reference back to this object so the native
        # buffers cannot be freed while any view (e.g. the Python
        # BalanceMirror) is still alive.
        self.lo = np.ctypeslib.as_array(
            lib.tb_fp_balances_lo(self._fp), shape=(account_capacity, 4)
        ).view(_OwnedView)
        self.lo._owner = self
        self.hi = np.ctypeslib.as_array(
            lib.tb_fp_balances_hi(self._fp), shape=(account_capacity, 4)
        ).view(_OwnedView)
        self.hi._owner = self
        # Reusable output buffers (sized for the largest batch).
        n_max = 8192
        self._results = np.empty(n_max, np.uint32)
        self._dr_slot = np.empty(n_max, np.int32)
        self._cr_slot = np.empty(n_max, np.int32)
        # Deltas are bounded both by touched columns (4/account) and by
        # 4 per event (a post/void touches dp+dpo and cp+cpo).
        d_max = min(4 * account_capacity, 4 * n_max) + 8
        self._dslot = np.empty(d_max, np.int64)
        self._dcol = np.empty(d_max, np.int64)
        self._dlo = np.empty(d_max, np.uint64)
        self._dhi = np.empty(d_max, np.uint64)
        self._ndeltas = ctypes.c_uint32(0)
        self._packed = None
        self._field_dtypes = None
        self._last_applied = ctypes.c_int32(-1)
        # Two-phase resolver outputs (reused per call).
        self._tp_amt_lo = np.empty(n_max, np.uint64)
        self._tp_amt_hi = np.empty(n_max, np.uint64)
        self._tp_ud128_lo = np.empty(n_max, np.uint64)
        self._tp_ud128_hi = np.empty(n_max, np.uint64)
        self._tp_ud64 = np.empty(n_max, np.uint64)
        self._tp_ud32 = np.empty(n_max, np.uint32)
        self._tp_ledger = np.empty(n_max, np.uint32)
        self._tp_code = np.empty(n_max, np.uint32)
        self._tp_inb = np.empty(n_max, np.uint32)
        self._tp_dur_rows = np.empty(n_max, np.int64)
        self._tp_dur_status = np.empty(n_max, np.uint32)
        self._tp_ndur = ctypes.c_uint32(0)
        self._tp_empty_u64 = np.zeros(n_max, np.uint64)
        self._tp_empty_u32 = np.zeros(n_max, np.uint32)
        self._tp_empty_i32 = np.full(n_max, -1, np.int32)
        self._tp_empty_i64 = np.full(n_max, -1, np.int64)

    def __del__(self):
        if getattr(self, "_fp", None):
            self._lib.tb_fp_destroy(self._fp)
            self._fp = None

    def add_accounts(self, id_lo, id_hi, flags, ledger, base_slot: int) -> None:
        id_lo = np.ascontiguousarray(id_lo, np.uint64)
        id_hi = np.ascontiguousarray(id_hi, np.uint64)
        flags = np.ascontiguousarray(flags, np.uint32)
        ledger = np.ascontiguousarray(ledger, np.uint32)
        self._lib.tb_fp_add_accounts(
            self._fp, _p(id_lo, _U64P), _p(id_hi, _U64P),
            _p(flags, _U32P), _p(ledger, _U32P), len(id_lo), base_slot,
        )

    def remove_accounts(self, id_lo, id_hi) -> None:
        id_lo = np.ascontiguousarray(id_lo, np.uint64)
        id_hi = np.ascontiguousarray(id_hi, np.uint64)
        self._lib.tb_fp_remove_accounts(
            self._fp, _p(id_lo, _U64P), _p(id_hi, _U64P), len(id_lo)
        )

    def add_transfer_ids(self, id_lo, id_hi, base_row: int) -> None:
        id_lo = np.ascontiguousarray(id_lo, np.uint64)
        id_hi = np.ascontiguousarray(id_hi, np.uint64)
        self._lib.tb_fp_add_transfer_ids(
            self._fp, _p(id_lo, _U64P), _p(id_hi, _U64P), base_row, len(id_lo)
        )

    def remove_transfer_ids(self, id_lo, id_hi) -> None:
        id_lo = np.ascontiguousarray(id_lo, np.uint64)
        id_hi = np.ascontiguousarray(id_hi, np.uint64)
        self._lib.tb_fp_remove_transfer_ids(
            self._fp, _p(id_lo, _U64P), _p(id_hi, _U64P), len(id_lo)
        )

    def commit_exact(self, ev: dict, field_order, dstat_init, B: int,
                     n: int, ts_base: int):
        """Serial exact engine (native/tb_exact.inc): same inputs and
        packed-output layout as the JAX scan kernel, so the caller
        unpacks with kernel.unpack_outputs.  Mutates the shared mirror;
        returns (packed (B, N_COLS) u64, deltas views) — the packed
        buffer is reused per call (the engine fully overwrites rows
        [0, B))."""
        from tigerbeetle_tpu.state_machine import kernel

        dtypes = self._field_dtypes
        if dtypes is None:
            dtypes = self._field_dtypes = [
                np.dtype(dt) for _name, dt in field_order
            ]
        arrays = []
        ptrs = (ctypes.c_void_p * len(field_order))()
        for k, (name, _dt) in enumerate(field_order):
            a = np.ascontiguousarray(ev[name], dtypes[k])
            arrays.append(a)  # keep alive for the call
            ptrs[k] = a.ctypes.data

        dstat = np.ascontiguousarray(dstat_init, np.uint32)
        packed = self._packed
        if packed is None or packed.shape[0] < B:
            packed = self._packed = np.empty(
                (max(B, 8192), kernel.N_COLS), np.uint64
            )
        packed = packed[:B]
        rc = self._lib.tb_fp_commit_exact(
            self._fp, ptrs, len(field_order), _p(dstat, _U32P), B, n, ts_base,
            kernel.N_COLS,
            _p(packed, _U64P), _p(self._dslot, _I64P), _p(self._dcol, _I64P),
            _p(self._dlo, _U64P), _p(self._dhi, _U64P),
            ctypes.byref(self._ndeltas),
        )
        assert rc == 0, f"exact engine field-order skew ({rc})"
        k = self._ndeltas.value
        return packed, (
            self._dslot[:k], self._dcol[:k], self._dlo[:k], self._dhi[:k]
        )

    def commit_transfers(self, body: bytes, n: int, ts_base: int):
        """-> None (fallback) or (results, dr_slot, cr_slot,
        (dslot, dcol, dlo, dhi)) — views into reusable buffers, valid
        until the next call."""
        if n > len(self._results):
            return None  # oversized batch: take the exact path
        # Zero-copy pointer into the immutable bytes object (the C side
        # only reads).
        buf = ctypes.cast(ctypes.c_char_p(body), _U8P)
        rc = self._lib.tb_fp_commit_transfers(
            self._fp, buf, n, ts_base,
            _p(self._results, _U32P), _p(self._dr_slot, _I32P),
            _p(self._cr_slot, _I32P), _p(self._dslot, _I64P),
            _p(self._dcol, _I64P), _p(self._dlo, _U64P),
            _p(self._dhi, _U64P), ctypes.byref(self._ndeltas),
        )
        if rc != 0:
            return None
        k = self._ndeltas.value
        return (
            self._results[:n], self._dr_slot[:n], self._cr_slot[:n],
            (self._dslot[:k], self._dcol[:k], self._dlo[:k], self._dhi[:k]),
        )


    def commit_linked(self, body: bytes, n: int, ts_base: int):
        """Serial native resolver for linked-chain / limit-account
        batches (native/tb_linked.inc).  -> None (fallback) or
        (results, dr_slot, cr_slot, deltas, last_applied)."""
        if n > len(self._results):
            return None
        buf = ctypes.cast(ctypes.c_char_p(body), _U8P)
        rc = self._lib.tb_fp_commit_linked(
            self._fp, buf, n, ts_base,
            _p(self._results, _U32P), _p(self._dr_slot, _I32P),
            _p(self._cr_slot, _I32P), _p(self._dslot, _I64P),
            _p(self._dcol, _I64P), _p(self._dlo, _U64P),
            _p(self._dhi, _U64P), ctypes.byref(self._ndeltas),
            ctypes.byref(self._last_applied),
        )
        if rc != 0:
            return None
        k = self._ndeltas.value
        return (
            self._results[:n], self._dr_slot[:n], self._cr_slot[:n],
            (self._dslot[:k], self._dcol[:k], self._dlo[:k], self._dhi[:k]),
            int(self._last_applied.value),
        )

    def commit_two_phase(self, body: bytes, n: int, ts_base: int,
                         join: dict | None):
        """Serial native resolver for two-phase batches
        (native/tb_two_phase.inc).  `join` carries the durable pending
        targets' columns (None when the batch references none);
        -> None (fallback) or a dict of output views valid until the
        next native call."""
        if n > len(self._results):
            return None
        buf = ctypes.cast(ctypes.c_char_p(body), _U8P)
        if join is None:
            j_row = self._tp_empty_i64
            j_flags = j_ledger = j_code = j_ud32 = j_timeout = j_status = (
                self._tp_empty_u32
            )
            j_dr = j_cr = self._tp_empty_i32
            j_amt_lo = j_amt_hi = j_u128lo = j_u128hi = j_ud64 = (
                self._tp_empty_u64
            )
        else:
            j_row = np.ascontiguousarray(join["row"], np.int64)
            j_flags = np.ascontiguousarray(join["flags"], np.uint32)
            j_dr = np.ascontiguousarray(join["dr_slot"], np.int32)
            j_cr = np.ascontiguousarray(join["cr_slot"], np.int32)
            j_amt_lo = np.ascontiguousarray(join["amount_lo"], np.uint64)
            j_amt_hi = np.ascontiguousarray(join["amount_hi"], np.uint64)
            j_ledger = np.ascontiguousarray(join["ledger"], np.uint32)
            j_code = np.ascontiguousarray(join["code"], np.uint32)
            j_u128lo = np.ascontiguousarray(join["ud128_lo"], np.uint64)
            j_u128hi = np.ascontiguousarray(join["ud128_hi"], np.uint64)
            j_ud64 = np.ascontiguousarray(join["ud64"], np.uint64)
            j_ud32 = np.ascontiguousarray(join["ud32"], np.uint32)
            j_timeout = np.ascontiguousarray(join["timeout"], np.uint32)
            j_status = np.ascontiguousarray(join["status"], np.uint32)
        rc = self._lib.tb_fp_commit_two_phase(
            self._fp, buf, n, ts_base,
            _p(j_row, _I64P), _p(j_flags, _U32P), _p(j_dr, _I32P),
            _p(j_cr, _I32P), _p(j_amt_lo, _U64P), _p(j_amt_hi, _U64P),
            _p(j_ledger, _U32P), _p(j_code, _U32P), _p(j_u128lo, _U64P),
            _p(j_u128hi, _U64P), _p(j_ud64, _U64P), _p(j_ud32, _U32P),
            _p(j_timeout, _U32P), _p(j_status, _U32P),
            _p(self._results, _U32P), _p(self._dr_slot, _I32P),
            _p(self._cr_slot, _I32P), _p(self._tp_amt_lo, _U64P),
            _p(self._tp_amt_hi, _U64P), _p(self._tp_ud128_lo, _U64P),
            _p(self._tp_ud128_hi, _U64P), _p(self._tp_ud64, _U64P),
            _p(self._tp_ud32, _U32P), _p(self._tp_ledger, _U32P),
            _p(self._tp_code, _U32P), _p(self._tp_inb, _U32P),
            _p(self._tp_dur_rows, _I64P), _p(self._tp_dur_status, _U32P),
            ctypes.byref(self._tp_ndur),
            _p(self._dslot, _I64P), _p(self._dcol, _I64P),
            _p(self._dlo, _U64P), _p(self._dhi, _U64P),
            ctypes.byref(self._ndeltas), ctypes.byref(self._last_applied),
        )
        if rc != 0:
            return None
        k = self._ndeltas.value
        nd = self._tp_ndur.value
        return {
            "results": self._results[:n],
            "row_dr": self._dr_slot[:n],
            "row_cr": self._cr_slot[:n],
            "amt_lo": self._tp_amt_lo[:n],
            "amt_hi": self._tp_amt_hi[:n],
            "ud128_lo": self._tp_ud128_lo[:n],
            "ud128_hi": self._tp_ud128_hi[:n],
            "ud64": self._tp_ud64[:n],
            "ud32": self._tp_ud32[:n],
            "ledger": self._tp_ledger[:n],
            "code": self._tp_code[:n],
            "inb_status": self._tp_inb[:n],
            "dur_rows": self._tp_dur_rows[:nd],
            "dur_status": self._tp_dur_status[:nd],
            "deltas": (
                self._dslot[:k], self._dcol[:k], self._dlo[:k], self._dhi[:k]
            ),
            "last_applied": int(self._last_applied.value),
        }


def available() -> bool:
    return _load() is not None


# ----------------------------------------------------------------------
# Columnar ingest: batch frame verification + batch reply finalize
# (the server-drain half of the fast path — runtime/server.py).


def batch_verify_available() -> bool:
    lib = _load()
    return lib is not None and getattr(
        lib, "tb_fp_verify_frames", None
    ) is not None


def verify_frames(arena: np.ndarray, offsets: np.ndarray,
                  lens: np.ndarray, n: int):
    """One native pass over `n` frames packed in `arena`: header +
    body checksums, version, size — exactly wire.verify_header per
    frame.  -> u8 ok flags, or None when the native library lacks the
    symbol (caller takes the vectorized Python fallback).  The flag
    buffer is allocated per call: several buses poll concurrently in
    one process (in-process test clusters, router + shards) and
    ctypes releases the GIL during the C pass — a shared module
    buffer raced."""
    lib = _load()
    if lib is None or getattr(lib, "tb_fp_verify_frames", None) is None:
        return None
    ok = np.empty(n, np.uint8)
    offsets = np.ascontiguousarray(offsets[:n], np.uint64)
    lens = np.ascontiguousarray(lens[:n], np.uint32)
    lib.tb_fp_verify_frames(
        ctypes.cast(arena.ctypes.data, _U8P),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _p(lens, _U32P), n, _p(ok, _U8P),
    )
    return ok


def verify_frames2(arena: np.ndarray, offsets: np.ndarray,
                   lens: np.ndarray, n: int):
    """Counted r23 verify: same contract as verify_frames, plus the
    call opens a new digest-table crossing (verified body digests are
    cached for the build seams, the previous drain's entries die) and
    returns the body bytes hashed.  -> (ok u8 flags, bytes_hashed), or
    None when the library lacks the r23 symbols."""
    lib = _load()
    if lib is None or getattr(lib, "tb_fp_verify_frames2", None) is None:
        return None
    ok = np.empty(n, np.uint8)
    offsets = np.ascontiguousarray(offsets[:n], np.uint64)
    lens = np.ascontiguousarray(lens[:n], np.uint32)
    bytes_hashed = lib.tb_fp_verify_frames2(
        ctypes.cast(arena.ctypes.data, _U8P),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _p(lens, _U32P), n, _p(ok, _U8P),
    )
    return ok, int(bytes_hashed)


def verify_frames_py2(arena: np.ndarray, offsets: np.ndarray,
                      lens: np.ndarray, n: int,
                      hdrs: np.ndarray | None = None):
    """Pure-Python vectorized fallback: structural checks (version,
    size) in one numpy pass, checksums per frame via hashlib (C-speed
    SHA-256 — the same hashes the legacy path paid, minus its
    per-message numpy/dispatch churn).  Pass `hdrs` when the caller
    already gathered the header records (verify_and_gather) so the
    fallback arm doesn't pay the gather twice.  Returns (ok u8 flags,
    body bytes hashed) — the byte count matches the native pass by
    construction (a frame failing the header checksum never reaches
    its body hash)."""
    from tigerbeetle_tpu.vsr import wire

    if hdrs is None:
        hdrs = wire.headers_from_arena(arena, offsets, n)
    ok = (
        (hdrs["version"] == wire.VERSION)
        & (hdrs["size"] == lens[:n])
        & (lens[:n] >= np.uint32(256))
    )
    bytes_hashed = 0
    mv = memoryview(arena)  # zero-copy per-frame slices
    for i in np.nonzero(ok)[0]:
        off = int(offsets[i])
        size = int(lens[i])
        frame = mv[off : off + size]
        c = wire.checksum(frame[16:256])
        if (
            int(hdrs[i]["checksum_lo"]) != c & 0xFFFFFFFFFFFFFFFF
            or int(hdrs[i]["checksum_hi"]) != c >> 64
        ):
            ok[i] = False
            continue
        bytes_hashed += size - 256
        cb = wire.checksum(frame[256:])
        if (
            int(hdrs[i]["checksum_body_lo"]) != cb & 0xFFFFFFFFFFFFFFFF
            or int(hdrs[i]["checksum_body_hi"]) != cb >> 64
        ):
            ok[i] = False
    return ok.astype(np.uint8), bytes_hashed


def verify_frames_py(arena: np.ndarray, offsets: np.ndarray,
                     lens: np.ndarray, n: int,
                     hdrs: np.ndarray | None = None) -> np.ndarray:
    """verify_frames_py2 without the byte count (r20 signature)."""
    return verify_frames_py2(arena, offsets, lens, n, hdrs=hdrs)[0]


def verify_and_gather(arena: np.ndarray, moffs: np.ndarray,
                      mlens: np.ndarray):
    """The shared drain-decode sequence (server dispatch + open-loop
    client completions): one batch checksum pass over the message
    frames — native, or the vectorized Python fallback — plus one
    vectorized header gather.  -> (ok u8 flags, (n,) HEADER_DTYPE
    records, native bool, body bytes hashed).  bytes_hashed is None
    only on the stale-.so corner (old uncounted symbol present, new
    one absent) — callers skip the counter rather than guess."""
    from tigerbeetle_tpu.vsr import wire

    n = len(moffs)
    hdrs = wire.headers_from_arena(arena, moffs, n)
    res = verify_frames2(arena, moffs, mlens, n)
    if res is not None:
        ok, bytes_hashed = res
        return ok, hdrs, True, bytes_hashed
    ok = verify_frames(arena, moffs, mlens, n)
    if ok is not None:
        return ok, hdrs, True, None
    ok, bytes_hashed = verify_frames_py2(arena, moffs, mlens, n, hdrs=hdrs)
    return ok, hdrs, False, bytes_hashed


# ----------------------------------------------------------------------
# Native commit pipeline (round 20): per-prepare header construction,
# journal append framing, and the primary's in-flight slot table live
# in native/tb_pipeline.cpp; VsrReplica (vsr/multi.py) keeps view
# changes, checkpoints, and recovery.  The differential contract is
# absolute: TB_NATIVE_PIPELINE=0/1 must produce bit-identical frames.

# Expected tb_pl_abi_version().  Bump in lockstep with
# native/tb_pipeline.cpp whenever any tb_pl_* signature changes.
# ABI 2 = the r22 C-resident drain loop batch family
# (tb_pl_build_prepares / tb_pl_accept_prepares / tb_pl_on_acks /
# tb_pl_commit_ready_run).  ABI 3 = the r23 hash-once commit path:
# tb_pl_build_prepare / tb_pl_build_prepares grew a digest-reuse
# flags word, and the library carries the hash pool + counted verify
# (tb_fp_verify_frames2 / tb_hash_configure / tb_hash_engine /
# tb_hash_stats).
PIPELINE_ABI = 3

_PIPELINE_HINT = (
    "libtb_fastpath.so is stale (missing/mismatched tb_pl_* pipeline "
    "symbols) and the automatic rebuild did not replace it — run "
    "`make -C native` (or `make -C native asan` under "
    "TB_NATIVE_SANITIZE=asan) and check runtime/native.py build_error()"
)
_pipeline_warned = False


def pipeline_error() -> str | None:
    """Why the native pipeline is unavailable even though the fastpath
    library loaded (stale-.so forensics), else None."""
    lib = _load()
    if lib is None:
        return None  # no library at all: the normal pure-Python path
    if getattr(lib, "tb_pl_abi_version", None) is None:
        return _PIPELINE_HINT
    got = int(lib.tb_pl_abi_version())
    if got != PIPELINE_ABI:
        return (
            f"libtb_fastpath.so pipeline ABI {got} != expected "
            f"{PIPELINE_ABI} — {_PIPELINE_HINT}"
        )
    return None


def pipeline_available() -> bool:
    lib = _load()
    return lib is not None and pipeline_error() is None


def drain_error() -> str | None:
    """Why the r22 C-resident drain loop is unavailable even though
    the fastpath library loaded (stale-.so forensics extended to the
    batch symbols), else None.  A library missing any batch symbol
    also reports pipeline ABI != 3, so this usually collapses into
    pipeline_error(); the getattr probe is belt and braces."""
    err = pipeline_error()
    if err is not None:
        return err
    lib = _load()
    if lib is None:
        return None
    if getattr(lib, "tb_pl_build_prepares", None) is None:
        return _PIPELINE_HINT
    return None


def drain_available() -> bool:
    lib = _load()
    return lib is not None and drain_error() is None


def create_pipeline():
    """A NativePipeline for one VsrReplica, or None when the native
    library is absent (pure-Python fallback).  A LOADED-BUT-STALE
    library fails fast: RuntimeError with the rebuild hint when the
    operator explicitly demanded TB_NATIVE_PIPELINE=1, a one-shot
    RuntimeWarning + fallback when the knob was defaulted."""
    global _pipeline_warned
    lib = _load()
    if lib is None:
        return None
    err = pipeline_error()
    if err is not None:
        if envcheck.env_is_set("TB_NATIVE_PIPELINE"):
            raise RuntimeError(err)
        if not _pipeline_warned:
            _pipeline_warned = True
            import warnings

            warnings.warn(
                f"native pipeline unavailable ({err}); "
                "falling back to the Python per-prepare path",
                RuntimeWarning, stacklevel=2,
            )
        return None
    return NativePipeline(lib)


class NativePipeline:
    """One native in-flight slot table + header builder per replica.

    Headers cross the boundary as raw 256-byte buffers; built headers
    come back as fresh HEADER_DTYPE records (bit-identical to the
    wire.make_header/copy_trace/finalize_header sequence)."""

    def __init__(self, lib) -> None:
        from tigerbeetle_tpu.vsr.wire import HEADER_DTYPE

        self._lib = lib
        self._pl = lib.tb_pl_create()
        assert self._pl, "tb_pl_create failed"
        self._dtype = HEADER_DTYPE

    def __del__(self):  # noqa: D105
        try:
            if getattr(self, "_pl", None):
                self._lib.tb_pl_destroy(self._pl)
                self._pl = None
        # tbcheck: allow(broad-except): __del__ at interpreter
        # teardown — the lib handle may already be gone.
        except Exception:
            pass

    def build_prepare(self, request: np.void, body: bytes, *, cluster: int,
                      view: int, op: int, commit: int, timestamp: int,
                      parent: int, replica: int, context: int,
                      release: int, reuse: bool = False) -> np.void:
        out = np.empty(1, self._dtype)
        self._lib.tb_pl_build_prepare(
            request.tobytes(), body, len(body),
            cluster & 0xFFFFFFFFFFFFFFFF, cluster >> 64, view, op,
            commit, timestamp, parent & 0xFFFFFFFFFFFFFFFF, parent >> 64,
            replica, context, release, 1 if reuse else 0,
            ctypes.cast(out.ctypes.data, _U8P),
        )
        return out[0]

    def build_prepare_ok(self, prepare: np.void, view: int,
                         replica: int) -> np.void:
        out = np.empty(1, self._dtype)
        self._lib.tb_pl_build_prepare_ok(
            prepare.tobytes(), view, replica,
            ctypes.cast(out.ctypes.data, _U8P),
        )
        return out[0]

    def note_prepare(self, header: np.void, synced: bool,
                     self_replica: int) -> None:
        self._lib.tb_pl_note_prepare(
            self._pl, header.tobytes(), 1 if synced else 0, self_replica
        )

    def on_ack(self, header: np.void) -> int | None:
        """Vote count after recording the ack, or None when the op has
        no in-flight entry / the checksum names a stale sibling — the
        same cases _on_prepare_ok drops."""
        votes = self._lib.tb_pl_on_ack(self._pl, header.tobytes())
        return None if votes < 0 else int(votes)

    def on_acks(self, headers: np.ndarray, cluster: int,
                view: int) -> tuple[int, np.ndarray]:
        """Vote a contiguous run of prepare_ok headers in one call
        (r22).  Returns (accepted_count, verdicts) where verdicts[i]
        is the entry's vote count after ack i, or negative for the
        drops the per-ack path also takes: -4 foreign cluster, -3
        stale/future view, -1 unknown op, -2 stale-sibling checksum."""
        k = len(headers)
        assert headers.dtype.itemsize == 256
        out = np.empty(k, np.int64)
        accepted = self._lib.tb_pl_on_acks(
            self._pl, headers.tobytes(), k,
            cluster & 0xFFFFFFFFFFFFFFFF, cluster >> 64, view,
            _p(out, _I64P),
        )
        return int(accepted), out

    def commit_ready_run(self, commit_min: int, quorum: int) -> int:
        """Length of the contiguous commit-ready run above commit_min
        — tb_pl_commit_ready extended to the whole drain (r22)."""
        return int(
            self._lib.tb_pl_commit_ready_run(self._pl, commit_min, quorum)
        )

    def mark_all_synced(self) -> None:
        self._lib.tb_pl_mark_all_synced(self._pl)

    def set_synced(self, op: int, synced: bool) -> bool:
        return self._lib.tb_pl_set_synced(
            self._pl, op, 1 if synced else 0
        ) == 0

    def drop(self, op: int) -> None:
        self._lib.tb_pl_drop(self._pl, op)

    def commit_ready(self, commit_min: int, quorum: int) -> bool:
        return bool(self._lib.tb_pl_commit_ready(self._pl, commit_min, quorum))

    def votes(self, op: int) -> int:
        return int(self._lib.tb_pl_votes(self._pl, op))

    def reset(self) -> None:
        self._lib.tb_pl_reset(self._pl)

    def size(self) -> int:
        return int(self._lib.tb_pl_size(self._pl))


def frame_prepare(header: np.void, body: bytes, headers_ring: np.ndarray,
                  slot: int, headers_per_sector: int, sector_size: int,
                  out_prepare: np.ndarray, out_sector: np.ndarray) -> int:
    """Journal append framing in one C pass: builds the sector-padded
    prepare buffer into `out_prepare` (returns the padded length),
    writes `headers_ring[slot] = header` in place, and builds the
    slot's redundant-header sector into `out_sector` — byte-identical
    to journal.write_prepare's Python framing.  Caller guarantees the
    library is loaded (pipeline_available())."""
    lib = _load()
    assert headers_ring.flags["C_CONTIGUOUS"]
    return int(lib.tb_pl_frame_prepare(
        header.tobytes(), body, len(body),
        ctypes.cast(headers_ring.ctypes.data, _U8P), slot,
        headers_per_sector, sector_size,
        ctypes.cast(out_prepare.ctypes.data, _U8P),
        ctypes.cast(out_sector.ctypes.data, _U8P),
    ))


def _padded_total(body_lens: np.ndarray, sector_size: int) -> int:
    """Sum of sector-padded prepare sizes — sized exactly like the C
    side's capacity check so a successful allocation here can never
    overflow there."""
    msgs = body_lens + np.uint64(256 + sector_size - 1)
    return int((msgs // np.uint64(sector_size)).sum()) * sector_size


def build_prepares(pl: NativePipeline, req_hdrs: np.ndarray, bodies: list,
                   timestamps: np.ndarray, contexts: np.ndarray, *,
                   cluster: int, view: int, op0: int, commit: int,
                   parent: int, replica: int, release: int, synced: bool,
                   headers_ring: np.ndarray, slot_count: int,
                   headers_per_sector: int, sector_size: int,
                   reuse: bool = False):
    """One C call for a whole drain's prepare builds (r22): K headers
    chained parent->checksum, registered in the slot table with the
    self-vote, and framed for the journal.  Returns (prepares, frames)
    where `prepares` is a (K,) HEADER_DTYPE array and `frames` is the
    WAL write-descriptor tuple (wal_arena, wal_off, wal_len, slots,
    sector_arena, sector_index), or None on arena overflow (caller
    loops the per-prepare path; nothing was mutated)."""
    lib = _load()
    k = len(bodies)
    assert req_hdrs.dtype.itemsize == 256 and req_hdrs.flags["C_CONTIGUOUS"]
    assert headers_ring.flags["C_CONTIGUOUS"]
    ptrs = (_U8P * k)(
        *[ctypes.cast(ctypes.c_char_p(b), _U8P) for b in bodies]
    )
    blens = np.array([len(b) for b in bodies], np.uint64)
    ts = np.ascontiguousarray(timestamps, np.uint64)
    ctx = np.ascontiguousarray(contexts, np.uint64)
    from tigerbeetle_tpu.vsr.wire import HEADER_DTYPE

    prepares = np.empty(k, HEADER_DTYPE)
    wal_arena = np.zeros(_padded_total(blens, sector_size), np.uint8)
    sector_arena = np.zeros(k * sector_size, np.uint8)
    wal_off = np.empty(k, np.uint64)
    wal_len = np.empty(k, np.uint64)
    slots = np.empty(k, np.uint64)
    sector_index = np.empty(k, np.uint64)
    rc = lib.tb_pl_build_prepares(
        pl._pl, req_hdrs.tobytes(), ptrs, _p(blens, _U64P),
        _p(ts, _U64P), _p(ctx, _U64P), k,
        cluster & 0xFFFFFFFFFFFFFFFF, cluster >> 64, view, op0, commit,
        parent & 0xFFFFFFFFFFFFFFFF, parent >> 64, replica, release,
        1 if synced else 0, 1 if reuse else 0,
        ctypes.cast(prepares.ctypes.data, _U8P),
        ctypes.cast(headers_ring.ctypes.data, _U8P), slot_count,
        headers_per_sector, sector_size,
        ctypes.cast(wal_arena.ctypes.data, _U8P), len(wal_arena),
        _p(wal_off, _U64P), _p(wal_len, _U64P), _p(slots, _U64P),
        ctypes.cast(sector_arena.ctypes.data, _U8P),
        _p(sector_index, _U64P),
    )
    if rc < 0:
        return None
    return prepares, (wal_arena, wal_off, wal_len, slots, sector_arena,
                      sector_index)


def accept_prepares(hdrs: np.ndarray, bodies: list, *, view: int,
                    replica: int, build_oks: bool,
                    headers_ring: np.ndarray, slot_count: int,
                    headers_per_sector: int, sector_size: int):
    """One C call for a backup drain's accepted-prepare run (r22):
    frame K prepares for the journal and build their prepare_ok
    headers.  Returns (oks, frames) — `oks` a (K,) HEADER_DTYPE array
    (contents undefined when build_oks=False) and `frames` as in
    build_prepares — or None on arena overflow (nothing mutated)."""
    lib = _load()
    k = len(bodies)
    assert hdrs.dtype.itemsize == 256 and hdrs.flags["C_CONTIGUOUS"]
    assert headers_ring.flags["C_CONTIGUOUS"]
    ptrs = (_U8P * k)(
        *[ctypes.cast(ctypes.c_char_p(b), _U8P) for b in bodies]
    )
    blens = np.array([len(b) for b in bodies], np.uint64)
    from tigerbeetle_tpu.vsr.wire import HEADER_DTYPE

    oks = np.empty(k, HEADER_DTYPE)
    wal_arena = np.zeros(_padded_total(blens, sector_size), np.uint8)
    sector_arena = np.zeros(k * sector_size, np.uint8)
    wal_off = np.empty(k, np.uint64)
    wal_len = np.empty(k, np.uint64)
    slots = np.empty(k, np.uint64)
    sector_index = np.empty(k, np.uint64)
    rc = lib.tb_pl_accept_prepares(
        hdrs.tobytes(), ptrs, _p(blens, _U64P), k, view, replica,
        1 if build_oks else 0,
        ctypes.cast(oks.ctypes.data, _U8P),
        ctypes.cast(headers_ring.ctypes.data, _U8P), slot_count,
        headers_per_sector, sector_size,
        ctypes.cast(wal_arena.ctypes.data, _U8P), len(wal_arena),
        _p(wal_off, _U64P), _p(wal_len, _U64P), _p(slots, _U64P),
        ctypes.cast(sector_arena.ctypes.data, _U8P),
        _p(sector_index, _U64P),
    )
    if rc < 0:
        return None
    return oks, (wal_arena, wal_off, wal_len, slots, sector_arena,
                 sector_index)


def finalize_headers(headers: np.ndarray, bodies: list) -> bool:
    """Batch reply finalize: set size + checksum_body + checksum on
    each 256-byte header record in the contiguous `headers` array for
    its body in `bodies` — one C call instead of 2n hashlib calls.
    Returns False when the native symbol is unavailable (caller loops
    wire.finalize_header)."""
    lib = _load()
    if lib is None or getattr(lib, "tb_fp_finalize_headers", None) is None:
        return False
    n = len(headers)
    assert headers.dtype.itemsize == 256 and headers.flags["C_CONTIGUOUS"]
    assert len(bodies) == n
    ptrs = (_U8P * n)(
        *[ctypes.cast(ctypes.c_char_p(b), _U8P) for b in bodies]
    )
    blens = np.array([len(b) for b in bodies], np.uint32)
    lib.tb_fp_finalize_headers(
        ctypes.cast(headers.ctypes.data, _U8P), n, ptrs, _p(blens, _U32P)
    )
    return True


# ----------------------------------------------------------------------
# Hash-once commit path (round 23): pool configuration, engine
# identity, and the scalar-fallback forensics.

# tb_hash_engine() codes (native/sha256.h Sha256Engine).
HASH_ENGINE_NAMES = {1: "evp", 2: "sha256-legacy", 3: "scalar"}

_scalar_warned = False


def configure_hash(threads: int | None = None, force_engine: int = 0) -> bool:
    """(Re)apply the hash-pool lane count (default: the validated
    TB_HASH_THREADS) and optionally force a SHA-256 engine tier for
    the --hash-only bench grid (0 = auto).  Returns False when the
    library is absent or lacks the r23 symbols (inline hashlib/scalar
    hashing everywhere — nothing to configure)."""
    lib = _load()
    if lib is None or getattr(lib, "tb_hash_configure", None) is None:
        return False
    if threads is None:
        threads = envcheck.hash_threads()
    lib.tb_hash_configure(threads, force_engine)
    return True


def hash_engine_name() -> str:
    """Which SHA-256 implementation the native library dispatches to
    ("evp" = libcrypto EVP one-shot / SHA-NI, "sha256-legacy" =
    libcrypto's compat entry, "scalar" = the portable ~225 MB/s core),
    or "hashlib" when no native library serves the hot path (Python's
    hashlib — itself OpenSSL-backed).  Recorded in bench rows so a
    number can never silently come from the wrong engine."""
    lib = _load()
    if lib is None or getattr(lib, "tb_hash_engine", None) is None:
        return "hashlib"
    return HASH_ENGINE_NAMES.get(int(lib.tb_hash_engine()), "unknown")


def hash_scalar_fallback() -> int:
    """1 when the native library resolved NEITHER libcrypto tier and
    every native checksum runs on the 225 MB/s scalar core — surfaced
    as the hash.scalar_fallback gauge plus a one-time RuntimeWarning
    (a silent 8x hash regression must never pass as a normal run)."""
    global _scalar_warned
    if hash_engine_name() != "scalar":
        return 0
    if not _scalar_warned:
        _scalar_warned = True
        import warnings

        warnings.warn(
            "native SHA-256 resolved neither libcrypto's EVP one-shot "
            "nor SHA256(): hashing runs on the ~225 MB/s scalar "
            "fallback core (expect ~8x slower checksums; install a "
            "libcrypto.so to restore SHA-NI dispatch)",
            RuntimeWarning, stacklevel=2,
        )
    return 1


def hash_stats() -> dict:
    """Process-global hash-pool counters: jobs executed on worker
    lanes (hash.lanes_busy), drain-scoped digest-table hits, and the
    configured lane count.  Zeros when the library lacks the r23
    symbols."""
    lib = _load()
    if lib is None or getattr(lib, "tb_hash_stats", None) is None:
        return {"lane_jobs": 0, "table_hits": 0, "threads": 0}
    out = np.zeros(3, np.uint64)
    lib.tb_hash_stats(_p(out, _U64P))
    return {
        "lane_jobs": int(out[0]),
        "table_hits": int(out[1]),
        "threads": int(out[2]),
    }
