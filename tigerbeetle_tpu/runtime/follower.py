"""Root-attested follower serving: read scale-out that can never lie
about staleness (round 19).

Production read traffic (balance lookups, history/filter queries)
dwarfs writes, yet a read through the consensus pipeline consumes
primary capacity.  A follower tails the primary's durable AOF
(vsr/aof.py — self-framing, checksum-verified, offset-resumable),
replays it deterministically into its own state machine, and serves
the read-only operations at a stated `commit_min`.  The r15 state
commitment turns that from "trust me" into an attestation (the
AlDBaran light-client angle, arXiv:2508.10493):

- every follower reply carries (state_root, commit_min) in the
  reserved-byte attestation carve-out (vsr/wire.py), so a client can
  verify integrity AND staleness against the cluster commitment;
- the follower itself continuously cross-checks its replayed roots
  against the upstream replica's root ring (the `state_root` at-op
  query) and REFUSES to serve the moment it cannot prove its state.

The robustness contract — refuse, never lie
-------------------------------------------
A follower under crash / lag / partition / log corruption degrades to
a typed refusal (`wire.FollowerRefuse`), never to a wrong answer:

- torn tailed log (crashed writer)  -> replay parks at the resume
  offset and heals when bytes land; meanwhile the follower lags and
  the staleness bound redirects reads.
- corrupt tailed log / op gap       -> replay refuses to advance
  (`corrupt`/`gap`); state stays at the last verified point.
- replay divergence (the follower's root at op N differs from the
  primary's root at op N)           -> `poisoned`, a terminal refusal:
  the follower's state machine can no longer be trusted at ANY op.
- partition from the upstream       -> attestations stop, the lag
  estimate ages, and the staleness bound eventually refuses.

What this does and does not guarantee: replies at ops the attestation
loop has already verified are proven; replies in the (bounded) window
between `attested_op` and `commit_min` rest on the AOF's checksums +
deterministic replay, and the carried root lets the CLIENT close that
window by verifying against the primary's root ring — which is why
the attestation rides every reply instead of being an internal check.

Determinism: this module runs inside the seeded simulators
(testing/cluster.py drives FollowerCore tick-by-tick), so it reads no
wall clocks and draws no entropy — FollowerServer takes an injected
`clock_ns` from its process entry point (cli.py / bench.py).
"""

from __future__ import annotations

import dataclasses

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import HEADER_SIZE
from tigerbeetle_tpu.state_machine.demuxer import batch_logical_allowed
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.aof import AofTail
from tigerbeetle_tpu.vsr.wire import Command, FollowerRefuse, VsrOperation

# Operations a follower may answer (int view of the one shared
# definition, types.READ_OPERATIONS — the state machine's executors
# and the router's steering key on the same set).
READ_OPERATIONS = frozenset(int(op) for op in types.READ_OPERATIONS)


class _StopReplay(Exception):
    """Internal: abort the current pump() batch after a latch."""


@dataclasses.dataclass
class FollowerReply:
    """A served read: the reply body plus the attestation the wire
    reply will carry."""

    body: bytes
    commit_min: int
    root: bytes


@dataclasses.dataclass
class FollowerRefusal:
    """A typed decline (refuse-not-lie): WHY plus how far behind."""

    reason: FollowerRefuse
    lag_ops: int
    commit_min: int


class FollowerCore:
    """Sans-IO follower: AOF tail replay + attestation + serving gate.

    Drivers own all I/O and time: `pump()` advances replay from the
    tail source, `on_attestation()` feeds upstream (root, op) answers,
    `serve()` answers one read or returns a typed refusal.  All state
    transitions are pure functions of those calls — the deterministic
    simulators (testing/cluster.py SimFollower, the VOPR follower
    nemesis) drive the exact code the TCP server runs.
    """

    def __init__(self, source_or_path, *, cluster: int,
                 state_machine, follower_id: int = 0,
                 offset: int = 0,
                 staleness_ops: int | None = None,
                 attest_max_age_ns: int | None = None,
                 root_ring: int | None = None,
                 registry=None, qos=None) -> None:
        from tigerbeetle_tpu import envcheck, obs

        self.cluster = cluster
        self.follower_id = follower_id
        self.sm = state_machine
        assert hasattr(self.sm, "execute_read"), (
            "follower state machine must expose execute_read()"
        )
        self.tail = AofTail(source_or_path, offset=offset)
        self.qos = qos
        self.staleness_ops = (
            envcheck.read_staleness_ops()
            if staleness_ops is None else int(staleness_ops)
        )
        # Attestation-age bound: lag_ops is a high-water-mark estimate
        # that a FULL partition freezes at 0 — the age of the last
        # successful attestation is what actually keeps the staleness
        # bound honest there.  The clock is the same driver-supplied
        # now_ns that serve() takes (ticks in sims, injected wall
        # clock in the server); 0 disables the bound.
        self.attest_max_age_ns = (
            envcheck.follower_attest_max_ms() * 1_000_000
            if attest_max_age_ns is None else int(attest_max_age_ns)
        )
        self.last_attest_ns = 0
        self.ring_max = (
            envcheck.follower_ring() if root_ring is None
            else int(root_ring)
        )
        # Replay state.
        self.commit_min = 0
        self.gapped = False          # op discontinuity in the tail
        self.incompatible = False    # state machine rejected a record
        # Own per-op roots (bounded ring) — what attestations verify
        # against and what replies carry.
        self._roots: dict[int, bytes] = {}
        # Attestation state.
        self.attested_op = 0         # highest op verified upstream
        self.last_primary_op = 0     # freshest upstream commit point
        self.poisoned = False        # verified MISMATCH — terminal
        self._pending_attest: dict[int, bytes] = {}
        # Instruments (ISSUE contract: lag_ops / redirects / refused).
        self.registry = registry if registry is not None else obs.Registry()
        self._c_applied = self.registry.counter("follower.applied")
        self._c_served = self.registry.counter("follower.served")
        # redirects: transient declines (lagging / overload) — the
        # client's next stop is the primary, the follower stays in
        # rotation.  refused: integrity declines (unattested /
        # poisoned / corrupt / gap / non-read op) — the follower
        # cannot prove its state.
        self._c_redirects = self.registry.counter("follower.redirects")
        self._c_refused = self.registry.counter("follower.refused")
        self._c_attest_ok = self.registry.counter("follower.attest_ok")
        self._c_attest_mismatch = self.registry.counter(
            "follower.attest_mismatch"
        )
        self._c_attest_missed = self.registry.counter(
            "follower.attest_missed"
        )
        self._c_gap = self.registry.counter("follower.tail_gap")
        self._c_corrupt = self.registry.counter("follower.tail_corrupt")
        self._c_incompatible = self.registry.counter(
            "follower.incompatible"
        )
        self.registry.gauge_fn("follower.id", lambda: self.follower_id)
        self.registry.gauge_fn("follower.commit_min",
                               lambda: self.commit_min)
        self.registry.gauge_fn("follower.lag_ops", lambda: self.lag_ops())
        self.registry.gauge_fn("follower.attested_op",
                               lambda: self.attested_op)
        self.registry.gauge_fn("follower.poisoned",
                               lambda: int(self.poisoned))
        # Optional flight hook (FollowerServer attaches its recorder);
        # None in the sim unless a test wires one.
        self.flight = None

    # -- replay --------------------------------------------------------

    def lag_ops(self) -> int:
        return max(0, self.last_primary_op - self.commit_min)

    def pump(self, max_records: int = 512) -> int:
        """Advance replay from the tail; returns ops applied.  Never
        raises on bad log bytes — torn tails park (resume offset
        retained), corruption and op gaps latch a refusal state."""
        if self.gapped or self.poisoned or self.incompatible:
            return 0
        was_corrupt = self.tail.corrupt
        entries = self.tail.poll(limit=max_records)
        if self.tail.corrupt and not was_corrupt:
            self._c_corrupt.inc()
            self._note("follower_tail_corrupt",
                       reason=self.tail.corrupt_reason or "")
        applied = 0
        for header, body in entries:
            if int(header["command"]) != int(Command.prepare):
                continue
            if wire.u128(header, "cluster") != self.cluster:
                continue
            op = int(header["op"])
            if op <= self.commit_min:
                continue  # duplicate (re-tail after restart)
            if op != self.commit_min + 1:
                # Discontinuity: ops the log lost (a crash that beat
                # the writer's gap-fill) — replaying past it would
                # fabricate a state no replica ever held.  Latch and
                # refuse; the operator re-seeds the follower.
                self.gapped = True
                self._c_gap.inc()
                self._note("follower_tail_gap", at=op,
                           commit_min=self.commit_min)
                break
            try:
                self._apply(header, body)
            except _StopReplay:
                break
            applied += 1
        return applied

    def _apply(self, header, body: bytes) -> None:
        op = int(header["op"])
        operation = int(header["operation"])
        if operation in READ_OPERATIONS:
            # Committed READS change no state: skip execution and
            # carry the previous root forward.  This keeps follower
            # replay cost proportional to WRITE volume — otherwise a
            # read-heavy cluster (the exact workload followers exist
            # to absorb, including the reads the router redirects on
            # refusal) commits read ops faster than a follower can
            # re-execute them, and the lag feedback loop never
            # converges.
            self._advance(op, self._roots.get(op - 1))
            return
        if operation >= int(types.Operation.pulse):
            timestamp = int(header["timestamp"])
            sm_op = types.Operation(operation)
            # Logically-batched prepare (vsr/multi.py): context = sub
            # count, body = concatenated event bytes + demux trailer.
            # The follower commits the EVENT bytes exactly like the
            # replica commit path (per-client reply slicing is the
            # primary's job, not replay's).
            n_subs = wire.u128(header, "context")
            if n_subs and batch_logical_allowed(sm_op):
                from tigerbeetle_tpu.state_machine import demuxer

                try:
                    body, _subs = demuxer.decode_trailer(body, n_subs)
                except (AssertionError, ValueError):
                    self.incompatible = True
                    self._c_incompatible.inc()
                    self._note("follower_incompatible", at=op,
                               operation=operation, body_len=len(body))
                    raise _StopReplay()
            if not self.sm.input_valid(sm_op, body):
                # A checksum-valid committed record the follower's
                # state machine rejects = config/software mismatch
                # (e.g. the upstream accepts larger batches).  Latch
                # and refuse — applying a guess would serve fabricated
                # state; crashing would take the redirect path down
                # with it.
                self.incompatible = True
                self._c_incompatible.inc()
                self._note("follower_incompatible", at=op,
                           operation=operation, body_len=len(body))
                raise _StopReplay()
            self.sm.prepare_timestamp = timestamp
            self.sm.prefetch(sm_op, body, prefetch_timestamp=timestamp)
            self.sm.commit(0, op, timestamp, sm_op, body)
        # VSR-internal ops (register, reconfigure) advance the op
        # stream without touching ledger state — the root is carried
        # forward so every op has a recorded root.
        self._advance(op, None)

    def _advance(self, op: int, carried_root: bytes | None) -> None:
        """Record `op` replayed: advance commit_min, ring the root
        (carried forward for state-neutral ops, recomputed/read from
        the state machine otherwise), verify any parked attestation."""
        self.commit_min = op
        self._c_applied.inc()
        root = carried_root
        if root is None:
            root = self.sm.state_root()
        self._roots[op] = root
        while len(self._roots) > self.ring_max:
            self._roots.pop(next(iter(self._roots)))
        claim = self._pending_attest.pop(op, None)
        if claim is not None:
            self._verify(op, claim, root)

    # -- attestation ---------------------------------------------------

    def on_attestation(self, root: bytes, op: int,
                       now_ns: int = 0) -> None:
        """Feed one upstream `state_root` answer (at-op or current).
        Matching roots raise `attested_op`; a mismatch at an op both
        sides committed is proof of divergence and poisons the
        follower.  `now_ns` (same clock as serve()) feeds the
        attestation-age bound."""
        if self.poisoned:
            return
        self.last_attest_ns = max(self.last_attest_ns, now_ns)
        self.last_primary_op = max(self.last_primary_op, op)
        own = self._roots.get(op)
        if own is not None:
            self._verify(op, root, own)
        elif op > self.commit_min:
            # Ahead of our replay: park the claim, verified the moment
            # replay reaches it (bounded — keep the freshest few).
            self._pending_attest[op] = root
            while len(self._pending_attest) > 8:
                self._pending_attest.pop(
                    min(self._pending_attest)
                )
        else:
            # Behind our ring floor (extreme lag of the QUERY, not the
            # follower) — can neither confirm nor deny.
            self._c_attest_missed.inc()

    def _verify(self, op: int, claimed: bytes, own: bytes) -> None:
        if claimed == own:
            self.attested_op = max(self.attested_op, op)
            self._c_attest_ok.inc()
        else:
            self.poisoned = True
            self._c_attest_mismatch.inc()
            self._note("follower_poisoned", op=op,
                       own=own.hex(), claimed=claimed.hex())

    def _note(self, name: str, **args) -> None:
        if self.flight is not None:
            self.flight.note(name, **args)

    # -- serving -------------------------------------------------------

    def refuse_reason(self, now_ns: int = 0) -> FollowerRefuse | None:
        """The gate, in precedence order: integrity refusals first
        (they say "do not trust me"), staleness last (it says "the
        primary is fresher").  Staleness is TWO checks: the op-lag
        estimate, and the AGE of the last attestation — a full
        partition freezes the former at 0, so only the latter refuses
        there (the contract: degrade to redirect, never serve
        unboundedly frozen state as fresh)."""
        if self.poisoned:
            return FollowerRefuse.poisoned
        if self.tail.corrupt:
            return FollowerRefuse.corrupt
        if self.gapped:
            return FollowerRefuse.gap
        if self.incompatible:
            return FollowerRefuse.incompatible
        if self.attested_op == 0:
            return FollowerRefuse.unattested
        if self.lag_ops() > self.staleness_ops:
            return FollowerRefuse.lagging
        if (
            self.attest_max_age_ns > 0
            and now_ns > self.last_attest_ns + self.attest_max_age_ns
        ):
            return FollowerRefuse.lagging
        return None

    def refusal(self, reason: FollowerRefuse) -> FollowerRefusal:
        (self._c_redirects if reason in (
            FollowerRefuse.lagging, FollowerRefuse.overload
        ) else self._c_refused).inc()
        return FollowerRefusal(reason, self.lag_ops(), self.commit_min)

    def serve(self, operation: int, body: bytes, *, now_ns: int = 0,
              tenant: int = 0):
        """Answer one read, or refuse typed.  `now_ns` feeds the QoS
        bucket clock (tick-derived in sims, injected wall clock in the
        server)."""
        if int(operation) not in READ_OPERATIONS:
            return self.refusal(FollowerRefuse.not_readable)
        reason = self.refuse_reason(now_ns)
        if reason is not None:
            return self.refusal(reason)
        if self.qos is not None:
            self.qos.observe(tenant, now_ns)
            if not self.qos.admit(tenant, now_ns, 0,
                                  body_bytes=len(body)):
                self.qos.on_shed(tenant)
                return self.refusal(FollowerRefuse.overload)
            self.qos.on_admit(tenant)
        reply = self.sm.execute_read(types.Operation(operation), body)
        root = self._roots.get(self.commit_min)
        if root is None:
            root = self.sm.state_root()
        self._c_served.inc()
        return FollowerReply(reply, self.commit_min, root)


class FollowerServer:
    """TCP read-only follower: the `tigerbeetle follower` process.

    Joins the server family next to ReplicaServer/RouterServer:
    clients speak the normal wire protocol (register is answered
    sessionless — reads are idempotent, at-most-once state would be
    dead weight), read operations are served with the attestation
    stamped into the reply header, everything else gets the typed
    follower busy.  The upstream replica is polled for attestations on
    the TB_FOLLOWER_ATTEST_MS cadence, alternating "root at MY
    commit_min" (verification) with "current root" (lag estimate).

    `clock_ns` is injected (time.monotonic_ns at the process entry
    point) — this module stays wall-clock-free for the simulators.
    """

    def __init__(self, listen_address: str, *, aof_path: str,
                 upstream_address: str, cluster: int,
                 state_machine, clock_ns, follower_id: int = 0,
                 staleness_ops: int | None = None,
                 message_size_max: int | None = None) -> None:
        from tigerbeetle_tpu import envcheck, obs
        from tigerbeetle_tpu.obs.flight import FlightRecorder
        from tigerbeetle_tpu.runtime.native import (
            EV_CLOSED, EV_MESSAGE, NativeBus,
        )
        from tigerbeetle_tpu.runtime.server import parse_address

        self._ev_message = EV_MESSAGE
        self._ev_closed = EV_CLOSED
        self.cluster = cluster
        self.clock_ns = clock_ns
        self.registry = obs.Registry()
        qos = None
        if envcheck.tenant_qos():
            from tigerbeetle_tpu.qos import TenantQos

            qos = TenantQos(
                rate=envcheck.tenant_rate(),
                rate_bytes=envcheck.tenant_rate_bytes(),
                weights=envcheck.tenant_weights(),
                registry=self.registry.scope("follower.qos"),
            )
        self.core = FollowerCore(
            aof_path, cluster=cluster, state_machine=state_machine,
            follower_id=follower_id, staleness_ops=staleness_ops,
            registry=self.registry, qos=qos,
        )
        flight_path = envcheck.env_str(
            "TB_FLIGHT_PATH", f"tb_flight_f{follower_id}.json"
        )
        self._flight_path = flight_path
        self.flight = FlightRecorder(
            process_id=1000 + follower_id, dump_path=flight_path,
            stats_fn=lambda: self.registry.snapshot(),
        )
        self.core.flight = self.flight
        self.bus = NativeBus(
            message_size_max or cfg.PRODUCTION.message_size_max
        )
        host, port = parse_address(listen_address)
        self.port = self.bus.listen(host, port)
        self.upstream = parse_address(upstream_address)
        self._up_conn: int | None = None
        self._attest_ns = envcheck.follower_attest_ms() * 1_000_000
        # Anchor at NOW: the first query fires one full cadence in —
        # the clock is an arbitrary monotonic epoch, and `0` would
        # read as "due since boot".
        self._last_attest = clock_ns()
        self._attest_request = 0x0F0110000
        self._attest_current = False  # alternate at-op / current

    # -- upstream attestation ------------------------------------------

    def _upstream_conn(self) -> int | None:
        if self._up_conn is not None:
            return self._up_conn
        try:
            self._up_conn = self.bus.connect(*self.upstream)
        except OSError:
            return None
        return self._up_conn

    def _send_attest_query(self) -> None:
        from tigerbeetle_tpu.state_machine import commitment

        conn = self._upstream_conn()
        if conn is None:
            return
        self._attest_request += 1
        self._attest_current = not self._attest_current
        if self._attest_current or self.core.commit_min == 0:
            qbody = b""  # current root: refreshes the lag estimate
        else:
            qbody = commitment.root_query_body(self.core.commit_min)
        h = wire.make_header(
            command=Command.request, operation=VsrOperation.state_root,
            cluster=self.cluster, client=0,
            request=self._attest_request & 0xFFFFFFFF,
        )
        wire.finalize_header(h, qbody)
        self.bus.send(conn, h.tobytes() + qbody)

    def _on_upstream(self, header, body: bytes) -> None:
        from tigerbeetle_tpu.state_machine import commitment

        if int(header["command"]) != int(Command.reply):
            return
        if int(header["operation"]) != int(VsrOperation.state_root):
            return
        try:
            root, op = commitment.parse_root_body(bytes(body))
        except ValueError:
            return
        if root != bytes(16):  # all-zero = upstream has no commitment
            self.core.on_attestation(root, op, now_ns=self.clock_ns())

    # -- client serving ------------------------------------------------

    def _reply(self, conn: int, req_header, operation: int,
               body: bytes, attest: tuple | None) -> None:
        h = wire.make_header(
            command=Command.reply, cluster=self.cluster,
            client=wire.u128(req_header, "client"),
            request=int(req_header["request"]),
            operation=operation,
            replica=self.core.follower_id & 0xFF,
        )
        wire.copy_trace(h, req_header)
        if attest is not None:
            wire.stamp_attestation(h, attest[0], attest[1])
        wire.finalize_header(h, body)
        self.bus.send(conn, h.tobytes() + body)

    def _refuse(self, conn: int, req_header,
                refusal: FollowerRefusal) -> None:
        payload = wire.follower_busy_body(
            int(refusal.reason), self.core.follower_id,
            refusal.lag_ops, refusal.commit_min,
        )
        h = wire.make_header(
            command=Command.client_busy, cluster=self.cluster,
            client=wire.u128(req_header, "client"),
            request=int(req_header["request"]),
            replica=self.core.follower_id & 0xFF,
        )
        wire.copy_trace(h, req_header)
        wire.finalize_header(h, payload)
        self.bus.send(conn, h.tobytes() + payload)
        self.flight.note(
            "follower_refuse", reason=int(refusal.reason),
            lag=refusal.lag_ops, commit_min=refusal.commit_min,
        )

    def _on_request(self, conn: int, header, body: bytes) -> None:
        operation = int(header["operation"])
        if operation == int(VsrOperation.stats):
            from tigerbeetle_tpu.obs.scrape import stats_reply

            reply, rbody = stats_reply(self.registry.snapshot(), header)
            self.bus.send(conn, reply.tobytes() + rbody)
            return
        if operation == int(VsrOperation.state_root):
            from tigerbeetle_tpu.obs.scrape import state_root_reply
            from tigerbeetle_tpu.state_machine import commitment

            core = self.core
            at_op = commitment.parse_root_query(bytes(body))
            root = None if at_op is None else core._roots.get(at_op)
            if root is not None:
                commit_min = at_op
            else:
                root = core._roots.get(core.commit_min)
                if root is None:
                    root = core.sm.state_root()
                commit_min = core.commit_min
            reply, rbody = state_root_reply(root, commit_min, header)
            self.bus.send(conn, reply.tobytes() + rbody)
            return
        if operation == int(VsrOperation.register):
            # Sessionless register: reads are idempotent, so the
            # follower keeps no session table — but answering lets
            # unmodified clients (OpenLoopSession, the C client)
            # connect without a special mode.
            self._reply(conn, header, operation, b"", None)
            return
        tenant = wire.tenant_of(header, body)
        result = self.core.serve(
            operation, bytes(body), now_ns=self.clock_ns(),
            tenant=tenant,
        )
        if isinstance(result, FollowerRefusal):
            self._refuse(conn, header, result)
            return
        self._reply(conn, header, operation, result.body,
                    (result.root, result.commit_min))

    # -- loop ----------------------------------------------------------

    def poll_once(self, timeout_ms: int = 10) -> None:
        for ev_type, conn, payload in self.bus.poll(timeout_ms):
            if ev_type == self._ev_closed:
                if conn == self._up_conn:
                    self._up_conn = None
                continue
            if ev_type != self._ev_message or len(payload) < HEADER_SIZE:
                continue
            header = wire.header_from_bytes(payload[:HEADER_SIZE])
            body = payload[HEADER_SIZE:]
            if not wire.verify_header(header, body):
                continue
            if conn == self._up_conn:
                self._on_upstream(header, body)
            elif int(header["command"]) == int(Command.request):
                self._on_request(conn, header, body)
        # Bounded replay burst per poll: a RECORD is a whole client
        # batch (up to 8k events of host state-machine CPU), so even a
        # few per poll keep replay throughput high while reads,
        # scrapes, and attestation replies stay responsive during a
        # deep catch-up — an unbounded pump starved them for the
        # whole backlog.
        self.core.pump(max_records=4)
        now = self.clock_ns()
        if now - self._last_attest >= self._attest_ns:
            self._last_attest = now
            self._send_attest_query()

    def serve_forever(self) -> None:
        while True:
            self.poll_once()

    def close(self) -> None:
        self.bus.close()
