"""Replica core pinning (TB_CPU_AFFINITY; round 20).

Multi-process configs (replicated bench, sharded clusters, the
server/router/follower CLIs) used to leave every Python VSR loop on
the scheduler's default mask — on a small box three replicas fight
over the same cores and the consensus pipeline serializes.  This
module turns the validated TB_CPU_AFFINITY knob (envcheck.py) into
actual ``os.sched_setaffinity`` calls, keyed by a process SLOT (the
replica index, the shard*replicas+replica index, or 0 for routers):

- "none"  -> no pinning (inherit the parent mask).
- "auto"  -> slot i pins to core (i mod cpu_count).
- "0,1,2" -> slot i pins to the (i mod len)'th listed core.

``plan`` is pure (the bench calls it to RECORD ``pinned_cores`` per
subprocess without being the subprocess); ``apply`` performs the
pinning in the target process and degrades to None on platforms
without sched_setaffinity rather than failing the spawn.
"""

from __future__ import annotations

import os

from tigerbeetle_tpu import envcheck


def plan(slot: int, spec: str | None = None) -> tuple[int, ...] | None:
    """The core set slot `slot` would pin to under `spec` (default:
    the TB_CPU_AFFINITY environment), or None for no pinning."""
    if spec is None:
        spec = envcheck.cpu_affinity()
    if spec == "none":
        return None
    if spec == "auto":
        count = os.cpu_count() or 1
        return (slot % count,)
    cores = [int(p) for p in spec.split(",")]
    return (cores[slot % len(cores)],)


def apply(slot: int = 0, spec: str | None = None) -> tuple[int, ...] | None:
    """Pin the CURRENT process per plan(slot, spec).  Returns the
    pinned core set, or None when pinning is off / unsupported / the
    planned core does not exist on this box (a 4-core list on a
    2-core container must not kill the replica — it just runs
    unpinned and the bench's pinned_cores record says so)."""
    cores = plan(slot, spec)
    if cores is None:
        return None
    if not hasattr(os, "sched_setaffinity"):
        return None
    try:
        os.sched_setaffinity(0, cores)
    except OSError:
        return None
    return cores
