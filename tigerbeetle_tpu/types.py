"""Wire types for the TPU-native TigerBeetle-compatible framework.

Byte-for-byte compatible with the reference `extern struct` layouts
(reference: src/tigerbeetle.zig:7-322). All integers are little-endian;
u128 fields are represented as two little-endian u64 limbs ``(lo, hi)``
so the 16-byte little-endian layout is preserved exactly.

Every dtype below is asserted to have the exact size/offsets of the Zig
struct it mirrors (reference: src/tigerbeetle.zig:25-29,106-110 asserts
sizeof==128 for Account/Transfer).
"""

from __future__ import annotations

import enum
import hashlib

import numpy as np

U128_MAX = (1 << 128) - 1
U64_MAX = (1 << 64) - 1
NS_PER_S = 1_000_000_000

# reference: src/lsm/timestamp_range.zig:4-5
TIMESTAMP_MIN = 1
TIMESTAMP_MAX = (1 << 64) - 2


def _u128(name: str) -> list[tuple[str, str]]:
    """A u128 field as two u64 limbs, little-endian (lo first)."""
    return [(f"{name}_lo", "<u8"), (f"{name}_hi", "<u8")]


# reference: src/tigerbeetle.zig:7-29 (Account, 128 bytes)
ACCOUNT_DTYPE = np.dtype(
    _u128("id")
    + _u128("debits_pending")
    + _u128("debits_posted")
    + _u128("credits_pending")
    + _u128("credits_posted")
    + _u128("user_data_128")
    + [
        ("user_data_64", "<u8"),
        ("user_data_32", "<u4"),
        ("reserved", "<u4"),
        ("ledger", "<u4"),
        ("code", "<u2"),
        ("flags", "<u2"),
        ("timestamp", "<u8"),
    ]
)

# reference: src/tigerbeetle.zig:80-111 (Transfer, 128 bytes)
TRANSFER_DTYPE = np.dtype(
    _u128("id")
    + _u128("debit_account_id")
    + _u128("credit_account_id")
    + _u128("amount")
    + _u128("pending_id")
    + _u128("user_data_128")
    + [
        ("user_data_64", "<u8"),
        ("user_data_32", "<u4"),
        ("timeout", "<u4"),
        ("ledger", "<u4"),
        ("code", "<u2"),
        ("flags", "<u2"),
        ("timestamp", "<u8"),
    ]
)

# reference: src/tigerbeetle.zig:65-78 (AccountBalance, 128 bytes)
ACCOUNT_BALANCE_DTYPE = np.dtype(
    _u128("debits_pending")
    + _u128("debits_posted")
    + _u128("credits_pending")
    + _u128("credits_posted")
    + [
        ("timestamp", "<u8"),
        ("reserved", "u1", (56,)),
    ]
)

# reference: src/tigerbeetle.zig:288-307 (AccountFilter, 64 bytes)
ACCOUNT_FILTER_DTYPE = np.dtype(
    _u128("account_id")
    + [
        ("timestamp_min", "<u8"),
        ("timestamp_max", "<u8"),
        ("limit", "<u4"),
        ("flags", "<u4"),
        ("reserved", "u1", (24,)),
    ]
)

# reference: src/tigerbeetle.zig:267-285 (CreateAccountsResult/CreateTransfersResult)
CREATE_RESULT_DTYPE = np.dtype([("index", "<u4"), ("result", "<u4")])

# A bare u128 on the wire (lookup_accounts / lookup_transfers events):
# two little-endian u64 limbs, lo first.
U128_PAIR_DTYPE = np.dtype([("lo", "<u8"), ("hi", "<u8")])

# reference: src/state_machine.zig:259-269 (TransferPending, 16 bytes)
TRANSFER_PENDING_DTYPE = np.dtype(
    [("timestamp", "<u8"), ("status", "u1"), ("padding", "u1", (7,))]
)

# reference: src/state_machine.zig:296-315 (AccountBalancesGrooveValue, 256 bytes)
ACCOUNT_BALANCES_GROOVE_DTYPE = np.dtype(
    _u128("dr_account_id")
    + _u128("dr_debits_pending")
    + _u128("dr_debits_posted")
    + _u128("dr_credits_pending")
    + _u128("dr_credits_posted")
    + _u128("cr_account_id")
    + _u128("cr_debits_pending")
    + _u128("cr_debits_posted")
    + _u128("cr_credits_pending")
    + _u128("cr_credits_posted")
    + [
        ("timestamp", "<u8"),
        ("reserved", "u1", (88,)),
    ]
)

assert ACCOUNT_DTYPE.itemsize == 128, ACCOUNT_DTYPE.itemsize
assert TRANSFER_DTYPE.itemsize == 128, TRANSFER_DTYPE.itemsize
assert ACCOUNT_BALANCE_DTYPE.itemsize == 128, ACCOUNT_BALANCE_DTYPE.itemsize
assert ACCOUNT_FILTER_DTYPE.itemsize == 64, ACCOUNT_FILTER_DTYPE.itemsize
assert CREATE_RESULT_DTYPE.itemsize == 8
assert TRANSFER_PENDING_DTYPE.itemsize == 16
assert ACCOUNT_BALANCES_GROOVE_DTYPE.itemsize == 256


class EngineState(enum.Enum):
    """Device-authoritative engine lifecycle
    (state_machine/device_engine.py):

    - ``healthy``: the HBM table is authoritative; semantic kernels
      compute result codes on device.
    - ``degraded``: the device link was lost (fatal error or retry
      budget exhausted); the host mirror is authoritative and every
      request is served by the exact host engine, bit-identically.
    - ``repromoting``: a health probe succeeded and the engine is
      re-uploading the table from the mirror; it becomes healthy only
      after the checksum handshake passes.
    """

    healthy = "healthy"
    degraded = "degraded"
    repromoting = "repromoting"


class AccountFlags(enum.IntFlag):
    """reference: src/tigerbeetle.zig:42-63"""

    linked = 1 << 0
    debits_must_not_exceed_credits = 1 << 1
    credits_must_not_exceed_debits = 1 << 2
    history = 1 << 3

    _valid_mask = (1 << 4) - 1


class TransferFlags(enum.IntFlag):
    """reference: src/tigerbeetle.zig:127-140"""

    linked = 1 << 0
    pending = 1 << 1
    post_pending_transfer = 1 << 2
    void_pending_transfer = 1 << 3
    balancing_debit = 1 << 4
    balancing_credit = 1 << 5

    _valid_mask = (1 << 6) - 1


class AccountFilterFlags(enum.IntFlag):
    """reference: src/tigerbeetle.zig:309-322"""

    debits = 1 << 0
    credits = 1 << 1
    reversed = 1 << 2

    _valid_mask = (1 << 3) - 1


class TransferPendingStatus(enum.IntEnum):
    """reference: src/tigerbeetle.zig:113-125"""

    none = 0
    pending = 1
    posted = 2
    voided = 3
    expired = 4


class CreateAccountResult(enum.IntEnum):
    """Error codes ordered by descending precedence.

    reference: src/tigerbeetle.zig:145-180
    """

    ok = 0
    linked_event_failed = 1
    linked_event_chain_open = 2
    timestamp_must_be_zero = 3
    reserved_field = 4
    reserved_flag = 5
    id_must_not_be_zero = 6
    id_must_not_be_int_max = 7
    flags_are_mutually_exclusive = 8
    debits_pending_must_be_zero = 9
    debits_posted_must_be_zero = 10
    credits_pending_must_be_zero = 11
    credits_posted_must_be_zero = 12
    ledger_must_not_be_zero = 13
    code_must_not_be_zero = 14
    exists_with_different_flags = 15
    exists_with_different_user_data_128 = 16
    exists_with_different_user_data_64 = 17
    exists_with_different_user_data_32 = 18
    exists_with_different_ledger = 19
    exists_with_different_code = 20
    exists = 21


class CreateTransferResult(enum.IntEnum):
    """Error codes ordered by descending precedence.

    reference: src/tigerbeetle.zig:185-265
    """

    ok = 0
    linked_event_failed = 1
    linked_event_chain_open = 2
    timestamp_must_be_zero = 3
    reserved_flag = 4
    id_must_not_be_zero = 5
    id_must_not_be_int_max = 6
    flags_are_mutually_exclusive = 7
    debit_account_id_must_not_be_zero = 8
    debit_account_id_must_not_be_int_max = 9
    credit_account_id_must_not_be_zero = 10
    credit_account_id_must_not_be_int_max = 11
    accounts_must_be_different = 12
    pending_id_must_be_zero = 13
    pending_id_must_not_be_zero = 14
    pending_id_must_not_be_int_max = 15
    pending_id_must_be_different = 16
    timeout_reserved_for_pending_transfer = 17
    amount_must_not_be_zero = 18
    ledger_must_not_be_zero = 19
    code_must_not_be_zero = 20
    debit_account_not_found = 21
    credit_account_not_found = 22
    accounts_must_have_the_same_ledger = 23
    transfer_must_have_the_same_ledger_as_accounts = 24
    pending_transfer_not_found = 25
    pending_transfer_not_pending = 26
    pending_transfer_has_different_debit_account_id = 27
    pending_transfer_has_different_credit_account_id = 28
    pending_transfer_has_different_ledger = 29
    pending_transfer_has_different_code = 30
    exceeds_pending_transfer_amount = 31
    pending_transfer_has_different_amount = 32
    pending_transfer_already_posted = 33
    pending_transfer_already_voided = 34
    pending_transfer_expired = 35
    exists_with_different_flags = 36
    exists_with_different_debit_account_id = 37
    exists_with_different_credit_account_id = 38
    exists_with_different_amount = 39
    exists_with_different_pending_id = 40
    exists_with_different_user_data_128 = 41
    exists_with_different_user_data_64 = 42
    exists_with_different_user_data_32 = 43
    exists_with_different_timeout = 44
    exists_with_different_code = 45
    exists = 46
    overflows_debits_pending = 47
    overflows_credits_pending = 48
    overflows_debits_posted = 49
    overflows_credits_posted = 50
    overflows_debits = 51
    overflows_credits = 52
    overflows_timeout = 53
    exceeds_credits = 54
    exceeds_debits = 55


class Operation(enum.IntEnum):
    """State-machine operations; values = vsr_operations_reserved + n.

    reference: src/state_machine.zig:341-350, src/constants.zig:47
    """

    pulse = 128
    create_accounts = 129
    create_transfers = 130
    lookup_accounts = 131
    lookup_transfers = 132
    get_account_transfers = 133
    get_account_balances = 134


# Read-only operations: pure queries over committed state, safe to
# serve outside the commit stream (the follower read path and the
# router's read steering key on this; CpuStateMachine's executors are
# the per-op twin).  ONE definition — three hand-maintained copies
# would let a new query op silently miss follower serving.
READ_OPERATIONS = frozenset({
    Operation.lookup_accounts,
    Operation.lookup_transfers,
    Operation.get_account_transfers,
    Operation.get_account_balances,
})


# Event/Result wire types per operation.
# reference: src/state_machine.zig:503-525
EVENT_DTYPE = {
    Operation.pulse: None,
    Operation.create_accounts: ACCOUNT_DTYPE,
    Operation.create_transfers: TRANSFER_DTYPE,
    Operation.lookup_accounts: U128_PAIR_DTYPE,
    Operation.lookup_transfers: U128_PAIR_DTYPE,
    Operation.get_account_transfers: ACCOUNT_FILTER_DTYPE,
    Operation.get_account_balances: ACCOUNT_FILTER_DTYPE,
}

RESULT_DTYPE = {
    Operation.pulse: None,
    Operation.create_accounts: CREATE_RESULT_DTYPE,
    Operation.create_transfers: CREATE_RESULT_DTYPE,
    Operation.lookup_accounts: ACCOUNT_DTYPE,
    Operation.lookup_transfers: TRANSFER_DTYPE,
    Operation.get_account_transfers: TRANSFER_DTYPE,
    Operation.get_account_balances: ACCOUNT_BALANCE_DTYPE,
}


# ----------------------------------------------------------------------
# Account-range sharding (runtime/router.py).
#
# A multi-cluster deployment partitions the account space across N
# independent consensus groups; every layer that routes by account
# (router batch split, client hints, recovery scans, the VOPR's
# checkers) must agree on ONE deterministic mapping, so it lives here
# next to the wire types.

# Odd golden-ratio multiplier: a multiplicative mix so sequential
# account ids (the common allocation pattern) spread across shards
# instead of striping modulo N.
_SHARD_MIX = 0x9E3779B97F4A7C15


def shard_of_account(account_id: int, n_shards: int) -> int:
    """Deterministic account -> shard mapping.

    Pure function of (id, n_shards): every router incarnation, client,
    and checker derives the same placement with no directory service.
    """
    assert 0 <= account_id <= U128_MAX
    if n_shards <= 1:
        return 0
    lo = account_id & U64_MAX
    hi = account_id >> 64
    mixed = ((lo ^ hi) * _SHARD_MIX) & U64_MAX
    return int((mixed >> 32) % n_shards)


# Coordinator-owned ledger accounts (cross-shard 2PC): each shard holds
# one settlement account per ledger in a tagged id namespace that real
# clients must not allocate from.  A cross-shard transfer becomes a
# pending hold against the settlement account on each side; the
# coordinator posts or voids both.
COORD_ID_TAG = 0xC0 << 120
# Ledger-registry bookkeeping rides its own ledger so client-visible
# ledgers never see registry rows.
COORD_REGISTRY_LEDGER = 0xC0C0
# Registry accounts (per shard, fixed ids): a posted registry transfer
# whose AMOUNT is the ledger number records "this shard has a
# settlement account for ledger L" durably in the shard's own log —
# a restarted coordinator enumerates ledgers by scanning the registry
# account's transfers (get_account_transfers), with no local state.
COORD_REGISTRY_ACCOUNT = COORD_ID_TAG | (0xEE << 64)
COORD_REGISTRY_FUNDING = COORD_ID_TAG | (0xEF << 64)


def coord_account_id(ledger: int) -> int:
    """The settlement account id for `ledger` (same id on every shard;
    each shard's account table is independent)."""
    assert 0 < ledger <= 0xFFFFFFFF
    return COORD_ID_TAG | ledger


def is_coord_account(account_id: int) -> bool:
    return (account_id >> 120) == 0xC0


# Cross-shard 2PC leg tags, carried in the holds' user_data_64 so a
# recovery scan can reconstruct (tid, leg, peer shard) from the rows
# alone: (peer_shard << 8) | leg.
XLEG_DEBIT = 1  # client debit account -> settlement (debit shard)
XLEG_CREDIT = 2  # settlement -> client credit account (credit shard)


def xleg_tag(leg: int, peer_shard: int) -> int:
    assert leg in (XLEG_DEBIT, XLEG_CREDIT)
    return (peer_shard << 8) | leg


def xleg_untag(tag: int) -> tuple[int, int]:
    """-> (leg, peer_shard)."""
    return tag & 0xFF, tag >> 8


class XShardIds:
    """Deterministic derived transfer ids for one cross-shard transfer.

    The client's transfer id `tid` is the idempotency key; every 2PC
    artifact (the two holds, the post/void resolutions, the
    budget-violation compensation) derives its id from (tid, role) by
    hashing into the upper half of the u128 space.  Determinism is
    what makes the protocol crash-safe: a restarted coordinator
    re-derives the same ids, so re-driving any leg is deduplicated by
    the state machine's id-uniqueness (`exists`) instead of by
    coordinator-local state.
    """

    __slots__ = ("tid", "hold_debit", "hold_credit", "post_debit",
                 "post_credit", "void_debit", "void_credit", "comp")

    _ROLES = ("hold_debit", "hold_credit", "post_debit", "post_credit",
              "void_debit", "void_credit", "comp")

    def __init__(self, tid: int) -> None:
        self.tid = tid
        for role in self._ROLES:
            setattr(self, role, self._derive(tid, role))

    @staticmethod
    def _derive(tid: int, role: str) -> int:
        digest = hashlib.sha256(
            b"tb-xshard-2pc:" + role.encode() + b":"
            + tid.to_bytes(16, "little")
        ).digest()
        value = int.from_bytes(digest[:16], "little") | (1 << 127)
        if value == U128_MAX:  # id_must_not_be_int_max
            value -= 1
        return value


def u128_get(row: np.void, name: str) -> int:
    """Read a u128 field from a structured-array row as a Python int."""
    return int(row[f"{name}_lo"]) | (int(row[f"{name}_hi"]) << 64)


def u128_set(row: np.void, name: str, value: int) -> None:
    """Write a Python int into a u128 (lo, hi) field pair."""
    assert 0 <= value <= U128_MAX
    row[f"{name}_lo"] = value & U64_MAX
    row[f"{name}_hi"] = value >> 64
