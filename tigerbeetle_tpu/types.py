"""Wire types for the TPU-native TigerBeetle-compatible framework.

Byte-for-byte compatible with the reference `extern struct` layouts
(reference: src/tigerbeetle.zig:7-322). All integers are little-endian;
u128 fields are represented as two little-endian u64 limbs ``(lo, hi)``
so the 16-byte little-endian layout is preserved exactly.

Every dtype below is asserted to have the exact size/offsets of the Zig
struct it mirrors (reference: src/tigerbeetle.zig:25-29,106-110 asserts
sizeof==128 for Account/Transfer).
"""

from __future__ import annotations

import enum

import numpy as np

U128_MAX = (1 << 128) - 1
U64_MAX = (1 << 64) - 1
NS_PER_S = 1_000_000_000

# reference: src/lsm/timestamp_range.zig:4-5
TIMESTAMP_MIN = 1
TIMESTAMP_MAX = (1 << 64) - 2


def _u128(name: str) -> list[tuple[str, str]]:
    """A u128 field as two u64 limbs, little-endian (lo first)."""
    return [(f"{name}_lo", "<u8"), (f"{name}_hi", "<u8")]


# reference: src/tigerbeetle.zig:7-29 (Account, 128 bytes)
ACCOUNT_DTYPE = np.dtype(
    _u128("id")
    + _u128("debits_pending")
    + _u128("debits_posted")
    + _u128("credits_pending")
    + _u128("credits_posted")
    + _u128("user_data_128")
    + [
        ("user_data_64", "<u8"),
        ("user_data_32", "<u4"),
        ("reserved", "<u4"),
        ("ledger", "<u4"),
        ("code", "<u2"),
        ("flags", "<u2"),
        ("timestamp", "<u8"),
    ]
)

# reference: src/tigerbeetle.zig:80-111 (Transfer, 128 bytes)
TRANSFER_DTYPE = np.dtype(
    _u128("id")
    + _u128("debit_account_id")
    + _u128("credit_account_id")
    + _u128("amount")
    + _u128("pending_id")
    + _u128("user_data_128")
    + [
        ("user_data_64", "<u8"),
        ("user_data_32", "<u4"),
        ("timeout", "<u4"),
        ("ledger", "<u4"),
        ("code", "<u2"),
        ("flags", "<u2"),
        ("timestamp", "<u8"),
    ]
)

# reference: src/tigerbeetle.zig:65-78 (AccountBalance, 128 bytes)
ACCOUNT_BALANCE_DTYPE = np.dtype(
    _u128("debits_pending")
    + _u128("debits_posted")
    + _u128("credits_pending")
    + _u128("credits_posted")
    + [
        ("timestamp", "<u8"),
        ("reserved", "u1", (56,)),
    ]
)

# reference: src/tigerbeetle.zig:288-307 (AccountFilter, 64 bytes)
ACCOUNT_FILTER_DTYPE = np.dtype(
    _u128("account_id")
    + [
        ("timestamp_min", "<u8"),
        ("timestamp_max", "<u8"),
        ("limit", "<u4"),
        ("flags", "<u4"),
        ("reserved", "u1", (24,)),
    ]
)

# reference: src/tigerbeetle.zig:267-285 (CreateAccountsResult/CreateTransfersResult)
CREATE_RESULT_DTYPE = np.dtype([("index", "<u4"), ("result", "<u4")])

# A bare u128 on the wire (lookup_accounts / lookup_transfers events):
# two little-endian u64 limbs, lo first.
U128_PAIR_DTYPE = np.dtype([("lo", "<u8"), ("hi", "<u8")])

# reference: src/state_machine.zig:259-269 (TransferPending, 16 bytes)
TRANSFER_PENDING_DTYPE = np.dtype(
    [("timestamp", "<u8"), ("status", "u1"), ("padding", "u1", (7,))]
)

# reference: src/state_machine.zig:296-315 (AccountBalancesGrooveValue, 256 bytes)
ACCOUNT_BALANCES_GROOVE_DTYPE = np.dtype(
    _u128("dr_account_id")
    + _u128("dr_debits_pending")
    + _u128("dr_debits_posted")
    + _u128("dr_credits_pending")
    + _u128("dr_credits_posted")
    + _u128("cr_account_id")
    + _u128("cr_debits_pending")
    + _u128("cr_debits_posted")
    + _u128("cr_credits_pending")
    + _u128("cr_credits_posted")
    + [
        ("timestamp", "<u8"),
        ("reserved", "u1", (88,)),
    ]
)

assert ACCOUNT_DTYPE.itemsize == 128, ACCOUNT_DTYPE.itemsize
assert TRANSFER_DTYPE.itemsize == 128, TRANSFER_DTYPE.itemsize
assert ACCOUNT_BALANCE_DTYPE.itemsize == 128, ACCOUNT_BALANCE_DTYPE.itemsize
assert ACCOUNT_FILTER_DTYPE.itemsize == 64, ACCOUNT_FILTER_DTYPE.itemsize
assert CREATE_RESULT_DTYPE.itemsize == 8
assert TRANSFER_PENDING_DTYPE.itemsize == 16
assert ACCOUNT_BALANCES_GROOVE_DTYPE.itemsize == 256


class EngineState(enum.Enum):
    """Device-authoritative engine lifecycle
    (state_machine/device_engine.py):

    - ``healthy``: the HBM table is authoritative; semantic kernels
      compute result codes on device.
    - ``degraded``: the device link was lost (fatal error or retry
      budget exhausted); the host mirror is authoritative and every
      request is served by the exact host engine, bit-identically.
    - ``repromoting``: a health probe succeeded and the engine is
      re-uploading the table from the mirror; it becomes healthy only
      after the checksum handshake passes.
    """

    healthy = "healthy"
    degraded = "degraded"
    repromoting = "repromoting"


class AccountFlags(enum.IntFlag):
    """reference: src/tigerbeetle.zig:42-63"""

    linked = 1 << 0
    debits_must_not_exceed_credits = 1 << 1
    credits_must_not_exceed_debits = 1 << 2
    history = 1 << 3

    _valid_mask = (1 << 4) - 1


class TransferFlags(enum.IntFlag):
    """reference: src/tigerbeetle.zig:127-140"""

    linked = 1 << 0
    pending = 1 << 1
    post_pending_transfer = 1 << 2
    void_pending_transfer = 1 << 3
    balancing_debit = 1 << 4
    balancing_credit = 1 << 5

    _valid_mask = (1 << 6) - 1


class AccountFilterFlags(enum.IntFlag):
    """reference: src/tigerbeetle.zig:309-322"""

    debits = 1 << 0
    credits = 1 << 1
    reversed = 1 << 2

    _valid_mask = (1 << 3) - 1


class TransferPendingStatus(enum.IntEnum):
    """reference: src/tigerbeetle.zig:113-125"""

    none = 0
    pending = 1
    posted = 2
    voided = 3
    expired = 4


class CreateAccountResult(enum.IntEnum):
    """Error codes ordered by descending precedence.

    reference: src/tigerbeetle.zig:145-180
    """

    ok = 0
    linked_event_failed = 1
    linked_event_chain_open = 2
    timestamp_must_be_zero = 3
    reserved_field = 4
    reserved_flag = 5
    id_must_not_be_zero = 6
    id_must_not_be_int_max = 7
    flags_are_mutually_exclusive = 8
    debits_pending_must_be_zero = 9
    debits_posted_must_be_zero = 10
    credits_pending_must_be_zero = 11
    credits_posted_must_be_zero = 12
    ledger_must_not_be_zero = 13
    code_must_not_be_zero = 14
    exists_with_different_flags = 15
    exists_with_different_user_data_128 = 16
    exists_with_different_user_data_64 = 17
    exists_with_different_user_data_32 = 18
    exists_with_different_ledger = 19
    exists_with_different_code = 20
    exists = 21


class CreateTransferResult(enum.IntEnum):
    """Error codes ordered by descending precedence.

    reference: src/tigerbeetle.zig:185-265
    """

    ok = 0
    linked_event_failed = 1
    linked_event_chain_open = 2
    timestamp_must_be_zero = 3
    reserved_flag = 4
    id_must_not_be_zero = 5
    id_must_not_be_int_max = 6
    flags_are_mutually_exclusive = 7
    debit_account_id_must_not_be_zero = 8
    debit_account_id_must_not_be_int_max = 9
    credit_account_id_must_not_be_zero = 10
    credit_account_id_must_not_be_int_max = 11
    accounts_must_be_different = 12
    pending_id_must_be_zero = 13
    pending_id_must_not_be_zero = 14
    pending_id_must_not_be_int_max = 15
    pending_id_must_be_different = 16
    timeout_reserved_for_pending_transfer = 17
    amount_must_not_be_zero = 18
    ledger_must_not_be_zero = 19
    code_must_not_be_zero = 20
    debit_account_not_found = 21
    credit_account_not_found = 22
    accounts_must_have_the_same_ledger = 23
    transfer_must_have_the_same_ledger_as_accounts = 24
    pending_transfer_not_found = 25
    pending_transfer_not_pending = 26
    pending_transfer_has_different_debit_account_id = 27
    pending_transfer_has_different_credit_account_id = 28
    pending_transfer_has_different_ledger = 29
    pending_transfer_has_different_code = 30
    exceeds_pending_transfer_amount = 31
    pending_transfer_has_different_amount = 32
    pending_transfer_already_posted = 33
    pending_transfer_already_voided = 34
    pending_transfer_expired = 35
    exists_with_different_flags = 36
    exists_with_different_debit_account_id = 37
    exists_with_different_credit_account_id = 38
    exists_with_different_amount = 39
    exists_with_different_pending_id = 40
    exists_with_different_user_data_128 = 41
    exists_with_different_user_data_64 = 42
    exists_with_different_user_data_32 = 43
    exists_with_different_timeout = 44
    exists_with_different_code = 45
    exists = 46
    overflows_debits_pending = 47
    overflows_credits_pending = 48
    overflows_debits_posted = 49
    overflows_credits_posted = 50
    overflows_debits = 51
    overflows_credits = 52
    overflows_timeout = 53
    exceeds_credits = 54
    exceeds_debits = 55


class Operation(enum.IntEnum):
    """State-machine operations; values = vsr_operations_reserved + n.

    reference: src/state_machine.zig:341-350, src/constants.zig:47
    """

    pulse = 128
    create_accounts = 129
    create_transfers = 130
    lookup_accounts = 131
    lookup_transfers = 132
    get_account_transfers = 133
    get_account_balances = 134


# Event/Result wire types per operation.
# reference: src/state_machine.zig:503-525
EVENT_DTYPE = {
    Operation.pulse: None,
    Operation.create_accounts: ACCOUNT_DTYPE,
    Operation.create_transfers: TRANSFER_DTYPE,
    Operation.lookup_accounts: U128_PAIR_DTYPE,
    Operation.lookup_transfers: U128_PAIR_DTYPE,
    Operation.get_account_transfers: ACCOUNT_FILTER_DTYPE,
    Operation.get_account_balances: ACCOUNT_FILTER_DTYPE,
}

RESULT_DTYPE = {
    Operation.pulse: None,
    Operation.create_accounts: CREATE_RESULT_DTYPE,
    Operation.create_transfers: CREATE_RESULT_DTYPE,
    Operation.lookup_accounts: ACCOUNT_DTYPE,
    Operation.lookup_transfers: TRANSFER_DTYPE,
    Operation.get_account_transfers: TRANSFER_DTYPE,
    Operation.get_account_balances: ACCOUNT_BALANCE_DTYPE,
}


def u128_get(row: np.void, name: str) -> int:
    """Read a u128 field from a structured-array row as a Python int."""
    return int(row[f"{name}_lo"]) | (int(row[f"{name}_hi"]) << 64)


def u128_set(row: np.void, name: str, value: int) -> None:
    """Write a Python int into a u128 (lo, hi) field pair."""
    assert 0 <= value <= U128_MAX
    row[f"{name}_lo"] = value & U64_MAX
    row[f"{name}_hi"] = value >> 64
