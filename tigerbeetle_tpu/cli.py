"""CLI: format | start | version | repl | benchmark.

reference: src/tigerbeetle/cli.zig:106-128 (same subcommands),
src/tigerbeetle/benchmark_driver.zig + benchmark_load.zig (benchmark
formats a temp single-replica cluster when no --addresses is given,
then streams transfer batches and reports throughput + latency
percentiles).
"""
# tbcheck: allow-file(no-print): the CLI's stdout IS its interface
# (command results, usage, listen-port handshake).

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

from tigerbeetle_tpu import flags
from tigerbeetle_tpu import constants as cfg

VERSION = "0.1.0"

# Reference Start.cache_accounts/cache_transfers default analog: one
# value, used by the flag spec, the factory defaults, and the --cpu
# warning alike.
CACHE_DEFAULT = 1 << 16

USAGE = """usage: tigerbeetle-tpu <command> [flags]

commands:
  format     --cluster=<int> --replica=<i> --replica-count=<n> <path>
  start      --addresses=<host:port,...> --replica=<i> [--cpu]
             [--aof=<path>] [--trace=<path>] [--standby-count=<n>]
             [--cache-accounts=<n>] [--cache-transfers=<n>]
             <path>...
  router     --listen=<host:port> --shards=<addrs;addrs;...>
             [--cluster=<int>] [--no-recover]
             [--followers=<shard:host:port;...>]
             (account-sharded multi-cluster front-end: each ';'-
              separated entry is one shard's comma-joined replica
              address list; on start the router recovers in-doubt
              cross-shard transfers from shard state; --followers
              names read-only followers reads steer to under
              TB_READ_POLICY)
  follower   --listen=<host:port> --aof=<path> --upstream=<host:port>
             --cluster=<int> [--id=<n>]
             (read-only follower: tails the upstream replica's AOF,
              replays it, serves lookup/filter reads at a stated
              commit_min with every reply carrying the r15 state root,
              attested against the upstream's root ring — refuses
              typed rather than serve unverifiable state)
  version
  repl       --addresses=<host:port> [--cluster=<int>] [--command=<stmts>]
  benchmark  [--transfers=N] [--accounts=N] [--batch=N] [--addresses=...]
             [--statsd-port=N]
  bindings   [--out=<dir>]   (generate C / TypeScript / Go type bindings)
  lint       [--json] [paths...]
             (tbcheck: AST invariant lint over the package — exits
              nonzero on any unsuppressed finding)
  trace-demo [--out=<path>] [--replicas=N] [--batches=N]
             (drive a replicated drain with tracing on and write one
              merged Perfetto-loadable timeline)
"""


def _sm_factory(use_cpu: bool, cache_accounts: int = CACHE_DEFAULT,
                cache_transfers: int = CACHE_DEFAULT):
    """Capacities follow the reference's static-allocation design:
    operator-configured cache sizes pre-size every large buffer
    (reference: src/tigerbeetle/cli.zig Start.cache_accounts /
    cache_transfers)."""
    if use_cpu:
        from tigerbeetle_tpu.state_machine import CpuStateMachine

        if (cache_accounts, cache_transfers) != (CACHE_DEFAULT, CACHE_DEFAULT):
            print(
                "warning: --cache-accounts/--cache-transfers have no "
                "effect with --cpu (the CPU engine is dict-backed and "
                "unbounded)",
                file=sys.stderr,
            )
        return lambda: CpuStateMachine(cfg.PRODUCTION)
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    return lambda: TpuStateMachine(
        cfg.PRODUCTION,
        account_capacity=cache_accounts,
        transfer_capacity=cache_transfers,
    )


def cmd_format(args: list[str]) -> None:
    opts, paths = flags.parse(
        args, {"cluster": None, "replica": 0, "replica_count": 1}
    )
    if len(paths) != 1:
        flags.fatal("format requires exactly one data-file path")
    from tigerbeetle_tpu.runtime.server import format_data_file

    format_data_file(
        paths[0], cluster=int(opts["cluster"], 0)
        if isinstance(opts["cluster"], str) else opts["cluster"],
        replica_index=opts["replica"], replica_count=opts["replica_count"],
    )
    print(f"formatted {paths[0]}")


def cmd_start(args: list[str]) -> None:
    opts, paths = flags.parse(
        args,
        {"addresses": None, "replica": 0, "cluster": "", "cpu": False,
         "aof": "", "trace": "", "standby_count": 0,
         "cache_accounts": CACHE_DEFAULT, "cache_transfers": CACHE_DEFAULT},
    )
    if len(paths) != 1:
        flags.fatal("start requires exactly one data-file path")
    from tigerbeetle_tpu.runtime.server import ReplicaServer

    # --cluster is optional: the data file records it at format time
    # (reference: src/tigerbeetle/main.zig start reads the superblock);
    # passing it explicitly just adds a consistency check.
    cluster = None
    if opts["cluster"]:
        try:
            cluster = int(opts["cluster"], 0)
        except ValueError:
            flags.fatal(f"--cluster: invalid integer {opts['cluster']!r}")
    # Core pinning (TB_CPU_AFFINITY): slot = replica index, so a
    # cluster's replicas spread across cores under "auto".
    from tigerbeetle_tpu.runtime import affinity

    pinned = affinity.apply(slot=opts["replica"])
    if pinned is not None:
        print(f"pinned to cores {list(pinned)}", flush=True)
    server = ReplicaServer(
        paths[0], cluster=cluster,
        addresses=opts["addresses"].split(","), replica_index=opts["replica"],
        state_machine_factory=_sm_factory(
            opts["cpu"], cache_accounts=opts["cache_accounts"],
            cache_transfers=opts["cache_transfers"],
        ),
        aof_path=opts["aof"] or None,
        trace_path=opts["trace"] or None,
        standby_count=opts["standby_count"],
    )
    print(f"listening on port {server.port}", flush=True)
    # Graceful shutdown on SIGTERM/SIGINT: flush the AOF and write the
    # trace file (close() is the only writer of --trace output).
    import signal

    def _stop(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _stop)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # Crashes flush the trace/AOF too, not just clean shutdowns.
        server.close()


def cmd_router(args: list[str]) -> None:
    opts, paths = flags.parse(
        args,
        {"listen": "127.0.0.1:3000", "shards": None, "cluster": 0,
         "no_recover": False, "followers": ""},
    )
    if paths:
        flags.fatal("router takes no positional arguments")
    if not opts["shards"]:
        flags.fatal("router requires --shards=<addrs;addrs;...>")
    from tigerbeetle_tpu.runtime import affinity
    from tigerbeetle_tpu.runtime.router import RouterServer

    pinned = affinity.apply(slot=0)
    if pinned is not None:
        print(f"pinned to cores {list(pinned)}", flush=True)
    server = RouterServer(
        opts["listen"], opts["shards"].split(";"),
        cluster=opts["cluster"], recover=not opts["no_recover"],
        follower_addresses=(
            opts["followers"].split(";") if opts["followers"] else None
        ),
    )
    print(
        f"router listening on port {server.port} "
        f"({server.n_shards} shards)", flush=True,
    )
    import signal

    def _stop(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _stop)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()


def cmd_follower(args: list[str]) -> None:
    opts, paths = flags.parse(
        args,
        {"listen": "127.0.0.1:0", "aof": None, "upstream": None,
         "cluster": 0, "id": 0},
    )
    if paths:
        flags.fatal("follower takes no positional arguments")
    if not opts["aof"] or not opts["upstream"]:
        flags.fatal("follower requires --aof=<path> and "
                    "--upstream=<host:port>")
    from tigerbeetle_tpu.runtime import affinity
    from tigerbeetle_tpu.runtime.follower import FollowerServer
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    pinned = affinity.apply(slot=opts["id"])
    if pinned is not None:
        print(f"pinned to cores {list(pinned)}", flush=True)

    # Followers replay on the CPU state machine (deterministic host
    # replay, no device needed; r15 pins its state_root to the TPU
    # engine's for the same commit stream) — a device-engine follower
    # is a deliberate scope cut for now.
    server = FollowerServer(
        opts["listen"], aof_path=opts["aof"],
        upstream_address=opts["upstream"], cluster=opts["cluster"],
        state_machine=CpuStateMachine(cfg.PRODUCTION),
        clock_ns=time.monotonic_ns, follower_id=opts["id"],
    )
    print(f"follower listening on port {server.port}", flush=True)
    import signal

    def _stop(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _stop)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()


def cmd_repl(args: list[str]) -> None:
    opts, _ = flags.parse(
        args, {"addresses": None, "cluster": 0, "command": ""}
    )
    from tigerbeetle_tpu.client import Client
    from tigerbeetle_tpu import repl

    client = Client(opts["addresses"].split(",")[0], opts["cluster"])
    try:
        repl.run(client, command=opts["command"] or None)
    finally:
        client.close()


def cmd_benchmark(args: list[str]) -> None:
    opts, _ = flags.parse(
        args,
        {
            "addresses": "", "cluster": 0, "transfers": 100_000,
            "accounts": 10_000, "batch": 8190, "cpu": False,
            "statsd_port": 0,
        },
    )
    from tigerbeetle_tpu.benchmark import run_benchmark

    result = run_benchmark(
        addresses=opts["addresses"] or None, cluster=opts["cluster"],
        n_transfers=opts["transfers"], n_accounts=opts["accounts"],
        batch=opts["batch"], use_cpu=opts["cpu"],
        statsd_port=opts["statsd_port"] or None,
    )
    print(json.dumps(result))


def cmd_trace_demo(args: list[str]) -> None:
    opts, _ = flags.parse(
        args, {"out": "tb_trace_merged.json", "replicas": 2, "batches": 8}
    )
    from tigerbeetle_tpu.testing.cluster import trace_demo

    result = trace_demo(
        opts["out"], n_replicas=opts["replicas"], batches=opts["batches"]
    )
    print(json.dumps(result))
    print(
        f"load {opts['out']} at https://ui.perfetto.dev "
        "(or chrome://tracing)",
        file=sys.stderr,
    )


def cmd_bindings(args: list[str]) -> None:
    opts, _ = flags.parse(args, {"out": "bindings"})
    from tigerbeetle_tpu import bindings

    for path in bindings.generate(opts["out"]):
        print(f"wrote {path}")


def cmd_lint(args: list[str]) -> None:
    from tigerbeetle_tpu import analysis

    raise SystemExit(analysis.main(args))


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(USAGE)
        raise SystemExit(1)
    command, *rest = argv
    if command == "version":
        print(VERSION)
    elif command == "format":
        cmd_format(rest)
    elif command == "start":
        cmd_start(rest)
    elif command == "router":
        cmd_router(rest)
    elif command == "follower":
        cmd_follower(rest)
    elif command == "repl":
        cmd_repl(rest)
    elif command == "benchmark":
        cmd_benchmark(rest)
    elif command == "bindings":
        cmd_bindings(rest)
    elif command == "lint":
        cmd_lint(rest)
    elif command == "trace-demo":
        cmd_trace_demo(rest)
    else:
        print(USAGE)
        flags.fatal(f"unknown command {command!r}")


if __name__ == "__main__":
    main()
