"""Validated environment-variable parsing.

Tuning knobs (TB_DEV_WINDOW, TB_WAVES, ...) are read from the
environment at import or call time; a typo used to surface as a bare
``int()`` traceback or a failed ``assert`` deep inside the module that
consumed it.  These helpers fail fast with an error that names the
variable, the offending value, and the constraint it violated.
"""

from __future__ import annotations

import os


class EnvVarError(ValueError):
    """An environment variable holds an unusable value."""


def _fail(name: str, raw: str, why: str) -> "NoReturn":  # noqa: F821
    raise EnvVarError(f"{name}={raw!r} invalid: {why}")


def env_int(
    name: str,
    default: int,
    *,
    minimum: int | None = None,
    maximum: int | None = None,
) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        _fail(name, raw, "expected an integer")
    if minimum is not None and value < minimum:
        _fail(name, raw, f"must be >= {minimum}")
    if maximum is not None and value > maximum:
        _fail(name, raw, f"must be <= {maximum}")
    return value


def env_float(
    name: str,
    default: float,
    *,
    minimum: float | None = None,
) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        _fail(name, raw, "expected a number")
    if minimum is not None and value < minimum:
        _fail(name, raw, f"must be >= {minimum}")
    return value


def group_commit_max_us() -> int:
    """TB_GROUP_COMMIT_MAX_US: longest a replicated ack may wait for
    its covering WAL fdatasync, in microseconds.  0 disables group
    commit (one fsync per prepare, the pre-r10 behavior)."""
    return env_int(
        "TB_GROUP_COMMIT_MAX_US", 2000, minimum=0, maximum=10_000_000
    )


def ckpt_async() -> int:
    """TB_CKPT_ASYNC: 1 (default) runs the checkpoint's disk half
    (grid writeback join, fdatasync, superblock flip) on a background
    worker; 0 keeps the whole checkpoint on the commit path."""
    return env_int("TB_CKPT_ASYNC", 1, minimum=0, maximum=1)


def env_choice(name: str, default: str, choices: tuple[str, ...]) -> str:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if raw not in choices:
        _fail(name, raw, "expected one of " + "/".join(choices))
    return raw


def metrics_enabled() -> int:
    """TB_METRICS: 1 (default) records latency histograms in the obs
    registry; 0 skips the clock reads (counters stay live — logic and
    bench accounting depend on them)."""
    return env_int("TB_METRICS", 1, minimum=0, maximum=1)


def trace_backend() -> str:
    """TB_TRACE: span-tracer backend (utils/tracer.py) for processes
    that don't pass an explicit --trace path.  `json` writes a Chrome
    -trace file per process (TB_TRACE_PATH or tb_trace_r<i>.json)."""
    return env_choice("TB_TRACE", "none", ("none", "json"))
