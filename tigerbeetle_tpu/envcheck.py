"""Validated environment-variable parsing.

Tuning knobs (TB_DEV_WINDOW, TB_WAVES, ...) are read from the
environment at import or call time; a typo used to surface as a bare
``int()`` traceback or a failed ``assert`` deep inside the module that
consumed it.  These helpers fail fast with an error that names the
variable, the offending value, and the constraint it violated.
"""

from __future__ import annotations

import os


class EnvVarError(ValueError):
    """An environment variable holds an unusable value."""


def _fail(name: str, raw: str, why: str) -> "NoReturn":  # noqa: F821
    raise EnvVarError(f"{name}={raw!r} invalid: {why}")


def env_int(
    name: str,
    default: int,
    *,
    minimum: int | None = None,
    maximum: int | None = None,
) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        _fail(name, raw, "expected an integer")
    if minimum is not None and value < minimum:
        _fail(name, raw, f"must be >= {minimum}")
    if maximum is not None and value > maximum:
        _fail(name, raw, f"must be <= {maximum}")
    return value


def env_float(
    name: str,
    default: float,
    *,
    minimum: float | None = None,
) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        _fail(name, raw, "expected a number")
    if minimum is not None and value < minimum:
        _fail(name, raw, f"must be >= {minimum}")
    return value


def env_str(name: str, default: str | None = None) -> str | None:
    """String-valued knob (paths, engine names).  Empty counts as
    unset — consistent with env_int/env_float."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw


def env_is_set(name: str) -> bool:
    """True when the variable is present and non-empty (feature
    toggles whose VALUE is read elsewhere or irrelevant)."""
    return bool(os.environ.get(name))


def group_commit_max_us() -> int:
    """TB_GROUP_COMMIT_MAX_US: longest a replicated ack may wait for
    its covering WAL fdatasync, in microseconds.  0 disables group
    commit (one fsync per prepare, the pre-r10 behavior)."""
    return env_int(
        "TB_GROUP_COMMIT_MAX_US", 2000, minimum=0, maximum=10_000_000
    )


def ckpt_async() -> int:
    """TB_CKPT_ASYNC: 1 (default) runs the checkpoint's disk half
    (grid writeback join, fdatasync, superblock flip) on a background
    worker; 0 keeps the whole checkpoint on the commit path."""
    return env_int("TB_CKPT_ASYNC", 1, minimum=0, maximum=1)


def env_choice(name: str, default: str, choices: tuple[str, ...]) -> str:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if raw not in choices:
        _fail(name, raw, "expected one of " + "/".join(choices))
    return raw


def native_sanitize() -> str:
    """TB_NATIVE_SANITIZE: native-library build flavor.  "" (default)
    loads the plain optimized libraries; "asan" loads the
    address+undefined-sanitized builds from native/asan/ (built by
    `make -C native asan`) — the slow-tier replay test drives the
    fastpath fixture differential and torn-frame fuzz through them
    with the asan runtime LD_PRELOADed.  The flavor is recorded in the
    build-failure forensics (runtime/native.py), so a failing
    sanitizer build is never mistaken for a failing release build."""
    return env_choice("TB_NATIVE_SANITIZE", "", ("", "asan"))


def fastpath_decode() -> int:
    """TB_FASTPATH_DECODE: 1 (default) drains the server bus through
    the columnar ingest fast path — one arena drain + one batch
    checksum-verify pass per poll (native tb_fp_verify_frames, or the
    vectorized Python fallback), headers gathered in one vectorized
    pass, replies coalesced per drain.  0 forces the legacy per-message
    decode path end to end, for differential runs (replies must stay
    bit-identical either way)."""
    return env_int("TB_FASTPATH_DECODE", 1, minimum=0, maximum=1)


def native_pipeline() -> int:
    """TB_NATIVE_PIPELINE: 1 (default) runs the per-prepare hot loop
    through native/tb_pipeline.cpp — prepare/prepare_ok header
    construction + checksum stamping, journal append framing (sector
    padding, redundant-ring sector build), the primary's in-flight
    slot table, and the group-commit gate — falling back to Python
    when libtb_fastpath is unavailable.  0 forces the pure-Python
    per-prepare path for differential runs: reply frames, WAL bytes,
    and commit decisions must be bit-identical either way (the r14
    TB_FASTPATH_DECODE contract one layer higher).  Setting 1
    EXPLICITLY makes a stale/missing library a hard error instead of
    a silent fallback."""
    return env_int("TB_NATIVE_PIPELINE", 1, minimum=0, maximum=1)


def native_drain() -> int:
    """TB_NATIVE_DRAIN: 1 (default) runs a whole poll drain's
    prepare→ack→commit-decision work through ONE native call per
    batch seam (native/tb_pipeline.cpp tb_pl_build_prepares /
    tb_pl_accept_prepares / tb_pl_on_acks / tb_pl_commit_ready_run,
    ABI 3) — Python demoted to a per-BATCH orchestrator.  Requires
    the native pipeline (TB_NATIVE_PIPELINE=1 and a current .so);
    falls back to the per-item loop otherwise.  0 pins the per-item
    Python loop over the SAME batch seams for differential runs:
    consensus and reply frames must be bit-identical either way (the
    r20 contract extended from per-call to per-drain).  Setting 1
    EXPLICITLY makes a stale library a hard error naming
    `make -C native` instead of a silent fallback."""
    return env_int("TB_NATIVE_DRAIN", 1, minimum=0, maximum=1)


def hash_reuse() -> int:
    """TB_HASH_REUSE: 1 (default) makes the commit path hash each
    prepare body at most ONCE per replica role — the ingress verify
    pass already proved SHA-256(body), so the build seams
    (tb_pl_build_prepares and the Python mirror in _primary_prepare /
    finalize_header) consume that digest (the drain-scoped C digest
    table, falling back to the verified request header's own
    checksum_body field) instead of rehashing.  0 rehashes everywhere
    for differential runs: every consensus/reply frame must be
    bit-identical either way, only hash.bytes_hashed may differ."""
    return env_int("TB_HASH_REUSE", 1, minimum=0, maximum=1)


def hash_threads() -> int:
    """TB_HASH_THREADS: native hash-pool worker lanes that fan a
    drain's independent SHA-256 jobs (frame verifies, body digests,
    reply finalizes) out of the drain thread, inside the existing
    GIL-released crossings.  0 (default — right for this 1-core
    container) runs every hash inline on the calling thread; the named
    constraint is threads <= 16 (lanes beyond the physical cores of
    any target box only add contention on the submit path)."""
    value = env_int("TB_HASH_THREADS", 0, minimum=0)
    if value > 16:
        _fail(
            "TB_HASH_THREADS", str(value),
            "must be <= 16 — hash lanes beyond any target box's "
            "cores only add submit-path contention",
        )
    return value


def cpu_affinity() -> str:
    """TB_CPU_AFFINITY: replica/router/follower core pinning for the
    multi-process spawn paths (bench subprocess spawns and the
    `tigerbeetle` server/router/follower CLIs):

    - "none" (default): inherit the parent's affinity mask unchanged.
    - "auto": pin process slot i to core (i mod cpu_count) — spreads a
      cluster's replicas across cores so their Python VSR loops stop
      serializing on a shared core.
    - "0,1,2": explicit core list; slot i takes the (i mod len)'th
      core of the list.

    Validated here so a typo fails at spawn, not as a bare OSError
    inside sched_setaffinity; runtime/affinity.py applies it."""
    raw = env_str("TB_CPU_AFFINITY", "none")
    if raw in ("none", "auto"):
        return raw
    parts = raw.split(",")
    try:
        cores = [int(p) for p in parts]
    except ValueError:
        _fail("TB_CPU_AFFINITY", raw,
              'expected "none", "auto", or a comma-separated core '
              'list like "0,1,2"')
    if not cores or any(c < 0 for c in cores):
        _fail("TB_CPU_AFFINITY", raw, "core ids must be >= 0")
    return raw


def drain_batch_max() -> int:
    """TB_DRAIN_BATCH: cap on events pulled per columnar drain call —
    bounds the arena scan and the latency of one decode pass under a
    flood (excess events stay queued in the native bus and drain on
    the next zero-timeout round).  Must cover at least one pipeline's
    worth of messages or the drain loop degenerates to per-message
    rounds."""
    value = env_int("TB_DRAIN_BATCH", 4096, maximum=1 << 16)
    if value < 16:
        _fail(
            "TB_DRAIN_BATCH", str(value),
            "must be >= 16 — smaller drain batches degenerate the "
            "columnar decode into per-message rounds",
        )
    return value


def metrics_enabled() -> int:
    """TB_METRICS: 1 (default) records latency histograms in the obs
    registry; 0 skips the clock reads (counters stay live — logic and
    bench accounting depend on them)."""
    return env_int("TB_METRICS", 1, minimum=0, maximum=1)


def trace_backend() -> str:
    """TB_TRACE: span-tracer backend (utils/tracer.py) for processes
    that don't pass an explicit --trace path.  `json` writes a Chrome
    -trace file per process (TB_TRACE_PATH or tb_trace_r<i>.json)."""
    return env_choice("TB_TRACE", "none", ("none", "json"))


def trace_exemplars() -> int:
    """TB_TRACE_EXEMPLARS: tail-exemplar ring size (obs/anatomy.py) —
    how many slow-request stage timelines each replica retains for the
    `stats` scrape.  Must be > 0 (the recorder is disabled via
    TB_METRICS=0, not by an empty ring)."""
    return env_int("TB_TRACE_EXEMPLARS", 32, minimum=1, maximum=1 << 16)


def flight_ring() -> int:
    """TB_FLIGHT_RING: flight-recorder ring capacity (obs/flight.py) —
    recent trace events kept in memory per replica for the postmortem
    dump.  Must be > 0."""
    return env_int("TB_FLIGHT_RING", 4096, minimum=1, maximum=1 << 22)


def admit_queue(pipeline_depth: int) -> int:
    """TB_ADMIT_QUEUE: bound on the primary's client-request queue
    (runtime/server.py admission control).  Requests beyond it are
    shed with a typed Command.client_busy instead of growing the tail
    unboundedly.  Must be >= the prepare pipeline depth — a smaller
    bound would shed requests the pipeline could already hold."""
    value = env_int("TB_ADMIT_QUEUE", 1024, minimum=1)
    if value < pipeline_depth:
        _fail(
            "TB_ADMIT_QUEUE", str(value),
            f"must be >= pipeline depth ({pipeline_depth}) — a smaller "
            "queue sheds requests the prepare pipeline could hold",
        )
    return value


def open_loop_secs() -> float:
    """BENCH_OPEN_SECS: seconds per open-loop bench phase."""
    return env_float("BENCH_OPEN_SECS", 4.0, minimum=0.1)


def open_loop_batch() -> int:
    """BENCH_OPEN_BATCH: transfers per open-loop request (small
    batches make queueing dynamics visible; the closed-loop bench's
    8190-event batches would hide them)."""
    return env_int("BENCH_OPEN_BATCH", 256, minimum=1, maximum=8190)


def open_loop_hot_pct() -> float:
    """BENCH_OPEN_HOT_PCT: percentage of open-loop transfers that hit
    one of the few hot (celebrity) accounts — the multi-tenant
    contention mix."""
    raw = env_float("BENCH_OPEN_HOT_PCT", 20.0, minimum=0.0)
    if raw > 100.0:
        _fail("BENCH_OPEN_HOT_PCT", str(raw), "must be <= 100")
    return raw


def open_loop_burst() -> float:
    """BENCH_OPEN_BURST: burstiness multiplier — arrivals are Poisson
    at the phase rate, with periodic bursts at `burst`x the rate.
    1.0 = pure Poisson."""
    return env_float("BENCH_OPEN_BURST", 4.0, minimum=1.0)


def open_loop_read_pct() -> float:
    """BENCH_OPEN_READ_PCT: read requests (lookup_accounts /
    get_account_transfers filter queries) added ON TOP of the transfer
    stream, as a percentage of it — the read-heavy mix.  Additive so
    the write arrival rate (and comparability with earlier open-loop
    baselines) is unchanged."""
    raw = env_float("BENCH_OPEN_READ_PCT", 20.0, minimum=0.0)
    if raw > 100.0:
        _fail("BENCH_OPEN_READ_PCT", str(raw), "must be <= 100")
    return raw


# ----------------------------------------------------------------------
# Optimistic wave execution (state_machine/waves.py; round 18).


def waves_speculate() -> str:
    """TB_WAVES_SPECULATE: speculative (optimistic) execution mode for
    the device wave dispatcher (tpu._try_submit_device_waves):

    - "auto" (default): off-kernel window batches execute the WHOLE
      batch as one speculative device step, validate read-write
      conflicts on device, and replay only the conflicted residue
      through the wave plan — unless the host already knows too much
      of the batch must replay (the TB_WAVES_SPEC_RESIDUE_CAP gate).
    - "0": off — every admitted batch plans waves up front (the r8
      pessimistic path, the differential control arm).
    - "1": on — like auto, with the residue-cap gate still applied.
    - "force": forced-optimistic — route EVERY window batch (including
      shapes the semantic kernels could serve) through speculation and
      attempt it regardless of the residue gate.  Differential-test /
      bench routing: maximizes speculative-path coverage.
    """
    return env_choice(
        "TB_WAVES_SPECULATE", "auto", ("auto", "0", "1", "force")
    )


def spec_residue_cap() -> float:
    """TB_WAVES_SPEC_RESIDUE_CAP: fraction of a batch that may already
    be KNOWN host-side to need residue replay (linked-chain members,
    history-account events, serialized post/voids) before speculation
    is skipped and the batch plans waves up front.  A speculative miss
    still pays the full speculative step before replaying, so a batch
    that is mostly known-residue would speculate at a guaranteed loss.

    Named constraint: must be <= 1 — the cap is a fraction of the
    batch; a value above 1 could never bind and would silently
    misrepresent the gate the operator configured."""
    value = env_float("TB_WAVES_SPEC_RESIDUE_CAP", 0.25, minimum=0.0)
    if value > 1.0:
        _fail(
            "TB_WAVES_SPEC_RESIDUE_CAP", str(value),
            "must be <= 1 — the cap is a fraction of the batch and a "
            "larger value can never bind",
        )
    return value


# ----------------------------------------------------------------------
# Incremental state commitments (state_machine/commitment.py).


def state_commit() -> int:
    """TB_STATE_COMMIT: 1 (default) maintains the incremental state
    commitment — a per-row-hash digest of the account table updated
    from just the rows each step touched, kept bit-identically on the
    host mirror and the device engine.  Enables 16-byte scrub /
    re-promotion compares, checkpoint state roots, and the
    `state_root` query.  0 disables the digest machinery entirely
    (the A/B arm for grading its overhead); roots are then computed
    from scratch on demand and scrub falls back to the legacy
    full-digest compare."""
    return env_int("TB_STATE_COMMIT", 1, minimum=0, maximum=1)


def scrub_fallback_every() -> int:
    """TB_DEV_SCRUB_FALLBACK: run the full-fetch divergence-
    localization scrub every Nth healthy-mode scrub even when the
    cheap 16-byte digest compare matched (a belt-and-braces deep
    scrub against digest-collision paranoia).  0 (default) = the full
    fetch runs only on a digest mismatch."""
    return env_int("TB_DEV_SCRUB_FALLBACK", 0, minimum=0,
                   maximum=1 << 20)


# ----------------------------------------------------------------------
# Hot/cold account tiering (state_machine/hot_tier.py).


def hot_capacity() -> int:
    """TB_HOT_CAPACITY: device-resident hot-set rows for the tiered
    account table.  0 (default) keeps the whole logical table
    HBM-resident — bit-for-bit the untiered behavior.  A positive
    value below the logical capacity caps the device table at that
    many rows: the batch planner prefetches each batch's cold rows
    from the host mirror (the cold tier) before the device step, LRU
    admission/eviction rides the write-behind lane, and the 16-byte
    state root keeps covering the whole logical table as
    fold(hot_partial, cold_partial).  Values >= the logical capacity
    degenerate to all-resident.  Read at engine CONSTRUCTION time
    (per-arm env changes in one bench process work); forcing tiny
    values is the differential-fuzz lever."""
    return env_int("TB_HOT_CAPACITY", 0, minimum=0, maximum=1 << 31)


# ----------------------------------------------------------------------
# Root-attested follower serving (runtime/follower.py; round 19).


def root_ring() -> int:
    """TB_ROOT_RING: how many recent commits' state roots a replica
    retains for the `state_root` at-op query (the follower attestation
    primitive; 16 bytes + dict entry per op).  0 disables — at-op
    queries then answer the current root and followers can only attest
    when exactly caught up."""
    return env_int("TB_ROOT_RING", 4096, minimum=0, maximum=1 << 20)


def read_policy() -> str:
    """TB_READ_POLICY: where the router steers read operations
    (lookup/filter queries):

    - "primary" pins the legacy path end to end — every read rides
      consensus exactly as before followers existed.
    - "follower" prefers a configured follower whenever the read is
      follower-servable (single-shard), falling back to the primary on
      refusal/timeout.
    - "auto" (default): like "follower" when followers are configured,
      "primary" otherwise.
    """
    return env_choice(
        "TB_READ_POLICY", "auto", ("auto", "primary", "follower")
    )


def read_staleness_ops() -> int:
    """TB_READ_STALENESS_OPS: bounded-staleness policy — the most ops
    a serving follower may lag the primary's attested commit point
    before it refuses reads with a typed `lagging` busy (clients /
    the router then redirect to the primary).  0 = the follower only
    serves when fully caught up to the last attestation."""
    return env_int("TB_READ_STALENESS_OPS", 512, minimum=0,
                   maximum=1 << 30)


def follower_attest_ms() -> int:
    """TB_FOLLOWER_ATTEST_MS: cadence of the follower's attestation
    query (state_root at-op against the upstream replica).  Lower =
    fresher lag estimate + tighter divergence detection window, more
    query traffic."""
    return env_int("TB_FOLLOWER_ATTEST_MS", 100, minimum=1,
                   maximum=60_000)


def follower_attest_max_ms() -> int:
    """TB_FOLLOWER_ATTEST_MAX_MS: maximum age of the last successful
    attestation before a follower refuses reads as `lagging`.  The
    lag estimate (last_primary_op) is a high-water mark fed by
    attestation replies — under a FULL partition (upstream AND log
    unreachable) nothing moves it, so without an age bound a follower
    that attested once would serve frozen state forever while
    claiming lag 0.  Must exceed the attestation cadence
    (TB_FOLLOWER_ATTEST_MS) with room for a few lost replies; the
    default (2000 ms) is 20 cadences of the default 100 ms."""
    return env_int("TB_FOLLOWER_ATTEST_MAX_MS", 2000, minimum=1,
                   maximum=24 * 3600 * 1000)


def follower_ring() -> int:
    """TB_FOLLOWER_ROOT_RING: per-op state roots the FOLLOWER retains
    while replaying, for verifying primary attestations that answer a
    few ops behind its replay head.  Named constraint: must be >= 16 —
    a ring smaller than one attestation round trip's worth of commits
    discards the root every verification needs and the follower can
    never attest under write load."""
    return env_int("TB_FOLLOWER_ROOT_RING", 4096, minimum=16,
                   maximum=1 << 20)


def read_scale_secs() -> float:
    """BENCH_READ_SCALE_SECS: seconds per read-scale bench arm (one
    arm per follower count)."""
    return env_float("BENCH_READ_SCALE_SECS", 3.0, minimum=0.1)


def read_fallback_ms() -> int:
    """TB_READ_FALLBACK_MS: how long the router waits for a follower's
    read reply before re-driving the read through the primary path.
    Bounds the worst case a dead follower can add to one read; the
    per-follower backoff (qos.backoff_delay) keeps later reads from
    re-paying it every time."""
    return env_int("TB_READ_FALLBACK_MS", 250, minimum=10,
                   maximum=60_000)


# ----------------------------------------------------------------------
# Multi-tenant QoS (qos.py; round 16).  The tenant key is the LEDGER.


def tenant_qos() -> int:
    """TB_TENANT_QOS: 1 (default) keys admission, scheduling, and
    shedding by tenant (ledger) — per-tenant token buckets, bounded
    per-tenant queues, weighted-fair drain, typed busy payloads.
    0 pins today's single-queue path exactly (bit-identical
    differential runs)."""
    return env_int("TB_TENANT_QOS", 1, minimum=0, maximum=1)


def tenant_rate() -> float:
    """TB_TENANT_RATE: per-tenant admission rate, requests/second
    (token bucket, burst = one second's worth).  0 (default) disables
    rate limiting — QoS-on under non-overload stays bit-identical to
    QoS-off; the queue bounds still apply."""
    return env_float("TB_TENANT_RATE", 0.0, minimum=0.0)


def tenant_rate_bytes() -> float:
    """TB_TENANT_RATE_BYTES: per-tenant admission rate in BODY BYTES
    per second (a second token bucket next to the request-count one).
    Mixed-size batches cheat a request-count bucket — one tenant's
    8k-event batches cost the same token as another's single event —
    so overload protection for byte-bound resources (decode, WAL
    bandwidth, follower replay) charges by size.  0 (default)
    disables; both buckets must admit when both are configured."""
    return env_float("TB_TENANT_RATE_BYTES", 0.0, minimum=0.0)


def tenant_queue(admit_queue: int) -> int:
    """TB_TENANT_QUEUE: bound on one tenant's queued requests.  0
    (default) = the global TB_ADMIT_QUEUE bound (no extra per-tenant
    bound).  Must not exceed the global bound — a per-tenant bound
    above it could never bind and would silently misrepresent the
    isolation the operator configured."""
    value = env_int("TB_TENANT_QUEUE", 0, minimum=0)
    if value > admit_queue:
        _fail(
            "TB_TENANT_QUEUE", str(value),
            f"must be <= TB_ADMIT_QUEUE ({admit_queue}) — a per-tenant "
            "bound above the global queue bound can never bind",
        )
    return value if value else admit_queue


def tenant_weights() -> dict:
    """TB_TENANT_WEIGHTS: weighted-fair drain shares, e.g. "1:4,7:2"
    (ledger:weight; unlisted tenants weigh 1)."""
    from tigerbeetle_tpu import qos

    raw = env_str("TB_TENANT_WEIGHTS", "")
    try:
        return qos.parse_weights(raw)
    except ValueError as exc:
        _fail("TB_TENANT_WEIGHTS", raw, str(exc))


def qos_suite_secs() -> float:
    """BENCH_QOS_SECS: seconds per adversarial-QoS bench arm phase
    (bench.py --qos-suite: noisy-neighbor / cross-shard-heavy /
    pathological-contention)."""
    return env_float("BENCH_QOS_SECS", 3.0, minimum=0.1)


def busy_backoff_ms() -> float:
    """TB_BUSY_BACKOFF_MS: client-side base backoff after a typed
    client_busy — capped exponential (x2 per consecutive busy, 16x
    cap) plus deterministic seeded jitter, so shed storms don't
    self-amplify into retransmit storms.  0 disables (the legacy
    immediate-retransmit-cadence behavior)."""
    return env_float("TB_BUSY_BACKOFF_MS", 20.0, minimum=0.0)


# ----------------------------------------------------------------------
# Sharded multi-cluster (runtime/router.py).


def shards() -> int:
    """TB_SHARDS: number of account-range shards (independent
    consensus groups) behind the router.  1 = unsharded."""
    return env_int("TB_SHARDS", 1, minimum=1, maximum=64)


def router_queue() -> int:
    """TB_ROUTER_QUEUE: bound on concurrently open client requests in
    the router; fresh requests beyond it are shed with a typed
    Command.client_busy (the same admission contract the replicas
    use)."""
    return env_int("TB_ROUTER_QUEUE", 256, minimum=1)


def coord_retry_ms() -> int:
    """TB_COORD_RETRY_MS: coordinator sub-operation retry cadence —
    how long the router waits for a shard's reply to a 2PC leg before
    re-issuing it (idempotent: derived ids dedupe re-drives)."""
    return env_int("TB_COORD_RETRY_MS", 1000, minimum=10,
                   maximum=60_000)


def view_change_budget_s() -> float:
    """Worst-case time for one shard to elect a new primary: the
    backup's view-change timeout in wall-clock terms (vsr/multi.py
    VIEW_CHANGE_TICKS at the shared TICK_NS cadence)."""
    from tigerbeetle_tpu.constants import TICK_NS
    from tigerbeetle_tpu.vsr.multi import VIEW_CHANGE_TICKS

    return VIEW_CHANGE_TICKS * TICK_NS / 1e9


def coord_timeout_s() -> int:
    """TB_COORD_TIMEOUT_S: cross-shard hold timeout (seconds) — the
    pending-transfer timeout stamped on both 2PC holds, bounding how
    long an orphaned hold (coordinator lost before its decision) can
    reserve balances before the shard's own expiry pulse voids it.

    Named constraint: must EXCEED a shard's view-change budget.  The
    commit decision is durable the moment the debit-side hold posts;
    the credit-side post may then have to wait out a full primary
    failover on the credit shard, and a hold that can expire inside
    that window would turn a decided commit into a half-applied
    transfer (the compensation path — flagged, never silent — exists
    for exactly the case this constraint rules out)."""
    value = env_int("TB_COORD_TIMEOUT_S", 30, minimum=1,
                    maximum=24 * 3600)
    budget = view_change_budget_s()
    if value <= budget:
        _fail(
            "TB_COORD_TIMEOUT_S", str(value),
            f"must exceed the view-change budget ({budget:g}s) — a "
            "decided cross-shard commit must survive one primary "
            "failover on the credit shard without its hold expiring",
        )
    return value
