"""Sustained-load cluster liveness (VERDICT r4 #6).

The r4 graded bench died in exactly this regime: a 3-replica TCP
cluster under continuous client load crossing checkpoint boundaries,
where one slow tail blew a request timeout.  This test pins the
liveness properties that regime depends on:

- every request completes within a tail budget,
- NO view change fires (sustained load must not starve heartbeats into
  a spurious election — reference: src/vsr/replica_test.zig scenario
  style),
- every replica crosses >= 3 checkpoint boundaries and converges.

Real TCP sockets and the real ReplicaServer event loop; TEST_MIN
config (journal_slot_count=32 -> checkpoint every 24 ops,
reference: src/constants.zig:55-81 arithmetic) so three checkpoint
intervals fit a suite-friendly runtime.  The replicated bench config
(bench.py run_replicated) drives the same server/client machinery as
subprocesses at production scale.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.client import Client
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine

CLUSTER = 77
REQUEST_TAIL_BUDGET_S = 10.0
N_SESSIONS = 3


@pytest.fixture
def tcp_cluster(tmp_path):
    from tigerbeetle_tpu.runtime.server import ReplicaServer, format_data_file

    servers = []
    paths = [str(tmp_path / f"r{i}.tigerbeetle") for i in range(3)]
    addresses = ["127.0.0.1:0"] * 3
    for i in range(3):
        format_data_file(paths[i], cluster=CLUSTER, replica_index=i,
                         replica_count=3, config=cfg.TEST_MIN)
        s = ReplicaServer(
            paths[i], cluster=CLUSTER, addresses=list(addresses),
            replica_index=i,
            state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
            config=cfg.TEST_MIN,
        )
        addresses[i] = f"127.0.0.1:{s.port}"
        servers.append(s)
    for s in servers:
        s.bus.addresses = list(addresses)
    stop = [False]

    def loop():
        while not stop[0]:
            for s in servers:
                s.poll_once(timeout_ms=1)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    try:
        yield servers, addresses
    finally:
        stop[0] = True
        thread.join(timeout=5)
        for s in servers:
            s.close()


def test_sustained_load_across_checkpoints(tcp_cluster):
    servers, addresses = tcp_cluster
    interval = cfg.TEST_MIN.vsr_checkpoint_interval
    batch = cfg.TEST_MIN.batch_max_create_transfers
    # Enough create ops for >= 3 checkpoint boundaries on top of setup,
    # split across concurrent sessions (each session keeps one request
    # in flight -> the commit pipeline holds N_SESSIONS prepares).
    n_ops = 3 * interval + 12
    per_session = (n_ops + N_SESSIONS - 1) // N_SESSIONS

    addr = ",".join(addresses)
    setup = Client(addr, CLUSTER, client_id=900, timeout_ms=30_000)
    assert setup.create_accounts(
        [{"id": 1, "ledger": 1, "code": 1}, {"id": 2, "ledger": 1, "code": 1}]
    ) == []
    setup.close()

    worst = [0.0] * N_SESSIONS
    errors: list[str] = []

    def drive(s: int) -> None:
        try:
            c = Client(addr, CLUSTER, client_id=901 + s, timeout_ms=30_000)
            next_id = 1_000_000 * (s + 1)
            for _ in range(per_session):
                tr = [
                    {"id": next_id + k, "debit_account_id": 1,
                     "credit_account_id": 2, "amount": 1, "ledger": 1,
                     "code": 1}
                    for k in range(batch)
                ]
                next_id += batch
                t0 = time.perf_counter()
                assert c.create_transfers(tr) == []
                worst[s] = max(worst[s], time.perf_counter() - t0)
            c.close()
        except Exception as exc:  # noqa: BLE001
            errors.append(f"session {s}: {exc!r}")

    threads = [
        threading.Thread(target=drive, args=(s,)) for s in range(N_SESSIONS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    # A hung session (the exact r4 regime) must fail HERE, not slip
    # past the tail assertion with its partial worst-case.
    assert not any(t.is_alive() for t in threads), "client session hung"
    assert not errors, errors

    # Tail budget: the r4 zero was a request tail blowing its timeout.
    assert max(worst) < REQUEST_TAIL_BUDGET_S, f"request tails {worst}"

    # No spurious view change under sustained load.
    for s in servers:
        assert s.replica.view == 0, f"replica {s.replica.replica} view changed"
        assert s.replica.status == "normal"

    # Every replica crossed >= 3 checkpoint boundaries.
    deadline = time.time() + 30
    while time.time() < deadline:
        if all(s.replica.checkpoint_op >= 3 * interval for s in servers):
            break
        time.sleep(0.1)
    for s in servers:
        assert s.replica.checkpoint_op >= 3 * interval, (
            f"replica {s.replica.replica} checkpoint_op "
            f"{s.replica.checkpoint_op} < {3 * interval}"
        )

    # Convergence: every replica committed every session's last
    # transfer (backups apply asynchronously — poll briefly).
    total = per_session * N_SESSIONS * batch
    last_ids = [
        1_000_000 * (s + 1) + per_session * batch - 1
        for s in range(N_SESSIONS)
    ]
    deadline = time.time() + 30
    while time.time() < deadline:
        if all(
            s.replica.sm.transfer_timestamp(i) is not None
            for s in servers
            for i in last_ids
        ):
            break
        time.sleep(0.1)
    for s in servers:
        for i in last_ids:
            assert s.replica.sm.transfer_timestamp(i) is not None
    # Wire-level check through a fresh client.
    c = Client(addr, CLUSTER, client_id=990, timeout_ms=30_000)
    rows = c.lookup_accounts([1])
    assert types.u128_get(rows[0], "debits_posted") == total
    c.close()
