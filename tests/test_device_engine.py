"""Device-authoritative engine: differential + hazard + fallback tests.

The engine (state_machine/device_engine.py) computes create_transfers
result codes ON the device via the semantic kernels and materializes
replies from failure-sparse summaries.  These tests pin it to the CPU
oracle across the bench workload shapes and adversarial cases:
cross-batch hazards, fallback recovery, pulse interaction, and the
checkpoint checksum tripwire.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.types import (
    AccountFlags,
    CreateTransferResult,
    Operation,
    TransferFlags,
)

AF = AccountFlags
TF = TransferFlags
CTR = CreateTransferResult


def mk_pair():
    sm_d = TpuStateMachine(engine="device", account_capacity=1 << 12)
    sm_c = CpuStateMachine()
    return hz.SingleNodeHarness(sm_d), hz.SingleNodeHarness(sm_c)


def replay_both(h_d, h_c, ops):
    futs = [h_d.submit_async(op, body) for op, body in ops]
    replies_d = [f.result() for f in futs]
    replies_c = [h_c.submit(op, body) for op, body in ops]
    for i, (a, b) in enumerate(zip(replies_d, replies_c)):
        assert a == b, f"reply {i} differs: {ops[i][0]!r}"
    return replies_d


def accounts(ids, flags=0, ledger=1):
    return hz.pack([hz.account(i, flags=flags, ledger=ledger) for i in ids])


def transfers(rows):
    return hz.pack([hz.transfer(**r) for r in rows])


def test_bench_config_differential():
    """Scaled-down versions of every bench config, multi-fetch."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ["BENCH_BATCH"] = "400"
    import importlib

    import bench

    importlib.reload(bench)
    for name, gen in bench.CONFIGS.items():
        setup, timed, sizing = gen(4000)
        ops = setup + timed
        sm_d = TpuStateMachine(
            account_capacity=sizing[0], transfer_capacity=sizing[1],
            engine="device",
        )
        h_d = hz.SingleNodeHarness(sm_d)
        futs = [h_d.submit_async(op, body) for op, body in ops]
        replies_d = [f.result() for f in futs]
        sm_c = CpuStateMachine()
        h_c = hz.SingleNodeHarness(sm_c)
        for i, (op, body) in enumerate(ops):
            assert replies_d[i] == h_c.submit(op, body), f"{name} op {i}"
        acct_ids = bench.config_account_ids(name)
        tids = np.arange(bench.TID0, bench.TID0 + 2000).astype(np.uint64)
        assert bench.state_digest(h_d, acct_ids, tids) == bench.state_digest(
            h_c, acct_ids, tids
        ), name
        assert sm_d._dev.stat_semantic_events > 0, name
    os.environ.pop("BENCH_BATCH", None)
    importlib.reload(bench)


def test_cross_batch_pending_reference_hazard():
    """A post in batch k+1 referencing a pending created in batch k
    (still in flight) must drain and resolve exactly."""
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2]))]
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=10, debit_account_id=1, credit_account_id=2,
                         amount=50, flags=int(TF.pending)),
                ]
            ),
        )
    )
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=11, pending_id=10,
                         flags=int(TF.post_pending_transfer)),
                    dict(id=12, pending_id=10,
                         flags=int(TF.post_pending_transfer)),
                ]
            ),
        )
    )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2])))
    replay_both(h_d, h_c, ops)


def test_cross_batch_duplicate_id_hazard():
    """A duplicate id against an in-flight batch must not be treated
    as fresh."""
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2]))]
    t = dict(id=10, debit_account_id=1, credit_account_id=2, amount=5)
    ops.append((Operation.create_transfers, transfers([t])))
    ops.append((Operation.create_transfers, transfers([t])))  # exact dup
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [dict(id=10, debit_account_id=1, credit_account_id=2,
                      amount=6)]
            ),
        )
    )
    replay_both(h_d, h_c, ops)


def test_fallback_overflow_orderfree():
    """Amounts near 2^128 trip the admission check -> exact host
    fallback, still bit-identical to the oracle."""
    big = (1 << 127) + 5
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2, 3]))]
    # Two debits of ~2^127 on the same account: the second overflows
    # debits_posted, so total-sum admission must refuse the batch.
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=10, debit_account_id=1, credit_account_id=2,
                         amount=big),
                    dict(id=11, debit_account_id=1, credit_account_id=3,
                         amount=big),
                ]
            ),
        )
    )
    # Later clean batch must still be exact after recovery.
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [dict(id=12, debit_account_id=1, credit_account_id=3,
                      amount=7)]
            ),
        )
    )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3])))
    replay_both(h_d, h_c, ops)
    assert h_d.sm._dev.stat_fallback_batches >= 1


def test_fallback_recovery_redispatches_inflight(monkeypatch):
    """Batches dispatched AFTER one that falls back are re-executed
    against the corrected table."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 64)
    h_d, h_c = mk_pair()
    big = (1 << 127) + 5
    ops = [(Operation.create_accounts, accounts([1, 2, 3]))]
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=10, debit_account_id=1, credit_account_id=2,
                         amount=big),
                    dict(id=11, debit_account_id=1, credit_account_id=3,
                         amount=big),
                ]
            ),
        )
    )
    for k in range(4):
        ops.append(
            (
                Operation.create_transfers,
                transfers(
                    [dict(id=20 + k, debit_account_id=1,
                          credit_account_id=3, amount=3 + k)]
                ),
            )
        )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3])))
    replay_both(h_d, h_c, ops)
    assert h_d.sm._dev.stat_fallback_batches >= 1


def test_fallback_recovery_reentrant_drain(monkeypatch):
    """Recovery's host fallback re-enters drain() via table reads
    (JAX host path, no native fastpath); the recovering window must
    not be visible as launched to the nested rotate, or its futures
    double-resolve and mirror bookkeeping double-applies — the
    code-review repro for the _recovering detach."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 64)
    big = (1 << 127) + 5
    h_d, h_c = mk_pair()
    h_d.sm._native = None  # fallbacks take the JAX host path -> read()
    ops = [(Operation.create_accounts, accounts([1, 2, 3]))]
    for k in range(3):
        ops.append(
            (
                Operation.create_transfers,
                transfers(
                    [dict(id=10 + k, debit_account_id=1,
                          credit_account_id=2, amount=big)]
                ),
            )
        )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3])))
    replay_both(h_d, h_c, ops)
    assert h_d.sm._dev.stat_fallback_batches >= 1


def test_recovery_with_pending_window_stays_ordered(monkeypatch):
    """A full PENDING window queued behind a dirty one must not be
    launched by the recovery fallback's re-entrant drain — it would
    execute out of submission order against a table recovery is about
    to rebuild (and a nested dirty rotation would clobber the
    recovery slot)."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 4)
    big = (1 << 127) + 5
    h_d, h_c = mk_pair()
    h_d.sm._native = None  # fallbacks take the JAX host path -> read()
    ops = [(Operation.create_accounts, accounts([1, 2, 3]))]
    amounts = [big, big] + [3 + k for k in range(9)]
    for k, amount in enumerate(amounts):
        ops.append(
            (
                Operation.create_transfers,
                transfers(
                    [dict(id=10 + k, debit_account_id=1,
                          credit_account_id=2 + k % 2, amount=amount)]
                ),
            )
        )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3])))
    replay_both(h_d, h_c, ops)
    assert h_d.sm._dev.stat_fallback_batches >= 1
    assert h_d.sm._dev.stat_demotions == 0
    h_d.sm.verify_device_mirror()


def test_fallback_cap_exceeded():
    """More failures than the summary cap -> host re-execution with
    full failure list."""
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2]))]
    rows = [
        dict(id=100 + i, debit_account_id=1, credit_account_id=1, amount=1)
        for i in range(100)  # accounts_must_be_different x100 > cap 60
    ]
    ops.append((Operation.create_transfers, transfers(rows)))
    replay_both(h_d, h_c, ops)
    assert h_d.sm._dev.stat_fallback_batches >= 1


def test_linked_precondition_fallback():
    """Limit accounts with u128-scale balances exceed the fixpoint's
    u64-safety precondition -> device flags, host decides."""
    h_d, h_c = mk_pair()
    huge = 1 << 62
    ops = [
        (
            Operation.create_accounts,
            accounts([1], flags=int(AF.debits_must_not_exceed_credits))
            + accounts([2, 3]),
        )
    ]
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [dict(id=5, debit_account_id=2, credit_account_id=1,
                      amount=huge)]
            ),
        )
    )
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=10, debit_account_id=1, credit_account_id=2,
                         amount=10, flags=int(TF.linked)),
                    dict(id=11, debit_account_id=1, credit_account_id=3,
                         amount=20),
                ]
            ),
        )
    )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3])))
    replay_both(h_d, h_c, ops)


def test_linked_fixpoint_multi_iteration():
    """Interleaved chains contending on limited accounts force the
    Jacobi fixpoint past one iteration; verdicts stay exact."""
    rng = np.random.default_rng(7)
    n_acct = 6
    h_d, h_c = mk_pair()
    ops = [
        (
            Operation.create_accounts,
            accounts(
                range(1, n_acct + 1),
                flags=int(AF.debits_must_not_exceed_credits),
            )
            + accounts([99]),
        )
    ]
    # Fund tightly so later chain members trip limits depending on
    # earlier verdicts.
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=100 + i, debit_account_id=99,
                         credit_account_id=i + 1, amount=30)
                    for i in range(n_acct)
                ]
            ),
        )
    )
    rows = []
    tid = 200
    for _chain in range(40):
        ln = int(rng.integers(1, 5))
        for j in range(ln):
            dr = int(rng.integers(1, n_acct + 1))
            cr = int(rng.integers(1, n_acct + 1))
            if cr == dr:
                cr = dr % n_acct + 1
            rows.append(
                dict(
                    id=tid, debit_account_id=dr, credit_account_id=cr,
                    amount=int(rng.integers(1, 25)),
                    flags=int(TF.linked) if j < ln - 1 else 0,
                )
            )
            tid += 1
    ops.append((Operation.create_transfers, transfers(rows)))
    ops.append(
        (Operation.lookup_accounts, hz.ids_bytes(list(range(1, n_acct + 1))))
    )
    replay_both(h_d, h_c, ops)


def test_pulse_with_inflight_timeout_pending():
    """A timeout pending created through the device path must still
    expire on schedule (pulse drains the pipeline first)."""
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2]))]
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=10, debit_account_id=1, credit_account_id=2,
                         amount=50, flags=int(TF.pending), timeout=1),
                ]
            ),
        )
    )
    futs = [h_d.submit_async(op, body) for op, body in ops]
    replies_c = [h_c.submit(op, body) for op, body in ops]
    # Advance realtime past the expiry on both engines.
    later = int(2e9) + h_d.sm.prepare_timestamp
    # First submit advances prepare_timestamp past the expiry; the
    # second one's tick_pulses fires the pulse (prepare-time decision,
    # reference: src/vsr/replica.zig:3126-3143).
    for _ in range(2):
        a = h_d.submit_async(
            Operation.lookup_accounts, hz.ids_bytes([1, 2]), realtime=later
        )
        b = h_c.submit(
            Operation.lookup_accounts, hz.ids_bytes([1, 2]), realtime=later
        )
    for f, r in zip(futs, replies_c):
        assert f.result() == r
    assert a.result() == b
    acc = np.frombuffer(a.result(), dtype=types.ACCOUNT_DTYPE)
    assert int(acc[0]["debits_pending_lo"]) == 0  # expired and released


def test_checkpoint_checksum_catches_divergence():
    sm = TpuStateMachine(engine="device")
    h = hz.SingleNodeHarness(sm)
    h.submit(Operation.create_accounts, accounts([1, 2]))
    h.submit(
        Operation.create_transfers,
        transfers(
            [dict(id=10, debit_account_id=1, credit_account_id=2, amount=5)]
        ),
    )
    sm.verify_device_mirror()  # clean
    sm._mirror.lo[0, 1] += 1  # corrupt the mirror
    with pytest.raises(AssertionError, match="divergence"):
        sm.verify_device_mirror()
    sm._mirror.lo[0, 1] -= 1
    sm.snapshot()  # checkpoint barrier runs the verify


def test_lookup_accounts_sees_inflight_batches(monkeypatch):
    """Device-side balance gather reflects batches that have not
    materialized yet (no drain)."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 1000)
    sm = TpuStateMachine(engine="device")
    h = hz.SingleNodeHarness(sm)
    h.submit(Operation.create_accounts, accounts([1, 2]))
    f1 = h.submit_async(
        Operation.create_transfers,
        transfers(
            [dict(id=10, debit_account_id=1, credit_account_id=2, amount=5)]
        ),
    )
    f2 = h.submit_async(Operation.lookup_accounts, hz.ids_bytes([1, 2]))
    assert not f1.done()  # still in flight
    acc = np.frombuffer(f2.result(), dtype=types.ACCOUNT_DTYPE)
    assert int(acc[0]["debits_posted_lo"]) == 5
    assert int(acc[1]["credits_posted_lo"]) == 5
    assert f1.result() == b""


def test_pipelined_double_finalize_same_pending(monkeypatch):
    """Two pipelined one-event batches posting the SAME durable
    pending: the second must drain on the recorded pending-ref key of
    the first (not just its transfer id) and fail with
    already_posted — the code-review repro for the id_keys hazard."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 64)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2]))]
    ops.append(
        (
            Operation.create_transfers,
            transfers(
                [
                    dict(id=10, debit_account_id=1, credit_account_id=2,
                         amount=50, flags=int(TF.pending)),
                    dict(id=11, debit_account_id=1, credit_account_id=2,
                         amount=500, flags=int(TF.pending)),
                ]
            ),
        )
    )
    futs1 = [h_d.submit_async(op, body) for op, body in ops]
    replies_1 = [f.result() for f in futs1]  # pendings land durably
    ops2 = [
        (
            Operation.create_transfers,
            transfers(
                [dict(id=30, pending_id=10,
                      flags=int(TF.post_pending_transfer))]
            ),
        ),
        (
            Operation.create_transfers,
            transfers(
                [dict(id=31, pending_id=10,
                      flags=int(TF.post_pending_transfer))]
            ),
        ),
        (Operation.lookup_accounts, hz.ids_bytes([1, 2])),
    ]
    futs2 = [h_d.submit_async(op, body) for op, body in ops2]
    replies_d = replies_1 + [f.result() for f in futs2]
    replies_c = [h_c.submit(op, body) for op, body in ops + ops2]
    assert replies_d == replies_c
    res = np.frombuffer(replies_d[-2], dtype=types.CREATE_RESULT_DTYPE)
    assert len(res) == 1
    assert res[0]["result"] == int(CTR.pending_transfer_already_posted)


def test_two_phase_cross_batch_durable_targets():
    """Pendings land durably (drained), then posts/voids reference them
    from later batches, including double-finalize races."""
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts([1, 2, 3]))]
    pends = [
        dict(id=10 + i, debit_account_id=1, credit_account_id=2,
             amount=10 + i, flags=int(TF.pending))
        for i in range(6)
    ]
    ops.append((Operation.create_transfers, transfers(pends)))
    finalize = [
        dict(id=30, pending_id=10, flags=int(TF.post_pending_transfer)),
        dict(id=31, pending_id=11, flags=int(TF.void_pending_transfer)),
        dict(id=32, pending_id=10, flags=int(TF.void_pending_transfer)),
        dict(id=33, pending_id=12, flags=int(TF.post_pending_transfer),
             amount=5),
        dict(id=34, pending_id=99, flags=int(TF.post_pending_transfer)),
    ]
    ops.append((Operation.create_transfers, transfers(finalize)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2])))
    ops.append(
        (Operation.lookup_transfers, hz.ids_bytes([30, 31, 32, 33, 34]))
    )
    replay_both(h_d, h_c, ops)


def test_hot_tail_store_equivalence():
    """The C wire->store decode tail in _finish_native_fast must write
    EXACTLY the columns the shared _post_process_transfers path does —
    the two implementations are pinned together here so a bookkeeping
    change landing in only one fails loudly (per _finish_fast's
    one-implementation invariant)."""
    from tigerbeetle_tpu.runtime import fastpath
    from tigerbeetle_tpu.state_machine.tpu import _STORE_FIELDS

    if fastpath._load() is None:
        pytest.skip("native library unavailable")

    results = {}
    for hot in (True, False):
        rng = np.random.default_rng(11)  # same stream both runs
        sm = TpuStateMachine(account_capacity=1 << 12)
        if sm._native is None:
            pytest.skip("native fastpath unavailable")
        if not hot:
            # Disabling the native fast path routes the same batch
            # through the Python fast path + the SHARED bookkeeping
            # (_finish_fast -> _post_process_transfers).
            sm._native = None
        h = hz.SingleNodeHarness(sm)
        h.submit(Operation.create_accounts, accounts(range(1, 51)))
        rows = []
        for i in range(400):
            dr = int(rng.integers(1, 51))
            cr = dr % 50 + 1
            flags = int(TF.pending) if i % 5 == 0 else 0
            rows.append(
                dict(id=1000 + i, debit_account_id=dr,
                     credit_account_id=cr,
                     amount=int(rng.integers(1, 90)), flags=flags)
            )
        h.submit(Operation.create_transfers, transfers(rows))
        store = sm._store
        results[hot] = {
            name: np.asarray(store.col(name)).copy()
            for name in _STORE_FIELDS
        }

    for name in results[True]:
        assert (results[True][name] == results[False][name]).all(), (
            f"store column {name} diverges between the hot tail and "
            "the shared bookkeeping path"
        )


def test_grow_with_window_in_flight(monkeypatch):
    """Capacity growth triggered by create_accounts while a transfer
    window is still in flight: grow() must drain the stream, widen the
    tables, and every reply (before and after) must stay exact."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 64)
    sm_d = TpuStateMachine(engine="device", account_capacity=64)
    h_d = hz.SingleNodeHarness(sm_d)
    h_c = hz.SingleNodeHarness(CpuStateMachine())
    ops = [(Operation.create_accounts, accounts(range(1, 41)))]
    # In-flight transfers against the small table...
    for k in range(6):
        ops.append(
            (
                Operation.create_transfers,
                transfers(
                    [dict(id=100 + k, debit_account_id=1 + k % 40,
                          credit_account_id=1 + (k + 1) % 40,
                          amount=5 + k)]
                ),
            )
        )
    futs = [h_d.submit_async(op, body) for op, body in ops]
    cap_before = sm_d._dev.capacity
    assert sm_d._dev.has_inflight()
    # ...then an account burst that forces _ensure_balance_capacity ->
    # DeviceEngine.grow() mid-stream.
    grow_ops = [(Operation.create_accounts, accounts(range(41, 101)))]
    for k in range(4):
        grow_ops.append(
            (
                Operation.create_transfers,
                transfers(
                    [dict(id=200 + k, debit_account_id=90 + k,
                          credit_account_id=1 + k, amount=7 + k)]
                ),
            )
        )
    grow_ops.append(
        (Operation.lookup_accounts, hz.ids_bytes(list(range(1, 101))))
    )
    futs += [h_d.submit_async(op, body) for op, body in grow_ops]
    replies_d = [f.result() for f in futs]
    replies_c = [h_c.submit(op, body) for op, body in ops + grow_ops]
    assert replies_d == replies_c
    assert sm_d._dev.capacity > cap_before
    # The point is DEVICE-path coverage: a regression that demotes the
    # engine would still reply exactly (host fallback) — fail loudly
    # instead of passing vacuously.
    assert sm_d._dev.stat_demotions == 0
    assert sm_d._dev.state is types.EngineState.healthy
    sm_d.verify_device_mirror()


def test_remove_accounts_with_window_in_flight(monkeypatch):
    """A linked create_accounts chain that fails mid-chain rolls back
    its slots (DeviceEngine.remove_accounts) while transfer batches
    are still in flight; the meta zeroing must sequence with the
    stream and later replies stay exact."""
    import tigerbeetle_tpu.state_machine.device_engine as de

    monkeypatch.setattr(de, "_WINDOW", 64)
    h_d, h_c = mk_pair()
    setup = (Operation.create_accounts, accounts([1, 2]))
    h_d.submit(*setup)
    h_c.submit(*setup)
    futs = []
    ops = []
    for k in range(3):
        op = (
            Operation.create_transfers,
            transfers(
                [dict(id=10 + k, debit_account_id=1, credit_account_id=2,
                      amount=3 + k)]
            ),
        )
        ops.append(op)
        futs.append(h_d.submit_async(*op))
    assert h_d.sm._dev.has_inflight()
    # Linked chain: second member duplicates id 1 -> whole chain fails
    # -> rollback removes the chain's already-allocated slots while
    # the transfer window above is still in flight.
    chain = (
        Operation.create_accounts,
        hz.pack(
            [
                hz.account(50, flags=int(AF.linked)),
                hz.account(1),
            ]
        ),
    )
    ops.append(chain)
    futs.append(h_d.submit_async(*chain))
    # Transfers naming the rolled-back account must fail identically.
    post = (
        Operation.create_transfers,
        transfers(
            [dict(id=20, debit_account_id=50, credit_account_id=2,
                  amount=9),
             dict(id=21, debit_account_id=1, credit_account_id=2,
                  amount=11)]
        ),
    )
    ops.append(post)
    futs.append(h_d.submit_async(*post))
    look = (Operation.lookup_accounts, hz.ids_bytes([1, 2, 50]))
    ops.append(look)
    futs.append(h_d.submit_async(*look))
    replies_d = [f.result() for f in futs]
    replies_c = [h_c.submit(op, body) for op, body in ops]
    assert replies_d == replies_c
    # Device-path coverage must be real, not a silent host fallback.
    assert h_d.sm._dev.stat_demotions == 0
    assert h_d.sm._dev.state is types.EngineState.healthy
    h_d.sm.verify_device_mirror()


def test_tight_and_wide_inputs_agree(monkeypatch):
    """The tight (B, 5) u32 order-free input and the wide u64 format
    must produce byte-identical replies and final state for the same
    stream — the tight path is an ENCODING, not a semantics change.
    The wide run shrinks the router's amount gate to zero so the same
    small-amount stream routes through the u64 format."""
    import tigerbeetle_tpu.state_machine.tpu as tpu_mod

    def stream():
        rng = np.random.default_rng(11)
        ops = [(Operation.create_accounts, accounts(range(1, 40)))]
        tid = 100
        for _ in range(3):
            rows = []
            for _k in range(50):
                dr = int(rng.integers(1, 40))
                cr = dr % 39 + 1
                rows.append(
                    hz.transfer(tid, debit_account_id=dr,
                                credit_account_id=cr,
                                amount=int(rng.integers(1, 90)))
                )
                tid += 1
            ops.append((Operation.create_transfers, hz.pack(rows)))
        ops.append((Operation.lookup_accounts, hz.ids_bytes(range(1, 40))))
        return ops

    def run():
        sm = TpuStateMachine(engine="device", account_capacity=1 << 10)
        h = hz.SingleNodeHarness(sm)
        return [h.submit(op, body) for op, body in stream()], sm

    replies_tight, sm_t = run()
    assert sm_t.stat_device_semantic_events > 0

    monkeypatch.setattr(tpu_mod, "_TIGHT_AMOUNT_LIMIT", 0)
    replies_wide, sm_w = run()
    assert sm_w.stat_device_semantic_events > 0
    assert replies_tight == replies_wide


# ---------------------------------------------------------------------------
# Link-error taxonomy: classification is MEASURED against the
# declarative marker table, not guessed (ROADMAP "Real-link error
# taxonomy") — a new marker harvested from a real tunnel flake is one
# table row plus one parametrized case here.


def _pjrt_style_message(marker: str) -> str:
    """A message shaped like what JAX/PJRT actually surfaces: gRPC
    status name + detail, wrapped in the XlaRuntimeError prefix."""
    return (
        f"jaxlib.xla_extension.XlaRuntimeError: {marker}: stream "
        "executor failure while transferring buffer d2h (axon tunnel)"
    )


from tigerbeetle_tpu.state_machine.device_engine import LINK_ERROR_MARKERS


@pytest.mark.parametrize("marker,expected", list(LINK_ERROR_MARKERS))
def test_link_error_marker_classification(marker, expected):
    from tigerbeetle_tpu.state_machine import device_engine as de

    exc = RuntimeError(_pjrt_style_message(marker))
    assert de.classify_link_error(exc) == expected


def test_link_error_first_match_wins_and_default_fatal():
    from tigerbeetle_tpu.state_machine import device_engine as de

    # Typed exceptions bypass the table entirely.
    assert de.classify_link_error(de.TransientLinkError("x")) == "transient"
    assert de.classify_link_error(de.FatalLinkError("x")) == "fatal"
    # Unknown messages default to fatal (demote, never spin retrying).
    assert de.classify_link_error(RuntimeError("segfault in plugin")) == "fatal"
    # Declaration order arbitrates multi-marker messages: UNAVAILABLE
    # precedes INTERNAL in the table, so the transient row wins.
    both = RuntimeError(_pjrt_style_message("UNAVAILABLE") + " INTERNAL")
    assert de.classify_link_error(both) == "transient"


def test_link_error_taxonomy_is_declarative():
    """The table stays the single source of truth: every row
    classifies one way, and both classes are represented (a taxonomy
    with one class is a boolean, not a taxonomy)."""
    from tigerbeetle_tpu.state_machine import device_engine as de

    kinds = {kind for _m, kind in de.LINK_ERROR_MARKERS}
    assert kinds == {"transient", "fatal"}
    markers = [m for m, _k in de.LINK_ERROR_MARKERS]
    assert len(markers) == len(set(markers)), "duplicate marker rows"


# ---------------------------------------------------------------------------
# Healthy-mode scrub jitter: a deterministic per-engine offset keeps
# TB_DEV_SCRUB_EVERY scrubs off the same fetch ordinal across engines
# (each scrub costs a ~105 ms checksum fetch on the real link).


def test_scrub_offset_deterministic_and_bounded(monkeypatch):
    import tigerbeetle_tpu.state_machine.device_engine as de
    from tigerbeetle_tpu.state_machine.mirror import BalanceMirror

    monkeypatch.setattr(de, "_SCRUB_EVERY", 256)
    monkeypatch.setattr(de, "_SCRUB_JITTER", -1)  # auto: every // 8

    def offset(seed):
        eng = de.DeviceEngine(64, BalanceMirror(64), seed=seed)
        return eng._scrub_offset

    a1, a2 = offset(7), offset(7)
    assert a1 == a2, "same seed must give the same offset"
    cap = de._scrub_jitter_cap(256, -1)
    assert cap == 32
    offsets = {offset(s) for s in range(40)}
    assert all(0 <= o <= cap for o in offsets)
    assert len(offsets) > 1, "offsets never vary: jitter is vacuous"
    # Default seeds mix in a per-process construction ordinal: a fleet
    # of SAME-capacity engines must not scrub in lockstep.
    defaults = {
        de.DeviceEngine(64, BalanceMirror(64))._scrub_offset
        for _ in range(8)
    }
    assert len(defaults) > 1, "same-capacity engines share one offset"


def test_scrub_jitter_shifts_first_scrub(monkeypatch):
    """The first scrub fires TB_DEV_SCRUB_EVERY - offset fetches in
    (phase-shifted), subsequent scrubs keep the full cadence."""
    import tigerbeetle_tpu.state_machine.device_engine as de
    from tigerbeetle_tpu.state_machine.mirror import BalanceMirror

    monkeypatch.setattr(de, "_SCRUB_EVERY", 16)
    monkeypatch.setattr(de, "_SCRUB_JITTER", 5)
    eng = de.DeviceEngine(64, BalanceMirror(64), seed=3)
    off = eng._scrub_offset
    assert 0 <= off <= 5
    scrubbed = []
    real_scrub = eng.scrub

    def counting_scrub():
        scrubbed.append(eng.stat_fetches)
        return real_scrub()

    eng.scrub = counting_scrub
    for fetch in range(1, 64):
        eng.stat_fetches = fetch
        eng.tick()
    assert scrubbed, "scrub never fired"
    assert scrubbed[0] == 16 - off
    if len(scrubbed) > 1:
        assert scrubbed[1] - scrubbed[0] == 16


def test_scrub_jitter_disabled_when_zero(monkeypatch):
    import tigerbeetle_tpu.state_machine.device_engine as de
    from tigerbeetle_tpu.state_machine.mirror import BalanceMirror

    monkeypatch.setattr(de, "_SCRUB_EVERY", 256)
    monkeypatch.setattr(de, "_SCRUB_JITTER", 0)
    eng = de.DeviceEngine(64, BalanceMirror(64), seed=12345)
    assert eng._scrub_offset == 0
